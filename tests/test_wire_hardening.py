"""Wire-boundary hardening (ISSUE 4 satellites 2-4): typed errors on
half-dead peers, seeded codec fuzzing, and RemoteSolver backoff reset."""

import io
import socket
import struct
import threading
import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.service.codec import (
    MAX_FRAME,
    CodecError,
    FrameTooLarge,
    SolveRequest,
    SolveResponse,
    TruncatedFrame,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    read_frame,
    write_frame,
)
from koordinator_tpu.service.client import (
    PlacementClient,
    SolverUnavailable,
)
from koordinator_tpu.service.server import PlacementService


def _problem(n_nodes=4, n_pods=6):
    rng = np.random.default_rng(0)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    node = {
        "alloc": alloc,
        "used_req": np.zeros_like(alloc),
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([1000, 2000], n_pods)
    pods = {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": np.zeros(n_pods, bool),
        "is_daemonset": np.zeros(n_pods, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return SolveRequest(node=node, pods=pods, params=params)


class _HalfDeadServer:
    """Accepts one connection, reads the request frame, writes a length
    prefix promising a full response — then delivers only half of it
    and dies. The canonical mid-response-frame crash."""

    def __init__(self, addr):
        self.addr = addr
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(addr)
        self._sock.listen(1)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        conn, _ = self._sock.accept()
        stream = conn.makefile("rwb")
        try:
            read_frame(stream)
            payload = encode_response(SolveResponse(
                assignments=np.zeros(4, np.int32)
            ))
            stream.write(struct.pack(">I", len(payload)))
            stream.write(payload[: len(payload) // 2])
            stream.flush()
        finally:
            stream.close()
            conn.close()

    def stop(self):
        self._sock.close()


class TestHalfDeadPeer:
    def test_client_mid_response_death_is_typed(self, tmp_path):
        """Satellite 2: a server dying mid-response-frame surfaces as
        SolverUnavailable — never struct.error or a bare EOFError."""
        addr = str(tmp_path / "halfdead.sock")
        server = _HalfDeadServer(addr)
        try:
            client = PlacementClient(addr, timeout=5.0)
            with pytest.raises(SolverUnavailable):
                client.solve(_problem())
            client.close()
        finally:
            server.stop()

    def test_client_immediate_close_is_typed(self, tmp_path):
        """A peer closing cleanly before the response is the same typed
        failure (it used to be a bare ConnectionError)."""
        addr = str(tmp_path / "closer.sock")
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(addr)
        sock.listen(1)

        def close_on_accept():
            conn, _ = sock.accept()
            conn.close()

        t = threading.Thread(target=close_on_accept, daemon=True)
        t.start()
        try:
            client = PlacementClient(addr, timeout=5.0)
            with pytest.raises(SolverUnavailable):
                client.solve(_problem())
            client.close()
        finally:
            sock.close()

    def test_server_survives_truncated_request(self, tmp_path):
        """Satellite 2, server side: a client dying mid-request-frame
        (and one sending an insane length prefix) is dropped quietly —
        no handler traceback, and the NEXT client solves normally."""
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        handler_errors = []
        service._server.handle_error = (
            lambda *a: handler_errors.append(a)
        )
        service.start()
        try:
            # truncated request: promise 4096 bytes, deliver 10, die
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(addr)
            sock.sendall(struct.pack(">I", 4096) + b"x" * 10)
            sock.close()
            # oversized length prefix
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(addr)
            sock.sendall(struct.pack(">I", MAX_FRAME + 1))
            sock.close()
            time.sleep(0.1)  # let the handler threads run their course
            with PlacementClient(addr, timeout=30.0) as client:
                resp = client.solve(_problem())
                assert (resp.assignments >= 0).all()
            assert handler_errors == []
        finally:
            service.stop()


class TestCodecFuzz:
    """Satellite 3: every malformed payload yields a TYPED error —
    CodecError / TruncatedFrame / FrameTooLarge — never a hang, an
    unbounded allocation, or a raw numpy/zipfile internal."""

    DECODE_OK = (CodecError,)
    FRAME_OK = (TruncatedFrame, FrameTooLarge)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_decoders_fuzz(self, seed):
        rng = np.random.default_rng(seed)
        req_payload = encode_request(_problem())
        resp_payload = encode_response(SolveResponse(
            assignments=np.array([0, 1, -1], np.int32),
            node_used_req=np.ones((2, NUM_RESOURCES), np.int32),
        ))
        for trial in range(200):
            base = req_payload if trial % 2 else resp_payload
            decode = decode_request if trial % 2 else decode_response
            buf = bytearray(base)
            kind = trial % 4
            if kind == 0:  # truncate at a random point
                buf = buf[: int(rng.integers(0, len(buf)))]
            elif kind == 1:  # flip random bytes
                for _ in range(int(rng.integers(1, 16))):
                    buf[int(rng.integers(0, len(buf)))] ^= int(
                        rng.integers(1, 256)
                    )
            elif kind == 2:  # random garbage of random length
                buf = bytes(rng.integers(0, 256, int(rng.integers(0, 512)),
                                         dtype=np.uint8))
            else:  # truncate AND corrupt
                buf = buf[: int(rng.integers(1, len(buf)))]
                if buf:
                    buf[int(rng.integers(0, len(buf)))] ^= 0xFF
            try:
                decode(bytes(buf))
            except self.DECODE_OK:
                pass  # typed: the contract
            # anything else (KeyError, zipfile.BadZipFile, struct.error,
            # OverflowError, ...) propagates and fails the test

    @pytest.mark.parametrize("seed", [0, 1])
    def test_read_frame_fuzz(self, seed):
        rng = np.random.default_rng(seed)
        payload = encode_request(_problem(n_nodes=2, n_pods=2))
        frame = struct.pack(">I", len(payload)) + payload
        for trial in range(200):
            buf = bytearray(frame)
            kind = trial % 3
            if kind == 0:  # truncate (header or payload)
                buf = buf[: int(rng.integers(0, len(buf)))]
            elif kind == 1:  # corrupt the length prefix
                buf[int(rng.integers(0, 4))] ^= int(rng.integers(1, 256))
            else:  # corrupt payload bytes (framing intact)
                buf[int(rng.integers(4, len(buf)))] ^= 0xFF
            stream = io.BytesIO(bytes(buf))
            try:
                out = read_frame(stream, max_frame=len(payload) * 4)
                assert out is None or isinstance(out, bytes)
            except self.FRAME_OK:
                pass

    def test_oversized_prefix_refused_before_allocation(self):
        """The MAX_FRAME cap fires on the 4 header bytes alone: no
        payload is read (or allocated) for a prefix past the cap."""
        stream = io.BytesIO(struct.pack(">I", MAX_FRAME + 1))
        with pytest.raises(FrameTooLarge):
            read_frame(stream)
        # nothing beyond the header was consumed
        assert stream.tell() == 4

        # a caller-narrowed cap fires the same way
        stream = io.BytesIO(struct.pack(">I", 5000) + b"x" * 5000)
        with pytest.raises(FrameTooLarge):
            read_frame(stream, max_frame=4096)

    def test_truncated_frame_is_typed(self):
        stream = io.BytesIO(struct.pack(">I", 100) + b"x" * 10)
        with pytest.raises(TruncatedFrame):
            read_frame(stream)

    def test_valid_roundtrip_still_works(self):
        req = _problem()
        buf = io.BytesIO()
        write_frame(buf, encode_request(req))
        buf.seek(0)
        decoded = decode_request(read_frame(buf))
        np.testing.assert_array_equal(
            decoded.node["alloc"], req.node["alloc"]
        )


class _FlakySidecar:
    """Real solves, except while ``shed`` is armed: then a typed
    ``overloaded`` error per request, decrementing the counter."""

    def __init__(self, addr):
        from koordinator_tpu.service.admission import error_response
        from koordinator_tpu.service.server import solve_from_request

        self.shed = [0]
        self.requests = 0
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(addr)
        self._sock.listen(4)
        self._sock.settimeout(0.2)

        def serve_conn(conn):
            stream = conn.makefile("rwb")
            try:
                while True:
                    payload = read_frame(stream)
                    if payload is None:
                        return
                    self.requests += 1
                    if self.shed[0] > 0:
                        self.shed[0] -= 1
                        resp = error_response("overloaded", "scripted")
                    else:
                        resp = solve_from_request(decode_request(payload))
                    write_frame(stream, encode_response(resp))
                    stream.flush()
            except (OSError, EOFError, ValueError):
                pass
            finally:
                stream.close()
                conn.close()

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except (socket.timeout, OSError):
                    continue
                threading.Thread(
                    target=serve_conn, args=(conn,), daemon=True
                ).start()

        self._thread = threading.Thread(target=accept_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._sock.close()


class TestBackoffReset:
    def test_backoff_resets_after_successful_solve(self, tmp_path, monkeypatch):
        """Satellite 4: the exponential backoff is per-solve state — a
        fail→succeed→fail sequence starts the second failure's delays
        back at the base, not where the first run left off."""
        import jax.numpy as jnp

        import koordinator_tpu.service.client as client_mod
        from koordinator_tpu.ops.binpack import (
            NodeState,
            PodBatch,
            ScoreParams,
            SolverConfig,
        )
        from koordinator_tpu.service.client import RemoteSolver

        req = _problem()
        state = NodeState(**{k: jnp.asarray(v) for k, v in req.node.items()})
        batch = PodBatch.build(
            **{k: jnp.asarray(v) for k, v in req.pods.items()})
        params = ScoreParams(
            **{k: jnp.asarray(v) for k, v in req.params.items()})
        args = (state, batch, params, SolverConfig())

        sleeps = []

        class _Time:
            monotonic = staticmethod(time.monotonic)

            @staticmethod
            def sleep(s):
                sleeps.append(s)

        monkeypatch.setattr(client_mod, "time", _Time)

        class _Rng:
            def random(self):
                return 1.0  # jitter factor 1: delay == base * 2**attempt

        addr = str(tmp_path / "flaky.sock")
        sidecar = _FlakySidecar(addr)
        try:
            solver = RemoteSolver(
                addr, backoff_base_s=0.01, backoff_cap_s=10.0,
                retry_total_s=60.0, rng=_Rng(),
            )
            sidecar.shed[0] = 2
            solver.solve_result(*args)           # fail, fail, succeed
            first = list(sleeps)
            assert first == [0.01, 0.02]         # exponential from base
            sleeps.clear()
            sidecar.shed[0] = 2
            solver.solve_result(*args)           # fail, fail, succeed
            assert sleeps == first               # RESET: base again
            solver.close()
        finally:
            sidecar.stop()
