"""Differential: the device Balance sweep vs the host classifier.

The host LoadAware eviction walk (descheduler/loadaware.py, itself
bit-parity-tested against the scalar oracle in test_rebalance_oracle)
is the semantics oracle for the device sweep (ops/rebalance.py
``run_balance_sweep``: one lax.scan over the flattened host-ordered
candidate list). These tests require the ORDERED eviction sequence to
match exactly across backends over randomized clusters, through the
refusal fixpoint, the dry-run proposal path, and the multi-sweep
debounce — plus the numeric contracts: the reference's float64
threshold truncation, the strict over-threshold compare, the i32
staging domain, and the candidate bucket law.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_rebalance_oracle import RecordingEvictor, random_cluster

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.descheduler import LowNodeLoad, LowNodeLoadArgs, NodePool
from koordinator_tpu.ops.rebalance import (
    SweepBatch,
    replay_sweep_host,
    run_balance_sweep,
    sweep_candidate_bucket,
    threshold_quantities,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


def _args(rng, backend, consecutive=1):
    return LowNodeLoadArgs(
        backend=backend,
        node_pools=[NodePool(
            low_thresholds={CPU: int(rng.integers(20, 50)),
                            MEM: int(rng.integers(20, 60))},
            high_thresholds={CPU: int(rng.integers(55, 80)),
                             MEM: int(rng.integers(65, 90))},
            resource_weights={CPU: int(rng.integers(1, 4)),
                              MEM: int(rng.integers(1, 4))},
            consecutive_abnormalities=consecutive,
        )],
    )


def _sweep(backend, seed, evictor_cls=RecordingEvictor, sweeps=1,
           consecutive=1, dry_run=False):
    rng = np.random.default_rng(seed)
    snapshot = random_cluster(rng)
    args = _args(rng, backend, consecutive=consecutive)
    args.dry_run = dry_run
    plugin = LowNodeLoad(args)
    sequences, proposals = [], []
    for _ in range(sweeps):
        evictor = evictor_cls()
        plugin.balance(snapshot, evictor)
        sequences.append(evictor.sequence)
        proposals.append([p.uid for p in plugin.last_proposals])
    return sequences, proposals


@pytest.mark.parametrize("seed", range(12))
def test_device_sweep_ordered_parity(seed):
    """Victim sets AND order: the device sweep must reproduce the host
    walk's eviction sequence exactly."""
    want, _ = _sweep("host", seed)
    got, _ = _sweep("device", seed)
    assert got == want


@pytest.mark.parametrize("seed", range(4))
def test_verify_backend_round(seed):
    """backend="verify" runs the device sweep, asserts its decision
    streams bit-equal to the pure-host replica, then applies — the
    applied sequence still matches the host walk."""
    want, _ = _sweep("host", 200 + seed)
    got, _ = _sweep("verify", 200 + seed)
    assert got == want


def test_parity_suite_not_vacuous():
    total = 0
    for seed in range(12):
        seqs, _ = _sweep("host", seed)
        total += len(seqs[0])
    assert total > 0, "no seed produced evictions: the suite is vacuous"


class RefusingEvictor(RecordingEvictor):
    """Deterministically refuses ~30% of evictions: exercises the
    device backend's blocked-mask fixpoint re-scan (a refusal must not
    perturb decisions for the already-walked prefix)."""

    def __init__(self, seed):
        super().__init__()
        self._rng = np.random.default_rng(seed)
        self.refused = 0

    def _do_evict(self, snapshot, pod, reason) -> bool:
        if self._rng.random() < 0.3:
            self.refused += 1
            return False
        return True


@pytest.mark.parametrize("seed", range(6))
def test_refusal_fixpoint_parity(seed):
    """Both backends call the evictor in the SAME sequence, so the
    refusal rng draws align and the applied sequences must match."""
    results = {}
    for backend in ("host", "device", "verify"):
        rng = np.random.default_rng(seed)
        snapshot = random_cluster(rng)
        plugin = LowNodeLoad(_args(rng, backend))
        evictor = RefusingEvictor(seed=1000 + seed)
        plugin.balance(snapshot, evictor)
        results[backend] = (evictor.sequence, evictor.refused)
    assert results["device"] == results["host"]
    assert results["verify"] == results["host"]


@pytest.mark.parametrize("seed", range(6))
def test_dry_run_proposal_parity(seed):
    """Dry run proposes (and keeps subtracting, per the reference) but
    never evicts: identical proposal lists, zero evictions."""
    want_seq, want_prop = _sweep("host", 400 + seed, dry_run=True)
    got_seq, got_prop = _sweep("device", 400 + seed, dry_run=True)
    assert got_prop == want_prop
    assert want_seq == got_seq == [[]]


@pytest.mark.parametrize("seed", range(6))
def test_multi_sweep_debounce_parity(seed):
    """consecutive_abnormalities=2: the first sweep only arms the
    anomaly counters, the second evicts — streak state must carry
    identically across backends."""
    want, _ = _sweep("host", 600 + seed, sweeps=3, consecutive=2)
    got, _ = _sweep("device", 600 + seed, sweeps=3, consecutive=2)
    assert got == want
    assert want[0] == [], "debounce did not suppress the first sweep"


# -- numeric contracts -------------------------------------------------------


def test_float64_threshold_truncation():
    """The reference computes quantities through float64 and truncates:
    29% of 100000 is 28999.999... -> 28999, NOT 29000. Both the
    resolver and the staged device compare must live on that value."""
    alloc = np.zeros((1, 8), dtype=np.int64)
    alloc[0, int(CPU)] = 100000
    usage = np.zeros((1, 8), dtype=np.int64)
    low_p = np.full(8, -1, dtype=np.int64)
    high_p = np.full(8, -1, dtype=np.int64)
    high_p[int(CPU)] = 29
    low_p[int(CPU)] = 10
    _low_q, high_q, mask = threshold_quantities(
        usage, alloc, low_p, high_p, active=np.ones(1, bool))
    assert int(high_q[0, int(CPU)]) == 28999
    assert bool(mask[int(CPU)])


def _edge_world(cpu_usage):
    """One over-threshold node (exactly at/over the truncated edge) and
    one empty low node to absorb; high CPU threshold 29% of 100000."""
    nodes = [
        NodeSpec(name="hot", allocatable={CPU: 100000, MEM: 1 << 20}),
        NodeSpec(name="cold", allocatable={CPU: 100000, MEM: 1 << 20}),
    ]
    pods = [PodSpec(name="p0", node_name="hot",
                    requests={CPU: 100, MEM: 64})]
    metrics = {
        "hot": NodeMetric(
            node_name="hot",
            node_usage={CPU: cpu_usage, MEM: 1024},
            pod_usages={pods[0].uid: {CPU: cpu_usage, MEM: 1024}},
            update_time=100.0),
        "cold": NodeMetric(node_name="cold",
                           node_usage={CPU: 0, MEM: 0},
                           update_time=100.0),
    }
    return ClusterSnapshot(nodes=nodes, pods=pods, node_metrics=metrics,
                           now=120.0)


@pytest.mark.parametrize("backend", ["host", "device", "verify"])
def test_percent_rounding_threshold_edges(backend):
    """The over compare is STRICT (> high_q): usage 28999 (== the
    truncated quantity) stays put, 29000 evicts — on every backend.
    The integer-percent config value 29000 would mistakenly keep if the
    sweep recomputed 29% as 29000."""
    args = LowNodeLoadArgs(backend=backend, node_pools=[NodePool(
        low_thresholds={CPU: 10}, high_thresholds={CPU: 29},
    )])
    at_edge = RecordingEvictor()
    LowNodeLoad(args).balance(_edge_world(28999), at_edge)
    assert at_edge.sequence == []
    over_edge = RecordingEvictor()
    LowNodeLoad(args).balance(_edge_world(29000), over_edge)
    assert [n for n, _ in over_edge.sequence] == ["hot"]


# -- the staged kernel -------------------------------------------------------


def _random_batch(rng, k, r=4):
    node_start = np.zeros(k, bool)
    node_start[0] = True
    for i in range(1, k):
        node_start[i] = rng.random() < 0.3
    return SweepBatch(
        node_start=node_start,
        usage0=rng.integers(0, 10000, size=(k, r)).astype(np.int64),
        high_q=rng.integers(0, 9000, size=(k, r)).astype(np.int64),
        metric=rng.integers(0, 500, size=(k, r)).astype(np.int64),
        has_metric=rng.random(k) < 0.8,
        valid=rng.random(k) < 0.9,
    )


@pytest.mark.parametrize("seed", range(8))
def test_kernel_matches_host_replay(seed):
    rng = np.random.default_rng(seed)
    k = int(rng.integers(1, 40))
    batch = _random_batch(rng, k)
    available = rng.integers(0, 5000, size=4).astype(np.int64)
    res_mask = rng.random(4) < 0.7
    blocked = rng.random(k) < 0.2
    got = run_balance_sweep(batch, available, res_mask, blocked)
    want = replay_sweep_host(batch, available, res_mask, blocked)
    for g, w, name in zip(got, want, ("propose", "over", "avail_ok")):
        np.testing.assert_array_equal(g, w, err_msg=name)


def test_sweep_candidate_bucket_values():
    assert [sweep_candidate_bucket(n) for n in (0, 1, 7, 8, 9, 100)] == [
        8, 8, 8, 8, 16, 128]
    # monotone power-of-two law: padding shrinks recompiles to log(n)
    for n in range(1, 300):
        b = sweep_candidate_bucket(n)
        assert b >= n and (b & (b - 1)) == 0


def test_i32_overflow_raises():
    rng = np.random.default_rng(0)
    batch = _random_batch(rng, 4)
    batch.usage0[0, 0] = np.int64(1) << 40
    with pytest.raises(ValueError, match="int32 device domain"):
        run_balance_sweep(batch, np.zeros(4, np.int64),
                          np.ones(4, bool), np.zeros(4, bool))


def test_available_endpoint_overflow_raises():
    """The carry's furthest travel (all masked metrics subtracted) must
    stay i32 even when every individual staged value fits."""
    rng = np.random.default_rng(1)
    batch = _random_batch(rng, 4)
    available = np.full(4, np.iinfo(np.int32).min + 100, dtype=np.int64)
    with pytest.raises(ValueError, match="int32 device domain"):
        run_balance_sweep(batch, available, np.ones(4, bool),
                          np.zeros(4, bool))


def test_batch_must_open_with_node_start():
    rng = np.random.default_rng(2)
    batch = _random_batch(rng, 4)
    batch.node_start[0] = False
    with pytest.raises(ValueError, match="node_start"):
        run_balance_sweep(batch, np.zeros(4, np.int64),
                          np.ones(4, bool), np.zeros(4, bool))
