"""graftcheck v3: static shape-flow — per-pass self-tests + teeth.

The ISSUE 15 layers, mirroring test_graftcheck_v2.py's structure:

1. each new pass detects its seeded-violation fixture
   (``tests/fixtures/graftcheck/``) and stays quiet on the sanctioned
   idioms beside it (bucket calls, aligned widths, pad remainders);
2. the real repo is clean across all passes AND the enumeration is
   non-vacuous (the committed bucket images really contain the hot
   buckets — an empty enumeration would pass a coverage check for the
   wrong reason);
3. injected violations in REAL source fail loudly: the pre-PR 8 storm
   shape itself (a stripped bucket call in ``_pad_pods``), an
   un-adopted ``solve_batch`` (cold-on-every-recovery), and a renamed
   binding (unknown recompile surface + stale declaration);
4. the runtime sentinel (testing/shapeflow.py) convicts
   out-of-enumeration compiles — unit-level on synthetic signatures
   and END TO END against a live PlacementModel driving two pod
   buckets — and its chaos/streaming teeth live in
   test_chaos.py/test_streaming.py as autouse window fixtures;
5. the CLI exports the signature-space sidecar and the new
   whole-program passes run full-graph under ``--changed-files``.
"""

import ast
import json
from pathlib import Path

import pytest

from koordinator_tpu.analysis.graftcheck import (
    ModuleFile,
    default_rules,
    load_allowlist,
    load_module,
    run_checks,
)
from koordinator_tpu.analysis.graftcheck.callgraph import (
    Program,
    build_program,
)
from koordinator_tpu.analysis.graftcheck.engine import (
    iter_repo_modules,
    run_checks_timed,
)
from koordinator_tpu.analysis.graftcheck.rules import (
    BINDING_SPECS,
    AxisSpec,
    BindingSpec,
    BucketFlowRule,
    BucketFn,
    LabelDomain,
    MetricsHygieneRule,
    MetricsSpec,
    SignatureSpaceRule,
    WarmCoverageRule,
)
from koordinator_tpu.analysis.graftcheck.rules.shape_flow import (
    enumerate_axis,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "graftcheck"

SF_PATH = "tests/fixtures/graftcheck/shape_flow_bad.py"
SIG_PATH = "tests/fixtures/graftcheck/sig_space_bindings.py"
MET_PATH = "tests/fixtures/graftcheck/metrics_bad.py"

FX_BUCKETS = (BucketFn(name="fx_bucket", path=SF_PATH,
                       qualname="fx_bucket", exempt_body=True),)

FX_SPECS = (
    BindingSpec(name="fx_declared", path=SIG_PATH, axes=(AxisSpec(
        axis="pods",
        bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
        kwargs_options=((("floor", 8),),), bound=256,
        bound_source="fixture"),)),
    BindingSpec(name="fx_weird_statics", path=SIG_PATH, axes=(AxisSpec(
        axis="pods",
        bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
        kwargs_options=((("floor", 8),),), bound=256,
        bound_source="fixture"),)),
    BindingSpec(name="fx_cold", path=SIG_PATH, axes=(AxisSpec(
        axis="pods",
        bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
        kwargs_options=((("floor", 8),),), bound=256,
        bound_source="fixture"),)),
)


def _fixture(name: str) -> ModuleFile:
    rel = f"tests/fixtures/graftcheck/{name}"
    return load_module(FIXTURES / name, rel)


@pytest.fixture(scope="module")
def repo_program():
    return build_program(list(iter_repo_modules(REPO)))


# -- 1. the new passes detect their seeded fixtures --------------------------

def test_bucket_flow_fixture_detected():
    module = _fixture("shape_flow_bad.py")
    rule = BucketFlowRule(scope=(SF_PATH,), buckets=FX_BUCKETS)
    violations = rule.check_program(Program([module]))
    by_func = {v.func for v in violations}
    assert by_func == {
        "raw_len_zeros", "raw_len_struct", "raw_len_pad",
        "raw_comprehension_asarray", "raw_augassign_zeros",
        "raw_arith_shape",
        # the interprocedural case reports at the sink, inside the
        # helper the raw len flowed into
        "_make_axis",
    }, [v.format() for v in violations]
    for quiet in ("clean_bucketed", "clean_aligned",
                  "clean_pad_remainder", "clean_constant",
                  "clean_augassign_constant", "clean_nested_return",
                  "clean_nested_return_caller"):
        assert quiet not in by_func
    assert all("raw-dynamic" in v.message for v in violations)


def test_signature_space_fixture_detected():
    module = _fixture("sig_space_bindings.py")
    rule = SignatureSpaceRule(specs=FX_SPECS)
    violations = rule.check_program(Program([module]))
    assert [v.symbol for v in violations] == ["fx_undeclared"], (
        [v.format() for v in violations]
    )
    assert "no BindingSpec" in violations[0].message
    # the sidecar carries the enumerated images for the declared ones
    space = rule.last_space
    assert set(space) == {"fx_declared", "fx_weird_statics", "fx_cold"}
    assert space["fx_declared"]["adopted"] is True
    assert space["fx_cold"]["adopted"] is False
    values = space["fx_declared"]["axes"][0]["values"]
    assert 8 in values and 256 in values and 9 not in values


def test_signature_space_stale_spec_detected():
    module = _fixture("sig_space_bindings.py")
    ghost = FX_SPECS + (BindingSpec(
        name="fx_ghost", path=SIG_PATH, axes=()),)
    rule = SignatureSpaceRule(specs=ghost)
    violations = rule.check_program(Program([module]))
    assert any(
        v.symbol == "fx_ghost" and "stale" in v.message
        for v in violations
    ), [v.format() for v in violations]


def test_warm_coverage_fixture_detected():
    module = _fixture("sig_space_bindings.py")
    rule = WarmCoverageRule(specs=FX_SPECS, hot_scope=(SIG_PATH,))
    violations = rule.check_program(Program([module]))
    by_symbol = {v.symbol for v in violations}
    # statics outside the hashable registry + the two never-adopted
    # hot bindings; the declared+adopted one stays quiet
    assert by_symbol == {"fx_weird_statics", "fx_cold",
                        "fx_undeclared"}, (
        [v.format() for v in violations]
    )
    weird = [v for v in violations if v.symbol == "fx_weird_statics"]
    assert any("session" in v.message for v in weird)
    cold = [v for v in violations if v.symbol == "fx_cold"]
    assert any("cold-on-every-recovery" in v.message for v in cold)


def test_opaque_adoption_never_resolves_to_factory_binding():
    """A return-factory binding has no assignment target; an OPAQUE
    adopt expression in the same module must be flagged as
    unresolvable, never silently resolved to the factory (which would
    also fake the factory adopted, hiding its cold-on-every-recovery
    finding)."""
    from koordinator_tpu.analysis.graftcheck.shapeflow import (
        find_adoptions,
        find_observed_bindings,
    )

    path = "tests/fixtures/graftcheck/opaque_inline.py"
    src = (
        "import jax\n"
        "from koordinator_tpu.obs.device import DEVICE_OBS\n"
        "from koordinator_tpu.service.warmpool import WARM_POOL\n"
        "\n"
        "\n"
        "def fx_solve(state, pods, params, config):\n"
        "    return pods\n"
        "\n"
        "\n"
        "def fx_make():\n"
        "    return DEVICE_OBS.jit(\"fx_factory\", jax.jit(\n"
        "        fx_solve, static_argnames=(\"config\",),\n"
        "        donate_argnums=()\n"
        "    ))\n"
        "\n"
        "\n"
        "WARM_POOL.adopt(fx_make(), fx_solve, config_argpos=3)\n"
    )
    program = Program([_reparse(path, src)])
    bindings = find_observed_bindings(program)
    assert [b.name for b in bindings] == ["fx_factory"]
    adoptions = find_adoptions(program, bindings=bindings)
    assert [a.binding for a in adoptions] == [""], adoptions

    spec = (BindingSpec(name="fx_factory", path=path, axes=(AxisSpec(
        axis="pods",
        bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
        kwargs_options=((("floor", 8),),), bound=64,
        bound_source="fixture"),)),)
    rule = WarmCoverageRule(specs=spec, hot_scope=(path,))
    violations = rule.check_program(program)
    assert any(
        "does not resolve" in v.message for v in violations
    ), [v.format() for v in violations]
    assert any(
        v.symbol == "fx_factory" and "cold-on-every-recovery" in v.message
        for v in violations
    ), [v.format() for v in violations]


def test_metrics_hygiene_fixture_detected():
    module = _fixture("metrics_bad.py")
    spec = MetricsSpec(
        components_path=MET_PATH,
        registries=("SERVED", "ORPHAN"),
        label_domains={
            "lane": LabelDomain(kind="enum", values=("a", "b")),
            "user": LabelDomain(kind="folded",
                                fold_symbol="OVERFLOW_USER"),
        },
    )
    rule = MetricsHygieneRule(spec=spec)
    violations = rule.check_program(Program([module]))
    by_symbol = {v.symbol for v in violations}
    assert by_symbol == {"fx_unbounded_total", "ORPHAN"}, (
        [v.format() for v in violations]
    )
    # and the fold check has teeth: pointing the domain at a deleted
    # symbol flags the folded metric too
    spec2 = MetricsSpec(
        components_path=MET_PATH, registries=("SERVED",),
        label_domains={
            "lane": LabelDomain(kind="enum", values=("a", "b")),
            "user": LabelDomain(kind="folded", fold_symbol="GONE"),
            "pod_name": LabelDomain(kind="enum", values=("x",)),
        },
    )
    flagged = MetricsHygieneRule(spec=spec2).check_program(
        Program([module])
    )
    assert any(
        v.symbol == "fx_folded_total" and "GONE" in v.message
        for v in flagged
    )


# -- 2. the real repo: clean AND the enumeration is non-vacuous --------------

def test_repo_wide_clean_with_v3_rules(repo_program):
    violations, _, stats = run_checks_timed(
        repo_program.modules, default_rules(),
        load_allowlist(REPO / "graftcheck.toml"),
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    assert set(stats) >= {
        "bucket-flow", "signature-space", "warm-coverage",
        "metrics-hygiene",
    }
    assert all(s["violations"] == 0 for s in stats.values())


def test_repo_enumeration_nonvacuous(repo_program):
    rule = SignatureSpaceRule(specs=BINDING_SPECS)
    assert rule.check_program(repo_program) == []
    space = rule.last_space
    # the live hot path really is inside the enumeration: the default
    # pod bucket floor and the first few buckets of every family
    solve = space["solve_batch"]
    pods = next(a for a in solve["axes"] if a["axis"] == "pods")
    assert {64, 80, 96, 256} <= set(pods["values"])
    assert solve["adopted"] is True
    scatter = space["scatter_node_rows_copied"]
    dirty = scatter["axes"][0]
    assert {8, 16, 32} <= set(dirty["values"])
    # every adopted binding enumerates finite and nonzero
    for name, entry in space.items():
        if entry["adopted"]:
            assert entry["signature_space_bound"] > 0, name
            assert entry["axes"], name


def test_axis_images_come_from_live_functions():
    """The enumeration evaluates the REAL bucket functions — the image
    of pow2_quarter_bucket must match a direct evaluation, not a
    hand-copied table."""
    from koordinator_tpu.parallel.mesh import pow2_quarter_bucket

    spec = AxisSpec(
        axis="pods",
        bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
        kwargs_options=((("floor", 64),),), bound=1000,
        bound_source="test",
    )
    image = enumerate_axis(spec)
    assert set(image) == {
        pow2_quarter_bucket(n, floor=64) for n in range(1001)
    }


# -- 3. injected violations in REAL source fail loudly -----------------------

def _reparse(path: str, source: str) -> ModuleFile:
    return ModuleFile(path=path, tree=ast.parse(source, filename=path),
                      source=source)


def _run_with_replacement(path: str, source: str):
    mods = {m.path: m for m in iter_repo_modules(REPO)}
    mods[path] = _reparse(path, source)
    return run_checks(
        list(mods.values()), default_rules(),
        load_allowlist(REPO / "graftcheck.toml"),
    )


_BUCKET_ANCHOR = "        target = self.pod_bucket(n_real)"


def test_injected_stripped_bucket_call_fails():
    """The pre-PR 8 storm shape itself: _pad_pods padding to the RAW
    pod count instead of its bucket — one compiled program per queue
    length, now machine-rejected."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    assert _BUCKET_ANCHOR in source, (
        "bucket anchor drifted — update the teeth"
    )
    injected = source.replace(_BUCKET_ANCHOR, "        target = n_real")
    violations, _ = _run_with_replacement(path, injected)
    hits = [v for v in violations if v.rule == "bucket-flow"]
    assert any(
        v.func.startswith("PlacementModel._pad_pods")
        and "raw-dynamic" in v.message for v in hits
    ), [v.format() for v in violations]


_ADOPT_ANCHOR = (
    "        WARM_POOL.adopt(self._solve, solve_batch, config_argpos=3)"
)


def test_injected_unadopted_solve_batch_fails():
    """Un-adopt the flagship binding: every recovery path would
    re-trace + recompile it — warm-coverage must fail loudly."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    assert _ADOPT_ANCHOR in source, (
        "adopt anchor drifted — update the teeth"
    )
    injected = source.replace(_ADOPT_ANCHOR, "        pass")
    violations, _ = _run_with_replacement(path, injected)
    hits = [v for v in violations if v.rule == "warm-coverage"]
    assert any(
        v.symbol == "solve_batch"
        and "cold-on-every-recovery" in v.message for v in hits
    ), [v.format() for v in violations]


def test_injected_renamed_binding_fails():
    """A binding the registry doesn't know is an unknown recompile
    surface (and its old declaration goes stale) — both directions of
    the census cross-check must fire."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    assert '"solve_batch", jax.jit(' in source
    injected = source.replace(
        '"solve_batch", jax.jit(', '"solve_batch_rogue", jax.jit(', 1
    )
    violations, _ = _run_with_replacement(path, injected)
    sig = [v for v in violations if v.rule == "signature-space"]
    assert any(
        v.symbol == "solve_batch_rogue" and "no BindingSpec" in v.message
        for v in sig
    ), [v.format() for v in sig]
    assert any(
        v.symbol == "solve_batch" and "stale" in v.message for v in sig
    ), [v.format() for v in sig]


# -- 4. the runtime sentinel -------------------------------------------------

def _sig(*shapes):
    """A synthetic observed signature: (treedef-ish, leaves)."""
    return ("tree", tuple((s, "int32") for s in shapes))


def test_sentinel_convicts_out_of_enumeration():
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    s = ShapeFlowSentinel(allowed={"b": {8, 16, 32}})
    s.check_entries([
        ("b", _sig((100, 4), (8,))),
        ("b", _sig((100, 4), (10,))),   # axis varies: 10 not in image
    ])
    report = s.report()
    kinds = [(v["kind"], v.get("value")) for v in report["violations"]]
    assert ("out-of-enumeration", 10) in kinds, report
    # the constant (100, 4) leaf is structural: never convicted
    assert not any(v.get("value") in (100, 4)
                   for v in report["violations"])


def test_sentinel_unknown_binding_and_quiet_paths():
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    s = ShapeFlowSentinel(allowed={"b": {8, 16}})
    s.check_entries([
        ("mystery", _sig((4,))),        # undeclared binding
        ("b", _sig((100, 4), (8,))),
        ("b", _sig((100, 4), (16,))),   # varies inside the image: ok
    ])
    report = s.report()
    assert [v["kind"] for v in report["violations"]] == [
        "unknown-binding"
    ], report
    assert report["dims_checked"] == 2
    assert report["dims_covered"] >= 2


def test_sentinel_axis_consistency():
    """Union membership alone must not let one axis's values launder
    another's (a config-capped raw lane range covers every small
    integer): a varying position whose values straddle two different
    axis images is flagged even though each value is enumerated."""
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    images = (frozenset({1, 2, 3}), frozenset({64, 128}))
    s = ShapeFlowSentinel(allowed={"b": {1, 2, 3, 64, 128}},
                          axis_images={"b": images})
    s.check_entries([
        ("b", _sig((2,))),
        ("b", _sig((64,))),   # varies ACROSS two different axis images
    ])
    kinds = [v["kind"] for v in s.report()["violations"]]
    assert kinds == ["axis-inconsistent"], s.report()

    ok = ShapeFlowSentinel(allowed={"b": {1, 2, 3, 64, 128}},
                           axis_images={"b": images})
    ok.check_entries([("b", _sig((64,))), ("b", _sig((128,)))])
    assert ok.report()["violations"] == [], ok.report()


def test_sentinel_static_build_is_memoized():
    """Arming twice must reuse one program analysis (the build costs
    seconds and both the chaos and streaming suites arm)."""
    from koordinator_tpu.testing import shapeflow as sf

    a = sf.ShapeFlowSentinel.from_static_analysis()
    assert sf._STATIC_CACHE
    b = sf.ShapeFlowSentinel.from_static_analysis()
    assert a.allowed == b.allowed
    assert a.axis_images == b.axis_images
    # instances never share mutable state through the cache
    a.allowed["solve_batch"].add(-1)
    assert -1 not in b.allowed["solve_batch"]


def test_sentinel_refuses_broken_registry(monkeypatch):
    """from_static_analysis must not arm from a registry the static
    pass rejects — a sentinel with a silently-empty enumeration would
    pass every suite vacuously."""
    import koordinator_tpu.analysis.graftcheck.rules as rules_mod
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    ghost = rules_mod.BINDING_SPECS + (BindingSpec(
        name="fx_never_exists", path="nowhere.py", axes=()),)
    monkeypatch.setattr(rules_mod, "BINDING_SPECS", ghost)
    with pytest.raises(AssertionError, match="refuses to arm"):
        ShapeFlowSentinel.from_static_analysis()


def test_sentinel_end_to_end_nonvacuous():
    """The acceptance property, driven directly: a live model solving
    two bucketed batch sizes stays inside the enumeration (with the
    membership check EXERCISED on a varying axis), and the same model
    solving raw unbucketed axes is convicted."""
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.testing import example_problem
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    sentinel = ShapeFlowSentinel.from_static_analysis()
    assert sentinel.report()["enumerated_values"] > 0

    model = PlacementModel(SolverConfig())
    state1, pods1, _ = example_problem(20, 10, seed=0)
    state2, pods2, _ = example_problem(20, 200, seed=1)

    sentinel.begin_window()
    b1, _, _ = model._pad_pods(pods1, None, None, 10)    # bucket 64
    model.solve(state1, b1)
    b2, _, _ = model._pad_pods(pods2, None, None, 200)   # bucket 256
    model.solve(state2, b2)
    sentinel.verify_window()
    report = sentinel.report()
    assert report["violations"] == [], report
    assert report["observed_compiles"] >= 2
    # non-vacuity: the varying pod axis was CHECKED and covered
    assert report["dims_checked"] > 0
    assert report["dims_covered"] > 0

    # the negative arm: raw, unbucketed axes through the same binding
    rogue = ShapeFlowSentinel.from_static_analysis()
    rogue.begin_window()
    model.solve(state1, pods1)    # raw 10
    model.solve(state2, pods2)    # raw 200
    rogue.verify_window()
    bad = rogue.report()["violations"]
    assert any(
        v["kind"] == "out-of-enumeration" and v["fn"] == "solve_batch"
        for v in bad
    ), bad


# -- 5. CLI: sidecar + incremental full-graph --------------------------------

def test_cli_json_sidecar_and_changed_files(capsys):
    from koordinator_tpu.analysis.graftcheck.__main__ import main

    rc = main([
        "--changed-files=koordinator_tpu/ops/binpack.py",
        "--format=json",
    ])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violation_count"] == 0
    # the new whole-program passes ran full-graph despite the narrowed
    # local set (same contract as sync-reach)
    for name in ("bucket-flow", "signature-space", "warm-coverage",
                 "metrics-hygiene"):
        assert name in payload["rules"], name
        assert payload["rules"][name]["violations"] == 0
    space = payload["signature_space"]
    assert space["solve_batch"]["adopted"] is True
    assert space["solve_batch"]["signature_space_bound"] > 0
    assert all(a["values"] for a in space["solve_batch"]["axes"])
