"""Webhook admission tests (VERDICT round-1 item 6).

Reference: pkg/webhook/pod/mutating/cluster_colocation_profile.go,
pod/validating/cluster_colocation_profile.go,
elasticquota/quota_topology.go.
"""

import pytest

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName as R,
)
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec, QuotaSpec
from koordinator_tpu.webhook import (
    ClusterColocationProfile,
    PodMutatingWebhook,
    PodValidatingWebhook,
    QuotaTopologyError,
    QuotaTopologyGuard,
)


class TestMutating:
    def _webhook(self):
        wh = PodMutatingWebhook()
        wh.update_profile(
            ClusterColocationProfile(
                name="colocation-batch",
                namespace_selector={"colocation": "enabled"},
                labels={"injected": "yes"},
                qos_class=QoSClass.BE,
                priority=5500,  # batch band
                koordinator_priority=333,
            )
        )
        wh.set_namespace_labels("batch-ns", {"colocation": "enabled"})
        wh.set_namespace_labels("prod-ns", {})
        return wh

    def test_unlabeled_pod_gains_qos_priority_and_batch_resources(self):
        wh = self._webhook()
        pod = PodSpec(
            name="job", namespace="batch-ns",
            requests={R.CPU: 4000, R.MEMORY: 2048},
            limits={R.CPU: 8000},
        )
        wh.mutate(pod)
        assert pod.qos == QoSClass.BE
        assert pod.priority == 5500
        assert pod.priority_class == PriorityClass.BATCH
        assert pod.sub_priority == 333
        assert pod.labels["injected"] == "yes"
        # native resources translated to batch extended resources
        assert pod.requests == {R.BATCH_CPU: 4000, R.BATCH_MEMORY: 2048}
        assert pod.limits == {R.BATCH_CPU: 8000}

    def test_non_matching_namespace_untouched(self):
        wh = self._webhook()
        pod = PodSpec(name="svc", namespace="prod-ns",
                      requests={R.CPU: 1000})
        wh.mutate(pod)
        assert pod.qos == QoSClass.NONE
        assert pod.requests == {R.CPU: 1000}

    def test_object_selector_and_key_mapping(self):
        wh = PodMutatingWebhook()
        wh.update_profile(
            ClusterColocationProfile(
                name="map",
                selector={"app": "ml"},
                label_keys_mapping={"quota-name": "team"},
            )
        )
        pod = PodSpec(name="a", labels={"app": "ml", "team": "vision"})
        wh.mutate(pod)
        assert pod.labels["quota-name"] == "vision"
        other = PodSpec(name="b", labels={"team": "vision"})
        wh.mutate(other)
        assert "quota-name" not in other.labels

    def test_mid_translation_and_limit_only_request(self):
        # translation only runs for profile-managed pods (reference
        # :66-69) — use a match-all profile
        wh = PodMutatingWebhook([ClusterColocationProfile(name="all")])
        pod = PodSpec(name="m", priority=7500,  # mid band
                      limits={R.CPU: 2000})
        wh.mutate(pod)
        # limit-only extended resource gains a matching request
        # (restrictResourceRequestAndLimit)
        assert pod.limits == {R.MID_CPU: 2000}
        assert pod.requests == {R.MID_CPU: 2000}

    def test_prod_pod_resources_untouched(self):
        wh = PodMutatingWebhook([ClusterColocationProfile(name="all")])
        pod = PodSpec(name="p", priority=9500, requests={R.CPU: 1000})
        wh.mutate(pod)
        assert pod.requests == {R.CPU: 1000}

    def test_unmanaged_batch_pod_not_translated(self):
        """No profile matched: the reference skips mutatePodResourceSpec
        entirely — a directly-created batch-band pod keeps native cpu."""
        wh = PodMutatingWebhook()
        pod = PodSpec(name="raw", priority=5500, requests={R.CPU: 4000})
        wh.mutate(pod)
        assert pod.requests == {R.CPU: 4000}

    def test_end_to_end_mutated_pod_schedules_on_batch_resources(self):
        """The ingress story: an unlabeled pod passes the webhook, gains
        BE/batch identity, and the scheduler places it against the node's
        batch allocatable."""
        from koordinator_tpu.scheduler import Scheduler

        wh = self._webhook()
        s = Scheduler()
        s.add_node(
            NodeSpec(name="n0", allocatable={
                R.CPU: 16000, R.MEMORY: 32768,
                R.BATCH_CPU: 6000, R.BATCH_MEMORY: 8192,
            })
        )
        s.update_node_metric(
            NodeMetric(node_name="n0", node_usage={}, update_time=99.0)
        )
        pod = PodSpec(name="job", namespace="batch-ns",
                      requests={R.CPU: 4000, R.MEMORY: 2048})
        s.add_pod(wh.mutate(pod))
        out = s.schedule_pending(now=100.0)
        assert out["batch-ns/job"] == "n0"
        # a second batch pod exceeding batch-cpu is rejected even though
        # native cpu would fit
        pod2 = PodSpec(name="job2", namespace="batch-ns",
                       requests={R.CPU: 4000})
        s.add_pod(wh.mutate(pod2))
        out2 = s.schedule_pending(now=101.0)
        assert out2["batch-ns/job2"] is None


class TestMultiQuotaTreeAffinity:
    """multi_quota_tree_affinity.go:37-113: a pod whose ElasticQuota
    belongs to a quota tree with a node-selector profile gets that
    selector injected as REQUIRED node affinity at admission."""

    def _webhook(self):
        from koordinator_tpu.quota.profile import QuotaProfile

        wh = PodMutatingWebhook()
        wh.update_quota(QuotaSpec(
            name="team-a", tree_id="tree-1",
            min={R.CPU: 8000}, max={R.CPU: 16000},
        ))
        wh.update_quota(QuotaSpec(
            name="team-free", min={R.CPU: 8000}, max={R.CPU: 16000},
        ))
        wh.update_quota_profile(QuotaProfile(
            name="pool-a", quota_name="root-a", tree_id="tree-1",
            node_selector={"pool": "a"},
        ))
        return wh

    def test_tree_quota_pod_gains_selector(self):
        wh = self._webhook()
        pod = wh.mutate(PodSpec(name="p", quota="team-a"))
        assert pod.node_selector == {"pool": "a"}

    def test_treeless_quota_untouched(self):
        wh = self._webhook()
        pod = wh.mutate(PodSpec(name="p", quota="team-free"))
        assert pod.node_selector is None

    def test_unknown_quota_untouched(self):
        wh = self._webhook()
        pod = wh.mutate(PodSpec(name="p", quota="nope"))
        assert pod.node_selector is None

    def test_existing_selector_merges_and_conflicts_unsatisfiable(self):
        from koordinator_tpu.webhook.mutating import UNSATISFIABLE

        wh = self._webhook()
        pod = wh.mutate(PodSpec(
            name="p", quota="team-a", node_selector={"zone": "z1"},
        ))
        assert pod.node_selector == {"zone": "z1", "pool": "a"}
        # a conflicting required value can match no node (the reference
        # merges In requirements into every term: AND of disjoint Ins)
        pod2 = wh.mutate(PodSpec(
            name="p2", quota="team-a", node_selector={"pool": "b"},
        ))
        assert pod2.node_selector["pool"] == UNSATISFIABLE

    def test_tree_pod_lands_only_on_tree_nodes(self):
        """The done-criterion differential: the tree pod takes the tree
        node even though the off-tree node is emptier and scores
        higher; without the webhook it would land off-tree."""
        from koordinator_tpu.scheduler import Scheduler

        def cluster():
            s = Scheduler()
            # off-tree node: empty, scores higher
            s.add_node(NodeSpec(name="big-free",
                                allocatable={R.CPU: 64000, R.MEMORY: 65536}))
            # tree node: smaller and busier
            s.add_node(NodeSpec(name="tree-node", labels={"pool": "a"},
                                allocatable={R.CPU: 16000, R.MEMORY: 16384}))
            for n in ("big-free", "tree-node"):
                s.update_node_metric(NodeMetric(
                    node_name=n, node_usage={}, update_time=99.0))
            s.update_quota(QuotaSpec(
                name="team-a", tree_id="tree-1",
                min={R.CPU: 8000, R.MEMORY: 8192},
                max={R.CPU: 16000, R.MEMORY: 16384},
            ))
            return s

        def pod():
            return PodSpec(name="p", quota="team-a",
                           requests={R.CPU: 1000, R.MEMORY: 1024})

        s = cluster()
        s.add_pod(pod())  # no webhook: scores win
        assert s.schedule_pending(now=100.0)["default/p"] == "big-free"

        s = cluster()
        s.add_pod(self._webhook().mutate(pod()))  # admission: tree wins
        assert s.schedule_pending(now=100.0)["default/p"] == "tree-node"

    def test_wired_through_bus(self):
        """The registries fill from ElasticQuota/ElasticQuotaProfile
        watches (wire_pod_webhook), including deletes."""
        from koordinator_tpu.client.bus import APIServer, Kind
        from koordinator_tpu.client.wiring import wire_pod_webhook
        from koordinator_tpu.quota.profile import QuotaProfile

        bus = APIServer()
        wh = PodMutatingWebhook()
        wire_pod_webhook(bus, wh)
        bus.apply(Kind.QUOTA, "team-a", QuotaSpec(
            name="team-a", tree_id="tree-1",
            min={R.CPU: 1000}, max={R.CPU: 2000},
        ))
        bus.apply(Kind.QUOTA_PROFILE, "pool-a", QuotaProfile(
            name="pool-a", quota_name="root-a", tree_id="tree-1",
            node_selector={"pool": "a"},
        ))
        pod = wh.mutate(PodSpec(name="p", quota="team-a"))
        assert pod.node_selector == {"pool": "a"}
        bus.delete(Kind.QUOTA_PROFILE, "pool-a")
        pod2 = wh.mutate(PodSpec(name="p2", quota="team-a"))
        assert pod2.node_selector is None


class TestValidating:
    def test_batch_resources_require_be(self):
        v = PodValidatingWebhook()
        pod = PodSpec(name="x", qos=QoSClass.LS,
                      requests={R.BATCH_CPU: 1000})
        assert any("QoS BE" in e for e in v.validate(pod))
        ok = PodSpec(name="y", qos=QoSClass.BE, priority=5500,
                     requests={R.BATCH_CPU: 1000})
        assert v.validate(ok) == []

    def test_forbidden_combinations(self):
        v = PodValidatingWebhook()
        # BE + prod priority: forbidden
        pod = PodSpec(name="x", qos=QoSClass.BE, priority=9500)
        assert any("combination" in e for e in v.validate(pod))
        # LSR + batch priority: forbidden
        pod = PodSpec(name="y", qos=QoSClass.LSR, priority=5500,
                      requests={R.CPU: 2000})
        assert any("combination" in e for e in v.validate(pod))
        # LSR + prod: fine
        pod = PodSpec(name="z", qos=QoSClass.LSR, priority=9500,
                      requests={R.CPU: 2000})
        assert v.validate(pod) == []

    def test_lsr_integer_cpu(self):
        v = PodValidatingWebhook()
        pod = PodSpec(name="x", qos=QoSClass.LSR, priority=9500,
                      requests={R.CPU: 1500})
        assert any("integer" in e for e in v.validate(pod))
        pod = PodSpec(name="y", qos=QoSClass.LSE, priority=9500)
        assert any("must declare" in e for e in v.validate(pod))

    def test_immutable_on_update(self):
        v = PodValidatingWebhook()
        old = PodSpec(name="x", qos=QoSClass.LS, priority=9500)
        new = PodSpec(name="x", qos=QoSClass.BE, priority=5500)
        errs = v.validate(new, old_pod=old)
        assert any("qosClass" in e for e in errs)
        assert any("spec.priority" in e for e in errs)


class TestQuotaTopologyGuard:
    def _guard(self):
        g = QuotaTopologyGuard()
        g.validate_add(
            QuotaSpec(name="parent", is_parent=True,
                      min={R.CPU: 10000}, max={R.CPU: 20000})
        )
        return g

    def test_negative_and_min_over_max_rejected(self):
        g = QuotaTopologyGuard()
        with pytest.raises(QuotaTopologyError, match="< 0"):
            g.validate_add(QuotaSpec(name="neg", min={R.CPU: -1},
                                     max={R.CPU: 100}))
        with pytest.raises(QuotaTopologyError, match="min > max"):
            g.validate_add(QuotaSpec(name="inv", min={R.CPU: 200},
                                     max={R.CPU: 100}))

    def test_parent_checks(self):
        g = self._guard()
        with pytest.raises(QuotaTopologyError, match="not found"):
            g.validate_add(QuotaSpec(name="orphan", parent="ghost",
                                     min={R.CPU: 1}, max={R.CPU: 1},
                                     is_parent=True))
        g.validate_add(QuotaSpec(name="leaf", parent="parent",
                                 min={R.CPU: 1000}, max={R.CPU: 20000}))
        with pytest.raises(QuotaTopologyError, match="not a parent"):
            g.validate_add(QuotaSpec(name="under-leaf", parent="leaf",
                                     min={R.CPU: 1}, max={R.CPU: 20000},
                                     is_parent=True))

    def test_sibling_min_sum_capped_by_parent(self):
        g = self._guard()
        g.validate_add(QuotaSpec(name="a", parent="parent",
                                 min={R.CPU: 6000}, max={R.CPU: 20000}))
        with pytest.raises(QuotaTopologyError, match="brothers"):
            g.validate_add(QuotaSpec(name="b", parent="parent",
                                     min={R.CPU: 6000}, max={R.CPU: 20000}))
        g.validate_add(QuotaSpec(name="b", parent="parent",
                                 min={R.CPU: 4000}, max={R.CPU: 20000}))

    def test_max_keys_must_match_parent(self):
        g = self._guard()
        with pytest.raises(QuotaTopologyError, match="max keys"):
            g.validate_add(
                QuotaSpec(name="c", parent="parent",
                          min={R.CPU: 100},
                          max={R.CPU: 20000, R.MEMORY: 1024})
            )

    def test_delete_with_children_forbidden(self):
        g = self._guard()
        g.validate_add(QuotaSpec(name="kid", parent="parent",
                                 min={R.CPU: 100}, max={R.CPU: 20000}))
        with pytest.raises(QuotaTopologyError, match="children"):
            g.validate_delete("parent")
        g.validate_delete("kid")
        g.validate_delete("parent")

    def test_tree_id_immutable_on_update(self):
        g = self._guard()
        with pytest.raises(QuotaTopologyError, match="immutable"):
            g.validate_update(
                QuotaSpec(name="parent", is_parent=True, tree_id="other",
                          min={R.CPU: 10000}, max={R.CPU: 20000})
            )


class TestNodeWebhook:
    """Reference: pkg/webhook/node/plugins/resourceamplification —
    kubelet allocatable updates re-amplify; ratio protocol validated."""

    def _ratio_node(self, cpu=32000, ratio=1.5):
        return _ratio_node(cpu=cpu, ratio=ratio)  # shared module helper

    def test_create_passes_through(self):
        from koordinator_tpu.webhook import NodeMutatingWebhook

        node = self._ratio_node()
        NodeMutatingWebhook().mutate(node, old_node=None)
        assert node.allocatable[R.CPU] == 32000  # untouched on CREATE

    def test_kubelet_update_reamplifies(self):
        from koordinator_tpu.webhook import NodeMutatingWebhook

        old = self._ratio_node(cpu=32000)
        old.raw_allocatable = {R.CPU: 32000, R.MEMORY: 65536}
        old.allocatable = {R.CPU: 48000, R.MEMORY: 65536}
        new = self._ratio_node(cpu=40000)  # kubelet re-reported raw
        NodeMutatingWebhook().mutate(new, old_node=old)
        assert new.allocatable[R.CPU] == 60000        # 40000 * 1.5
        assert new.raw_allocatable[R.CPU] == 40000

    def test_unchanged_update_amplifies_from_stored_raw(self):
        """Reference semantics: with raw recorded and no kubelet change,
        every UPDATE re-amplifies from the STORED raw — idempotent, never
        compounding."""
        from koordinator_tpu.webhook import NodeMutatingWebhook

        old = self._ratio_node(cpu=48000)  # visible (amplified)
        old.raw_allocatable = {R.CPU: 32000, R.MEMORY: 65536}
        new = self._ratio_node(cpu=48000)
        NodeMutatingWebhook().mutate(new, old_node=old)
        assert new.allocatable[R.CPU] == 48000        # 32000 * 1.5
        assert new.raw_allocatable[R.CPU] == 32000    # raw preserved

    def test_validate_rejects_shrinking_ratio(self):
        from koordinator_tpu.webhook import NodeValidatingWebhook

        node = self._ratio_node(ratio=0.8)
        violations = NodeValidatingWebhook().validate(node)
        assert violations and "[1.0, 100.0]" in violations[0]

    def test_validate_rejects_malformed_annotation(self):
        from koordinator_tpu.apis.extension import (
            ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
        )
        from koordinator_tpu.apis.types import NodeSpec
        from koordinator_tpu.webhook import NodeValidatingWebhook

        node = NodeSpec(name="n0", annotations={
            ANNOTATION_RESOURCE_AMPLIFICATION_RATIO: "not json"})
        assert NodeValidatingWebhook().validate(node)


class TestSLOConfigWebhook:
    """Reference: pkg/webhook/cm/plugins/sloconfig checkers."""

    def test_valid_defaults_admitted(self):
        from koordinator_tpu.manager.sloconfig import (
            ColocationStrategy,
            CPUBurstStrategy,
            ResourceQOSStrategy,
            ResourceThresholdStrategy,
        )
        from koordinator_tpu.webhook import SLOConfigValidatingWebhook

        w = SLOConfigValidatingWebhook()
        assert w.validate_colocation(ColocationStrategy()) == []
        assert w.validate_cpu_burst(CPUBurstStrategy()) == []
        assert w.validate_threshold(ResourceThresholdStrategy()) == []
        assert w.validate_resource_qos(ResourceQOSStrategy()) == []

    def test_colocation_bounds(self):
        from koordinator_tpu.manager.sloconfig import ColocationStrategy
        from koordinator_tpu.webhook import SLOConfigValidatingWebhook

        bad = ColocationStrategy(cpu_reclaim_threshold_percent=150,
                                 degrade_time_minutes=0,
                                 cpu_calculate_policy="banana")
        v = SLOConfigValidatingWebhook().validate_colocation(bad)
        assert len(v) == 3

    def test_cpu_burst_bounds(self):
        from koordinator_tpu.manager.sloconfig import CPUBurstStrategy
        from koordinator_tpu.webhook import SLOConfigValidatingWebhook

        bad = CPUBurstStrategy(policy="never", cfs_quota_burst_percent=50)
        v = SLOConfigValidatingWebhook().validate_cpu_burst(bad)
        assert len(v) == 2

    def test_resource_qos_bvt_and_resctrl(self):
        from koordinator_tpu.manager.sloconfig import ResourceQOSStrategy
        from koordinator_tpu.webhook import SLOConfigValidatingWebhook

        bad = ResourceQOSStrategy()
        bad.be.cpu.group_identity = 7
        bad.ls.resctrl.cat_range_start_percent = 80
        bad.ls.resctrl.cat_range_end_percent = 20
        v = SLOConfigValidatingWebhook().validate_resource_qos(bad)
        assert len(v) == 2

    def test_manager_gates_wire_node_and_cm_webhooks(self):
        from koordinator_tpu.cmd.manager import ManagerConfig, build_manager

        off = build_manager(ManagerConfig())
        assert off.node_mutating_webhook is None  # gates default False
        on = build_manager(ManagerConfig(
            feature_gates="NodeMutatingWebhook=true,"
                          "NodeValidatingWebhook=true,"
                          "ConfigMapValidatingWebhook=true"))
        assert on.node_mutating_webhook is not None
        assert on.node_validating_webhook is not None
        assert on.slo_config_webhook is not None
        from koordinator_tpu.apis.types import NodeSpec

        node, violations = on.admit_node(
            NodeSpec(name="n0", allocatable={R.CPU: 1000}))
        assert violations == [] and node.allocatable[R.CPU] == 1000

def _ratio_node(cpu=32000, ratio=1.5):
    import json

    from koordinator_tpu.apis.extension import (
        ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    )
    from koordinator_tpu.apis.types import NodeSpec

    return NodeSpec(
        name="n0",
        allocatable={R.CPU: cpu, R.MEMORY: 65536},
        annotations={ANNOTATION_RESOURCE_AMPLIFICATION_RATIO: json.dumps(
            {str(int(R.CPU)): ratio})},
    )


def test_echoed_amplified_update_is_noop():
    """An UPDATE echoing the amplified allocatable back must not
    compound the ratio (code-review regression)."""
    from koordinator_tpu.webhook import NodeMutatingWebhook

    old = _ratio_node(cpu=60000)   # already amplified (raw 40000)
    old.raw_allocatable = {R.CPU: 40000, R.MEMORY: 65536}
    echoed = _ratio_node(cpu=60000)
    NodeMutatingWebhook().mutate(echoed, old_node=old)
    assert echoed.allocatable[R.CPU] == 60000   # NOT 90000


def test_non_dict_ratio_json_is_violation_not_crash():
    from koordinator_tpu.apis.extension import (
        ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    )
    from koordinator_tpu.apis.types import NodeSpec
    from koordinator_tpu.webhook import (
        NodeMutatingWebhook,
        NodeValidatingWebhook,
    )

    for payload in ('[1.5]', '"1.5"', '1.5'):
        node = NodeSpec(name="n0", allocatable={R.CPU: 1000},
                        annotations={
            ANNOTATION_RESOURCE_AMPLIFICATION_RATIO: payload})
        assert NodeValidatingWebhook().validate(node)  # violation
        NodeMutatingWebhook().mutate(
            node, old_node=NodeSpec(name="n0"))        # no crash


def test_ratio_annotation_added_later_takes_effect():
    """Adding the ratio annotation to an existing node must amplify on
    that very UPDATE even though allocatable didn't change
    (code-review regression; reference records raw when absent)."""
    from koordinator_tpu.apis.types import NodeSpec
    from koordinator_tpu.webhook import NodeMutatingWebhook

    old = NodeSpec(name="n0", allocatable={R.CPU: 32000, R.MEMORY: 65536})
    new = _ratio_node(cpu=32000)    # same allocatable + new annotation
    NodeMutatingWebhook().mutate(new, old_node=old)
    assert new.allocatable[R.CPU] == 48000
    assert new.raw_allocatable[R.CPU] == 32000


def test_ratio_bump_reamplifies_from_stored_raw():
    from koordinator_tpu.webhook import NodeMutatingWebhook

    old = _ratio_node(cpu=48000)      # amplified at 1.5 from raw 32000
    old.raw_allocatable = {R.CPU: 32000, R.MEMORY: 65536}
    new = _ratio_node(cpu=48000, ratio=2.0)
    NodeMutatingWebhook().mutate(new, old_node=old)
    assert new.allocatable[R.CPU] == 64000      # 32000 * 2.0, no compound


def test_ratio_removal_cleans_raw_record():
    import json

    from koordinator_tpu.apis.extension import (
        ANNOTATION_NODE_RAW_ALLOCATABLE,
    )
    from koordinator_tpu.apis.types import NodeSpec
    from koordinator_tpu.webhook import NodeMutatingWebhook

    old = _ratio_node(cpu=48000)
    old.raw_allocatable = {R.CPU: 32000, R.MEMORY: 65536}
    new = NodeSpec(name="n0", allocatable={R.CPU: 48000, R.MEMORY: 65536},
                   annotations={ANNOTATION_NODE_RAW_ALLOCATABLE:
                                json.dumps({"cpu": 32000})})
    NodeMutatingWebhook().mutate(new, old_node=old)
    assert ANNOTATION_NODE_RAW_ALLOCATABLE not in new.annotations
    assert new.raw_allocatable is None


def test_infinite_and_nan_ratios_rejected():
    import json

    from koordinator_tpu.apis.extension import (
        ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    )
    from koordinator_tpu.apis.types import NodeSpec
    from koordinator_tpu.webhook import NodeValidatingWebhook

    for payload in ('{"0": Infinity}', '{"0": NaN}', '{"0": 1000.0}'):
        node = NodeSpec(name="n0", annotations={
            ANNOTATION_RESOURCE_AMPLIFICATION_RATIO: payload})
        assert NodeValidatingWebhook().validate(node)


def test_cm_checker_matches_runtime_is_valid():
    """The admission checker must reject everything the slo controllers'
    is_valid rejects (code-review regression: they had diverged)."""
    import dataclasses as dc

    from koordinator_tpu.manager.sloconfig import ColocationStrategy
    from koordinator_tpu.webhook.cm import check_colocation

    for bad in (
        ColocationStrategy(metric_report_interval_seconds=0),
        ColocationStrategy(resource_diff_threshold=0),
        ColocationStrategy(metric_aggregate_duration_seconds=0),
        ColocationStrategy(cpu_reclaim_threshold_percent=0),
        ColocationStrategy(memory_reclaim_threshold_percent=200),
        ColocationStrategy(degrade_time_minutes=0),
    ):
        assert not bad.is_valid()
        assert check_colocation(bad), dc.asdict(bad)


def test_raw_survives_serialization_via_annotation():
    """Compounding protection must work when old_node arrives with only
    the raw ANNOTATION (typed field lost to serialization/restart) —
    code-review regression."""
    import json

    from koordinator_tpu.apis.extension import (
        ANNOTATION_NODE_RAW_ALLOCATABLE,
    )
    from koordinator_tpu.webhook import NodeMutatingWebhook

    old = _ratio_node(cpu=48000)       # amplified; typed raw field LOST
    old.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
        {"cpu": 32000})
    assert old.raw_allocatable is None
    echoed = _ratio_node(cpu=48000)    # label-patch echo
    NodeMutatingWebhook().mutate(echoed, old_node=old)
    assert echoed.allocatable[R.CPU] == 48000   # 32000*1.5, NOT 72000
    assert echoed.raw_allocatable[R.CPU] == 32000


def test_corrupt_raw_annotation_does_not_crash_admission():
    """A garbage raw-allocatable annotation value falls back to
    never-recorded instead of raising (code-review regression)."""
    import json

    from koordinator_tpu.apis.extension import (
        ANNOTATION_NODE_RAW_ALLOCATABLE,
    )
    from koordinator_tpu.webhook import NodeMutatingWebhook
    from koordinator_tpu.webhook.node import stored_raw_allocatable

    old = _ratio_node(cpu=48000)
    old.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
        {"cpu": "garbage"})
    assert stored_raw_allocatable(old) is None
    echoed = _ratio_node(cpu=48000)
    NodeMutatingWebhook().mutate(echoed, old_node=old)  # no crash
    assert echoed.raw_allocatable[R.CPU] == 48000       # treated as raw
