"""Regression tests for review findings: waiting-pod resolution, child
quota enforcement on the batch path, unknown-gang blocking, gang
scale-down cycle hygiene, reservation unreserve delta, quota used release."""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
    resources_to_vector,
)
from koordinator_tpu.gang.manager import GangManager
from koordinator_tpu.scheduler import Scheduler


def _mk(n_nodes=4, cpu=16000, mem=32768):
    s = Scheduler(cluster_total={R.CPU: n_nodes * cpu, R.MEMORY: n_nodes * mem})
    for i in range(n_nodes):
        s.add_node(NodeSpec(name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem}))
        s.update_node_metric(
            NodeMetric(node_name=f"n{i}", node_usage={R.CPU: 500}, update_time=99.0)
        )
    return s


def test_waiting_pods_commit_when_gang_completes_next_round():
    s = _mk()
    s.update_gang(GangSpec(name="g", min_member=4, mode=GangMode.NON_STRICT))
    for i in range(2):
        s.add_pod(PodSpec(name=f"g{i}", gang="g", requests={R.CPU: 1000}))
    out1 = s.schedule_pending(now=100.0)
    assert set(out1.waiting) == {"default/g0", "default/g1"}
    assert out1["default/g0"] is None

    # the rest of the gang arrives; everyone must now be committed
    for i in range(2, 4):
        s.add_pod(PodSpec(name=f"g{i}", gang="g", requests={R.CPU: 1000}))
    out2 = s.schedule_pending(now=101.0)
    assert out2["default/g2"] is not None and out2["default/g3"] is not None
    # previously-waiting members are re-reported as committed with their held node
    assert out2["default/g0"] is not None and out2["default/g1"] is not None
    assert not out2.waiting
    assert s._waiting == {}


def test_child_quota_enforced_on_batch_path():
    s = _mk()
    s.update_quota(
        QuotaSpec(
            name="team",
            is_parent=True,
            min={R.CPU: 0, R.MEMORY: 0},
            max={R.CPU: 64000, R.MEMORY: 131072},
        )
    )
    s.update_quota(
        QuotaSpec(
            name="team/child",
            parent="team",
            min={R.CPU: 0, R.MEMORY: 0},
            max={R.CPU: 2000, R.MEMORY: 131072},  # tight child cap
        )
    )
    s.add_pod(PodSpec(name="a", quota="team/child", requests={R.CPU: 2000}))
    s.add_pod(PodSpec(name="b", quota="team/child", requests={R.CPU: 2000}))
    out = s.schedule_pending(now=100.0)
    placed = [uid for uid, n in out.items() if n is not None]
    assert len(placed) == 1  # child max 2000 admits exactly one


def test_unknown_gang_pod_blocked_on_batch_path():
    s = _mk()
    # pod references a gang whose spec was never registered
    s.add_pod(PodSpec(name="orphan", gang="ghost", requests={R.CPU: 1000}))
    out = s.schedule_pending(now=100.0)
    assert out["default/orphan"] is None


def test_gang_scale_down_does_not_wedge_cycle():
    mgr = GangManager()
    mgr.update_gang(GangSpec(name="g", min_member=1))
    for i in range(3):
        mgr.on_pod_add(f"p{i}", "g")
    for i in range(3):
        assert mgr.pre_filter(f"p{i}") is None
    mgr.reject_gang_group("g")
    # gang scales down to one pod
    mgr.on_pod_delete("p1")
    mgr.on_pod_delete("p2")
    # p0 retries: the attempt set reflects the remaining child only (p0
    # already attempted), so the cycle reopens immediately instead of
    # wedging forever on the deleted pods' stale attempts
    assert mgr.pre_filter("p0") is None


def test_reservation_unreserve_subtracts_clamped_delta():
    s = _mk(1, cpu=10000)
    s.update_reservation(
        ReservationSpec(
            name="resv",
            requests={R.CPU: 10000},
            allocatable={R.CPU: 10000},
            allocated={R.CPU: 8000},  # prior owners hold 8 cores
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
            allocate_once=False,
        )
    )
    from koordinator_tpu.scheduler.framework import CycleState
    from koordinator_tpu.scheduler.plugins.reservation import ReservationPlugin

    plugin = ReservationPlugin()
    snap = s.cache.snapshot(now=100.0)
    pod = PodSpec(name="p", requests={R.CPU: 5000}, labels={"team": "ml"})
    state = CycleState()
    plugin.before_pre_filter(state, snap, pod)
    node = snap.nodes[0]
    plugin.reserve(state, snap, pod, node)
    resv = snap.reservations[0]
    assert resv.allocated[R.CPU] == 10000  # clamped at allocatable
    plugin.unreserve(state, snap, pod, node)
    # only the 2000 actually added may be subtracted
    assert resv.allocated[R.CPU] == 8000


def test_quota_used_released_when_pod_removed():
    s = _mk(2)
    s.update_quota(
        QuotaSpec(name="t", min={R.CPU: 0, R.MEMORY: 0},
                  max={R.CPU: 4000, R.MEMORY: 131072})
    )
    pod = PodSpec(name="a", quota="t", requests={R.CPU: 4000})
    s.add_pod(pod)
    assert s.schedule_one("default/a", now=100.0).status == "bound"
    assert s.quota_manager.quotas["t"].used[R.CPU] == 4000
    s.remove_pod(pod)
    assert s.quota_manager.quotas["t"].used[R.CPU] == 0
    assert s.quota_manager.quotas["t"].request[R.CPU] == 0
    # quota capacity is usable again
    s.add_pod(PodSpec(name="b", quota="t", requests={R.CPU: 4000}))
    assert s.schedule_one("default/b", now=101.0).status == "bound"
