"""Full-BASELINE-scale correctness: sharded == unsharded == host oracle.

VERDICT r3 #1: every headline number previously rested on reduced-shape
oracle checks; a bug manifesting only past tile boundaries or at 5k-node
padding would have shipped. These tests run the flagship shape (10k pods
x 5k nodes) end-to-end:

- the single-device scan must equal the vectorized host oracle
  (sequential reference semantics, oracle/vectorized.py), and
- the 8-device virtual-CPU-mesh solve (GSPMD cross-shard argmax and
  all) must be bit-identical to the single-device scan — cross-shard
  tie-breaks included.

Slowest tests in the suite (~30 s total on CPU); they are the ones that
make the 100k pods/s headline a proven number rather than an
extrapolation.
"""

import numpy as np
import pytest

import jax

from __graft_entry__ import _example_problem
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

FLAGSHIP_NODES = 5000
FLAGSHIP_PODS = 10000


@pytest.fixture(scope="module")
def flagship_problem():
    return _example_problem(FLAGSHIP_NODES, FLAGSHIP_PODS)


@pytest.fixture(scope="module")
def single_device_solution(flagship_problem):
    state, pods, params = flagship_problem
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    new_state, assign = solve(state, pods, params)
    return np.asarray(assign), new_state


def test_flagship_scan_matches_oracle_full_scale(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.oracle.vectorized import (
        oracle_args,
        schedule_vectorized,
    )

    state, pods, params = flagship_problem
    assign, _ = single_device_solution
    oracle = schedule_vectorized(*oracle_args(state, pods, params))
    np.testing.assert_array_equal(assign, oracle)
    assert (assign >= 0).sum() > 0


def test_flagship_sharded_matches_single_device(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )

    state, pods, params = flagship_problem
    want_assign, want_state = single_device_solution
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must force the 8-device CPU mesh"
    mesh = make_mesh(devices[:8])
    sstate = shard_node_state(state, mesh)
    solve = shard_solver(mesh)
    new_state, assign = solve(sstate, pods, params)
    np.testing.assert_array_equal(np.asarray(assign), want_assign)
    # the mutated node-side carry must agree too, not just the argmax
    np.testing.assert_array_equal(
        np.asarray(new_state.used_req), np.asarray(want_state.used_req)
    )
    np.testing.assert_array_equal(
        np.asarray(new_state.est_extra), np.asarray(want_state.est_extra)
    )
    assert len(new_state.used_req.devices()) == 8
