"""Full-BASELINE-scale correctness: sharded == unsharded == host oracle.

VERDICT r3 #1: every headline number previously rested on reduced-shape
oracle checks; a bug manifesting only past tile boundaries or at 5k-node
padding would have shipped. These tests run the flagship shape (10k pods
x 5k nodes) end-to-end:

- the single-device scan must equal the vectorized host oracle
  (sequential reference semantics, oracle/vectorized.py), and
- the 8-device virtual-CPU-mesh solve (GSPMD cross-shard argmax and
  all) must be bit-identical to the single-device scan — cross-shard
  tie-breaks included.

Slowest tests in the suite (~30 s total on CPU); they are the ones that
make the 100k pods/s headline a proven number rather than an
extrapolation.
"""

import numpy as np
import pytest

import jax

from __graft_entry__ import _example_problem
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

FLAGSHIP_NODES = 5000
FLAGSHIP_PODS = 10000


@pytest.fixture(scope="module")
def flagship_problem():
    return _example_problem(FLAGSHIP_NODES, FLAGSHIP_PODS)


@pytest.fixture(scope="module")
def single_device_solution(flagship_problem):
    state, pods, params = flagship_problem
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    new_state, assign = solve(state, pods, params)
    return np.asarray(assign), new_state


def test_flagship_scan_matches_oracle_full_scale(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.oracle.vectorized import (
        oracle_args,
        schedule_vectorized,
    )

    state, pods, params = flagship_problem
    assign, _ = single_device_solution
    oracle = schedule_vectorized(*oracle_args(state, pods, params))
    np.testing.assert_array_equal(assign, oracle)
    assert (assign >= 0).sum() > 0


def test_full_feature_sharded_matches_single_device():
    """Quota + gang + NUMA all enabled: the 8-device sharded full solve
    (shard_full_solver) must be bit-identical to the single-device path
    at a non-toy shape — cross-shard argmax tie-breaks, the quota gate,
    and the gang epilogue's segment reductions included."""
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import NumaAux, solve_batch
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.ops.quota import QuotaState
    from koordinator_tpu.parallel.mesh import make_mesh, shard_full_solver

    n_nodes, n_pods, n_quota, n_gangs = 1024, 2048, 12, 32
    state, pods, params = _example_problem(n_nodes, n_pods, seed=21)
    rng = np.random.default_rng(21)
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(numa_cap=jnp.asarray(cap),
                           numa_free=jnp.asarray(free))
    gang_id = np.full(n_pods, -1, np.int32)
    gang_id[: n_gangs * 8] = np.repeat(np.arange(n_gangs, dtype=np.int32), 8)
    pods = pods._replace(
        quota_id=jnp.asarray(rng.integers(0, n_quota, n_pods).astype(np.int32)),
        gang_id=jnp.asarray(gang_id),
        has_numa_policy=jnp.asarray(rng.uniform(size=n_pods) < 0.4),
    )
    total = cap.astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mn[:, ResourceName.CPU] = total[ResourceName.CPU] // (2 * n_quota)
    mn[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // (2 * n_quota)
    mx[:, ResourceName.CPU] = total[ResourceName.CPU] // 8
    mx[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // 8
    qid = np.asarray(pods.quota_id)
    req_np = np.asarray(pods.req).astype(np.int64)
    child_request = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    np.add.at(child_request, qid, req_np)
    quota_state = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=child_request,
    )
    gang_state = GangState.build(min_member=[8] * n_gangs)
    numa_aux = NumaAux(
        node_policy=jnp.asarray(rng.uniform(size=n_nodes) < 0.5)
    )

    single = jax.jit(
        lambda s, p, pr, q, g, n: solve_batch(
            s, p, pr, SolverConfig(), q, g, numa=n
        )
    )(state, pods, params, quota_state, gang_state, numa_aux)

    mesh = make_mesh(jax.devices()[:8])
    solve = shard_full_solver(mesh)
    sharded = solve(state, pods, params, quota_state, gang_state, numa_aux)

    np.testing.assert_array_equal(
        np.asarray(sharded.assign), np.asarray(single.assign)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.commit), np.asarray(single.commit)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.used_req),
        np.asarray(single.node_state.used_req),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.numa_free),
        np.asarray(single.node_state.numa_free),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.quota_state.used),
        np.asarray(single.quota_state.used),
    )
    assert len(sharded.node_state.used_req.devices()) == 8
    assert int(np.asarray(sharded.commit).sum()) > 0


def test_flagship_sharded_matches_single_device(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )

    state, pods, params = flagship_problem
    want_assign, want_state = single_device_solution
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must force the 8-device CPU mesh"
    mesh = make_mesh(devices[:8])
    sstate = shard_node_state(state, mesh)
    solve = shard_solver(mesh)
    new_state, assign = solve(sstate, pods, params)
    np.testing.assert_array_equal(np.asarray(assign), want_assign)
    # the mutated node-side carry must agree too, not just the argmax
    np.testing.assert_array_equal(
        np.asarray(new_state.used_req), np.asarray(want_state.used_req)
    )
    np.testing.assert_array_equal(
        np.asarray(new_state.est_extra), np.asarray(want_state.est_extra)
    )
    assert len(new_state.used_req.devices()) == 8
