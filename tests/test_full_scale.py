"""Full-BASELINE-scale correctness: sharded == unsharded == host oracle.

VERDICT r3 #1: every headline number previously rested on reduced-shape
oracle checks; a bug manifesting only past tile boundaries or at 5k-node
padding would have shipped. These tests run the flagship shape (10k pods
x 5k nodes) end-to-end:

- the single-device scan must equal the vectorized host oracle
  (sequential reference semantics, oracle/vectorized.py), and
- the 8-device virtual-CPU-mesh solve (GSPMD cross-shard argmax and
  all) must be bit-identical to the single-device scan — cross-shard
  tie-breaks included.

Slowest tests in the suite (~30 s total on CPU); they are the ones that
make the 100k pods/s headline a proven number rather than an
extrapolation.
"""

import numpy as np
import pytest

import jax

from __graft_entry__ import _example_problem
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

FLAGSHIP_NODES = 5000
FLAGSHIP_PODS = 10000


@pytest.fixture(scope="module")
def flagship_problem():
    return _example_problem(FLAGSHIP_NODES, FLAGSHIP_PODS)


@pytest.fixture(scope="module")
def single_device_solution(flagship_problem):
    state, pods, params = flagship_problem
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    new_state, assign = solve(state, pods, params)
    return np.asarray(assign), new_state


def test_flagship_scan_matches_oracle_full_scale(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.oracle.vectorized import (
        oracle_args,
        schedule_vectorized,
    )

    state, pods, params = flagship_problem
    assign, _ = single_device_solution
    oracle = schedule_vectorized(*oracle_args(state, pods, params))
    np.testing.assert_array_equal(assign, oracle)
    assert (assign >= 0).sum() > 0


def test_full_feature_sharded_matches_single_device_flagship_shape():
    """EVERY feature — quota, strict gangs, NUMA, reservations — at the
    FLAGSHIP shape (5k nodes x 10k pods): the 8-device sharded full
    solve (shard_full_solver) must be bit-identical to the single-device
    path — cross-shard argmax tie-breaks, the quota gate, reservation
    credit scatter, and the gang epilogue's segment reductions included
    (VERDICT r4 #4)."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.parallel.mesh import make_mesh, shard_full_solver
    from koordinator_tpu.testing import (
        assert_full_identity,
        full_feature_problem,
    )

    (state, pods, params, quota_state, gang_state, numa_aux,
     resv) = full_feature_problem(
        FLAGSHIP_NODES, FLAGSHIP_PODS, n_quota=50, n_gangs=100, n_resv=64,
        seed=21,
    )

    single = jax.jit(
        lambda s, p, pr, q, g, r, n: solve_batch(
            s, p, pr, SolverConfig(), q, g, resv=r, numa=n
        )
    )(state, pods, params, quota_state, gang_state, resv, numa_aux)

    mesh = make_mesh(jax.devices()[:8])
    solve = shard_full_solver(mesh)
    sharded = solve(state, pods, params, quota_state, gang_state,
                    numa_aux, resv=resv)
    assert_full_identity(sharded, single)
    assert int((np.asarray(sharded.resv_vstar) >= 0).sum()) > 0


def test_flagship_sharded_matches_single_device(
    flagship_problem, single_device_solution
):
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )

    state, pods, params = flagship_problem
    want_assign, want_state = single_device_solution
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must force the 8-device CPU mesh"
    mesh = make_mesh(devices[:8])
    sstate = shard_node_state(state, mesh)
    solve = shard_solver(mesh)
    new_state, assign = solve(sstate, pods, params)
    np.testing.assert_array_equal(np.asarray(assign), want_assign)
    # the mutated node-side carry must agree too, not just the argmax
    np.testing.assert_array_equal(
        np.asarray(new_state.used_req), np.asarray(want_state.used_req)
    )
    np.testing.assert_array_equal(
        np.asarray(new_state.est_extra), np.asarray(want_state.est_extra)
    )
    assert len(new_state.used_req.devices()) == 8
