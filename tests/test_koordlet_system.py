"""koordlet substrate tests: cgroup registry path/encoding, executor
cache + merge conditions + leveled batch, audit log.

Fake-cgroupfs pattern per the reference's testutil: a temp dir stands in
for /sys/fs/cgroup (reference: pkg/koordlet/util/system tests +
NewTestResourceExecutor).
"""

import os

import pytest

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.resourceexecutor import (
    CgroupUpdater,
    ResourceUpdateExecutor,
    merge_if_cfs_quota_larger,
    merge_if_cpuset_looser,
    merge_if_value_larger,
)
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.system import (
    SystemConfig,
    convert_cpu_shares_to_weight,
    convert_cpu_weight_to_shares,
    get_resource,
)


@pytest.fixture
def v1(tmp_path):
    cfg = SystemConfig(cgroup_root=str(tmp_path), use_cgroup_v2=False)
    ensure_cgroup_dir("kubepods/pod1", cfg)
    return cfg


@pytest.fixture
def v2(tmp_path):
    cfg = SystemConfig(cgroup_root=str(tmp_path), use_cgroup_v2=True)
    ensure_cgroup_dir("kubepods/pod1", cfg)
    return cfg


class TestRegistry:
    def test_v1_path_nests_under_subsystem(self, v1):
        r = get_resource("cpu.cfs_quota_us")
        assert r.path("kubepods/pod1", v1).endswith(
            "/cpu/kubepods/pod1/cpu.cfs_quota_us"
        )

    def test_v2_path_unified(self, v2):
        r = get_resource("cpu.cfs_quota_us")
        assert r.path("kubepods/pod1", v2).endswith("/kubepods/pod1/cpu.max")

    def test_shares_weight_conversion_roundtrip(self):
        # KEP-2254 mapping (reference: cgroup2.go:283-315)
        assert convert_cpu_shares_to_weight(2) == 1
        assert convert_cpu_shares_to_weight(262144) == 10000
        assert convert_cpu_weight_to_shares(1) == 2
        assert convert_cpu_weight_to_shares(10000) == 262144
        assert convert_cpu_weight_to_shares(39) == 998  # kubelet example

    def test_bvt_validator(self):
        r = get_resource("cpu.bvt_warp_ns")
        assert r.validate("2") and r.validate("-1")
        assert not r.validate("3") and not r.validate("x")

    def test_cpuset_validator(self):
        r = get_resource("cpuset.cpus")
        assert r.validate("0-3,8,10-11") and r.validate("")
        assert not r.validate("3-1") and not r.validate("a-b")


class TestExecutorV1:
    def test_write_and_cache(self, v1):
        ex = ResourceUpdateExecutor(v1)
        u = CgroupUpdater("cpu.cfs_quota_us", "kubepods/pod1", "100000")
        assert ex.update(True, u)
        path = u.resource().path("kubepods/pod1", v1)
        assert open(path).read() == "100000"
        # same value again: cache short-circuits
        assert not ex.update(True, u)
        # different value writes
        u2 = CgroupUpdater("cpu.cfs_quota_us", "kubepods/pod1", "50000")
        assert ex.update(True, u2)

    def test_cache_expiry_rewrites(self, v1):
        now = [0.0]
        ex = ResourceUpdateExecutor(v1, cache_ttl=10.0, clock=lambda: now[0])
        u = CgroupUpdater("cpu.cfs_quota_us", "kubepods/pod1", "100000")
        assert ex.update(True, u)
        now[0] = 5.0
        assert not ex.update(True, u)
        now[0] = 11.0  # expired: external drift gets corrected
        assert ex.update(True, u)

    def test_invalid_value_rejected_and_audited(self, v1):
        ex = ResourceUpdateExecutor(v1)
        u = CgroupUpdater("cpu.bvt_warp_ns", "kubepods/pod1", "7")
        assert not ex.update(False, u)
        assert ex.auditor.query(operation="reject")

    def test_audit_records_write(self, v1):
        ex = ResourceUpdateExecutor(v1)
        ex.update(False, CgroupUpdater("cpu.shares", "kubepods/pod1", "1024"))
        events = ex.auditor.query(operation="update")
        assert len(events) == 1 and "1024" in events[0].detail

    def test_procs_written_to_all_v1_hierarchies(self, v1):
        # cgroup.procs must move the task in EVERY hierarchy, not just cpu
        ex = ResourceUpdateExecutor(v1)
        ex.update(False, CgroupUpdater("cgroup.procs", "kubepods/pod1", "42"))
        import os
        for fs in ("cpu", "cpuset", "memory"):
            p = os.path.join(v1.cgroup_root, fs, "kubepods/pod1",
                             "cgroup.procs")
            assert open(p).read() == "42", fs

    def test_max_literal_translated_on_v1(self, v1):
        ex = ResourceUpdateExecutor(v1)
        ex.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "max"))
        assert get_resource("cpu.cfs_quota_us").read(
            "kubepods/pod1", v1) == "-1"

    def test_missing_dir_fails_gracefully(self, v1):
        ex = ResourceUpdateExecutor(v1)
        u = CgroupUpdater("cpu.shares", "kubepods/ghost", "1024")
        assert not ex.update(False, u)
        assert ex.auditor.query(operation="error")


class TestExecutorV2:
    def test_cfs_quota_packs_cpu_max(self, v2):
        ex = ResourceUpdateExecutor(v2)
        r = get_resource("cpu.cfs_quota_us")
        r.write("kubepods/pod1", "max 100000", v2)
        ex.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "50000"))
        assert r.read("kubepods/pod1", v2) == "50000 100000"
        # -1 -> "max", period preserved
        ex.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "-1"))
        assert r.read("kubepods/pod1", v2) == "max 100000"

    def test_period_preserves_quota(self, v2):
        ex = ResourceUpdateExecutor(v2)
        r = get_resource("cpu.cfs_period_us")
        r.write("kubepods/pod1", "50000 100000", v2)
        ex.update(False, CgroupUpdater(
            "cpu.cfs_period_us", "kubepods/pod1", "200000"))
        assert r.read("kubepods/pod1", v2) == "50000 200000"

    def test_shares_written_as_weight(self, v2):
        ex = ResourceUpdateExecutor(v2)
        ex.update(False, CgroupUpdater("cpu.shares", "kubepods/pod1", "2"))
        assert get_resource("cpu.shares").read("kubepods/pod1", v2) == "1"

    def test_memory_limit_negative_is_max(self, v2):
        ex = ResourceUpdateExecutor(v2)
        ex.update(False, CgroupUpdater(
            "memory.limit_in_bytes", "kubepods/pod1", "-1"))
        assert get_resource("memory.limit_in_bytes").read(
            "kubepods/pod1", v2) == "max"

    def test_max_literal_encodes_without_crash(self, v2):
        ex = ResourceUpdateExecutor(v2)
        assert ex.update(False, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "max"))
        assert get_resource("cpu.cfs_quota_us").read(
            "kubepods/pod1", v2).startswith("max")
        assert ex.update(False, CgroupUpdater(
            "memory.limit_in_bytes", "kubepods/pod1", "max"))
        # period rejects "max" (no unlimited period exists)
        assert not ex.update(False, CgroupUpdater(
            "cpu.cfs_period_us", "kubepods/pod1", "max"))

    def test_packed_file_cache_no_collision(self, v2):
        # cpu.cfs_quota_us and cpu.cfs_period_us share cpu.max: caching by
        # path alone would skip a quota write after an equal period write
        ex = ResourceUpdateExecutor(v2)
        r = get_resource("cpu.cfs_quota_us")
        r.write("kubepods/pod1", "max 100000", v2)
        assert ex.update(True, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "50000"))
        assert ex.update(True, CgroupUpdater(
            "cpu.cfs_period_us", "kubepods/pod1", "200000"))
        assert ex.update(True, CgroupUpdater(
            "cpu.cfs_quota_us", "kubepods/pod1", "200000"))
        assert r.read("kubepods/pod1", v2) == "200000 200000"


class TestMergeConditions:
    def test_value_larger(self):
        assert merge_if_value_larger("100", "200") == ("200", True)
        assert merge_if_value_larger("200", "100") == ("100", False)

    def test_cfs_quota_unlimited_is_largest(self):
        # reference: MergeConditionIfCFSQuotaIsLarger
        assert merge_if_cfs_quota_larger("-1", "100000")[1] is False
        assert merge_if_cfs_quota_larger("100000", "-1")[1] is True
        assert merge_if_cfs_quota_larger("100000", "200000")[1] is True
        assert merge_if_cfs_quota_larger("max 100000", "50000")[1] is False

    def test_cpuset_looser_unions(self):
        merged, need = merge_if_cpuset_looser("0-3", "2-5")
        assert need and merged == "0,1,2,3,4,5"
        _, need = merge_if_cpuset_looser("0-5", "1-2")
        assert not need


class TestLeveledBatch:
    def test_shrink_applies_children_first(self, v1):
        """Shrinking quota: merge pass must not shrink the parent while
        children still hold larger quotas (reference: executor.go:114)."""
        ensure_cgroup_dir("kubepods/pod1/c1", v1)
        ex = ResourceUpdateExecutor(v1)
        quota = get_resource("cpu.cfs_quota_us")
        quota.write("kubepods/pod1", "400000", v1)
        quota.write("kubepods/pod1/c1", "400000", v1)

        parent = CgroupUpdater("cpu.cfs_quota_us", "kubepods/pod1",
                               "100000", merge_if_cfs_quota_larger)
        child = CgroupUpdater("cpu.cfs_quota_us", "kubepods/pod1/c1",
                              "100000", merge_if_cfs_quota_larger)
        ex.leveled_update_batch([[parent], [child]])
        assert quota.read("kubepods/pod1", v1) == "100000"
        assert quota.read("kubepods/pod1/c1", v1) == "100000"

    def test_grow_applies_parent_first_via_merge(self, v1):
        ensure_cgroup_dir("kubepods/pod1/c1", v1)
        ex = ResourceUpdateExecutor(v1)
        cpuset = get_resource("cpuset.cpus")
        cpuset.write("kubepods/pod1", "0-1", v1)
        cpuset.write("kubepods/pod1/c1", "0-1", v1)

        parent = CgroupUpdater("cpuset.cpus", "kubepods/pod1", "0-3",
                               merge_if_cpuset_looser)
        child = CgroupUpdater("cpuset.cpus", "kubepods/pod1/c1", "2-3",
                              merge_if_cpuset_looser)
        ex.leveled_update_batch([[parent], [child]])
        assert cpuset.read("kubepods/pod1", v1) == "0-3"
        assert cpuset.read("kubepods/pod1/c1", v1) == "2-3"


class TestAuditor:
    def test_ring_bound_and_query(self):
        a = Auditor(capacity=3, clock=lambda: 1.0)
        for i in range(5):
            a.log("g", f"s{i}", "op")
        assert len(a) == 3
        assert [e.subject for e in a.query()] == ["s4", "s3", "s2"]
        assert a.query(subject="s3", limit=1)[0].subject == "s3"
        assert a.query(group="other") == []
