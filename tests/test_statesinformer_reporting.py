"""statesinformer reporting pipeline (VERDICT weak item 5): kubelet-style
pod source, NodeResourceTopology + Device reporting feeding the
scheduler's NUMA/DeviceShare plugins end-to-end.

Reference: pkg/koordlet/statesinformer/impl/{kubelet_stub.go,
states_noderesourcetopology.go,states_device_linux.go}.
"""

import json

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName as R,
)
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.device.cache import DeviceEntry, DeviceType
from koordinator_tpu.device.cache import DeviceResourceName as DR
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.statesinformer import (
    DeviceReporter,
    NodeTopologyReporter,
    PodsInformer,
    StatesInformer,
)
from koordinator_tpu.koordlet.system.cgroup import SystemConfig
from koordinator_tpu.koordlet.system.cpuinfo import (
    parse_cpulist,
    read_cpu_infos,
)
from koordinator_tpu.numa.hints import NUMATopologyPolicy
from koordinator_tpu.scheduler import Scheduler


def fake_proc_sys(tmp_path, sockets=1, cores=4, threads=2, numa_nodes=2):
    """Fake /proc/cpuinfo + /sys NUMA cpulists."""
    proc = tmp_path / "proc"
    proc.mkdir(exist_ok=True)
    n = sockets * cores * threads
    blocks = []
    for cpu in range(n):
        core = cpu // threads
        blocks.append(
            f"processor\t: {cpu}\n"
            f"physical id\t: {core // (cores // sockets) if sockets > 1 else 0}\n"
            f"core id\t: {core}\n"
        )
    (proc / "cpuinfo").write_text("\n".join(blocks) + "\n")
    per_node = n // numa_nodes
    for node in range(numa_nodes):
        d = tmp_path / "sys" / "devices" / "system" / "node" / f"node{node}"
        d.mkdir(parents=True, exist_ok=True)
        lo, hi = node * per_node, (node + 1) * per_node - 1
        (d / "cpulist").write_text(f"{lo}-{hi}\n")
    return SystemConfig(
        proc_root=str(proc), sysfs_root=str(tmp_path / "sys"),
        cgroup_root=str(tmp_path / "cg"),
    )


def test_parse_cpulist():
    assert parse_cpulist("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
    assert parse_cpulist("") == []


def test_read_cpu_infos(tmp_path):
    cfg = fake_proc_sys(tmp_path)
    infos = read_cpu_infos(cfg)
    assert len(infos) == 8
    assert infos[0].node_id == 0 and infos[7].node_id == 1
    assert infos[0].core_id == infos[1].core_id  # hyperthreads share cores


def test_pods_informer_publishes():
    informer = StatesInformer()

    class Stub:
        def get_all_pods(self):
            return [PodMeta(uid="p1", cgroup_dir="kubepods/podp1",
                            qos=QoSClass.LS)]

    pods = PodsInformer(Stub(), informer).sync()
    assert [p.uid for p in informer.running_pods()] == ["p1"]
    assert pods[0].uid == "p1"


def test_topology_and_device_reporting_feed_scheduler(tmp_path):
    """The full pipeline: koordlet discovers topology + devices and
    reports; the scheduler then pins a cpuset pod and allocates a GPU on
    that node — topology no longer appears 'by fiat'."""
    cfg = fake_proc_sys(tmp_path)
    s = Scheduler()
    s.add_node(NodeSpec(name="node-a", allocatable={R.CPU: 8000, R.MEMORY: 32768}))
    s.update_node_metric(
        NodeMetric(node_name="node-a", node_usage={}, update_time=99.0)
    )

    nrt = NodeTopologyReporter(
        node_name="node-a",
        system_config=cfg,
        report=s.update_node_topology,
        policy=NUMATopologyPolicy.NONE,
        numa_memory_mib={0: 16384, 1: 16384},
    )
    report = nrt.sync()
    assert report is not None
    opts = s.numa_manager.get_topology("node-a")
    assert opts.cpu_topology is not None and opts.cpu_topology.num_cpus == 8
    assert opts.numa_node_resources[0][R.CPU] == 4000
    assert opts.numa_node_resources[1][R.MEMORY] == 16384

    class GPUSource:
        def list_devices(self):
            return [
                DeviceEntry(
                    minor=i, device_type=DeviceType.GPU,
                    resources={DR.GPU_CORE: 100, DR.GPU_MEMORY: 16384,
                               DR.GPU_MEMORY_RATIO: 100},
                    numa_node=0, pcie_id="0",
                )
                for i in range(2)
            ]

    DeviceReporter("node-a", GPUSource(), s.update_node_devices).sync()
    assert s.device_cache.get("node-a") is not None

    # a cpuset LSR pod pins onto the reported topology
    s.add_pod(PodSpec(name="pin", qos=QoSClass.LSR, requests={R.CPU: 2000}))
    # a GPU pod allocates from the reported inventory
    s.add_pod(PodSpec(name="gpu", requests={R.CPU: 1000},
                      device_requests={"nvidia.com/gpu": 1}))
    out = s.schedule_pending(now=100.0)
    assert out["default/pin"] == "node-a"
    assert out["default/gpu"] == "node-a"
    pin = s.cache.pods["default/pin"]
    status = json.loads(pin.annotations[ANNOTATION_RESOURCE_STATUS])
    assert len(status["cpuset"]) == 2
    gpu_alloc = s.device_cache.get("node-a").allocations
    assert "default/gpu" in gpu_alloc


def test_offline_cpus_reserved_not_counted(tmp_path):
    """Sparse cpu ids (offline cpus) must be reserved out, not reported
    as phantom capacity (round-2 review fix)."""
    from koordinator_tpu.koordlet.system.cpuinfo import ProcessorInfo

    cfg = SystemConfig(proc_root=str(tmp_path), sysfs_root=str(tmp_path))
    infos = [
        ProcessorInfo(cpu_id=0, core_id=0, socket_id=0, node_id=0),
        ProcessorInfo(cpu_id=1, core_id=0, socket_id=0, node_id=0),
        ProcessorInfo(cpu_id=3, core_id=1, socket_id=0, node_id=0),  # cpu 2 offline
    ]
    reports = {}
    nrt = NodeTopologyReporter(
        "n", cfg, report=lambda name, opts: reports.update({name: opts}),
        cpu_infos=infos,
    )
    nrt.sync()
    opts = reports["n"]
    assert opts.numa_node_resources[0][R.CPU] == 3000  # 3 real cpus
    assert tuple(opts.reserved_cpus) == (2,)


def test_gate_overrides_do_not_leak_between_builds():
    from koordinator_tpu.cmd import SchedulerConfig, build_scheduler

    s1 = build_scheduler(SchedulerConfig(feature_gates="BatchedPlacement=false"))
    s2 = build_scheduler(SchedulerConfig())
    assert not s1.batched_placement
    assert s2.batched_placement  # default build unaffected (review fix)
