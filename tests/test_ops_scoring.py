"""Golden tests: JAX batched ops == reference-semantics oracle, bit-for-bit.

Randomized over realistic canonical-unit ranges plus adversarial boundary
cases (exact-threshold percentages, zero allocatable, req > capacity).
"""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.ops.common import percent_rounded as jax_percent
from koordinator_tpu.ops.fit import fit_filter, least_allocated_score
from koordinator_tpu.ops.loadaware import loadaware_filter, loadaware_score
from koordinator_tpu.oracle import scheduler as oracle

RNG = np.random.default_rng(0)


def _rand_nodes(n):
    alloc = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    alloc[:, ResourceName.CPU] = RNG.integers(1000, 128_000, n)       # 1..128 cores
    alloc[:, ResourceName.MEMORY] = RNG.integers(1024, 1_048_576, n)  # 1GiB..1TiB
    used = (alloc * RNG.uniform(0, 1.2, (n, NUM_RESOURCES))).astype(np.int64)
    return alloc, used


def test_percent_rounded_matches_oracle():
    # randomized check of the device formula against the exact-rational oracle
    used = RNG.integers(0, 10_000_000, 20_000)
    total = RNG.integers(1, 10_000_000, 20_000)
    got = np.asarray(jax_percent(jnp.asarray(used, jnp.int32), jnp.asarray(total, jnp.int32)))
    want = np.array([oracle.percent_rounded(int(u), int(t)) for u, t in zip(used, total)])
    np.testing.assert_array_equal(got, want)
    # boundary: exactly .5 rounds away from zero
    assert int(jax_percent(jnp.int32(1), jnp.int32(200))) == 1  # 0.5 -> 1
    assert int(jax_percent(jnp.int32(3), jnp.int32(200))) == 2  # 1.5 -> 2
    assert int(jax_percent(jnp.int32(0), jnp.int32(0))) == 0


def test_percent_rounded_documented_float64_deviation():
    # The reference computes the percentage through float64, which rounds
    # the exact boundary 23/40 = 57.5% *down* (57.4999999999999993). This
    # framework defines the exact rational semantics (57.5 -> 58) — a
    # deliberate, documented deviation; everywhere off the .5 boundary the
    # two agree.
    assert oracle.percent_rounded(23, 40) == 58
    assert oracle.percent_rounded_go_float64(23, 40) == 57
    assert int(jax_percent(jnp.int32(23), jnp.int32(40))) == 58
    mismatches = [
        (u, t)
        for u in range(0, 400)
        for t in range(1, 400)
        if oracle.percent_rounded(u, t) != oracle.percent_rounded_go_float64(u, t)
    ]
    # divergence only on exact .5 boundaries (a measure-zero input set)
    for u, t in mismatches:
        assert (200 * u) % (2 * t) == t  # exact half
    assert len(mismatches) < 0.001 * 400 * 400


def test_fit_filter_matches_oracle():
    n = 257
    alloc, used = _rand_nodes(n)
    req = np.zeros(NUM_RESOURCES, dtype=np.int64)
    req[ResourceName.CPU] = 4000
    req[ResourceName.MEMORY] = 8192
    got = np.asarray(
        fit_filter(jnp.asarray(req, jnp.int32), jnp.asarray(alloc, jnp.int32), jnp.asarray(used, jnp.int32))
    )
    want = np.array([oracle.fit_filter_node(req, alloc[i], used[i]) for i in range(n)])
    np.testing.assert_array_equal(got, want)
    assert got.any() and not got.all()  # exercise both branches


def test_fit_filter_zero_request_passes_overcommitted_dim():
    alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
    used = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
    alloc[0, ResourceName.CPU] = 1000
    # GPU dimension overcommitted but pod doesn't request it
    used[0, ResourceName.GPU] = 500
    req = np.zeros(NUM_RESOURCES, dtype=np.int64)
    req[ResourceName.CPU] = 500
    assert bool(
        fit_filter(
            jnp.asarray(req, jnp.int32),
            jnp.asarray(alloc, jnp.int32),
            jnp.asarray(used, jnp.int32),
        )[0]
    )


def test_least_allocated_matches_oracle():
    n = 311
    alloc, used = _rand_nodes(n)
    weights = np.zeros(NUM_RESOURCES, dtype=np.int64)
    weights[ResourceName.CPU] = 1
    weights[ResourceName.MEMORY] = 1
    req = np.zeros(NUM_RESOURCES, dtype=np.int64)
    req[ResourceName.CPU] = 2500
    req[ResourceName.MEMORY] = 4096
    got = np.asarray(
        least_allocated_score(
            jnp.asarray(req, jnp.int32),
            jnp.asarray(alloc, jnp.int32),
            jnp.asarray(used, jnp.int32),
            jnp.asarray(weights, jnp.int32),
        )
    )
    want = np.array(
        [oracle.least_allocated_score_node(req, alloc[i], used[i], weights) for i in range(n)]
    )
    np.testing.assert_array_equal(got, want)
    assert (got > 0).any()


def _thresholds():
    thr = np.zeros(NUM_RESOURCES, dtype=np.int64)
    thr[ResourceName.CPU] = 65
    thr[ResourceName.MEMORY] = 95
    return thr


def test_loadaware_filter_matches_oracle():
    n = 409
    alloc, _ = _rand_nodes(n)
    usage = (alloc * RNG.uniform(0, 1.1, (n, NUM_RESOURCES))).astype(np.int64)
    prod_usage = (usage * RNG.uniform(0, 1.0, (n, NUM_RESOURCES))).astype(np.int64)
    fresh = RNG.uniform(size=n) < 0.8
    thr = _thresholds()
    for prod_thr_on in (False, True):
        prod_thr = thr // 2 if prod_thr_on else np.zeros_like(thr)
        for is_prod in (False, True):
            for is_ds in (False, True):
                got = np.asarray(
                    loadaware_filter(
                        jnp.asarray(alloc, jnp.int32),
                        jnp.asarray(usage, jnp.int32),
                        jnp.asarray(prod_usage, jnp.int32),
                        jnp.asarray(fresh),
                        jnp.asarray(thr, jnp.int32),
                        jnp.asarray(prod_thr, jnp.int32),
                        jnp.asarray(is_ds),
                        jnp.asarray(is_prod),
                    )
                )
                want = np.array(
                    [
                        oracle.loadaware_filter_node(
                            alloc[i], usage[i], prod_usage[i], bool(fresh[i]),
                            thr, prod_thr, is_ds, is_prod,
                        )
                        for i in range(n)
                    ]
                )
                np.testing.assert_array_equal(got, want)


def test_loadaware_filter_exact_threshold_unschedulable():
    # usage exactly at threshold => unschedulable (>= comparison)
    alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
    usage = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
    alloc[0, ResourceName.CPU] = 1000
    usage[0, ResourceName.CPU] = 650  # exactly 65%
    thr = _thresholds()
    mask = loadaware_filter(
        jnp.asarray(alloc, jnp.int32),
        jnp.asarray(usage, jnp.int32),
        jnp.asarray(np.zeros_like(usage), jnp.int32),
        jnp.asarray(np.array([True])),
        jnp.asarray(thr, jnp.int32),
        jnp.asarray(np.zeros_like(thr), jnp.int32),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    assert not bool(mask[0])
    # 64.5% rounds to 64 (wait: 645/1000 = 64.5 -> rounds away to 65 -> blocked)
    usage[0, ResourceName.CPU] = 645
    mask = loadaware_filter(
        jnp.asarray(alloc, jnp.int32),
        jnp.asarray(usage, jnp.int32),
        jnp.asarray(np.zeros_like(usage), jnp.int32),
        jnp.asarray(np.array([True])),
        jnp.asarray(thr, jnp.int32),
        jnp.asarray(np.zeros_like(thr), jnp.int32),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    assert not bool(mask[0])
    usage[0, ResourceName.CPU] = 644  # 64.4% -> 64 < 65 -> passes
    mask = loadaware_filter(
        jnp.asarray(alloc, jnp.int32),
        jnp.asarray(usage, jnp.int32),
        jnp.asarray(np.zeros_like(usage), jnp.int32),
        jnp.asarray(np.array([True])),
        jnp.asarray(thr, jnp.int32),
        jnp.asarray(np.zeros_like(thr), jnp.int32),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    assert bool(mask[0])


def test_loadaware_score_matches_oracle():
    n = 353
    alloc, _ = _rand_nodes(n)
    usage = (alloc * RNG.uniform(0, 1.0, (n, NUM_RESOURCES))).astype(np.int64)
    prod_base = (usage * RNG.uniform(0, 1.0, (n, NUM_RESOURCES))).astype(np.int64)
    est_extra = RNG.integers(0, 4000, (n, NUM_RESOURCES))
    fresh = RNG.uniform(size=n) < 0.8
    weights = np.zeros(NUM_RESOURCES, dtype=np.int64)
    weights[ResourceName.CPU] = 1
    weights[ResourceName.MEMORY] = 1
    pod_est = np.zeros(NUM_RESOURCES, dtype=np.int64)
    pod_est[ResourceName.CPU] = 850
    pod_est[ResourceName.MEMORY] = 717
    for score_prod in (False, True):
        for is_prod in (False, True):
            got = np.asarray(
                loadaware_score(
                    jnp.asarray(pod_est, jnp.int32),
                    jnp.asarray(alloc, jnp.int32),
                    jnp.asarray(usage, jnp.int32),
                    jnp.asarray(est_extra, jnp.int32),
                    jnp.asarray(prod_base, jnp.int32),
                    jnp.asarray(fresh),
                    jnp.asarray(weights, jnp.int32),
                    jnp.asarray(is_prod),
                    score_according_prod=score_prod,
                )
            )
            want = np.array(
                [
                    oracle.loadaware_score_node(
                        pod_est, alloc[i], usage[i], est_extra[i], prod_base[i],
                        bool(fresh[i]), weights, is_prod, score_prod,
                    )
                    for i in range(n)
                ]
            )
            np.testing.assert_array_equal(got, want)


class TestFloorDivExact:
    def test_matches_integer_division_exhaustively(self):
        """The reciprocal-multiply fast path is bit-identical to // for
        the score value ranges (divisor static, quotient <= ~100R)."""
        import numpy as np

        from koordinator_tpu.ops.common import floor_div_exact, reciprocal_for

        rng = np.random.default_rng(7)
        cap = rng.choice(
            [1, 3, 7, 100, 999, 16000, 65536, 10_700_000], size=4096
        ).astype(np.int32)
        y = (rng.integers(0, 101, 4096).astype(np.int64) * cap).astype(np.int32)
        # perturb off exact multiples + boundary cases
        y = np.concatenate([y, np.maximum(y - 1, 0), y + 1, np.zeros_like(y)])
        cap4 = np.concatenate([cap] * 4)
        recip = reciprocal_for(jnp.asarray(cap4))
        got = np.asarray(
            floor_div_exact(jnp.asarray(y), jnp.asarray(cap4), recip)
        )
        want = y.astype(np.int64) // np.maximum(cap4, 1)
        np.testing.assert_array_equal(got, want)

    def test_score_identity(self):
        import numpy as np

        from koordinator_tpu.ops.common import (
            least_requested_score,
            reciprocal_for,
        )

        rng = np.random.default_rng(8)
        cap = rng.choice([0, 1000, 16000, 32768, 10_700_000], size=(512, 6))
        cap = jnp.asarray(cap.astype(np.int32))
        requested = jnp.asarray(
            rng.integers(0, 11_000_000, (512, 6)).astype(np.int32)
        )
        slow = least_requested_score(requested, cap)
        fast = least_requested_score(requested, cap, reciprocal_for(cap))
        np.testing.assert_array_equal(np.asarray(slow), np.asarray(fast))
