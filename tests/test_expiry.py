"""Gang WaitTime expiry + Reservation expiration/GC (VERDICT item 7).

Reference: coscheduling WaitTime timeout → reject + release
(pkg/scheduler/plugins/coscheduling/core/gang.go:43-95, core/core.go:
390-408); reservation controller expiration/GC
(pkg/scheduler/plugins/reservation/controller/controller.go:186-266,
garbage_collection.go:35-82).
"""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.reservation_controller import (
    ReservationController,
)


def _one_node_scheduler(cpu=16000):
    s = Scheduler()
    s.add_node(NodeSpec(name="n0", allocatable={R.CPU: cpu, R.MEMORY: 32768}))
    s.update_node_metric(
        NodeMetric(node_name="n0", node_usage={}, update_time=99.0)
    )
    return s


class TestGangWaitTimeExpiry:
    def test_batched_waiting_pod_expires_and_releases(self):
        s = _one_node_scheduler()
        s.update_quota(QuotaSpec(name="t", min={R.CPU: 1000}, max={R.CPU: 8000}))
        s.update_gang(
            GangSpec(name="g", min_member=2, wait_time=30.0, mode=GangMode.NON_STRICT)
        )
        pod = PodSpec(name="w1", gang="g", quota="t", requests={R.CPU: 2000})
        s.add_pod(pod)
        out = s.schedule_pending(now=100.0)
        assert out.waiting.get("default/w1") == "n0"
        assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 2000

        # before the timeout nothing changes
        s.schedule_pending(now=120.0)
        assert "default/w1" in s._waiting

        # past WaitTime: rejected, resources released, pod back to pending
        released = s.expire_waiting(now=131.0)
        assert released == ["default/w1"]
        assert "default/w1" in s.cache.pending
        assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 0
        # next round it waits again (still only 1 member)
        out = s.schedule_pending(now=140.0)
        assert out.waiting.get("default/w1") == "n0"

    def test_strict_group_rejected_together(self):
        s = _one_node_scheduler()
        s.update_gang(GangSpec(name="g", min_member=3, wait_time=30.0))
        for i in range(3):
            s.add_pod(PodSpec(name=f"g{i}", gang="g", requests={R.CPU: 1000}))
        # incremental path: two members scheduled, both wait at Permit
        assert s.schedule_one("default/g0", now=100.0).status == "waiting"
        assert s.schedule_one("default/g1", now=105.0).status == "waiting"
        assert set(s._waiting) == {"default/g0", "default/g1"}

        # g0 times out at 130; Strict mode rejects the whole group
        released = s.expire_waiting(now=131.0)
        assert set(released) == {"default/g0", "default/g1"}
        assert "default/g0" in s.cache.pending
        assert "default/g1" in s.cache.pending
        assert not s._waiting

        # ... and the very next batched round re-places the whole gang
        # (all three members are pending together now)
        out = s.schedule_pending(now=132.0)
        assert all(out.get(f"default/g{i}") == "n0" for i in range(3))

    def test_gang_completion_stops_the_clock(self):
        s = _one_node_scheduler()
        s.update_gang(GangSpec(name="g", min_member=2, wait_time=30.0))
        s.add_pod(PodSpec(name="g0", gang="g", requests={R.CPU: 1000}))
        s.add_pod(PodSpec(name="g1", gang="g", requests={R.CPU: 1000}))
        assert s.schedule_one("default/g0", now=100.0).status == "waiting"
        assert s.schedule_one("default/g1", now=110.0).status == "bound"
        # barrier opened: g0 committed, no expiry later
        assert not s._waiting
        assert s.expire_waiting(now=500.0) == []
        assert s.cache.pods["default/g0"].node_name == "n0"


class TestReservationController:
    def test_expiration_releases_hold(self):
        s = _one_node_scheduler(cpu=10000)
        s.update_reservation(
            ReservationSpec(
                name="resv",
                requests={R.CPU: 8000},
                allocatable={R.CPU: 8000},
                owner_labels={"team": "ml"},
                node_name="n0",
                state=ReservationState.AVAILABLE,
                expiration_time=150.0,
            )
        )
        s.add_pod(PodSpec(name="big", requests={R.CPU: 6000}))
        out = s.schedule_pending(now=100.0)
        assert out["default/big"] is None  # blocked by the hold

        # controller expires the reservation at 150; hold released
        out = s.schedule_pending(now=151.0)
        assert out["default/big"] == "n0"
        assert s.cache.reservations["resv"].state == ReservationState.EXPIRED

    def test_ttl_and_zero_ttl(self):
        cache_s = _one_node_scheduler()
        c = cache_s.reservation_controller
        cache_s.update_reservation(
            ReservationSpec(
                name="ttl0", requests={R.CPU: 100}, node_name="n0",
                state=ReservationState.AVAILABLE, ttl=0, create_time=0.0,
            )
        )
        cache_s.update_reservation(
            ReservationSpec(
                name="ttl60", requests={R.CPU: 100}, node_name="n0",
                state=ReservationState.AVAILABLE, ttl=60.0, create_time=100.0,
            )
        )
        c.sync(now=1000.0)
        assert cache_s.cache.reservations["ttl0"].state == ReservationState.AVAILABLE
        assert cache_s.cache.reservations["ttl60"].state == ReservationState.EXPIRED

    def test_missing_node_expires(self):
        s = _one_node_scheduler()
        s.update_reservation(
            ReservationSpec(
                name="ghost", requests={R.CPU: 100}, node_name="gone",
                state=ReservationState.AVAILABLE,
            )
        )
        s.reservation_controller.sync(now=100.0)
        assert s.cache.reservations["ghost"].state == ReservationState.EXPIRED

    def test_gc_removes_after_grace(self):
        s = _one_node_scheduler()
        c = ReservationController(s.cache, gc_seconds=100.0)
        s.update_reservation(
            ReservationSpec(
                name="old", requests={R.CPU: 100}, node_name="n0",
                state=ReservationState.SUCCEEDED,
            )
        )
        c.sync(now=0.0)
        assert "old" in s.cache.reservations
        c.sync(now=99.0)
        assert "old" in s.cache.reservations
        c.sync(now=101.0)
        assert "old" not in s.cache.reservations

    def test_status_sync_releases_dead_pod_allocation(self):
        s = _one_node_scheduler()
        resv = ReservationSpec(
            name="multi",
            requests={R.CPU: 8000},
            allocatable={R.CPU: 8000},
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
            allocate_once=False,
        )
        s.update_reservation(resv)
        pod = PodSpec(name="mlpod", requests={R.CPU: 4000}, labels={"team": "ml"})
        s.add_pod(pod)
        out = s.schedule_pending(now=100.0)
        assert out["default/mlpod"] == "n0"
        assert resv.allocated.get(R.CPU) == 4000

        # the consuming pod dies; status sync returns the capacity
        s.remove_pod(pod)
        s.reservation_controller.sync(now=110.0)
        assert not resv.allocated.get(R.CPU)
        assert resv.allocated_pod_uids == []


def test_waiting_pod_reservation_rolled_back_on_expiry():
    """An allocate_once reservation consumed by a *waiting* gang member is
    restored (AVAILABLE, allocation removed) when the wait expires —
    review fix: the capacity must not be lost to a pod that never ran."""
    s = _one_node_scheduler()
    s.update_gang(
        GangSpec(name="g", min_member=2, wait_time=30.0, mode=GangMode.NON_STRICT)
    )
    resv = ReservationSpec(
        name="resv",
        requests={R.CPU: 4000},
        allocatable={R.CPU: 4000},
        owner_labels={"team": "ml"},
        node_name="n0",
        state=ReservationState.AVAILABLE,
        allocate_once=True,
    )
    s.update_reservation(resv)
    s.add_pod(
        PodSpec(name="w1", gang="g", requests={R.CPU: 2000}, labels={"team": "ml"})
    )
    out = s.schedule_pending(now=100.0)
    assert out.waiting.get("default/w1") == "n0"
    assert resv.state == ReservationState.SUCCEEDED
    assert resv.allocated.get(R.CPU) == 2000

    released = s.expire_waiting(now=131.0)
    assert released == ["default/w1"]
    assert resv.state == ReservationState.AVAILABLE
    assert not resv.allocated.get(R.CPU)
    assert resv.allocated_pod_uids == []


def test_incremental_waiting_cpuset_released_on_expiry():
    """Incremental-path waiting pod with a cpuset hold: expiry releases
    the pinned cpus (review fix: schedule_one now stashes its cycle
    state for rollback)."""
    import json as _json

    from koordinator_tpu.apis.extension import QoSClass
    from koordinator_tpu.numa.hints import NUMATopologyPolicy
    from koordinator_tpu.numa.manager import TopologyOptions
    from koordinator_tpu.numa.topology import CPUTopology

    s = _one_node_scheduler()
    topo = CPUTopology.build(
        sockets=2, nodes_per_socket=1, cores_per_node=4, threads_per_core=2
    )
    s.update_node_topology(
        "n0",
        TopologyOptions(
            cpu_topology=topo,
            policy=NUMATopologyPolicy.NONE,
            numa_node_resources={
                0: {R.CPU: 8000, R.MEMORY: 16384},
                1: {R.CPU: 8000, R.MEMORY: 16384},
            },
        ),
    )
    s.update_gang(GangSpec(name="g", min_member=2, wait_time=30.0))
    s.add_pod(
        PodSpec(name="c1", gang="g", qos=QoSClass.LSR, requests={R.CPU: 4000})
    )
    s.add_pod(PodSpec(name="c2", gang="g", requests={R.CPU: 99000}))  # never fits
    out = s.schedule_one("default/c1", now=100.0)
    assert out.status == "waiting"
    assert s.numa_manager.get_allocated_cpuset("n0", "default/c1") is not None

    released = s.expire_waiting(now=131.0)
    assert "default/c1" in released
    assert s.numa_manager.get_allocated_cpuset("n0", "default/c1") is None
