"""koord-manager tests: overcommit math, degrade, diff-threshold sync,
collect policy, NodeSLO rendering.

Semantics oracle: pkg/slo-controller/noderesource/plugins/batchresource
(calculateBatchResourceByPolicy util.go:38-91, calculateOnNode
plugin.go:226), midresource/plugin.go:128, nodemetric/collect_policy.go.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_CPU_NORMALIZATION_RATIO,
    ANNOTATION_NODE_RESERVATION,
    NUM_RESOURCES,
    PriorityClass,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.manager.nodemetric import node_metric_collect_policy
from koordinator_tpu.manager.nodeslo import NodeSLOController, NodeSLOOverride
from koordinator_tpu.manager.noderesource import NodeResourceController
from koordinator_tpu.manager.sloconfig import (
    ColocationConfig,
    ColocationStrategy,
    NodeSLOSpec,
    ResourceThresholdStrategy,
    default_node_slo_spec,
)
from koordinator_tpu.ops.overcommit import (
    CalculatePolicy,
    NodeOvercommitInputs,
    OvercommitParams,
    PodOvercommitInputs,
    batch_allocatable,
    mid_allocatable,
    needs_sync,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
BCPU, BMEM = ResourceName.BATCH_CPU, ResourceName.BATCH_MEMORY


def _params(cpu_pct=60, mem_pct=65, cpu_policy=CalculatePolicy.USAGE,
            mem_policy=CalculatePolicy.USAGE, mid_pct=100):
    reclaim = np.zeros(NUM_RESOURCES, np.int32)
    reclaim[CPU], reclaim[MEM] = cpu_pct, mem_pct
    mid = np.zeros(NUM_RESOURCES, np.int32)
    mid[CPU] = mid[MEM] = mid_pct
    return OvercommitParams(
        reclaim_percent=jnp.asarray(reclaim),
        mid_threshold_percent=jnp.asarray(mid),
        cpu_policy=jnp.asarray(cpu_policy, jnp.int32),
        memory_policy=jnp.asarray(mem_policy, jnp.int32),
    )


def _nodes(capacity, system=None, reserved=None, reclaimable=None, fresh=None):
    capacity = np.asarray(capacity, np.int32)
    n = capacity.shape[0]
    z = np.zeros_like(capacity)
    return NodeOvercommitInputs(
        capacity=jnp.asarray(capacity),
        system_used=jnp.asarray(system if system is not None else z),
        reserved=jnp.asarray(reserved if reserved is not None else z),
        prod_reclaimable=jnp.asarray(
            reclaimable if reclaimable is not None else z
        ),
        metric_fresh=jnp.asarray(
            fresh if fresh is not None else np.ones(n, bool)
        ),
    )


def _pods(node_idx, req, usage, has_metric, is_hp=None, is_lse=None):
    p = len(node_idx)
    return PodOvercommitInputs(
        node_idx=jnp.asarray(np.array(node_idx, np.int32)),
        req=jnp.asarray(np.array(req, np.int32)),
        usage=jnp.asarray(np.array(usage, np.int32)),
        has_metric=jnp.asarray(np.array(has_metric, bool)),
        is_hp=jnp.asarray(
            np.array(is_hp if is_hp is not None else [True] * p, bool)
        ),
        is_lse=jnp.asarray(
            np.array(is_lse if is_lse is not None else [False] * p, bool)
        ),
        active=jnp.ones(p, bool),
    )


def _vec(cpu=0, mem=0):
    v = np.zeros(NUM_RESOURCES, np.int64)
    v[CPU], v[MEM] = cpu, mem
    return v


class TestBatchAllocatable:
    def test_usage_policy_formula(self):
        # cap 10000m/10000Mi, reclaim 60%/65% -> margin 4000/3500
        # sys 1000/500, hp used 2000/1000
        nodes = _nodes([_vec(10000, 10000)], system=[_vec(1000, 500)])
        pods = _pods([0], [_vec(3000, 2000)], [_vec(2000, 1000)], [True])
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 10000 - 4000 - 1000 - 2000
        assert out[0, BMEM] == 10000 - 3500 - 500 - 1000

    def test_system_used_maxed_with_reserved(self):
        # reference util.go:42: systemUsed = max(systemUsed, nodeReserved)
        nodes = _nodes(
            [_vec(10000, 10000)],
            system=[_vec(500, 200)],
            reserved=[_vec(1500, 800)],
        )
        pods = _pods([0], [_vec(0, 0)], [_vec(0, 0)], [True], is_hp=[False])
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 10000 - 4000 - 1500
        assert out[0, BMEM] == 10000 - 3500 - 800

    def test_no_metric_pod_counts_request(self):
        # plugin.go:270-272: !hasMetric -> used += request
        nodes = _nodes([_vec(10000, 10000)])
        pods = _pods([0], [_vec(4000, 3000)], [_vec(0, 0)], [False])
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 10000 - 4000 - 4000
        assert out[0, BMEM] == 10000 - 3500 - 3000

    def test_lse_pod_mixes_cpu_request_memory_usage(self):
        # plugin.go:273-277: LSE pods don't reclaim CPU: used gets
        # (req.cpu, usage.mem)
        nodes = _nodes([_vec(10000, 10000)])
        pods = _pods(
            [0], [_vec(4000, 3000)], [_vec(1000, 1000)], [True],
            is_lse=[True],
        )
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 10000 - 4000 - 4000  # req cpu
        assert out[0, BMEM] == 10000 - 3500 - 1000  # usage mem

    def test_lp_pods_ignored(self):
        nodes = _nodes([_vec(10000, 10000)])
        pods = _pods(
            [0], [_vec(9000, 9000)], [_vec(9000, 9000)], [True],
            is_hp=[False],
        )
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 6000 and out[0, BMEM] == 6500

    def test_clamped_at_zero(self):
        nodes = _nodes([_vec(1000, 1000)], system=[_vec(900, 900)])
        pods = _pods([0], [_vec(500, 500)], [_vec(500, 500)], [True])
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 0 and out[0, BMEM] == 0

    def test_max_usage_request_policy(self):
        # util.go:51-53: by_max subtracts max(req, usage)
        nodes = _nodes([_vec(10000, 10000)])
        pods = _pods([0], [_vec(3000, 1000)], [_vec(2000, 2000)], [True])
        params = _params(
            cpu_policy=CalculatePolicy.MAX_USAGE_REQUEST,
            mem_policy=CalculatePolicy.MAX_USAGE_REQUEST,
        )
        out = np.asarray(batch_allocatable(nodes, pods, params))
        assert out[0, BCPU] == 10000 - 4000 - 3000
        assert out[0, BMEM] == 10000 - 3500 - 2000

    def test_request_policy_memory(self):
        # util.go:46-49: by_request subtracts reserved + hp requests
        nodes = _nodes(
            [_vec(10000, 10000)],
            system=[_vec(2000, 2000)],
            reserved=[_vec(100, 100)],
        )
        pods = _pods([0], [_vec(3000, 1000)], [_vec(100, 100)], [True])
        params = _params(mem_policy=CalculatePolicy.REQUEST)
        out = np.asarray(batch_allocatable(nodes, pods, params))
        assert out[0, BMEM] == 10000 - 3500 - 100 - 1000
        assert out[0, BCPU] == 10000 - 4000 - 2000 - 100  # usage policy

    def test_degrade_zeroes_stale_nodes(self):
        nodes = _nodes(
            [_vec(10000, 10000), _vec(10000, 10000)],
            fresh=[True, False],
        )
        pods = _pods([0], [_vec(0, 0)], [_vec(0, 0)], [True], is_hp=[False])
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 6000
        assert out[1, BCPU] == 0 and out[1, BMEM] == 0

    def test_multi_node_segment_sum(self):
        nodes = _nodes([_vec(10000, 10000)] * 3)
        pods = _pods(
            [0, 0, 2, -1],
            [_vec(1000, 500)] * 4,
            [_vec(800, 400)] * 4,
            [True] * 4,
        )
        out = np.asarray(batch_allocatable(nodes, pods, _params()))
        assert out[0, BCPU] == 6000 - 1600
        assert out[1, BCPU] == 6000
        assert out[2, BCPU] == 6000 - 800


class TestMidAllocatable:
    def test_min_of_reclaimable_and_threshold(self):
        # midresource/plugin.go:128-162
        nodes = _nodes(
            [_vec(10000, 10000)], reclaimable=[_vec(3000, 9000)]
        )
        out = np.asarray(mid_allocatable(nodes, _params(mid_pct=50)))
        assert out[0, ResourceName.MID_CPU] == 3000      # reclaimable
        assert out[0, ResourceName.MID_MEMORY] == 5000   # capped at 50%

    def test_degraded_zero(self):
        nodes = _nodes(
            [_vec(10000, 10000)],
            reclaimable=[_vec(3000, 3000)],
            fresh=[False],
        )
        out = np.asarray(mid_allocatable(nodes, _params()))
        assert out[0, ResourceName.MID_CPU] == 0


class TestNeedsSync:
    def test_threshold_gate(self):
        # util/resource.go:121-126: |new-old| > old*thr
        old = np.zeros((3, NUM_RESOURCES), np.int32)
        new = np.zeros((3, NUM_RESOURCES), np.int32)
        old[0, BCPU], new[0, BCPU] = 1000, 1099   # 9.9% < 10% -> no sync
        old[1, BCPU], new[1, BCPU] = 1000, 1101   # 10.1% -> sync
        old[2, BCPU], new[2, BCPU] = 0, 1         # zero-old nonzero-new
        out = np.asarray(
            needs_sync(jnp.asarray(old), jnp.asarray(new), jnp.asarray(10))
        )
        assert list(out) == [False, True, True]


class TestNodeResourceController:
    def _snapshot(self, now=1000.0):
        node = NodeSpec(
            "n0", allocatable={CPU: 10000, MEM: 10000},
        )
        pod = PodSpec(
            "p0", requests={CPU: 3000, MEM: 2000}, priority=9500,
            node_name="n0", qos=QoSClass.LS,
        )
        metric = NodeMetric(
            "n0",
            sys_usage={CPU: 1000, MEM: 500},
            pod_usages={pod.uid: {CPU: 2000, MEM: 1000}},
            update_time=now - 60,
        )
        return ClusterSnapshot(
            nodes=[node], pods=[pod], node_metrics={"n0": metric}, now=now
        )

    def test_reconcile_end_to_end(self):
        snap = self._snapshot()
        ctrl = NodeResourceController()
        updates = ctrl.reconcile_all(snap)
        assert len(updates) == 1
        upd = updates[0]
        assert upd.allocatable[BCPU] == 10000 - 4000 - 1000 - 2000
        assert upd.allocatable[BMEM] == 10000 - 3500 - 500 - 1000
        assert upd.synced and not upd.degraded
        # written back into the node for the scheduler to see
        assert snap.nodes[0].allocatable[BCPU] == upd.allocatable[BCPU]

    def test_degrade_on_stale_metric(self):
        snap = self._snapshot()
        snap.node_metrics["n0"].update_time = snap.now - 16 * 60
        ctrl = NodeResourceController()
        upd = ctrl.reconcile_all(snap)[0]
        assert upd.degraded and upd.allocatable[BCPU] == 0

    def test_dangling_pod_metric_subtracted(self):
        # pod reported in NodeMetric but gone from pod list: its usage
        # still subtracts (plugin.go:295-303)
        snap = self._snapshot()
        snap.node_metrics["n0"].pod_usages["ghost"] = {CPU: 500, MEM: 250}
        ctrl = NodeResourceController()
        upd = ctrl.reconcile_all(snap)[0]
        assert upd.allocatable[BCPU] == 10000 - 4000 - 1000 - 2000 - 500

    def test_dangling_lp_pod_ignored(self):
        snap = self._snapshot()
        snap.node_metrics["n0"].pod_usages["ghost"] = {CPU: 500}
        snap.node_metrics["n0"].pod_priority_class["ghost"] = (
            PriorityClass.BATCH
        )
        ctrl = NodeResourceController()
        upd = ctrl.reconcile_all(snap)[0]
        assert upd.allocatable[BCPU] == 10000 - 4000 - 1000 - 2000

    def test_node_reservation_annotation(self):
        snap = self._snapshot()
        snap.nodes[0].annotations[ANNOTATION_NODE_RESERVATION] = (
            '{"cpu": 1500, "memory": 800}'
        )
        ctrl = NodeResourceController()
        upd = ctrl.reconcile_all(snap)[0]
        # max(sys=1000, reserved=1500) = 1500
        assert upd.allocatable[BCPU] == 10000 - 4000 - 1500 - 2000

    def test_no_sync_when_diff_small(self):
        snap = self._snapshot()
        ctrl = NodeResourceController()
        first = ctrl.reconcile_all(snap)[0]
        assert first.synced
        # tiny usage wiggle below the 10% diff threshold
        snap.node_metrics["n0"].sys_usage[CPU] = 1010
        second = ctrl.reconcile_all(snap)[0]
        assert not second.synced

    def test_periodic_force_sync_after_time_threshold(self):
        # a node whose values drift below the diff threshold still
        # re-syncs once update_time_threshold_seconds elapses (ADVICE r1:
        # the reference's periodic force-update)
        snap = self._snapshot()
        ctrl = NodeResourceController()
        assert ctrl.reconcile_all(snap)[0].synced
        snap.node_metrics["n0"].sys_usage[CPU] = 1010  # < 10% diff
        assert not ctrl.reconcile_all(snap)[0].synced
        snap.now += 301  # default update_time_threshold_seconds = 300
        snap.node_metrics["n0"].update_time = snap.now - 60
        assert ctrl.reconcile_all(snap)[0].synced

    def test_disabled_strategy_no_sync(self):
        snap = self._snapshot()
        ctrl = NodeResourceController(
            ColocationConfig(cluster_strategy=ColocationStrategy(enable=False))
        )
        upd = ctrl.reconcile_all(snap)[0]
        assert not upd.synced

    def test_disabling_withdraws_batch_resources(self):
        # once colocation turns off, previously synced batch/mid values
        # must be reset to zero, not left stale
        snap = self._snapshot()
        NodeResourceController().reconcile_all(snap)
        assert snap.nodes[0].allocatable[BCPU] > 0
        off = NodeResourceController(
            ColocationConfig(cluster_strategy=ColocationStrategy(enable=False))
        )
        upd = off.reconcile_all(snap)[0]
        assert upd.synced and upd.allocatable[BCPU] == 0
        assert snap.nodes[0].allocatable[BCPU] == 0

    def test_annotation_only_change_sets_meta_synced(self):
        # amplification with batch diff below threshold must still flag a
        # node write-back (reference: NeedSyncMeta)
        snap = self._snapshot()
        ctrl = NodeResourceController()
        ctrl.reconcile_all(snap)
        snap.nodes[0].annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = "1.5"
        upd = ctrl.reconcile_all(snap)[0]
        assert upd.meta_synced
        # steady state: no further meta churn
        upd = ctrl.reconcile_all(snap)[0]
        assert not upd.meta_synced

    def test_huge_memory_node_no_overflow(self):
        # 64 TiB node: capacity * percent would wrap int32
        big = 64 * 1024 * 1024  # MiB
        snap = ClusterSnapshot(
            nodes=[NodeSpec("n0", allocatable={CPU: 10000, MEM: big})],
            pods=[],
            node_metrics={"n0": NodeMetric(
                "n0", prod_reclaimable={MEM: big // 2},
                update_time=940.0)},
            now=1000.0,
        )
        upd = NodeResourceController().reconcile_all(snap)[0]
        assert upd.allocatable[BMEM] == big - (big * 35) // 100
        assert upd.allocatable[ResourceName.MID_MEMORY] == big // 2

    def test_nonfinite_normalization_ratio_ignored(self):
        for bad in ("inf", "1e400", "nan", "1e15"):
            snap = self._snapshot()
            snap.nodes[0].annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = bad
            NodeResourceController().reconcile_all(snap)
            assert snap.nodes[0].allocatable[CPU] == 10000

    def test_cpu_normalization_amplifies(self):
        snap = self._snapshot()
        snap.nodes[0].annotations[ANNOTATION_CPU_NORMALIZATION_RATIO] = "1.5"
        ctrl = NodeResourceController()
        ctrl.reconcile_all(snap)
        assert snap.nodes[0].allocatable[CPU] == 15000
        assert snap.nodes[0].raw_allocatable[CPU] == 10000
        # idempotent: re-reconcile doesn't compound
        ctrl.reconcile_all(snap)
        assert snap.nodes[0].allocatable[CPU] == 15000
        # removing the ratio reverts to the raw allocatable
        del snap.nodes[0].annotations[ANNOTATION_CPU_NORMALIZATION_RATIO]
        ctrl.reconcile_all(snap)
        assert snap.nodes[0].allocatable[CPU] == 10000
        assert snap.nodes[0].raw_allocatable is None

    def test_malformed_reservation_annotation_ignored(self):
        # one bad annotation must not abort the cluster-wide reconcile
        for bad in ('{"cpu": "1500m"}', "[]", "not-json"):
            snap = self._snapshot()
            snap.nodes[0].annotations[ANNOTATION_NODE_RESERVATION] = bad
            upd = NodeResourceController().reconcile_all(snap)[0]
            assert upd.allocatable[BCPU] == 10000 - 4000 - 1000 - 2000

    def test_overrange_reclaim_percent_clamped(self):
        # malformed override (150%) must not mint capacity beyond the node
        from koordinator_tpu.manager.sloconfig import NodeStrategySelector

        snap = self._snapshot()
        cfg = ColocationConfig(
            cluster_strategy=ColocationStrategy(enable=True),
            node_strategies=[NodeStrategySelector(
                match_labels={},  # matches every node
                overrides={"cpu_reclaim_threshold_percent": 150},
            )],
        )
        upd = NodeResourceController(cfg).reconcile_all(snap)[0]
        # clamped to 100%: margin 0
        assert upd.allocatable[BCPU] == 10000 - 0 - 1000 - 2000
        assert upd.allocatable[BCPU] <= 10000

    def test_per_node_strategy_override(self):
        from koordinator_tpu.manager.sloconfig import NodeStrategySelector

        snap = self._snapshot()
        snap.nodes.append(
            NodeSpec("n1", allocatable={CPU: 10000, MEM: 10000},
                     labels={"pool": "aggressive"})
        )
        snap.node_metrics["n1"] = NodeMetric(
            "n1", sys_usage={CPU: 1000, MEM: 500}, update_time=snap.now - 60
        )
        cfg = ColocationConfig(
            cluster_strategy=ColocationStrategy(enable=True),
            node_strategies=[
                NodeStrategySelector(
                    match_labels={"pool": "aggressive"},
                    overrides={"cpu_reclaim_threshold_percent": 80},
                )
            ],
        )
        upds = NodeResourceController(cfg).reconcile_all(snap)
        assert upds[0].allocatable[BCPU] == 10000 - 4000 - 1000 - 2000
        assert upds[1].allocatable[BCPU] == 10000 - 2000 - 1000


class TestCollectPolicy:
    def test_policy_from_strategy(self):
        s = ColocationStrategy(enable=True)
        p = node_metric_collect_policy(s)
        assert p.aggregate_duration_seconds == 300
        assert p.report_interval_seconds == 60

    def test_disabled_returns_none(self):
        assert node_metric_collect_policy(ColocationStrategy()) is None

    def test_invalid_returns_none(self):
        s = ColocationStrategy(enable=True, degrade_time_minutes=0)
        assert node_metric_collect_policy(s) is None


class TestNodeSLO:
    def test_defaults(self):
        spec = default_node_slo_spec()
        t = spec.resource_used_threshold_with_be
        assert t.cpu_suppress_threshold_percent == 65
        assert t.memory_evict_threshold_percent == 70
        assert spec.resource_qos_strategy.be.cpu.group_identity == -1
        assert spec.resource_qos_strategy.ls.cpu.group_identity == 2
        assert spec.resource_qos_strategy.be.resctrl.cat_range_end_percent == 30
        assert spec.cpu_burst_strategy.cpu_burst_percent == 1000
        assert spec.system_strategy.min_free_kbytes_factor == 100

    def test_override_merge(self):
        # tuned cluster spec: override must only touch the keys it sets
        cluster = default_node_slo_spec()
        cluster.resource_used_threshold_with_be.memory_evict_threshold_percent = 80
        ctrl = NodeSLOController(
            cluster_spec=cluster,
            overrides=[
                NodeSLOOverride(
                    match_labels={"pool": "be"},
                    overrides={
                        "resource_used_threshold_with_be": {
                            "enable": True,
                            "cpu_suppress_threshold_percent": 50,
                        }
                    },
                )
            ],
        )
        hit = ctrl.render("n0", {"pool": "be"})
        miss = ctrl.render("n1", {"pool": "ls"})
        t = hit.resource_used_threshold_with_be
        assert t.cpu_suppress_threshold_percent == 50 and t.enable
        # partial override preserves the tuned cluster value
        assert t.memory_evict_threshold_percent == 80
        assert miss.resource_used_threshold_with_be.cpu_suppress_threshold_percent == 65

    def test_extender(self):
        def ext(name, labels, spec):
            spec.extensions["x"] = name

        ctrl = NodeSLOController(extenders=[ext])
        n0 = ctrl.render("n0", {})
        n1 = ctrl.render("n1", {})
        # rendered specs are independent copies, not shared state
        assert n0.extensions["x"] == "n0" and n1.extensions["x"] == "n1"
        assert ctrl.cluster_spec.extensions == {}

    def test_partial_colocation_override_preserves_cluster_strategy(self):
        from koordinator_tpu.manager.sloconfig import NodeStrategySelector

        cfg = ColocationConfig(
            cluster_strategy=ColocationStrategy(
                enable=True, cpu_reclaim_threshold_percent=70
            ),
            node_strategies=[
                NodeStrategySelector(
                    match_labels={"pool": "x"},
                    overrides={"memory_reclaim_threshold_percent": 50},
                )
            ],
        )
        s = cfg.strategy_for_node({"pool": "x"})
        assert s.enable and s.cpu_reclaim_threshold_percent == 70
        assert s.memory_reclaim_threshold_percent == 50


def test_be_host_app_usage_excluded_from_system_used():
    """BE host applications run on reclaimed resources: their usage is
    subtracted from system used so it doesn't shrink batch capacity
    (reference: batchresource hostAppBEUsed; round-2 review fix)."""
    from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
    from koordinator_tpu.apis.types import ClusterSnapshot, NodeMetric, NodeSpec
    from koordinator_tpu.manager.noderesource import NodeResourceController

    def snap(with_be_app):
        metric = NodeMetric(
            node_name="n0",
            node_usage={R.CPU: 10000, R.MEMORY: 8192},
            sys_usage={R.CPU: 4000, R.MEMORY: 2048},
            update_time=100.0,
        )
        if with_be_app:
            metric.host_app_usages["miner"] = {R.CPU: 3000, R.MEMORY: 1024}
            metric.host_app_qos["miner"] = QoSClass.BE
        return ClusterSnapshot(
            nodes=[NodeSpec(name="n0",
                            allocatable={R.CPU: 32000, R.MEMORY: 65536})],
            node_metrics={"n0": metric},
            now=110.0,
        )

    ctrl = NodeResourceController()
    plain = snap(False)
    ctrl.reconcile_all(plain)
    without_app = plain.nodes[0].allocatable.get(R.BATCH_CPU, 0)

    ctrl2 = NodeResourceController()
    s2 = snap(True)
    ctrl2.reconcile_all(s2)
    with_be_app = s2.nodes[0].allocatable.get(R.BATCH_CPU, 0)
    # the BE app's 3000m is returned to batch capacity
    assert with_be_app == without_app + 3000
