"""metriccache + metricsadvisor tests.

Aggregation oracle: pkg/koordlet/metriccache/util.go:55-100 (percentile =
ascending sort, idx = max(int(n*p)-1, 0)). Collector fixtures build a
fake /proc + cgroupfs tree (reference's testutil pattern).
"""

import os

import numpy as np
import pytest

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metriccache import (
    AggregationType,
    MetricCache,
    MetricKind,
)
from koordinator_tpu.koordlet.metricsadvisor.collectors import (
    BEResourceCollector,
    NodeResourceCollector,
    PodResourceCollector,
    PSICollector,
    SysResourceCollector,
    read_psi_avg10,
)
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    CollectorContext,
    MetricsAdvisor,
    PodMeta,
)
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.system.cgroup import SystemConfig

A = AggregationType


class TestMetricCache:
    def test_append_query_window(self):
        mc = MetricCache()
        for t in range(10):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), t * 100.0)
        ts, vals = mc.query(MetricKind.NODE_CPU_USAGE, start=3.0, end=7.0)
        assert list(ts) == [3.0, 4.0, 5.0, 6.0, 7.0]
        assert vals[0] == 300.0

    def test_ring_overwrites_oldest(self):
        mc = MetricCache(capacity_per_series=4)
        for t in range(6):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), float(t))
        ts, _ = mc.query(MetricKind.NODE_CPU_USAGE)
        assert list(ts) == [2.0, 3.0, 4.0, 5.0]

    def test_aggregations_match_reference(self):
        # percentile: sort asc, idx = int(n*p)-1 clamped 0 (util.go:91-95)
        mc = MetricCache()
        vals = [5.0, 1.0, 3.0, 2.0, 4.0]  # sorted: 1 2 3 4 5
        for i, v in enumerate(vals):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(i), v)
        agg = lambda a: mc.aggregate(MetricKind.NODE_CPU_USAGE, agg=a)
        assert agg(A.AVG) == 3.0
        assert agg(A.P50) == 2.0   # idx int(5*.5)-1 = 1
        assert agg(A.P90) == 4.0   # idx int(4.5)-1 = 3
        assert agg(A.P99) == 4.0   # idx int(4.95)-1 = 3
        assert agg(A.LAST) == 4.0  # last appended
        assert agg(A.COUNT) == 5.0
        assert mc.aggregate(MetricKind.POD_CPU_USAGE, {"pod": "x"}) is None

    def test_labels_separate_series(self):
        mc = MetricCache()
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "a"}, 1.0, 100.0)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "b"}, 1.0, 200.0)
        assert mc.aggregate(
            MetricKind.POD_CPU_USAGE, {"pod": "a"}, agg=A.LAST) == 100.0

    def test_aggregate_batch_matches_scalar(self):
        mc = MetricCache()
        rng = np.random.default_rng(0)
        pods = [f"p{i}" for i in range(5)]
        for p in pods:
            for t in range(rng.integers(1, 20)):
                mc.append(MetricKind.POD_CPU_USAGE, {"pod": p},
                          float(t), float(rng.uniform(0, 1000)))
        reqs = [(MetricKind.POD_CPU_USAGE, {"pod": p}) for p in pods]
        batch = mc.aggregate_batch(reqs, 0.0, 100.0,
                                   [A.AVG, A.P50, A.P90, A.LAST, A.COUNT])
        for (kind, labels), res in zip(reqs, batch):
            for a in (A.AVG, A.P50, A.P90, A.LAST, A.COUNT):
                expect = mc.aggregate(kind, labels, 0.0, 100.0, a)
                assert res[a] == pytest.approx(expect), (labels, a)

    def test_batch_empty_series(self):
        mc = MetricCache()
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "a"}, 1.0, 1.0)
        batch = mc.aggregate_batch(
            [(MetricKind.POD_CPU_USAGE, {"pod": "a"}),
             (MetricKind.POD_CPU_USAGE, {"pod": "ghost"})],
            0.0, 10.0, [A.AVG],
        )
        assert batch[0][A.AVG] == 1.0 and batch[1][A.AVG] is None

    def test_kv_storage(self):
        mc = MetricCache()
        mc.set("node_cpu_info", {"cores": 8})
        assert mc.get("node_cpu_info")["cores"] == 8
        assert mc.get("missing") is None

    def test_gc_drops_stale_series(self):
        mc = MetricCache(retention_seconds=60)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "old"}, 10.0, 1.0)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "new"}, 100.0, 1.0)
        assert mc.gc(now=120.0) == 1
        assert mc.aggregate(
            MetricKind.POD_CPU_USAGE, {"pod": "new"}, agg=A.LAST) == 1.0
        assert mc.aggregate(
            MetricKind.POD_CPU_USAGE, {"pod": "old"}, agg=A.LAST) is None


# -- collectors fixtures -----------------------------------------------------


def write_proc_stat(proc, busy, idle=1000):
    # user nice system idle iowait irq softirq steal
    os.makedirs(proc, exist_ok=True)
    with open(os.path.join(proc, "stat"), "w") as f:
        f.write(f"cpu  {busy} 0 0 {idle} 0 0 0 0 0 0\n")
        f.write("cpu0 0 0 0 0 0 0 0 0 0 0\n")


def write_meminfo(proc, total_kb, avail_kb):
    with open(os.path.join(proc, "meminfo"), "w") as f:
        f.write(f"MemTotal: {total_kb} kB\nMemFree: 0 kB\n"
                f"MemAvailable: {avail_kb} kB\n")


def write_pod_cgroup(cfg, pod_dir, cpu_ns, mem_bytes):
    ensure_cgroup_dir(pod_dir, cfg)
    from koordinator_tpu.koordlet.system.cgroup import (
        CPU_ACCT_USAGE,
        MEMORY_USAGE,
    )
    CPU_ACCT_USAGE.write(pod_dir, str(cpu_ns), cfg)
    MEMORY_USAGE.write(pod_dir, str(mem_bytes), cfg)


class StaticPods:
    def __init__(self, pods):
        self.pods = pods

    def running_pods(self):
        return self.pods


@pytest.fixture
def env(tmp_path):
    cfg = SystemConfig(
        cgroup_root=str(tmp_path / "cgroup"),
        proc_root=str(tmp_path / "proc"),
    )
    write_proc_stat(cfg.proc_root, busy=0)
    write_meminfo(cfg.proc_root, total_kb=16 * 1024 * 1024,
                  avail_kb=8 * 1024 * 1024)
    mc = MetricCache()
    return cfg, mc


class TestCollectors:
    def test_node_cpu_rate_and_memory(self, env):
        cfg, mc = env
        ctx = CollectorContext(metric_cache=mc, system_config=cfg)
        c = NodeResourceCollector()
        c.setup(ctx)
        c.collect(0.0)   # first tick primes the counter
        assert mc.aggregate(MetricKind.NODE_CPU_USAGE) is None
        # +200 busy jiffies over 1s at USER_HZ=100 -> 2 cores -> 2000 mCPU
        write_proc_stat(cfg.proc_root, busy=200)
        c.collect(1.0)
        assert mc.aggregate(
            MetricKind.NODE_CPU_USAGE, agg=A.LAST) == pytest.approx(2000.0)
        # memory: 16GiB total - 8GiB avail = 8192 MiB
        assert mc.aggregate(
            MetricKind.NODE_MEMORY_USAGE, agg=A.LAST
        ) == pytest.approx(8192.0)

    def test_pod_usage_and_sys_residual(self, env):
        cfg, mc = env
        pods = [
            PodMeta("be-1", "kubepods/besteffort/be-1", QoSClass.BE),
            PodMeta("ls-1", "kubepods/burstable/ls-1", QoSClass.LS),
        ]
        write_pod_cgroup(cfg, pods[0].cgroup_dir, 0, 512 * 1024 * 1024)
        write_pod_cgroup(cfg, pods[1].cgroup_dir, 0, 1024 * 1024 * 1024)
        ctx = CollectorContext(
            metric_cache=mc, system_config=cfg, pod_provider=StaticPods(pods)
        )
        adv = MetricsAdvisor(
            ctx,
            [NodeResourceCollector(), PodResourceCollector(),
             BEResourceCollector(), SysResourceCollector()],
        )
        adv.collect_all(0.0)
        # advance counters: node 3 cores, be pod 0.5 core, ls pod 1 core
        write_proc_stat(cfg.proc_root, busy=300)
        write_pod_cgroup(cfg, pods[0].cgroup_dir, int(0.5e9),
                         512 * 1024 * 1024)
        write_pod_cgroup(cfg, pods[1].cgroup_dir, int(1.0e9),
                         1024 * 1024 * 1024)
        adv.collect_all(1.0)

        last = lambda k, l=None: mc.aggregate(k, l, agg=A.LAST)
        assert last(MetricKind.POD_CPU_USAGE, {"pod": "be-1"}) == pytest.approx(500.0)
        assert last(MetricKind.POD_MEMORY_USAGE, {"pod": "ls-1"}) == pytest.approx(1024.0)
        assert last(MetricKind.BE_CPU_USAGE) == pytest.approx(500.0)
        # system residual: 3000 - 1500 = 1500 mCPU
        assert last(MetricKind.SYS_CPU_USAGE) == pytest.approx(1500.0)

    def test_pod_restart_counter_reset_clamped(self, env):
        cfg, mc = env
        pod = PodMeta("p1", "kubepods/p1", QoSClass.LS)
        write_pod_cgroup(cfg, pod.cgroup_dir, int(5e9), 1)
        ctx = CollectorContext(
            metric_cache=mc, system_config=cfg,
            pod_provider=StaticPods([pod]),
        )
        c = PodResourceCollector()
        c.setup(ctx)
        c.collect(0.0)
        # counter went backwards (container restart): rate clamps to 0
        write_pod_cgroup(cfg, pod.cgroup_dir, int(1e9), 1)
        c.collect(1.0)
        assert mc.aggregate(
            MetricKind.POD_CPU_USAGE, {"pod": "p1"}, agg=A.LAST) == 0.0

    def test_psi(self, env):
        cfg, mc = env
        pdir = os.path.join(cfg.proc_root, "pressure")
        os.makedirs(pdir)
        with open(os.path.join(pdir, "cpu"), "w") as f:
            f.write("some avg10=1.50 avg60=0.80 avg300=0.30 total=100\n")
        with open(os.path.join(pdir, "memory"), "w") as f:
            f.write("some avg10=2.00 avg60=0 avg300=0 total=0\n"
                    "full avg10=0.75 avg60=0 avg300=0 total=0\n")
        with open(os.path.join(pdir, "io"), "w") as f:
            f.write("some avg10=0.10 avg60=0 avg300=0 total=0\n")
        c = PSICollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg))
        assert c.enabled()
        c.collect(1.0)
        assert mc.aggregate(
            MetricKind.PSI_CPU_SOME_AVG10, agg=A.LAST) == 1.50
        assert mc.aggregate(
            MetricKind.PSI_MEM_FULL_AVG10, agg=A.LAST) == 0.75

    def test_advisor_tick_respects_interval(self, env):
        cfg, mc = env
        ctx = CollectorContext(metric_cache=mc, system_config=cfg)
        c = NodeResourceCollector()
        adv = MetricsAdvisor(ctx, [c], interval_seconds=10.0)
        adv.tick(0.0)
        write_proc_stat(cfg.proc_root, busy=100)
        adv.tick(5.0)   # too soon: no collection
        adv.tick(10.0)  # 1 core over 10s
        assert mc.aggregate(
            MetricKind.NODE_CPU_USAGE, agg=A.LAST) == pytest.approx(100.0)


class TestPersistence:
    def test_save_load_round_trip(self, tmp_path):
        """§5.4: the TSDB survives restarts (reference keeps it on
        disk) — aggregates over the restored cache match the original."""
        from koordinator_tpu.koordlet.metriccache import MetricCache

        mc = MetricCache()
        for t in range(20):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 100.0 + t)
            mc.append(MetricKind.POD_CPU_USAGE, {"pod": "u1"},
                      float(t), 50.0 + t)
        path = str(tmp_path / "tsdb.npz")
        mc.save(path)

        fresh = MetricCache()
        assert fresh.load(path)
        for kind, labels in ((MetricKind.NODE_CPU_USAGE, None),
                             (MetricKind.POD_CPU_USAGE, {"pod": "u1"})):
            for agg in (A.AVG, A.P90, A.LAST, A.COUNT):
                assert fresh.aggregate(kind, labels, agg=agg) == \
                    mc.aggregate(kind, labels, agg=agg)

    def test_load_missing_or_corrupt(self, tmp_path):
        from koordinator_tpu.koordlet.metriccache import MetricCache

        mc = MetricCache()
        assert not mc.load(str(tmp_path / "absent.npz"))
        bad = tmp_path / "bad.npz"
        bad.write_bytes(b"not an npz")
        assert not mc.load(str(bad))

    def test_daemon_checkpoint_restart(self, tmp_path):
        """A rebuilt daemon resumes with the previous TSDB + prediction
        state from --checkpoint-dir."""
        from koordinator_tpu.cmd.koordlet import (
            KoordletConfig,
            build_koordlet,
        )

        config = KoordletConfig(
            cgroup_root=str(tmp_path / "cg"),
            proc_root=str(tmp_path / "proc"),
            checkpoint_dir=str(tmp_path / "ckpt"),
        )
        d1 = build_koordlet(config)
        for t in range(10):
            d1.metric_cache.append(
                MetricKind.NODE_CPU_USAGE, None, float(t), 500.0)
            d1.predict_server.update("pod/u1", 700.0, 900.0, float(t))
        d1.checkpoint()

        d2 = build_koordlet(config)  # the restart
        assert d2.metric_cache.aggregate(
            MetricKind.NODE_CPU_USAGE, agg=A.AVG) == 500.0
        peak = d2.predict_server.peak("pod/u1")
        assert peak["cpu"] is not None and peak["cpu"] >= 700.0
