"""Differential: LowNodeLoad plugin vs the scalar rebalance oracle.

The oracle (oracle/rebalance.py) is an independent scalar transliteration
of the reference Balance pass (low_node_load.go:134-326 +
utilization_util.go + utils/sorter). These tests drive both over
randomized clusters — priority/QoS/cost diversity, pods missing from the
metric, stale metrics, unschedulable nodes, deviation thresholds,
multi-sweep debounce streaks — and require the ORDERED eviction sequence
to match exactly.
"""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.descheduler import (
    LowNodeLoad,
    LowNodeLoadArgs,
    NodePool,
)
from koordinator_tpu.descheduler.framework import Evictor
from koordinator_tpu.oracle.rebalance import RebalanceOracle

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY

_QOS_CHOICES = [QoSClass.NONE, QoSClass.LS, QoSClass.LSR, QoSClass.BE]


class RecordingEvictor(Evictor):
    """Approves every eviction, mutates nothing: the plugin's internal
    accounting is what's under test, and the snapshot must stay intact
    for the oracle run."""

    def _do_evict(self, snapshot, pod, reason) -> bool:
        return True

    @property
    def sequence(self):
        return [(p.node_name, p.uid) for p in self.evicted]


def random_cluster(rng, n_nodes=24, n_pods=120, metric_gap=0.2,
                   stale_frac=0.1, unsched_frac=0.1):
    nodes, pods, metrics = [], [], {}
    for i in range(n_nodes):
        nodes.append(NodeSpec(
            name=f"n{i}",
            allocatable={CPU: int(rng.integers(8000, 64000)),
                         MEM: int(rng.integers(16384, 131072))},
            unschedulable=bool(rng.random() < unsched_frac),
        ))
    for j in range(n_pods):
        node = nodes[int(rng.integers(n_nodes))]
        annotations = {}
        if rng.random() < 0.3:
            annotations["controller.kubernetes.io/pod-deletion-cost"] = str(
                int(rng.integers(-5, 5))
            )
        if rng.random() < 0.3:
            annotations["koordinator.sh/eviction-cost"] = str(
                int(rng.integers(-5, 5))
            )
        req_cpu = int(rng.integers(100, 3000))
        shape = rng.random()
        if shape < 0.3:
            requests = {CPU: req_cpu, MEM: 512}
            limits = dict(requests)          # guaranteed
        elif shape < 0.45:
            requests = {CPU: req_cpu}
            limits = {CPU: req_cpu}          # cpu-only: burstable, NOT
            #                                  guaranteed (memory unlimited)
        elif shape < 0.7:
            requests = {CPU: req_cpu, MEM: 512}
            limits = {CPU: req_cpu * 2}      # burstable
        else:
            requests = {CPU: req_cpu, MEM: 512}
            limits = {}                      # burstable (has requests)
        pods.append(PodSpec(
            name=f"p{j}",
            node_name=node.name,
            requests=requests,
            limits=limits,
            qos=_QOS_CHOICES[int(rng.integers(len(_QOS_CHOICES)))],
            priority=int(rng.integers(0, 3) * 1000),
            is_daemonset=bool(rng.random() < 0.1),
            creation_time=float(rng.integers(0, 50)),
            annotations=annotations,
        ))
    for i, node in enumerate(nodes):
        pod_usages = {}
        for pod in pods:
            if pod.node_name == node.name and rng.random() > metric_gap:
                pod_usages[pod.uid] = {
                    CPU: int(rng.integers(50, 4000)),
                    MEM: int(rng.integers(64, 2048)),
                }
        cap = node.allocatable
        metrics[node.name] = NodeMetric(
            node_name=node.name,
            node_usage={
                CPU: int(rng.integers(0, int(cap[CPU] * 1.1))),
                MEM: int(rng.integers(0, int(cap[MEM] * 1.1))),
            },
            pod_usages=pod_usages,
            update_time=(
                -1000.0 if rng.random() < stale_frac else 100.0
            ),
        )
    return ClusterSnapshot(nodes=nodes, pods=pods, node_metrics=metrics,
                           now=120.0)


def run_both(args, snapshot, sweeps=1, mutate=None, rng=None):
    plugin = LowNodeLoad(args)
    oracle = RebalanceOracle(args)
    for s in range(sweeps):
        if s and mutate is not None:
            mutate(snapshot, rng)
        evictor = RecordingEvictor()
        plugin.balance(snapshot, evictor)
        got = evictor.sequence
        want = oracle.sweep(snapshot)
        assert got == want, (
            f"sweep {s}: plugin {got[:8]}... != oracle {want[:8]}... "
            f"({len(got)} vs {len(want)} evictions)"
        )
    return len(want)


@pytest.mark.parametrize("seed", range(12))
def test_random_cluster_identity(seed):
    rng = np.random.default_rng(seed)
    snapshot = random_cluster(rng)
    args = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: int(rng.integers(20, 50)),
                        MEM: int(rng.integers(20, 60))},
        high_thresholds={CPU: int(rng.integers(55, 80)),
                         MEM: int(rng.integers(65, 90))},
        resource_weights={CPU: int(rng.integers(1, 4)),
                          MEM: int(rng.integers(1, 4))},
    )])
    run_both(args, snapshot)


def test_some_seed_actually_evicts():
    """Guard against the suite passing vacuously: across the seeds at
    least one cluster must produce a non-empty eviction sequence."""
    total = 0
    for seed in range(12):
        rng = np.random.default_rng(seed)
        snapshot = random_cluster(rng)
        args = LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: int(rng.integers(20, 50)),
                            MEM: int(rng.integers(20, 60))},
            high_thresholds={CPU: int(rng.integers(55, 80)),
                             MEM: int(rng.integers(65, 90))},
            resource_weights={CPU: int(rng.integers(1, 4)),
                              MEM: int(rng.integers(1, 4))},
        )])
        evictor = RecordingEvictor()
        LowNodeLoad(args).balance(snapshot, evictor)
        total += len(evictor.evicted)
    assert total > 0


@pytest.mark.parametrize("seed", range(6))
def test_deviation_mode_identity(seed):
    rng = np.random.default_rng(100 + seed)
    snapshot = random_cluster(rng, stale_frac=0.0)
    args = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: 10, MEM: 10},
        high_thresholds={CPU: 10, MEM: 10},
        use_deviation_thresholds=True,
    )])
    run_both(args, snapshot)


@pytest.mark.parametrize("seed", range(4))
def test_deviation_mode_asymmetric_thresholds_identity(seed):
    """Asymmetric deviation configs hit the reference's
    getNodeThresholds:100-102 quirk (the capacity special case keys
    BOTH sides off the LOW percent): low-only means 'above pool average
    is overutilized', high-only is inert. Plugin and oracle must agree
    on both."""
    rng = np.random.default_rng(300 + seed)
    snapshot = random_cluster(rng, stale_frac=0.0)
    low_only = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: 15},
        high_thresholds={},
        use_deviation_thresholds=True,
    )])
    run_both(low_only, snapshot)
    # high-only: the quirk resolves BOTH sides to full capacity (the
    # explicit high percent is ignored; only usage > capacity triggers)
    high_only = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={},
        high_thresholds={CPU: 10, MEM: 10},
        use_deviation_thresholds=True,
    )])
    run_both(high_only, snapshot)


@pytest.mark.parametrize("seed", range(6))
def test_multi_sweep_debounce_identity(seed):
    """consecutive_abnormalities=2: eviction needs a streak; detector
    state must evolve identically across sweeps with drifting usage."""
    rng = np.random.default_rng(200 + seed)
    snapshot = random_cluster(rng, stale_frac=0.0)
    args = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: 30, MEM: 30},
        high_thresholds={CPU: 60, MEM: 70},
        consecutive_abnormalities=2,
    )])

    def drift(snap, r):
        for metric in snap.node_metrics.values():
            cap_cpu = next(
                n.allocatable[CPU] for n in snap.nodes
                if n.name == metric.node_name
            )
            metric.node_usage[CPU] = int(r.integers(0, int(cap_cpu * 1.1)))

    run_both(args, snapshot, sweeps=4, mutate=drift, rng=rng)


@pytest.mark.parametrize("seed", range(4))
def test_dry_run_proposes_exactly_the_live_sequence(seed):
    """dry_run computes the same ordered victim sequence a live run
    would evict (reference evictPods' dry-run branch keeps the sweep
    accounting identical), touching the evictor not at all."""
    rng = np.random.default_rng(400 + seed)
    snapshot = random_cluster(rng, stale_frac=0.0)

    def args(dry):
        return LowNodeLoadArgs(dry_run=dry, node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 60, MEM: 75},
        )])

    live = RecordingEvictor()
    LowNodeLoad(args(False)).balance(snapshot, live)

    dry_evictor = RecordingEvictor()
    plugin = LowNodeLoad(args(True))
    plugin.balance(snapshot, dry_evictor)
    assert dry_evictor.evicted == []            # nothing actually evicted
    got = [(p.node_name, p.uid) for p in plugin.last_proposals]
    assert got == live.sequence
    # and the dry proposals equal the oracle's live sweep too
    assert got == RebalanceOracle(args(False)).sweep(snapshot)


def test_multi_pool_processed_exclusion():
    """A node claimed as a source by pool 1 must not be reprocessed by
    pool 2 (processedNodes threading)."""
    rng = np.random.default_rng(7)
    snapshot = random_cluster(rng, stale_frac=0.0, unsched_frac=0.0)
    args = LowNodeLoadArgs(node_pools=[
        NodePool(name="a", low_thresholds={CPU: 40},
                 high_thresholds={CPU: 60}),
        NodePool(name="b", low_thresholds={CPU: 30, MEM: 30},
                 high_thresholds={CPU: 50, MEM: 70}),
    ])
    run_both(args, snapshot)


def test_number_of_nodes_gate_identity():
    rng = np.random.default_rng(11)
    snapshot = random_cluster(rng, stale_frac=0.0)
    args = LowNodeLoadArgs(
        number_of_nodes=5,
        node_pools=[NodePool(
            low_thresholds={CPU: 35, MEM: 35},
            high_thresholds={CPU: 60, MEM: 75},
        )],
    )
    run_both(args, snapshot)


# -- defrag (headroom repack) parity: device plan vs scalar oracle ----------


@pytest.mark.parametrize("seed", range(8))
def test_defrag_plan_identity(seed):
    """The device headroom-repack planner (ops/preempt.headroom_repack)
    must match the scalar oracle (scheduler/preemption.plan_defrag)
    exactly — chosen node, drain set AND least-important-first order —
    over the same randomized clusters the rebalance differential uses."""
    from koordinator_tpu.apis.types import resources_to_vector
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.scheduler.preemption import plan_defrag
    from koordinator_tpu.state.cluster import lower_nodes

    rng = np.random.default_rng(500 + seed)
    snapshot = random_cluster(rng)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    for k in range(4):
        target = resources_to_vector({
            CPU: int(rng.integers(8000, 48000)),
            MEM: int(rng.integers(8192, 65536)),
        })
        max_prio = int(rng.integers(500, 2500))
        got = model.plan_defrag_device(arrays, resident, target, max_prio)
        plan = plan_defrag(snapshot, target, max_prio, arrays=arrays)
        want = None if plan is None else (plan[0], [v.uid for v in plan[1]])
        assert got == want, (
            f"seed {seed} target {k}: device {got} != oracle {want}"
        )


def test_threshold_float64_truncation_identity():
    """The documented float64 rounding case (ops/rebalance.py): a 29%
    threshold on a power-of-ten capacity resolves through
    ``int64(float64(29) * 0.01 * cap)`` — 28999…, NOT the integer
    ``29 * cap // 100`` — so a node at exactly 29% must classify as
    OVER the low threshold on both plugin and oracle (and the eviction
    sequences stay identical either way)."""
    nodes = [
        NodeSpec(name="hot", allocatable={CPU: 100000, MEM: 131072}),
        NodeSpec(name="cold", allocatable={CPU: 100000, MEM: 131072}),
    ]
    pods = [
        PodSpec(name=f"p{j}", node_name="hot",
                requests={CPU: 2000, MEM: 512}, qos=QoSClass.BE,
                creation_time=float(j))
        for j in range(4)
    ]
    metrics = {
        # 29000/100000 = exactly 29%: float64 truncation puts the
        # resolved low-threshold QUANTITY at 28999, so 29000 is above it
        "hot": NodeMetric(
            node_name="hot", node_usage={CPU: 29000, MEM: 0},
            pod_usages={p.uid: {CPU: 5000, MEM: 128} for p in pods},
            update_time=100.0,
        ),
        "cold": NodeMetric(node_name="cold", node_usage={CPU: 0, MEM: 0},
                           update_time=100.0),
    }
    snapshot = ClusterSnapshot(nodes=nodes, pods=pods,
                               node_metrics=metrics, now=120.0)
    args = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: 29},
        high_thresholds={CPU: 90},
    )])
    evictor = RecordingEvictor()
    LowNodeLoad(args).balance(snapshot, evictor)
    want = RebalanceOracle(args).sweep(snapshot)
    assert evictor.sequence == want
    # the truncation made "hot" properly utilized (29000 > 28999), so
    # nothing is over the high threshold and nothing evicts — but BOTH
    # implementations must have made the same call
    assert evictor.sequence == []
