"""ElasticQuota preemption + multi-tree + min-scaling + profile controller
(VERDICT round-1 item 4).

Reference: pkg/scheduler/plugins/elasticquota/preempt.go (canPreempt,
SelectVictimsOnNode), quota_handler.go (per-tree managers),
core/scale_minquota_when_over_root_res.go (proportional min scaling),
pkg/quota-controller/profile/profile_controller.go (profiles → trees).
"""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
)
from koordinator_tpu.quota.core import GroupQuotaManager
from koordinator_tpu.quota.profile import QuotaProfile, QuotaProfileController
from koordinator_tpu.quota.trees import QuotaTreeRegistry
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.preemption import can_preempt, find_preemption


def _mk(n_nodes=1, cpu=10000, mem=32768):
    s = Scheduler(
        cluster_total={R.CPU: max(n_nodes, 1) * cpu, R.MEMORY: max(n_nodes, 1) * mem}
    )
    for i in range(n_nodes):
        s.add_node(
            NodeSpec(name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem})
        )
        s.update_node_metric(
            NodeMetric(node_name=f"n{i}", node_usage={}, update_time=99.0)
        )
    return s


class TestCanPreempt:
    def test_same_quota_lower_priority_only(self):
        pod = PodSpec(name="p", quota="a", priority=100)
        assert can_preempt(pod, PodSpec(name="v1", quota="a", priority=50))
        # different quota group: never (preempt.go:293)
        assert not can_preempt(pod, PodSpec(name="v2", quota="b", priority=50))
        # equal or higher priority: never
        assert not can_preempt(pod, PodSpec(name="v3", quota="a", priority=100))
        # non-preemptible victim: never (preempt.go:277)
        assert not can_preempt(
            pod, PodSpec(name="v4", quota="a", priority=50, preemptible=False)
        )


class TestIncrementalPreemption:
    def test_nominates_and_evicts_lower_priority_same_quota(self):
        s = _mk(cpu=10000)
        s.update_quota(QuotaSpec(name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        victim = PodSpec(name="low", quota="a", priority=10, requests={R.CPU: 8000})
        s.add_pod(victim)
        assert s.schedule_one("default/low", now=100.0).status == "bound"

        high = PodSpec(name="high", quota="a", priority=100, requests={R.CPU: 8000})
        s.add_pod(high)
        out = s.schedule_one("default/high", now=101.0)
        assert out.status == "nominated"
        assert out.node == "n0"
        assert out.victims == ["default/low"]
        # the victim was evicted; the preemptor binds next attempt
        assert "default/low" not in s.cache.pods
        assert s.schedule_one("default/high", now=102.0).status == "bound"

    def test_no_preemption_across_quotas(self):
        s = _mk(cpu=10000)
        s.update_quota(QuotaSpec(name="a", min={R.CPU: 5000}, max={R.CPU: 10000}))
        s.update_quota(QuotaSpec(name="b", min={R.CPU: 5000}, max={R.CPU: 10000}))
        s.add_pod(PodSpec(name="other", quota="b", priority=10, requests={R.CPU: 8000}))
        assert s.schedule_one("default/other", now=100.0).status == "bound"
        s.add_pod(PodSpec(name="high", quota="a", priority=100, requests={R.CPU: 8000}))
        out = s.schedule_one("default/high", now=101.0)
        assert out.status == "unschedulable"
        assert "default/other" in s.cache.pods

    def test_reprieve_keeps_unneeded_victims(self):
        """Quota has headroom but the node is full: only as many victims
        as needed are evicted; the most important candidates are reprieved
        first (preempt.go:166-215)."""
        s = _mk(n_nodes=2, cpu=10000)
        s.update_quota(QuotaSpec(name="a", min={R.CPU: 20000}, max={R.CPU: 20000}))
        for i, prio in enumerate((30, 20)):
            pod = PodSpec(name=f"v{i}", quota="a", priority=prio,
                          requests={R.CPU: 4000}, node_name="n0")
            # add_pod accounts an already-assigned pod's quota used
            # (restart/standby catch-up contract) — no manual Reserve
            s.add_pod(pod)
        # n0 has 2000 free; the preemptor needs 4000 there: ONE victim
        # suffices. Fill n1 so it isn't a free alternative.
        filler = PodSpec(name="filler", priority=1000, preemptible=False,
                         requests={R.CPU: 9000}, node_name="n1")
        s.add_pod(filler)
        s.add_pod(PodSpec(name="high", quota="a", priority=100,
                          requests={R.CPU: 4000}))
        out = s.schedule_one("default/high", now=101.0)
        assert out.status == "nominated"
        assert out.node == "n0"
        # the higher-priority candidate (v0, prio 30) is reprieved; the
        # least important (v1, prio 20) is the victim
        assert out.victims == ["default/v1"]
        assert "default/v0" in s.cache.pods

    def test_over_runtime_quota_evicts_all_candidates(self):
        """When the quota is over its runtime even the fit-reprievable
        candidates stay victims — the reference checks the static
        PostFilter-snapshot used (preempt.go:191-199)."""
        s = _mk(cpu=10000)
        s.update_quota(QuotaSpec(name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        for i, prio in enumerate((30, 20)):
            s.add_pod(
                PodSpec(name=f"v{i}", quota="a", priority=prio,
                        requests={R.CPU: 4000})
            )
            s.schedule_one(f"default/v{i}", now=100.0)
        s.add_pod(PodSpec(name="high", quota="a", priority=100,
                          requests={R.CPU: 4000}))
        out = s.schedule_one("default/high", now=101.0)
        assert out.status == "nominated"
        assert set(out.victims) == {"default/v0", "default/v1"}

    def test_batched_round_preempts_unplaced(self):
        s = _mk(cpu=10000)
        s.update_quota(QuotaSpec(name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        s.add_pod(PodSpec(name="low", quota="a", priority=10, requests={R.CPU: 8000}))
        s.schedule_pending(now=100.0)
        assert s.cache.pods["default/low"].node_name == "n0"

        s.add_pod(PodSpec(name="high", quota="a", priority=100, requests={R.CPU: 8000}))
        out = s.schedule_pending(now=101.0)
        assert out["default/high"] is None
        assert out.nominations == {"default/high": "n0"}
        assert "default/low" not in s.cache.pods
        # next round the preemptor binds
        out2 = s.schedule_pending(now=102.0)
        assert out2["default/high"] == "n0"


class TestMultiTree:
    def test_trees_water_fill_independently(self):
        reg = QuotaTreeRegistry(cluster_total={R.CPU: 100000})
        reg.update_quota(
            QuotaSpec(name="root-a", tree_id="ta", is_parent=True,
                      min={R.CPU: 0}, max={R.CPU: 10**9},
                      total_resource={R.CPU: 10000})
        )
        reg.update_quota(
            QuotaSpec(name="a1", parent="root-a", tree_id="ta",
                      min={R.CPU: 2000}, max={R.CPU: 10000})
        )
        reg.update_quota(
            QuotaSpec(name="b1", tree_id="",
                      min={R.CPU: 2000}, max={R.CPU: 100000})
        )
        mgr_a = reg.manager_for_quota("a1")
        mgr_b = reg.manager_for_quota("b1")
        assert mgr_a is not mgr_b
        # tree A's water-filling is bounded by its pool total (10000),
        # not the cluster total
        mgr_a.add_request("a1", resources_to_vec({R.CPU: 50000}))
        rt = mgr_a.refresh_runtime("a1")
        assert rt[int(R.CPU)] <= 10000
        mgr_b.add_request("b1", resources_to_vec({R.CPU: 50000}))
        rt_b = mgr_b.refresh_runtime("b1")
        assert rt_b[int(R.CPU)] == 50000  # cluster tree has room

    def test_batched_path_uses_tree_totals(self):
        s = _mk(n_nodes=2, cpu=10000)
        # tree-a pool total is only 6000 despite 20000 of cluster capacity
        s.update_quota(
            QuotaSpec(name="pool", tree_id="ta", is_parent=True,
                      min={R.CPU: 6000}, max={R.CPU: 10**9},
                      total_resource={R.CPU: 6000, R.MEMORY: 65536})
        )
        s.update_quota(
            QuotaSpec(name="team", parent="pool", tree_id="ta",
                      min={R.CPU: 0}, max={R.CPU: 10**9})
        )
        for i in range(3):
            s.add_pod(PodSpec(name=f"p{i}", quota="team", requests={R.CPU: 3000}))
        out = s.schedule_pending(now=100.0)
        placed = [u for u, n in out.items() if n is not None]
        # runtime = tree total 6000 -> exactly two 3000 pods admitted
        assert len(placed) == 2


def resources_to_vec(res):
    from koordinator_tpu.apis.types import resources_to_vector

    return resources_to_vector(res)


class TestMinScaling:
    def test_scaled_proportionally_when_oversubscribed(self):
        """scale_minquota_when_over_root_res.go: enable-scale children
        share what remains after disable-scale children's mins."""
        mgr = GroupQuotaManager(cluster_total={R.CPU: 10000})
        mgr.update_quota(
            QuotaSpec(name="fixed", min={R.CPU: 4000}, max={R.CPU: 10000},
                      allow_lent_resource=False)
        )
        mgr.update_quota(
            QuotaSpec(name="s1", min={R.CPU: 6000}, max={R.CPU: 10000},
                      allow_lent_resource=False, enable_min_quota_scale=True)
        )
        mgr.update_quota(
            QuotaSpec(name="s2", min={R.CPU: 3000}, max={R.CPU: 10000},
                      allow_lent_resource=False, enable_min_quota_scale=True)
        )
        # sum of mins 13000 > total 10000; disable-scale 'fixed' keeps
        # 4000; s1/s2 share 6000 proportionally to 6000:3000 -> 4000/2000
        assert mgr.refresh_runtime("fixed")[int(R.CPU)] == 4000
        assert mgr.refresh_runtime("s1")[int(R.CPU)] == 4000
        assert mgr.refresh_runtime("s2")[int(R.CPU)] == 2000

    def test_no_scaling_when_total_sufficient(self):
        mgr = GroupQuotaManager(cluster_total={R.CPU: 20000})
        mgr.update_quota(
            QuotaSpec(name="s1", min={R.CPU: 6000}, max={R.CPU: 20000},
                      allow_lent_resource=False, enable_min_quota_scale=True)
        )
        mgr.update_quota(
            QuotaSpec(name="fixed", min={R.CPU: 4000}, max={R.CPU: 20000},
                      allow_lent_resource=False)
        )
        assert mgr.refresh_runtime("s1")[int(R.CPU)] == 6000


class TestProfileController:
    def test_profile_materialises_tree_root(self):
        s = _mk(n_nodes=0)
        s.add_node(NodeSpec(name="gpu-0", allocatable={R.CPU: 8000},
                            labels={"pool": "gpu"}))
        s.add_node(NodeSpec(name="gpu-1", allocatable={R.CPU: 8000},
                            labels={"pool": "gpu"}))
        s.add_node(NodeSpec(name="cpu-0", allocatable={R.CPU: 64000},
                            labels={"pool": "cpu"}))
        c = QuotaProfileController(s)
        c.update_profile(
            QuotaProfile(name="gpu-profile", quota_name="gpu-pool",
                         node_selector={"pool": "gpu"})
        )
        c.sync()
        spec = s.cache.quotas["gpu-pool"]
        assert spec.min[R.CPU] == 16000          # Σ selected allocatable
        assert spec.total_resource[R.CPU] == 16000
        assert spec.tree_id != ""
        # the tree's manager got the pool total
        mgr = s.quota_registry.manager_for_quota("gpu-pool")
        assert mgr.cluster_total[int(R.CPU)] == 16000

        # node pool grows -> resync updates the root min/total
        s.add_node(NodeSpec(name="gpu-2", allocatable={R.CPU: 8000},
                            labels={"pool": "gpu"}))
        c.sync()
        assert s.cache.quotas["gpu-pool"].min[R.CPU] == 24000


class TestPreemptionBackends:
    """The joint place+evict device path (ops/preempt.py) against the
    host oracle walk, through the real batched round: all three
    ``preemption_backend`` modes must produce identical nominations and
    evictions — "verify" additionally asserts per-pod victim ORDER
    bit-parity inline (scheduler/scheduler.py raises on divergence)."""

    def _storm(self, backend, seed=3):
        from koordinator_tpu.testing.chaos import preemption_storm

        nodes, residents, arrivals = preemption_storm(
            seed=seed, n_nodes=6, residents_per_node=3, n_arrivals=4,
            quota="q",
        )
        cpu = sum(n.allocatable[R.CPU] for n in nodes)
        mem = sum(n.allocatable[R.MEMORY] for n in nodes)
        s = Scheduler(cluster_total={R.CPU: cpu, R.MEMORY: mem},
                      preemption_backend=backend)
        s.update_quota(QuotaSpec(name="q", min={R.CPU: cpu, R.MEMORY: mem},
                                 max={R.CPU: cpu, R.MEMORY: mem}))
        for node in nodes:
            s.add_node(node)
        for pod in residents + arrivals:
            s.add_pod(pod)
        out = s.schedule_pending(now=100.0)
        return (
            dict(getattr(out, "nominations", None) or {}),
            sorted(uid for uid in s.cache.pods),
        )

    def test_device_host_verify_rounds_identical(self):
        host = self._storm("host")
        device = self._storm("device")
        verify = self._storm("verify")
        assert host == device == verify
        assert host[0], "storm produced no nominations"

    def test_quota_over_runtime_round_identical(self):
        """A quota pinned at its usage: the no-reprieve edge through the
        full round, host vs device."""

        def run(backend):
            s = _mk(n_nodes=2, cpu=12000)
            s.update_quota(QuotaSpec(
                name="a", min={R.CPU: 100}, max={R.CPU: 100000},
            ))
            for i, prio in enumerate((40, 30, 20)):
                s.add_pod(PodSpec(
                    name=f"v{i}", quota="a", priority=prio,
                    requests={R.CPU: 4000}, node_name="n0",
                    assign_time=float(i),
                ))
            s.preemption_backend = backend
            s.add_pod(PodSpec(name="high", quota="a", priority=900,
                              requests={R.CPU: 4000}))
            out = s.schedule_pending(now=101.0)
            return (
                dict(getattr(out, "nominations", None) or {}),
                sorted(s.cache.pods),
            )

        assert run("host") == run("device") == run("verify")
