"""Device-cost observatory tests (ISSUE 8, docs/DESIGN.md §17).

- Compile telemetry: instrumented jit callsites count real XLA
  compilations (cross-checked against the jit cache), a warmed churn
  tick counts ZERO — in agreement with the existing ``xla_compiles``
  log fixture — and the recorded wall/signature land in the ring and
  the prometheus series.
- Cost/memory analysis: present and finite on the CPU backend,
  produced lazily from recorded avals (no live buffers touched).
- Padding-waste gauges: exact against hand-computed bucket math for
  the pod, reservation, dirty-row, and coalesced buffers.
- Profiler windows: ``/debug/profile?rounds=K`` arms exactly one
  window (one trace directory written), the second request
  rate-limits, and the window closes after K rounds.
- Capability gate: an old-jax box degrades to loud skips — analysis
  reports unsupported, profile requests refuse, counters keep working.
- The observatory on vs off is tick-identical (observation only).
- ``tools/bench_diff.py``: a record diffed against itself is clean,
  seeded regressions (throughput drop, lost identity flag, compile
  leak, budget bust) exit nonzero.
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import koordinator_tpu.obs.device as device_mod
from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.wiring import wire_scheduler
from koordinator_tpu.metrics.components import (
    DEVICE_COMPILES,
    DEVICE_PADDING_WASTE,
)
from koordinator_tpu.obs.device import (
    DEVICE_OBS,
    DeviceObservatory,
    device_observatory_supported,
)
from koordinator_tpu.obs.flight import FLIGHT, _default_dump_dir
from koordinator_tpu.scheduler import Scheduler

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_obs():
    DEVICE_OBS.reset()
    DEVICE_OBS.set_enabled(True)
    yield
    DEVICE_OBS.reset()
    DEVICE_OBS.set_enabled(True)


def _wired(n_nodes=8):
    bus = APIServer()
    sched = Scheduler()
    wire_scheduler(bus, sched)
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}", node_usage={CPU: 1000 * (i % 4)},
            update_time=10.0))
    return bus, sched


def _arrive(bus, rng, t, n=12):
    for j in range(n):
        pod = PodSpec(name=f"t{t}p{j}",
                      requests={CPU: int(rng.integers(200, 1200)),
                                MEM: int(rng.integers(128, 1024))})
        bus.apply(Kind.POD, pod.uid, pod)


# -- compile telemetry + analysis --------------------------------------------

def test_smoke_compile_telemetry_and_analysis():
    """The zero→observed path: an instrumented jit records its compile
    (count, wall, shape signature) and the lazy analysis produces
    finite cost/memory numbers on the CPU backend."""
    if not device_observatory_supported():
        pytest.skip("jax build lacks AOT cost/memory analysis")
    import jax
    import jax.numpy as jnp

    before = DEVICE_COMPILES.value({"fn": "obs_probe"})
    probe = DEVICE_OBS.jit("obs_probe", jax.jit(
        lambda x, y: (x @ y).sum(), static_argnums=(), donate_argnums=()
    ))
    x = jnp.ones((32, 16), jnp.float32)
    y = jnp.ones((16, 8), jnp.float32)
    np.asarray(probe(x, y))
    np.asarray(probe(x, y))  # warm call must not count
    st = DEVICE_OBS.status()
    mine = [r for r in st["recent_compiles"] if r["fn"] == "obs_probe"]
    assert len(mine) == 1
    assert mine[0]["compile_s"] > 0
    assert "32x16" in mine[0]["shape"]
    assert DEVICE_COMPILES.value({"fn": "obs_probe"}) == before + 1
    # a distinct shape is a new variant
    np.asarray(probe(jnp.ones((64, 16)), jnp.ones((16, 8))))
    assert DEVICE_COMPILES.value({"fn": "obs_probe"}) == before + 2

    produced = DEVICE_OBS.analyze()
    ours = [a for a in produced if a["fn"] == "obs_probe"]
    assert len(ours) == 2 and all("error" not in a for a in ours)
    for a in ours:
        assert np.isfinite(a["cost"]["flops"]) and a["cost"]["flops"] > 0
        assert np.isfinite(a["cost"]["bytes_accessed"])
        mem = a["memory"]
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "peak_bytes"):
            assert isinstance(mem[key], int) and mem[key] >= 0
        assert mem["peak_bytes"] >= mem["argument_bytes"]
    # memoized: a second analyze() pass has nothing to do
    assert DEVICE_OBS.analyze() == []


def test_solve_variant_analysis_through_scheduler():
    """The real solve path: one scheduled round registers the
    solve_batch variant; its analysis is finite and its avals never
    touched live buffers (the solve already retired)."""
    if not device_observatory_supported():
        pytest.skip("jax build lacks AOT cost/memory analysis")
    bus, sched = _wired()
    _arrive(bus, np.random.default_rng(3), 0)
    sched.schedule_pending(now=20.0)
    produced = DEVICE_OBS.analyze()
    solves = [a for a in produced if a["fn"] == "solve_batch"]
    assert solves, "the model's solve variant must register for analysis"
    assert all("error" not in a for a in solves)
    assert all(a["cost"]["flops"] > 0 for a in solves)


def test_compile_counter_agrees_with_xla_compiles_fixture(xla_compiles):
    """The quantitative form of graftcheck's boolean guard: a WARMED
    churn tick performs zero XLA compiles by the log fixture — and the
    observatory's counters (per-fn AND the process-wide monitoring
    listener) must say exactly the same thing."""
    bus, sched = _wired()
    rng = np.random.default_rng(7)
    for t in range(3):  # warm: staging cache established, scatter built
        _arrive(bus, rng, t)
        sched.schedule_pending(now=20.0 + t)
    xla_compiles.clear()
    mark = DEVICE_OBS.mark()
    _arrive(bus, rng, 99)
    sched.schedule_pending(now=30.0)
    fp = DEVICE_OBS.fingerprint(mark)
    assert xla_compiles == [], "warmed tick recompiled (log fixture)"
    assert fp["compiles"] == 0, "per-fn counter disagrees with fixture"
    assert fp["xla_compiles"] == 0, "monitoring counter disagrees"


def test_compile_counter_survives_cache_clear():
    """A post-``jax.clear_caches`` recompile of a known shape is a real
    compile and must count — the high-water dedup resets when the
    pre-call cache size drops below the mark (review regression)."""
    import jax
    import jax.numpy as jnp

    before = DEVICE_COMPILES.value({"fn": "clear_probe"})
    probe = DEVICE_OBS.jit("clear_probe", jax.jit(
        lambda x: x - 2, static_argnums=(), donate_argnums=()
    ))
    np.asarray(probe(jnp.ones((9,))))
    assert DEVICE_COMPILES.value({"fn": "clear_probe"}) == before + 1
    np.asarray(probe(jnp.ones((9,))))  # warm: no count
    assert DEVICE_COMPILES.value({"fn": "clear_probe"}) == before + 1
    jax.clear_caches()
    np.asarray(probe(jnp.ones((9,))))  # real recompile: counts again
    assert DEVICE_COMPILES.value({"fn": "clear_probe"}) == before + 2


def test_fingerprint_carries_device_costs():
    if not device_observatory_supported():
        pytest.skip("jax build lacks AOT cost/memory analysis")
    mark = DEVICE_OBS.mark()
    # a node count no other test uses: jax shares compiled executables
    # across identical jit instances, so only a genuinely new shape is
    # guaranteed to compile (which is exactly what the counter reports)
    bus, sched = _wired(n_nodes=13)
    _arrive(bus, np.random.default_rng(5), 0)
    sched.schedule_pending(now=20.0)
    fp = DEVICE_OBS.fingerprint(mark)
    assert fp["compiles"] >= 1
    assert fp["flops"] > 0 and fp["peak_bytes"] > 0
    assert 0.0 <= fp["padding_waste_ratio"] < 1.0
    assert fp["live_buffers"] > 0 and fp["live_bytes"] > 0


# -- padding waste -----------------------------------------------------------

def test_padding_waste_gauge_exact_bucket_math():
    """Gauges must equal the hand-computed bucket arithmetic exactly:
    pod bucket (quarter-steps between powers of two, floor 64), resv
    bucket (pow2, floor 8), dirty-row bucket (pow2, floor 8)."""
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import bucket_row_update

    # pod bucket: 70 pods -> power 128, step 16 -> target 80
    assert PlacementModel.pod_bucket(70) == 80
    bus, sched = _wired()
    for j in range(70):
        bus.apply(Kind.POD, f"p{j}", PodSpec(
            name=f"p{j}", requests={CPU: 100, MEM: 64}))
    sched.schedule_pending(now=20.0)
    pad = DEVICE_OBS.status()["padding"]
    assert pad["pod_batch"] == {
        "real": 70, "padded": 80, "waste": 1.0 - 70 / 80,
    }
    assert DEVICE_PADDING_WASTE.value(
        {"buffer": "pod_batch"}) == pytest.approx(1.0 - 70 / 80)

    # dirty rows: 5 dirty -> bucket 8 -> waste 3/8
    idx = np.arange(5, dtype=np.int32)
    rows = {"a": np.ones((5, 2), np.int32)}
    sidx, _ = bucket_row_update(idx, rows)
    assert sidx.shape[0] == 8
    assert DEVICE_OBS.status()["padding"]["dirty_rows"] == {
        "real": 5, "padded": 8, "waste": 1.0 - 5 / 8,
    }

    # resv bucket: 3 reservations -> 8
    DEVICE_OBS.note_padding("resv_table", 3, 8)
    assert DEVICE_PADDING_WASTE.value(
        {"buffer": "resv_table"}) == pytest.approx(5 / 8)


def test_padding_note_disabled_is_noop():
    DEVICE_OBS.set_enabled(False)
    DEVICE_OBS.note_padding("pod_batch", 1, 64)
    assert "pod_batch" not in DEVICE_OBS.status()["padding"]


# -- live buffers ------------------------------------------------------------

def test_live_snapshot_counts_and_owner_attribution():
    import jax.numpy as jnp

    held = jnp.ones((128, 4), jnp.int32)  # noqa: F841 — must stay live
    DEVICE_OBS.register_owner("probe", lambda: 512)
    snap = DEVICE_OBS.live_snapshot()
    assert snap["count"] >= 1
    assert snap["bytes"] >= held.nbytes
    assert snap["owners"]["probe"] == 512


# -- profiler windows --------------------------------------------------------

def test_profile_endpoint_one_dir_and_rate_limit(tmp_path, monkeypatch):
    """ISSUE 8: the profile endpoint writes exactly ONE trace dir for
    one request and rate-limits the second."""
    from koordinator_tpu.utils.debug_http import DebugHTTPServer

    clock = [100.0]
    monkeypatch.setattr(DEVICE_OBS, "_clock", lambda: clock[0])
    DEVICE_OBS.configure(profile_dir=str(tmp_path),
                         profile_min_interval_s=30.0)
    server = DebugHTTPServer(
        device=DEVICE_OBS.debug_payload,
        profile=DEVICE_OBS.request_profile,
    ).start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/profile?rounds=2"
        with urllib.request.urlopen(url) as resp:
            armed = json.loads(resp.read())
        assert armed["armed"] is True and armed["rounds"] == 2
        # second request while armed: refused (429)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url)
        assert err.value.code == 429

        import jax.numpy as jnp

        for _ in range(3):  # K=2 rounds + the closing boundary
            DEVICE_OBS.on_round()
            _ = (jnp.ones((8, 8)) * 2).sum()
        windows = [d for d in os.listdir(tmp_path)
                   if d.startswith("window-")]
        assert len(windows) == 1, "exactly one trace dir per request"
        assert os.listdir(tmp_path / windows[0]), "window dir is empty"
        st = DEVICE_OBS.status()["profile"]
        assert st["armed_rounds"] == 0 and st["active_rounds_left"] == 0

        # window closed, but the rate limit still holds within the
        # interval...
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url)
        assert err.value.code == 429
        # ...and a request past the interval arms again
        clock[0] += 31.0
        with urllib.request.urlopen(url) as resp:
            assert json.loads(resp.read())["armed"] is True
    finally:
        server.stop()
        # drive the second window shut so no trace leaks into later
        # tests
        for _ in range(4):
            DEVICE_OBS.on_round()


def test_profile_windows_disk_capped(tmp_path, monkeypatch):
    clock = [0.0]
    monkeypatch.setattr(DEVICE_OBS, "_clock", lambda: clock[0])
    DEVICE_OBS.configure(profile_dir=str(tmp_path),
                         profile_min_interval_s=0.0,
                         profile_max_windows=2)
    for i in range(4):
        clock[0] += 1.0
        assert DEVICE_OBS.request_profile(rounds=1).get("armed")
        DEVICE_OBS.on_round()  # start
        DEVICE_OBS.on_round()  # close (rounds=1)
    windows = [d for d in os.listdir(tmp_path) if d.startswith("window-")]
    assert len(windows) == 2, "oldest window dirs must be pruned"


# -- capability gate ---------------------------------------------------------

def test_capability_gate_old_jax_degrades_loudly(monkeypatch):
    """An old-jax box (no AOT stages API, no profiler): analysis is a
    loud no-op, profile requests refuse with a reason, and compile
    COUNTING — pure python — keeps working."""
    import jax

    monkeypatch.setattr(device_mod, "_analysis_supported", lambda: False)
    monkeypatch.setattr(device_mod, "_profiler_supported", lambda: False)
    assert not device_observatory_supported()

    obs = DeviceObservatory()
    probe = obs.jit("gated", jax.jit(
        lambda x: x + 1, static_argnums=(), donate_argnums=()
    ))
    import jax.numpy as jnp

    np.asarray(probe(jnp.ones((4,))))
    st = obs.status()
    assert st["supported"] is False
    assert st["compiles_total"] == 1, "counting must survive the gate"
    assert obs.analyze() == []
    refusal = obs.request_profile(rounds=2)
    assert "error" in refusal and "unavailable" in refusal["error"]


# -- observation only: tick identity -----------------------------------------

def test_observatory_on_off_tick_identical():
    """The observatory enabled vs disabled is observation only: the
    same seeded churn places identically, bit for bit."""

    def drive(enabled):
        DEVICE_OBS.reset()
        DEVICE_OBS.set_enabled(enabled)
        bus, sched = _wired()
        rng = np.random.default_rng(11)
        log = []
        for t in range(4):
            _arrive(bus, rng, t)
            out = sched.schedule_pending(now=20.0 + t)
            log.append(sorted(out.items()))
        return log

    on = drive(True)
    off = drive(False)
    assert on == off and len(on) == 4


# -- surfaces ----------------------------------------------------------------

def test_placement_service_status_carries_device_section(tmp_path):
    from koordinator_tpu.service.server import PlacementService

    service = PlacementService(str(tmp_path / "solver.sock"))
    service.start()  # stop() joins serve_forever — never stop unstarted
    try:
        st = service.status()
        assert "device" in st
        assert "compiles_total" in st["device"]
        assert "padding" in st["device"]
        assert "live" in st["device"]
    finally:
        service.stop()


def test_flight_dump_carries_device_section(tmp_path):
    FLIGHT.reset()
    FLIGHT.configure(dump_dir=str(tmp_path), min_interval_s=0.0)
    try:
        DEVICE_OBS.note_padding("pod_batch", 60, 64)
        path = FLIGHT.trigger("manual", detail="device-obs test")
        assert path is not None
        with open(path) as f:
            dump = json.load(f)
        dev = dump["device"]
        assert "compiles_total" in dev and "xla_compiles_total" in dev
        assert dev["padding"]["pod_batch"]["real"] == 60
        assert "live" in dev
    finally:
        FLIGHT.reset()
        FLIGHT.configure(dump_dir=_default_dump_dir(), min_interval_s=1.0)


def test_debug_device_endpoint_serves_ring():
    from koordinator_tpu.utils.debug_http import DebugHTTPServer

    import jax
    import jax.numpy as jnp

    probe = DEVICE_OBS.jit("mux_probe", jax.jit(
        lambda x: x * 3, static_argnums=(), donate_argnums=()
    ))
    np.asarray(probe(jnp.ones((6,))))
    server = DebugHTTPServer(device=DEVICE_OBS.debug_payload).start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/device"
        with urllib.request.urlopen(url) as resp:
            payload = json.loads(resp.read())
        assert any(r["fn"] == "mux_probe"
                   for r in payload["recent_compiles"])
        if device_observatory_supported():
            assert any(a["fn"] == "mux_probe"
                       for a in payload["analyses"]), (
                "/debug/device materializes pending analyses"
            )
    finally:
        server.stop()


# -- bench_diff --------------------------------------------------------------

def _bench_diff(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_diff.py"),
         *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def _record(**overrides):
    leg = {
        "pods_per_sec": 50000.0, "p99_s": 0.01,
        "identical_to_oracle": True,
        "device": {"compiles": 6, "xla_compiles": 12, "flops": 1e9,
                   "bytes_accessed": 2e8, "peak_bytes": 4 << 20,
                   "padding_waste_ratio": 0.2},
    }
    leg.update(overrides)
    return {"metric": "test", "value": 50000.0, "unit": "pods/s",
            "matrix": {"9_churn": leg}, "graftcheck_violations": 0}


def test_bench_diff_smoke_self_clean(tmp_path):
    """The check.sh gate's contract: a record diffed against itself is
    clean (synthetic AND the committed r05 with its truncated tail)."""
    p = tmp_path / "r.json"
    p.write_text(json.dumps(_record()))
    out = _bench_diff(str(p), str(p))
    assert out.returncode == 0, out.stdout + out.stderr
    out = _bench_diff(os.path.join(REPO, "BENCH_r05.json"),
                      os.path.join(REPO, "BENCH_r05.json"))
    assert out.returncode == 0, out.stdout + out.stderr


@pytest.mark.parametrize("mutation, metric", [
    ({"pods_per_sec": 20000.0}, "pods_per_sec"),       # throughput cliff
    ({"p99_s": 0.05}, "p99_s"),                        # latency blow-up
    ({"identical_to_oracle": False}, "identical"),     # identity lost
    ({"device": {"compiles": 40, "xla_compiles": 12,
                 "flops": 1e9, "bytes_accessed": 2e8,
                 "peak_bytes": 4 << 20,
                 "padding_waste_ratio": 0.2}}, "compiles"),  # recompile leak
])
def test_bench_diff_catches_regressions(tmp_path, mutation, metric):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_record()))
    new.write_text(json.dumps(_record(**mutation)))
    out = _bench_diff(str(old), str(new))
    assert out.returncode == 1, out.stdout
    assert "REGRESSION" in out.stdout and metric in out.stdout


def test_bench_diff_budget_mode(tmp_path):
    rec = tmp_path / "r.json"
    rec.write_text(json.dumps(_record()))
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps({
        "9_churn": {"p99_s": {"max": 0.02},
                    "device.padding_waste_ratio": {"max": 0.5}},
    }))
    out = _bench_diff("--budget", str(budget), str(rec))
    assert out.returncode == 0, out.stdout + out.stderr
    budget.write_text(json.dumps({
        "9_churn": {"p99_s": {"max": 0.005}},
    }))
    out = _bench_diff("--budget", str(budget), str(rec))
    assert out.returncode == 1, out.stdout


def test_bench_diff_budget_equals_pins_flags(tmp_path):
    """The ``equals`` bound (ISSUE 11 satellite): identity/acceptance
    FLAGS can be pinned by a budget — a bit-identity boolean holding
    true passes, flipping false (or going missing) fails."""
    rec = tmp_path / "r.json"
    rec.write_text(json.dumps(_record()))
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps({
        "9_churn": {"identical_to_oracle": {"equals": True}},
    }))
    out = _bench_diff("--budget", str(budget), str(rec))
    assert out.returncode == 0, out.stdout + out.stderr
    rec.write_text(json.dumps(_record(identical_to_oracle=False)))
    out = _bench_diff("--budget", str(budget), str(rec))
    assert out.returncode == 1, out.stdout
    budget.write_text(json.dumps({
        "9_churn": {"no_such_flag": {"equals": True}},
    }))
    out = _bench_diff("--budget", str(budget), str(rec))
    assert out.returncode == 1, out.stdout
