"""Full-system e2e sim (VERDICT round-2 ask 7) — the kind-e2e analogue.

ALL five components composed on ONE bus with fake cgroupfs per node,
converging over multiple rounds (reference scope:
test/e2e/scheduling/ + test/e2e/slocontroller/):

  webhook admits (BE cpu -> batch-cpu) ->
  scheduler places (batched solver) ->
  koordlet actuates cpuset/bvt/cfs THROUGH THE NRI EVENT PATH and
  reports NodeMetric from its metric cache ->
  manager recomputes batch allocatable from the reports ->
  descheduler migrates off the hot node (reservation-first) ->
  the moved pod re-places and the BE pod lands on reclaimed capacity.
"""

import dataclasses

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import PodSpec
from koordinator_tpu.client import (
    APIServer,
    Kind,
    wire_descheduler,
    wire_koordlet,
    wire_manager,
    wire_scheduler,
)
from koordinator_tpu.cmd.manager import ManagerConfig, build_manager
from koordinator_tpu.descheduler.framework import (
    Descheduler,
    MigrationEvictor,
    Profile,
)
from koordinator_tpu.descheduler.loadaware import (
    LowNodeLoad,
    LowNodeLoadArgs,
    NodePool,
)
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
from koordinator_tpu.koordlet.pleg import PLEG
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.runtimehooks import RuntimeHooks
from koordinator_tpu.koordlet.statesinformer import (
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.koordlet.system.cgroup import (
    CPU_BVT_WARP_NS,
    CPU_CFS_QUOTA,
    SystemConfig,
)
from koordinator_tpu.manager.nodeslo import NodeSLOController
from koordinator_tpu.manager.sloconfig import NodeSLOSpec
from koordinator_tpu.scheduler import Scheduler

NODE_CPU = 10000
NODE_MEM = 32768


def enabled_slo_controller():
    """Cluster NodeSLO with the groupidentity tiers enabled — rendered
    per node by the manager and consumed by koordlets over the bus."""
    slo = NodeSLOSpec()
    for tier in ("lsr", "ls", "be"):
        getattr(slo.resource_qos_strategy, tier).enable = True
    return NodeSLOController(cluster_spec=slo)


class KoordletSim:
    """One node agent over fake cgroupfs, wired to the bus through
    wire_koordlet: its informer state (node, node's pods, NodeSLO) is
    driven entirely by bus watches; actuation runs through runtimehooks
    (NRI mode off the PLEG stream); NodeMetric reports flow back."""

    def __init__(self, bus, node_name, root):
        self.bus = bus
        self.node_name = node_name
        self.cfg = SystemConfig(cgroup_root=str(root / "cg"),
                                proc_root=str(root / "proc"))
        for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
            ensure_cgroup_dir(d, self.cfg)
        self.informer = StatesInformer()
        self.executor = ResourceUpdateExecutor(self.cfg, auditor=Auditor())
        self.hooks = RuntimeHooks(self.informer, self.executor)
        self.cache = MetricCache()
        self.loop = wire_koordlet(
            bus, self.informer, node_name,
            reporter=NodeMetricReporter(self.cache, self.informer),
        )
        self.pleg = PLEG(self.cfg)
        self.nri = self.hooks.attach_nri(self.pleg)
        self.pleg.poll()  # primer

    def step(self, now: float, usage_by_uid) -> None:
        """One agent tick: the informer already tracks the bus; let the
        "runtime" create cgroup dirs (PLEG -> NRI hooks actuate), sample
        usage into the cache, report NodeMetric onto the bus."""
        metas = self.informer.running_pods()
        for meta in metas:  # the runtime materializes the cgroups
            ensure_cgroup_dir(meta.cgroup_dir, self.cfg)
            for cdir in meta.containers.values():
                ensure_cgroup_dir(cdir, self.cfg)
        self.pleg.poll()   # lifecycle events -> NRI hook dispatch

        node_cpu = node_mem = 0
        for meta in metas:
            cpu, mem = usage_by_uid.get(meta.uid, (0, 0))
            self.cache.append(MetricKind.POD_CPU_USAGE, {"pod": meta.uid},
                              now, cpu)
            self.cache.append(MetricKind.POD_MEMORY_USAGE, {"pod": meta.uid},
                              now, mem)
            node_cpu += cpu
            node_mem += mem
        self.cache.append(MetricKind.SYS_CPU_USAGE, None, now, 300)
        self.cache.append(MetricKind.SYS_MEMORY_USAGE, None, now, 512)
        self.cache.append(MetricKind.NODE_CPU_USAGE, None, now,
                          node_cpu + 300)
        self.cache.append(MetricKind.NODE_MEMORY_USAGE, None, now,
                          node_mem + 512)
        self.loop.report(now)


def test_five_components_converge(tmp_path):
    bus = APIServer()

    # -- koord-manager: webhook chain + noderesource loop; a
    # ClusterColocationProfile makes label-selected pods BE/batch (the
    # reference injection path — translation only runs on profile match)
    from koordinator_tpu.webhook import ClusterColocationProfile

    manager = build_manager(ManagerConfig())
    manager.mutating_webhook.update_profile(ClusterColocationProfile(
        name="colo-be", selector={"colocation": "true"},
        qos_class=QoSClass.BE, priority=5500,
    ))
    manager_loop = wire_manager(bus, manager.noderesource,
                                nodeslo=enabled_slo_controller())

    # -- koord-scheduler (batched placement)
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)

    # -- koord-descheduler: LowNodeLoad -> reservation-first migration
    desch_loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="lnl", balance_plugins=[LowNodeLoad(
            LowNodeLoadArgs(node_pools=[NodePool(
                low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70},
            )])
        )])],
        evictor=MigrationEvictor(),
    ))

    # -- two nodes, each with its own koordlet over fake cgroupfs
    from koordinator_tpu.apis.types import NodeSpec

    for name in ("hot", "cold"):
        bus.apply(Kind.NODE, name, NodeSpec(
            name=name, allocatable={R.CPU: NODE_CPU, R.MEMORY: NODE_MEM}))
    sims = {name: KoordletSim(bus, name, tmp_path / name)
            for name in ("hot", "cold")}

    # -- workload arrives through admission
    web1 = PodSpec(name="web1", qos=QoSClass.LS, priority=9500,
                   requests={R.CPU: 3000, R.MEMORY: 4096})
    web2 = PodSpec(name="web2", qos=QoSClass.LS, priority=9500,
                   requests={R.CPU: 3000, R.MEMORY: 4096})
    batch = PodSpec(name="crunch", labels={"colocation": "true"},
                    requests={R.CPU: 2000, R.MEMORY: 2048})
    for pod in (web1, web2, batch):
        admitted, violations = manager.admit_pod(pod)
        assert not violations
        bus.apply(Kind.POD, admitted.uid, admitted)
    # the profile made the pod BE/batch and translated its resources
    assert batch.qos == QoSClass.BE and batch.priority == 5500
    assert batch.requests == {R.BATCH_CPU: 2000, R.BATCH_MEMORY: 2048}

    # usage model: web1 runs hot (8200m) until the rebalance spreads the
    # load, then normalizes; web2 and crunch stay light
    usage = {"default/web1": (8200, 4096), "default/web2": (600, 2048),
             "default/crunch": (400, 1024)}

    migrated = []
    web1_home = crunch_home = None
    for i in range(8):
        t = 100.0 + 40.0 * i
        for sim in sims.values():
            sim.step(t, usage)
        manager_loop.reconcile(now=t + 1)
        scheduler.schedule_pending(now=t + 2)
        if web1_home is None:
            web1_home = bus.get(Kind.POD, "default/web1").node_name
        if crunch_home is None:
            crunch_home = bus.get(Kind.POD, "default/crunch").node_name
        if i >= 2:  # metrics warmed: let the descheduler act
            migrated += desch_loop.run_once(now=t + 3)
        if migrated:
            usage["default/web1"] = (2000, 4096)

    # -- convergence assertions -------------------------------------------
    pods = {p.name: p for p in bus.list(Kind.POD).values()}

    # 1. everything is placed
    assert pods["web1"].node_name is not None
    assert pods["web2"].node_name is not None
    assert pods["crunch"].node_name is not None

    # 2. the manager recomputed batch allocatable from koordlet reports
    #    (BE pod schedules only against reclaimed kubernetes.io/batch-*)
    crunch_node = bus.get(Kind.NODE, pods["crunch"].node_name)
    assert crunch_node.allocatable.get(R.BATCH_CPU, 0) >= 2000

    # 3. the descheduler drained the hot node through a reservation-first
    #    migration. Victim order follows the reference PodSorter chain:
    #    the BE/batch pod evicts BEFORE the heavier LS pod (lower
    #    priority band wins over higher usage), and removing it already
    #    brings the node back under the high threshold — so crunch
    #    moves, web1 (prod, LS) stays put.
    assert "default/crunch" in migrated
    assert "default/web1" not in migrated
    assert len(bus.list(Kind.MIGRATION_JOB)) >= 1
    assert pods["crunch"].node_name != crunch_home  # actually moved
    assert pods["web1"].node_name == web1_home      # prod pod protected

    # 4. koordlet actuated QoS through the NRI path: bvt landed for the
    #    LS pods, cfs quota for the BE pod, on the RIGHT node's cgroupfs
    for name in ("web1", "web2"):
        node = pods[name].node_name
        sim = sims[node]
        assert sim.nri.handled.get("RunPodSandbox", 0) >= 1
        assert CPU_BVT_WARP_NS.read(
            f"kubepods/burstable/poddefault_{name}", sim.cfg) == "2"
    be_sim = sims[pods["crunch"].node_name]
    assert CPU_BVT_WARP_NS.read(
        "kubepods/besteffort/poddefault_crunch", be_sim.cfg) == "-1"
    # batch limit 2000m -> cfs quota 200000us on the container
    assert CPU_CFS_QUOTA.read(
        "kubepods/besteffort/poddefault_crunch/main", be_sim.cfg) == "200000"

    # 5. NodeMetric reports round-tripped: web1's current node reports
    #    its (normalized, windowed-average) usage on the bus
    hot_metric = bus.get(Kind.NODE_METRIC, pods["web1"].node_name)
    reported = hot_metric.pod_usages["default/web1"][R.CPU]
    assert 2000 <= reported <= 8200


def test_sim_survives_pod_churn(tmp_path):
    """Deleting a pod mid-sim: the koordlet drops it, the reporter stops
    reporting it, the manager's batch numbers grow back."""
    bus = APIServer()
    manager = build_manager(ManagerConfig())
    manager_loop = wire_manager(bus, manager.noderesource,
                                nodeslo=enabled_slo_controller())
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    from koordinator_tpu.apis.types import NodeSpec

    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: NODE_CPU, R.MEMORY: NODE_MEM}))
    sim = KoordletSim(bus, "n0", tmp_path)

    heavy = PodSpec(name="heavy", qos=QoSClass.LS, priority=9500,
                    requests={R.CPU: 6000, R.MEMORY: 8192})
    admitted, _ = manager.admit_pod(heavy)
    bus.apply(Kind.POD, admitted.uid, admitted)
    usage = {"default/heavy": (6000, 8192)}

    for i in range(3):
        t = 100.0 + 40.0 * i
        sim.step(t, usage)
        manager_loop.reconcile(now=t + 1)
        scheduler.schedule_pending(now=t + 2)
    low_batch = bus.get(Kind.NODE, "n0").allocatable.get(R.BATCH_CPU, 0)

    bus.delete(Kind.POD, "default/heavy")
    for i in range(3, 9):
        t = 100.0 + 40.0 * i
        sim.step(t, {})
        manager_loop.reconcile(now=t + 1)
    high_batch = bus.get(Kind.NODE, "n0").allocatable.get(R.BATCH_CPU, 0)
    assert high_batch > low_batch  # reclaimed capacity grew back
    metric = bus.get(Kind.NODE_METRIC, "n0")
    assert "default/heavy" not in metric.pod_usages
