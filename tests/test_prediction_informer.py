"""prediction + statesinformer + pleg tests.

Oracles: prediction/peak_predictor.go (p95 cpu / p98 mem x safety margin,
cold start, min of pod/priority views), statesinformer/impl/
states_nodemetric.go (NodeMetric assembly), pleg/watcher.go.
"""

import os

import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName
from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.pleg import PLEG
from koordinator_tpu.koordlet.pleg.pleg import EventType
from koordinator_tpu.koordlet.prediction import (
    HistogramBank,
    PeakPredictServer,
    PredictionConfig,
    prod_reclaimable,
)
from koordinator_tpu.koordlet.prediction.predict_server import (
    SYS_KEY,
    pod_key,
    priority_key,
)
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.statesinformer import (
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.koordlet.statesinformer.states_informer import StateKind
from koordinator_tpu.apis.types import NodeSpec
from koordinator_tpu.koordlet.system.cgroup import SystemConfig
from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy


class TestHistogramBank:
    def test_percentile_of_constant_stream(self):
        h = HistogramBank(first_bucket=25.0)
        for t in range(100):
            h.add("a", 500.0, float(t))
        p95 = h.percentile("a", 0.95)
        # VPA semantics: percentile returns the crossing bucket's START,
        # so a constant 500 stream reports within one 5% growth step below
        assert 500 / 1.05 <= p95 <= 500

    def test_percentile_orders(self):
        h = HistogramBank(first_bucket=25.0)
        for t in range(90):
            h.add("a", 100.0, float(t))
        for t in range(90, 100):
            h.add("a", 2000.0, float(t))
        p50 = h.percentile("a", 0.5)
        p99 = h.percentile("a", 0.99)
        assert p50 < 200 and p99 >= 2000 / 1.05

    def test_decay_forgets_old_peaks(self):
        h = HistogramBank(first_bucket=25.0, half_life_seconds=3600)
        h.add("a", 4000.0, 0.0)
        # 20 half-lives later, many low samples dominate
        for i in range(100):
            h.add("a", 100.0, 72000.0 + i)
        assert h.percentile("a", 0.95) < 200

    def test_unknown_key_none(self):
        h = HistogramBank(first_bucket=25.0)
        assert h.percentile("ghost", 0.95) is None

    def test_batch_matches_scalar(self):
        h = HistogramBank(first_bucket=25.0)
        import random
        rng = random.Random(0)
        for key in ("a", "b", "c"):
            for t in range(50):
                h.add(key, rng.uniform(10, 5000), float(t))
        batch = h.percentiles_batch(["a", "b", "ghost", "c"], [0.5, 0.95])
        for i, key in enumerate(["a", "b", "ghost", "c"]):
            for j, p in enumerate([0.5, 0.95]):
                assert batch[i][j] == h.percentile(key, p)

    def test_forget_and_state_roundtrip(self):
        h = HistogramBank(first_bucket=25.0)
        h.add("a", 100.0, 0.0)
        h.add("b", 200.0, 0.0)
        h.forget(["b"])
        assert h.percentile("a", 0.5) is None
        assert h.percentile("b", 0.5) is not None
        h2 = HistogramBank(first_bucket=25.0)
        h2.load_state(h.state())
        assert h2.percentile("b", 0.5) == h.percentile("b", 0.5)


class TestPeakPredictServer:
    def test_peak_applies_safety_margin(self):
        s = PeakPredictServer(PredictionConfig(safety_margin_percent=10))
        for t in range(100):
            s.update(pod_key("p"), 1000.0, 512.0, float(t))
        peak = s.peak(pod_key("p"))
        assert peak["cpu"] == pytest.approx(
            s.cpu.percentile(pod_key("p"), 0.95) * 1.1
        )

    def test_cold_start(self):
        s = PeakPredictServer(PredictionConfig(cold_start_seconds=900))
        s.update(pod_key("p"), 100.0, 10.0, 1000.0)
        assert s.in_cold_start(pod_key("p"), 1100.0)
        assert not s.in_cold_start(pod_key("p"), 2000.0)

    def test_checkpoint_roundtrip(self, tmp_path):
        path = str(tmp_path / "ckpt.json")
        s = PeakPredictServer(PredictionConfig(checkpoint_path=path))
        for t in range(50):
            s.update(pod_key("p"), 700.0, 300.0, float(t))
        s.save_checkpoint()
        s2 = PeakPredictServer(PredictionConfig(checkpoint_path=path))
        assert s2.load_checkpoint()
        assert s2.peak(pod_key("p"))["cpu"] == s.peak(pod_key("p"))["cpu"]


class TestProdReclaimable:
    def _server(self):
        s = PeakPredictServer(PredictionConfig(
            safety_margin_percent=0, cold_start_seconds=0))
        # pod p uses ~500 mCPU of a 2000 mCPU request
        for t in range(1000):
            s.update(pod_key("p"), 500.0, 256.0, float(t))
            s.update(priority_key("prod"), 500.0, 256.0, float(t))
            s.update(SYS_KEY, 100.0, 50.0, float(t))
        return s

    def test_min_of_pod_and_priority_views(self):
        s = self._server()
        rec = prod_reclaimable(s, [("p", 2000, 1024)], now=1000.0)
        pod_view = 2000 - s.peak(pod_key("p"))["cpu"]
        pri_view = (2000 - s.peak(priority_key("prod"))["cpu"]
                    - s.peak(SYS_KEY)["cpu"])
        assert rec["cpu"] == int(min(pod_view, pri_view))
        assert rec["cpu"] > 0

    def test_cold_start_pod_contributes_zero(self):
        s = PeakPredictServer(PredictionConfig(cold_start_seconds=1e6))
        s.update(pod_key("p"), 100.0, 10.0, 0.0)
        rec = prod_reclaimable(s, [("p", 2000, 1024)], now=100.0)
        assert rec["cpu"] == 0


class TestNodeMetricReporter:
    def test_report_assembles_nodemetric(self):
        mc = MetricCache()
        informer = StatesInformer()
        informer.set_node(NodeSpec("n0", allocatable={
            ResourceName.CPU: 8000, ResourceName.MEMORY: 16384}))
        pods = [
            PodMeta("ls", "kubepods/burstable/ls", QoSClass.LS,
                    cpu_request_mcpu=2000),
            PodMeta("be", "kubepods/besteffort/be", QoSClass.BE),
        ]
        informer.set_pods(pods)
        informer.set_collect_policy(NodeMetricCollectPolicy(300, 60))
        for t in range(10):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 3000.0)
            mc.append(MetricKind.NODE_MEMORY_USAGE, None, float(t), 8000.0)
            mc.append(MetricKind.POD_CPU_USAGE, {"pod": "ls"}, float(t), 2000.0)
            mc.append(MetricKind.POD_CPU_USAGE, {"pod": "be"}, float(t), 400.0)
            mc.append(MetricKind.SYS_CPU_USAGE, None, float(t), 600.0)
        reporter = NodeMetricReporter(mc, informer)
        m = reporter.report(now=10.0)
        assert m.node_usage[ResourceName.CPU] == 3000
        assert m.pod_usages["ls"][ResourceName.CPU] == 2000
        assert m.prod_usage[ResourceName.CPU] == 2000  # only the LS pod
        assert m.sys_usage[ResourceName.CPU] == 600
        assert m.aggregated_usage[95][ResourceName.CPU] == 3000
        assert m.report_interval == 60.0
        assert m.update_time == 10.0

    def test_report_feeds_manager(self):
        """The full colocation loop: reporter output drives the batch
        overcommit calculator."""
        from koordinator_tpu.apis.types import ClusterSnapshot, PodSpec
        from koordinator_tpu.manager import NodeResourceController

        mc = MetricCache()
        informer = StatesInformer()
        node = NodeSpec("n0", allocatable={
            ResourceName.CPU: 10000, ResourceName.MEMORY: 10000})
        informer.set_node(node)
        informer.set_pods([PodMeta(
            "default/prod0", "kubepods/p", QoSClass.LS,
            cpu_request_mcpu=3000)])
        for t in range(5):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 3000.0)
            mc.append(MetricKind.POD_CPU_USAGE,
                      {"pod": "default/prod0"}, float(t), 2000.0)
            mc.append(MetricKind.SYS_CPU_USAGE, None, float(t), 1000.0)
        m = NodeMetricReporter(mc, informer).report(now=5.0)

        pod = PodSpec("prod0", requests={ResourceName.CPU: 3000},
                      priority=9500, node_name="n0", qos=QoSClass.LS)
        snap = ClusterSnapshot(nodes=[node], pods=[pod],
                               node_metrics={"n0": m}, now=10.0)
        upd = NodeResourceController().reconcile_all(snap)[0]
        # batch cpu = 10000 - 4000(margin) - 1000(sys) - 2000(pod) = 3000
        assert upd.allocatable[ResourceName.BATCH_CPU] == 3000

    def test_memory_reclaimable_reported(self):
        """memory_request_mib flows into prod_reclaimable: MID memory is
        no longer permanently zero (ADVICE r1 medium)."""
        mc = MetricCache()
        informer = StatesInformer()
        informer.set_node(NodeSpec("n0", allocatable={
            ResourceName.CPU: 8000, ResourceName.MEMORY: 16384}))
        informer.set_pods([PodMeta(
            "p", "kubepods/p", QoSClass.LS,
            cpu_request_mcpu=2000, memory_request_mib=1024)])
        srv = PeakPredictServer(PredictionConfig(
            safety_margin_percent=0, cold_start_seconds=0))
        for t in range(1000):
            srv.update(pod_key("p"), 500.0, 256.0, float(t))
            srv.update(priority_key("prod"), 500.0, 256.0, float(t))
            srv.update(SYS_KEY, 100.0, 50.0, float(t))
            mc.append(MetricKind.POD_CPU_USAGE, {"pod": "p"}, float(t), 500.0)
        m = NodeMetricReporter(mc, informer, predict_server=srv).report(
            now=1000.0)
        assert m.prod_reclaimable[ResourceName.MEMORY] > 0

    def test_unlabeled_pod_defaults_to_prod_class(self):
        """Ordinary k8s pods (no koord QoS, priority 0) count as PROD in
        pod_priority_class so their usage stays in HP sums (reference
        GetPodPriorityClassWithDefault)."""
        from koordinator_tpu.apis.extension import PriorityClass

        mc = MetricCache()
        informer = StatesInformer()
        informer.set_node(NodeSpec("n0", allocatable={
            ResourceName.CPU: 8000, ResourceName.MEMORY: 16384}))
        informer.set_pods([
            PodMeta("plain", "kubepods/plain", QoSClass.NONE),
            PodMeta("be", "kubepods/besteffort/be", QoSClass.BE),
            PodMeta("batchband", "kubepods/bb", QoSClass.NONE,
                    priority=5500),
        ])
        for uid, mcpu in (("plain", 700.0), ("be", 400.0),
                          ("batchband", 300.0)):
            mc.append(MetricKind.POD_CPU_USAGE, {"pod": uid}, 1.0, mcpu)
        m = NodeMetricReporter(mc, informer).report(now=2.0)
        assert m.pod_priority_class["plain"] == PriorityClass.PROD
        assert m.pod_priority_class["be"] == PriorityClass.BATCH
        assert m.pod_priority_class["batchband"] == PriorityClass.BATCH
        assert m.prod_usage[ResourceName.CPU] == 700

    def test_callbacks_fire(self):
        informer = StatesInformer()
        seen = []
        informer.register_callback(
            StateKind.NODE_SLO, lambda k, v: seen.append(k))
        from koordinator_tpu.manager.sloconfig import NodeSLOSpec
        informer.set_node_slo(NodeSLOSpec())
        assert seen == [StateKind.NODE_SLO]


class TestPLEG:
    def test_poll_diff_events(self, tmp_path):
        cfg = SystemConfig(cgroup_root=str(tmp_path))
        ensure_cgroup_dir("kubepods/besteffort", cfg)
        pleg = PLEG(cfg)
        assert pleg.poll() == []  # primer

        ensure_cgroup_dir("kubepods/besteffort/pod1", cfg)
        events = pleg.poll()
        assert [e.event for e in events] == [EventType.POD_ADDED]
        assert events[0].cgroup_dir == "kubepods/besteffort/pod1"

        ensure_cgroup_dir("kubepods/besteffort/pod1/c1", cfg)
        events = pleg.poll()
        assert [e.event for e in events] == [EventType.CONTAINER_ADDED]

        import shutil
        shutil.rmtree(os.path.join(str(tmp_path), "cpu",
                                   "kubepods/besteffort/pod1"))
        events = pleg.poll()
        kinds = {e.event for e in events}
        assert EventType.POD_DELETED in kinds

    def test_handlers_invoked(self, tmp_path):
        cfg = SystemConfig(cgroup_root=str(tmp_path))
        ensure_cgroup_dir("kubepods", cfg)
        pleg = PLEG(cfg)
        got = []
        pleg.register(got.append)
        pleg.poll()
        ensure_cgroup_dir("kubepods/podX", cfg)
        pleg.poll()
        assert len(got) == 1 and got[0].event == EventType.POD_ADDED


def test_host_application_collection_and_report(tmp_path):
    """Host apps (NodeSLO hostApplications): collector reads their cgroup
    usage, the reporter publishes per-app usage on the NodeMetric
    (reference: collectors/hostapplication + HostApplicationMetric)."""
    import os

    from koordinator_tpu.apis.extension import ResourceName as R
    from koordinator_tpu.apis.types import NodeSpec
    from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
    from koordinator_tpu.koordlet.metricsadvisor.collectors import (
        HostApplicationCollector,
    )
    from koordinator_tpu.koordlet.metricsadvisor.framework import (
        CollectorContext,
    )
    from koordinator_tpu.koordlet.statesinformer import (
        NodeMetricReporter,
        StatesInformer,
    )
    from koordinator_tpu.koordlet.system.cgroup import SystemConfig
    from koordinator_tpu.manager.sloconfig import (
        HostApplicationSpec,
        NodeSLOSpec,
    )

    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    app_dir = "host-latency-sensitive/nginx"
    for sub in ("cpuacct", "memory"):
        os.makedirs(tmp_path / "cg" / sub / app_dir, exist_ok=True)
    cpu_path = tmp_path / "cg" / "cpuacct" / app_dir / "cpuacct.usage"
    mem_path = tmp_path / "cg" / "memory" / app_dir / "memory.usage_in_bytes"
    mem_path.write_text(str(256 * 1024 * 1024))

    informer = StatesInformer()
    informer.set_node(NodeSpec(name="n0", allocatable={R.CPU: 16000}))
    informer.set_node_slo(NodeSLOSpec(host_applications=[
        HostApplicationSpec(name="nginx", cgroup_dir=app_dir),
    ]))
    mc = MetricCache()
    ctx = CollectorContext(metric_cache=mc, system_config=cfg)
    collector = HostApplicationCollector(slo_provider=informer.get_node_slo)
    collector.setup(ctx)
    assert collector.enabled()
    cpu_path.write_text("0")
    collector.collect(now=0.0)
    cpu_path.write_text(str(2 * 10**9))  # 2 cpu-seconds over 1s -> 2000m
    collector.collect(now=1.0)
    ts, vs = mc.query(MetricKind.HOST_APP_CPU_USAGE, {"app": "nginx"})
    assert list(vs) == [2000.0]

    reporter = NodeMetricReporter(mc, informer)
    metric = reporter.report(now=2.0)
    assert metric.host_app_usages["nginx"][R.CPU] == 2000
    assert metric.host_app_usages["nginx"][R.MEMORY] == 256
