"""Pipelined tick path property tests (ISSUE 6).

The pipeline's whole claim is "overlap for free": staging for round
N+1 runs while round N's solve is in flight, the read-back + epilogue +
publish retire on a worker — and placements stay bit-identical to the
serial loop because ``begin_tick(N+1)`` orders strictly after tick N
retired. That makes bit-identity a TESTABLE property, chaos included:

- a mixed-feature churn (quota + gang Permit barrier bridging rounds +
  reservation consumption) through the pipelined loop vs the serial
  loop: per-tick placements, final node accounting, reservation credit,
  and quota used all bit-identical;
- a FencingError injected into the PUBLISH of tick N while tick N+1's
  staging is already warm: the deferred abort surfaces at the next
  round boundary, the fencing forget rolls the unpublished round back,
  and the run still ends bit-identical to a serial loop fenced at the
  same tick (with a clean auditor sweep at the end);
- a chaos slice: the solver sidecar SIGKILLed mid-pipeline under
  supervisor + failover (testing/chaos.py), bit-identical to the
  fault-free in-process run;
- run_loop cadence: the sleep is computed from round START (absolute
  deadline), not end-of-publish — fake-clock regression;
- the warmed pipelined tick performs zero XLA recompiles (the
  ``xla_compiles`` guard, same fixture as the graftcheck teeth).
"""

import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import (
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.leaderelection import FencingError
from koordinator_tpu.client.wiring import snapshot_from_bus, wire_scheduler
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.auditor import StateAuditor
from koordinator_tpu.scheduler.pipeline import TickPipeline
from koordinator_tpu.state.cluster import lower_nodes

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


@pytest.fixture(autouse=True, scope="module")
def _lock_order_under_pipeline(lock_order_shim):
    """The pipelined churn — coordinator + publisher + prestage threads
    crossing every mapped lock — runs under the runtime lock-order
    shim; the fixture asserts zero order violations at teardown."""
    yield lock_order_shim


N_NODES = 12


def _seed_bus(bus, rng, n_nodes=N_NODES):
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}",
            node_usage={CPU: int(rng.integers(0, 8000)),
                        MEM: int(rng.integers(0, 16384))},
            update_time=90.0))
    bus.apply(Kind.QUOTA, "team", QuotaSpec(
        name="team", min={CPU: 0, MEM: 0},
        max={CPU: 16000, MEM: 32768}))
    bus.apply(Kind.GANG, "g", GangSpec(
        name="g", min_member=3, mode=GangMode.NON_STRICT))
    bus.apply(Kind.RESERVATION, "r0", ReservationSpec(
        name="r0", requests={CPU: 8000, MEM: 8192},
        allocatable={CPU: 8000, MEM: 8192},
        owner_labels={"team": "ml"}, node_name="n0",
        state=ReservationState.AVAILABLE, allocate_once=False))


def _arrivals(rng, t):
    """Deterministic per-tick pod stream: plain churn + a quota'd pod
    every tick, gang members split across ticks 3 and 5 (the Permit
    barrier must bridge pipelined rounds), reservation-matching pods on
    a cadence."""
    pods = [
        PodSpec(name=f"t{t}p{j}",
                requests={CPU: int(rng.integers(200, 2000)),
                          MEM: int(rng.integers(128, 2048))})
        for j in range(4)
    ]
    pods.append(PodSpec(name=f"t{t}q", quota="team",
                        requests={CPU: 1000, MEM: 512}))
    if t == 3:
        pods += [PodSpec(name=f"gang{k}", gang="g",
                         requests={CPU: 800, MEM: 256})
                 for k in range(2)]
    if t == 5:
        pods.append(PodSpec(name="gang2", gang="g",
                            requests={CPU: 800, MEM: 256}))
    if t % 4 == 1:
        pods.append(PodSpec(name=f"t{t}r", labels={"team": "ml"},
                            requests={CPU: 700, MEM: 256}))
    return pods


def _drive(mode, seed=7, ticks=10, model=None, publish_wrap=None,
           hooks=None, warmup=0, boundary_drain=False):
    """Seeded bus-wired churn through either loop. Returns
    (per-tick placement log, bus, scheduler, pipeline|None, fenced)."""
    hooks = hooks or {}
    rng = np.random.default_rng(seed)
    bus = APIServer()
    sched = Scheduler(model=model or PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, rng)
    log = []
    fenced = 0
    pipeline = None
    if mode == "pipelined":
        pub = sched.publish_result
        if publish_wrap is not None:
            pub = publish_wrap(pub)
        pipeline = TickPipeline(
            sched, publish=pub, log=lambda *a: None,
            on_result=lambda out: log.append(sorted(out.items())),
        )
    elif publish_wrap is not None:
        # serial-with-injection: the same begin/commit/publish
        # decomposition schedule_and_publish runs, with the publish
        # step wrapped — identity of the decomposition itself is what
        # the un-injected tests prove
        pub = publish_wrap(sched.publish_result)
    for t in range(warmup):
        # compile-warming empty rounds (same shapes via pod bucketing)
        now = 95.0 + 0.1 * t
        if mode == "pipelined":
            pipeline.submit_round(now=now)
            pipeline.drain("warmup")
            log.clear()
        else:
            sched.schedule_pending(now=now)
    for t in range(ticks):
        now = 100.0 + t
        if boundary_drain and pipeline is not None:
            # deterministic error-surfacing point for the fencing
            # property: retire (and roll back) the previous tick BEFORE
            # this tick's arrivals, as a cadence gap would in run_loop.
            # Without it the forgotten pods' FIFO re-queue position
            # races the arrival stream — real async-publish behavior,
            # but not a bit-comparable schedule.
            try:
                pipeline.drain("boundary")
            except FencingError:
                fenced += 1
                sched.forget_assumed_unbound()
        for i in rng.choice(N_NODES, 2, replace=False):
            name = f"n{int(i)}"
            bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                node_name=name,
                node_usage={CPU: int(rng.integers(0, 12000)),
                            MEM: int(rng.integers(0, 32768))},
                update_time=now))
        for pod in _arrivals(rng, t):
            bus.apply(Kind.POD, pod.uid, pod)
        if t in hooks:
            hooks[t]()
        if mode == "pipelined":
            while True:
                try:
                    pipeline.submit_round(now=now)
                except FencingError:
                    # run_loop's deferred-abort handler, verbatim: the
                    # unpublished round is forgotten, the loop goes on
                    fenced += 1
                    sched.forget_assumed_unbound()
                    continue
                break
            # the overlap window run_loop drives between rounds
            pipeline.prestage(now=now)
        elif publish_wrap is None:
            out = sched.schedule_pending(now=now)
            log.append(sorted(out.items()))
        else:
            tick = sched.begin_tick(now)
            out = sched.commit_tick(tick)
            try:
                pub(out)
            except FencingError:
                fenced += 1
                sched.forget_assumed_unbound()
                continue  # the fenced round publishes nothing
            log.append(sorted(out.items()))
    if pipeline is not None:
        try:
            pipeline.drain("shutdown")
        except FencingError:
            fenced += 1
            sched.forget_assumed_unbound()
        pipeline.stop()
    return log, bus, sched, pipeline, fenced


def _assert_end_state_identical(a, b):
    """(bus, sched) pairs: node accounting, reservation credit, quota
    used — bit-for-bit."""
    (bus_a, sched_a), (bus_b, sched_b) = a, b
    got = lower_nodes(snapshot_from_bus(bus_a, now=500.0))
    want = lower_nodes(snapshot_from_bus(bus_b, now=500.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")
    resv_a = {
        name: (dict(r.allocated), getattr(r.state, "value", r.state),
               sorted(r.allocated_pod_uids))
        for name, r in bus_a.list(Kind.RESERVATION).items()
    }
    resv_b = {
        name: (dict(r.allocated), getattr(r.state, "value", r.state),
               sorted(r.allocated_pod_uids))
        for name, r in bus_b.list(Kind.RESERVATION).items()
    }
    assert resv_a == resv_b, "reservation credit diverged"
    used_a = {n: i.used.tolist()
              for n, i in sched_a.quota_manager.quotas.items()}
    used_b = {n: i.used.tolist()
              for n, i in sched_b.quota_manager.quotas.items()}
    assert used_a == used_b, "quota used diverged"


def test_pipeline_smoke_overlapped_identity():
    """check.sh's pipeline smoke slice: a short overlapped churn ends
    bit-identical to the serial loop, tick for tick."""
    ticks = 6
    p_log, p_bus, p_sched, pipeline, fenced = _drive(
        "pipelined", ticks=ticks)
    s_log, s_bus, s_sched, _, _ = _drive("serial", ticks=ticks)
    assert fenced == 0
    assert len(p_log) == ticks
    for t, (a, b) in enumerate(zip(p_log, s_log)):
        assert a == b, f"placements diverged at tick {t}"
    _assert_end_state_identical((p_bus, p_sched), (s_bus, s_sched))
    # the overlapped path actually ran overlapped machinery
    assert p_sched.model.staged_cache.last_path == "delta"
    status = pipeline.status()
    assert status["rounds"] == ticks and not status["inflight"]


def test_pipeline_property_mixed_churn_identity():
    """The full property: quota enforcement, a gang whose Permit
    barrier bridges pipelined rounds, and reservation consumption all
    ride the overlapped loop bit-identically."""
    ticks = 10
    p_log, p_bus, p_sched, _, _ = _drive("pipelined", ticks=ticks)
    s_log, s_bus, s_sched, _, _ = _drive("serial", ticks=ticks)
    assert len(p_log) == len(s_log) == ticks
    for t, (a, b) in enumerate(zip(p_log, s_log)):
        assert a == b, f"placements diverged at tick {t}"
    _assert_end_state_identical((p_bus, p_sched), (s_bus, s_sched))
    # the gang actually exercised the cross-round Permit barrier:
    # members waited at tick 3 and committed once the third arrived
    gang_uids = {"default/gang0", "default/gang1", "default/gang2"}
    bound = {u for u in gang_uids
             if getattr(p_bus.get(Kind.POD, u), "node_name", None)}
    assert bound == gang_uids
    assert not p_sched._waiting
    # reservation credit was actually consumed at least once
    resv = p_bus.get(Kind.RESERVATION, "r0")
    assert resv.allocated_pod_uids, "reservation never matched a pod"


def _fencing_wrap(fail_round):
    """Publish wrapper raising FencingError on the Nth publish call —
    a leader deposed between deciding and applying."""
    def wrap(inner):
        calls = {"n": 0}

        def publish(out):
            i = calls["n"]
            calls["n"] += 1
            if i == fail_round:
                raise FencingError("injected: deposed mid-publish")
            inner(out)

        return publish

    return wrap


def test_pipeline_fenced_publish_rollback_identity():
    """A FencingError in tick 4's PUBLISH — while tick 5's staging is
    already warm in the pipelined run — must not corrupt anything: the
    deferred abort surfaces at the next round boundary, the fencing
    forget releases the unpublished round, and the run ends
    bit-identical to a serial loop fenced at the same tick. A manual
    auditor sweep at the end must find ZERO drift."""
    ticks, fail_round = 8, 4
    p_log, p_bus, p_sched, _, p_fenced = _drive(
        "pipelined", ticks=ticks, publish_wrap=_fencing_wrap(fail_round),
        boundary_drain=True)
    s_log, s_bus, s_sched, _, s_fenced = _drive(
        "serial", ticks=ticks, publish_wrap=_fencing_wrap(fail_round))
    assert p_fenced == s_fenced == 1
    # the fenced tick published nothing and is absent from both logs
    assert len(p_log) == len(s_log) == ticks - 1
    for t, (a, b) in enumerate(zip(p_log, s_log)):
        assert a == b, f"placements diverged at surviving tick {t}"
    _assert_end_state_identical((p_bus, p_sched), (s_bus, s_sched))
    # the forgotten pods were re-placed in a later round, not lost
    assert not p_sched.cache.pending
    # and the trust chain is clean: no lingering assumes, no staging
    # drift, no accounting violations left behind by the abort
    report = StateAuditor(p_sched, p_bus, interval_rounds=0).sweep(
        "manual", now=200.0)
    assert report["detections"] == {}
    assert report["unrepaired"] == []


@pytest.mark.chaos
def test_pipeline_chaos_sidecar_sigkill_mid_flight(tmp_path):
    """Chaos slice: the solver sidecar is SIGKILLed mid-pipeline. The
    supervisor respawns it, the failover backend answers the outage
    ticks in-process (pipeline drained on both flips via the hooks
    run_loop wires), and the churn ends bit-identical to the fault-free
    in-process run."""
    from koordinator_tpu.service.client import RemoteSolver
    from koordinator_tpu.service.failover import FailoverSolver
    from koordinator_tpu.service.supervisor import SolverSupervisor
    from koordinator_tpu.testing.chaos import InProcessSidecar

    solver_addr = str(tmp_path / "solver.sock")
    ticks, kill_tick = 14, 5
    handles = []

    def spawn():
        handle = InProcessSidecar(solver_addr)
        handles.append(handle)
        return handle

    supervisor = SolverSupervisor(
        solver_addr, spawn_fn=spawn,
        probe_interval_s=0.2, probe_timeout_s=0.2, ready_timeout_s=30.0,
        # the respawn must be SLOWER than the post-kill tick's retry
        # budget (0.3s) by a wide margin, or a loaded machine can heal
        # the sidecar before the outage tick ever fails remotely and
        # the flip under test never happens (jittered to [1.0, 2.0]s)
        backoff_base_s=2.0, backoff_cap_s=2.0,
    ).start()
    remote = RemoteSolver(solver_addr, timeout=30.0, retries=0,
                          retry_total_s=0.3,
                          backoff_base_s=0.01, backoff_cap_s=0.02)
    backend = FailoverSolver(remote, failure_threshold=1,
                             recovery_probes=1)
    model = PlacementModel(backend=backend, use_pallas=False)

    def wait_respawn():
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if (supervisor.status()["state"] == "running"
                    and len(handles) > 1):
                return
            time.sleep(0.05)
        raise AssertionError("supervisor never respawned the sidecar")

    try:
        p_log, p_bus, p_sched, pipeline, fenced = _drive(
            "pipelined", ticks=ticks, model=model, warmup=2,
            hooks={
                kill_tick: lambda: handles[-1].kill(),
                kill_tick + 4: wait_respawn,
            })
        # run_loop wires the flip hooks; the driver above does not, so
        # exercise the hook contract directly instead: a drain on a
        # retired pipeline is immediate and error-free
        pipeline_status = pipeline.status()
        s_log, s_bus, s_sched, _, _ = _drive(
            "serial", ticks=ticks,
            model=PlacementModel(use_pallas=False), warmup=2)
        assert fenced == 0
        assert len(p_log) == ticks  # every tick completed
        for t, (a, b) in enumerate(zip(p_log, s_log)):
            assert a == b, f"placements diverged at tick {t}"
        _assert_end_state_identical((p_bus, p_sched), (s_bus, s_sched))
        status = backend.status()
        assert status["flips_to_degraded"] >= 1  # the outage was real
        assert status["local_solves"] >= 1
        assert len(handles) >= 2                 # a respawn happened
        assert not pipeline_status["inflight"]
    finally:
        supervisor.stop()
        backend.close()


def test_run_loop_sleeps_from_round_start():
    """Cadence regression (fake clock): the inter-round sleep is the
    remainder of an ABSOLUTE deadline from round start — a round that
    burns 0.3s of a 1.0s interval sleeps 0.7s, not 1.0s (the old
    behavior drifted every round by the round's own cost)."""
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop
    from koordinator_tpu.models.placement import ScheduleResult

    clock = {"t": 0.0}
    sleeps = []

    def now_fn():
        return clock["t"]

    def sleep_fn(s):
        sleeps.append(round(s, 6))
        clock["t"] += s

    class StubScheduler:
        def schedule_pending(self, now=None):
            clock["t"] += 0.3  # the round itself takes 0.3s
            return ScheduleResult({})

    rc = run_loop(
        StubScheduler(), SchedulerConfig(schedule_interval_seconds=1.0),
        max_rounds=3, now_fn=now_fn, sleep_fn=sleep_fn,
        log=lambda *a: None,
    )
    assert rc == 0
    # two sleeps (the last round returns before sleeping), both the
    # deadline remainder — not the full interval
    assert sleeps == [0.7, 0.7]


def test_run_loop_pipelined_mode_places_and_drains():
    """run_loop with a TickPipeline: rounds place pods, the loop drains
    at max_rounds, and the pipeline worker is stopped on exit."""
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop

    rng = np.random.default_rng(3)
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, rng)
    for j in range(5):
        pod = PodSpec(name=f"p{j}",
                      requests={CPU: 500 + 10 * j, MEM: 256})
        bus.apply(Kind.POD, pod.uid, pod)
    pipeline = TickPipeline(sched, log=lambda *a: None)
    skipped = run_loop(
        sched, SchedulerConfig(schedule_interval_seconds=0.0),
        max_rounds=3, log=lambda *a: None, pipeline=pipeline,
    )
    assert skipped == 0
    for j in range(5):
        assert getattr(bus.get(Kind.POD, f"default/p{j}"),
                       "node_name", None) is not None
    assert pipeline.status()["stopped"]
    # debug mux surface registered by run_loop
    assert "tick-pipeline" in sched.services.names()


def test_run_loop_standby_surfaces_deferred_fence():
    """A deferred publish-side FencingError must surface (and run the
    fencing forget) in the STANDBY branch, not wait out the standby
    period: a deposed leader's phantom assumes would otherwise hold
    quota/gang/reservation credit until re-election."""
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop

    rng = np.random.default_rng(17)
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, rng)
    pod = PodSpec(name="p0", requests={CPU: 500, MEM: 256})
    bus.apply(Kind.POD, pod.uid, pod)

    calls = {"n": 0}

    def pub(out):
        i = calls["n"]
        calls["n"] += 1
        if i == 0:
            raise FencingError("injected: deposed mid-publish")
        sched.publish_result(out)

    forgets = []
    orig_forget = sched.forget_assumed_unbound

    def forget():
        out = orig_forget()
        forgets.append(len(out))
        return out

    sched.forget_assumed_unbound = forget

    class FlakyElector:
        # round 1 leads (its publish is fenced), then one standby
        # iteration (where the deferred error MUST surface), then
        # leads again for round 2
        retry_period = 0.0

        def __init__(self):
            self.pattern = [True, False, True]

        def tick(self, now):
            return self.pattern.pop(0) if self.pattern else True

    logs = []
    pipeline = TickPipeline(sched, publish=pub, log=lambda *a: None)
    skipped = run_loop(
        sched, SchedulerConfig(schedule_interval_seconds=0.0),
        max_rounds=2, log=lambda *a: logs.append(" ".join(map(str, a))),
        pipeline=pipeline, elector=FlakyElector(),
    )
    assert skipped == 1
    assert forgets and forgets[0] >= 1  # the fenced round was rolled back
    # the forget ran IN the standby branch: the fence log precedes the
    # standby log (surfacing at the next submit would order them after)
    fence_idx = next(i for i, m in enumerate(logs)
                     if "leadership lost" in m)
    standby_idx = next(i for i, m in enumerate(logs) if "standby" in m)
    assert fence_idx < standby_idx
    # round 2 re-placed and published the forgotten pod
    assert getattr(bus.get(Kind.POD, "default/p0"),
                   "node_name", None) is not None


def test_run_loop_chains_preexisting_flip_hooks():
    """run_loop's pipeline-drain flip wrappers must CHAIN a
    pre-existing on_flip_degraded/on_flip_back callback (the set-once
    wiring pattern build_scheduler uses), not silently replace it, and
    must restore the originals on exit."""
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop
    from koordinator_tpu.models.placement import ScheduleResult

    fired = []

    class FakeBackend:
        on_flip_back = None
        on_flip_degraded = None

    backend = FakeBackend()
    backend.on_flip_back = lambda: fired.append("prev-back")
    backend.on_flip_degraded = lambda: fired.append("prev-degraded")
    prevs = (backend.on_flip_back, backend.on_flip_degraded)

    class StubTick:
        inflight = None
        at = 0.0

    class StubScheduler:
        class model:
            backend = None

            @staticmethod
            def prestage(snap):
                pass

        class cache:
            @staticmethod
            def snapshot(now=None):
                return None

        class services:
            _m = {}

            @classmethod
            def register(cls, name, fn):
                cls._m[name] = fn

        def begin_tick(self, now=None, trigger=None):
            return StubTick()

        def commit_tick(self, tick):
            return ScheduleResult({})

    sched = StubScheduler()
    sched.model.backend = backend

    def sleep_fn(_s):
        # mid-loop (wrappers installed): a flip must drain AND chain
        backend.on_flip_degraded()
        backend.on_flip_back()

    pipeline = TickPipeline(sched, log=lambda *a: None)
    run_loop(
        sched, SchedulerConfig(schedule_interval_seconds=0.0),
        max_rounds=2, log=lambda *a: None, pipeline=pipeline,
        sleep_fn=sleep_fn,
    )
    assert fired == ["prev-degraded", "prev-back"]
    # originals restored on exit — a re-invoked run_loop must not
    # chain wrappers over this stopped pipeline
    assert (backend.on_flip_back, backend.on_flip_degraded) == prevs


def test_stop_abandoned_worker_drops_late_retire():
    """A publisher wedged past STOP_TIMEOUT_S is abandoned by stop();
    when the wedge clears, the worker must DROP the rest of the retire
    (publish-side effects, result hook, prestage) and exit — a
    re-invoked loop's fresh pipeline owns the scheduler by then."""
    import threading

    rng = np.random.default_rng(23)
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, rng)
    pod = PodSpec(name="p0", requests={CPU: 500, MEM: 256})
    bus.apply(Kind.POD, pod.uid, pod)

    release = threading.Event()
    results = []
    logs = []

    def wedged_pub(out):
        assert release.wait(10.0), "test deadlock: release never set"

    pipeline = TickPipeline(
        sched, publish=wedged_pub,
        log=lambda *a: logs.append(" ".join(map(str, a))),
        on_result=results.append,
    )
    pipeline.STOP_TIMEOUT_S = 0.2
    try:
        pipeline.submit_round(now=100.0)
        pipeline.stop()  # times out against the wedge and abandons
        assert pipeline.status()["stopped"]
        assert any("abandoning" in m for m in logs)
    finally:
        release.set()
    pipeline._worker.join(timeout=10.0)
    assert not pipeline._worker.is_alive(), "abandoned worker never exited"
    # everything after the wedge was dropped: no result hook, no
    # last-round status, and a dropped-retire log
    assert results == []
    assert pipeline.status()["last_round"] is None
    assert any("dropping the rest of the retire" in m for m in logs)


def test_warmed_pipelined_tick_zero_recompiles(xla_compiles):
    """The pipelined steady state runs entirely out of the jit caches:
    after warmup ticks (which compile the solve buckets AND both
    scatter variants — the prestage's non-donating double buffer
    included), an overlapped churn tick performs ZERO XLA compilations."""
    rng = np.random.default_rng(11)
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, rng)
    pipeline = TickPipeline(sched, log=lambda *a: None)

    def tick(t, now):
        for i in ((t * 2) % N_NODES, (t * 2 + 1) % N_NODES):
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: 4000 + t, MEM: 8192},
                update_time=now))
        for j in range(4):
            pod = PodSpec(name=f"w{t}p{j}",
                          requests={CPU: 300 + j, MEM: 128})
            bus.apply(Kind.POD, pod.uid, pod)
        pipeline.submit_round(now=now)
        pipeline.prestage(now=now)

    try:
        now = 100.0
        for t in range(4):  # cold + delta-path + both scatters + margin
            tick(t, now)
            now += 1.0
        pipeline.drain("test")
        assert sched.model.staged_cache.last_path == "delta"
        assert xla_compiles, "fixture captured no warmup compilations"
        xla_compiles.clear()
        tick(4, now)
        pipeline.drain("test")
        assert xla_compiles == [], (
            "steady-state pipelined tick recompiled:\n"
            + "\n".join(xla_compiles))
    finally:
        pipeline.stop()
