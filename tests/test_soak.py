"""Churn soak (VERDICT r3 #8): hundreds of rounds with pods arriving and
dying, the leader killed and re-elected mid-run, and the solver sidecar
killed and restarted — invariants asserted EVERY round.

The reference gets this assurance from production exposure; this soak
synthesizes it: one bus, manager admission + overcommit, two
leader-elected schedulers (A dies mid-soak, B takes over), a koordlet
sim per node actuating through the NRI path, and a mid-soak sidecar
restart on the standby-turned-leader.

Invariants (per round):
1. no double placement — an assigned pod keeps its node until deleted;
2. no leaked holds — every scheduler-cached assignment corresponds to a
   live bus pod, and per-node assigned CPU requests fit allocatable;
3. quota accounting exact — each quota's ``used`` equals the summed
   requests of its assigned member pods (nothing leaks on delete);
4. cgroup consistency — every running LS pod's bvt is 2 and every BE
   pod's is -1 in that node's fake cgroupfs after actuation.
"""

import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import (
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
)
from koordinator_tpu.client import APIServer, Kind, wire_scheduler
from koordinator_tpu.client.leaderelection import FencingError, LeaderElector
from koordinator_tpu.cmd.manager import ManagerConfig, build_manager
from koordinator_tpu.client import wire_manager
from koordinator_tpu.koordlet.system.cgroup import CPU_BVT_WARP_NS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.service.client import SolverUnavailable

from test_e2e_sim import KoordletSim, enabled_slo_controller

NODES = ("n0", "n1", "n2")
NODE_CPU, NODE_MEM = 16000, 32768
ROUNDS = 150


def _mk_pod(i, rng):
    qos = [QoSClass.LS, QoSClass.LS, QoSClass.BE][i % 3]
    quota = ["team-a", "team-b"][i % 2]
    return PodSpec(
        name=f"pod-{i}",
        qos=qos,
        priority=9500 if qos is QoSClass.LS else 5500,
        requests={R.CPU: int(rng.choice([500, 1000, 1500])),
                  R.MEMORY: int(rng.choice([512, 1024]))},
        quota=quota,
        labels={},
    )


def _quota_used_by_pods(bus, quota_name):
    total = np.zeros(2, dtype=np.int64)
    for pod in bus.list(Kind.POD).values():
        if pod.quota == quota_name and pod.node_name is not None:
            total[0] += pod.requests.get(R.CPU, 0)
            total[1] += pod.requests.get(R.MEMORY, 0)
    return total


def test_churn_soak_with_leader_and_sidecar_failover(tmp_path):
    bus = APIServer()
    manager = build_manager(ManagerConfig())
    manager_loop = wire_manager(bus, manager.noderesource,
                                nodeslo=enabled_slo_controller())

    # two leader-elected schedulers on one bus; A leads first. Rounds
    # advance simulated time 30s, so the lease windows must be wider
    # than the default 15s/10s (a leader that cannot renew within the
    # deadline demotes itself — correct behavior, wrong cadence here).
    sched_a, sched_b = Scheduler(), Scheduler()
    ea = LeaderElector(bus, "koord-scheduler", "a",
                       lease_duration=90.0, renew_deadline=60.0)
    eb = LeaderElector(bus, "koord-scheduler", "b",
                       lease_duration=90.0, renew_deadline=60.0)
    wire_scheduler(bus, sched_a, elector=ea)
    wire_scheduler(bus, sched_b, elector=eb)

    for quota in (
        QuotaSpec(name="team-a",
                  min={R.CPU: 4000, R.MEMORY: 8192},
                  max={R.CPU: 30000, R.MEMORY: 60000}),
        QuotaSpec(name="team-b",
                  min={R.CPU: 4000, R.MEMORY: 8192},
                  max={R.CPU: 30000, R.MEMORY: 60000}),
    ):
        bus.apply(Kind.QUOTA, quota.name, quota)

    for name in NODES:
        bus.apply(Kind.NODE, name, NodeSpec(
            name=name, allocatable={R.CPU: NODE_CPU, R.MEMORY: NODE_MEM}))
    sims = {name: KoordletSim(bus, name, tmp_path / name) for name in NODES}

    rng = np.random.default_rng(42)
    placements = {}           # uid -> node, from the moment of binding
    next_pod = 0
    live = []                 # uids in arrival order
    leader_killed = False
    solver_outage_rounds = 0
    failover_blackout_s = None

    for i in range(ROUNDS):
        t = 100.0 + 30.0 * i

        # -- churn: arrivals every round, departures every 3rd ----------
        pod = _mk_pod(next_pod, rng)
        next_pod += 1
        admitted, violations = manager.admit_pod(pod)
        assert not violations
        bus.apply(Kind.POD, admitted.uid, admitted)
        live.append(admitted.uid)
        if i % 3 == 2 and len(live) > 6:
            victim = live.pop(int(rng.integers(0, len(live) - 4)))
            bus.delete(Kind.POD, victim)
            placements.pop(victim, None)

        # -- node agents + manager -------------------------------------
        usage = {
            uid: (400, 256) for uid in live
        }
        for sim in sims.values():
            sim.step(t, usage)
        manager_loop.reconcile(now=t + 1)

        # -- mid-soak failure events ------------------------------------
        if i == 50 and not leader_killed:
            leader_killed = True  # A simply stops ticking (process death)
        if i == 100:
            # the new leader's rounds survive a solver outage signal:
            # SolverUnavailable skips the round (run_loop semantics) —
            # emulated here by a one-round forced outage
            solver_outage_rounds = 1

        # -- elected scheduling rounds ----------------------------------
        def elected_round(elector, scheduler, now):
            if not elector.tick(now):
                return None
            return scheduler.schedule_pending(now=now)

        out_a = None
        if not leader_killed:
            out_a = elected_round(ea, sched_a, t + 2)
        if solver_outage_rounds > 0:
            solver_outage_rounds -= 1  # round skipped (retry next tick)
            out_b = None
        else:
            probe = leader_killed and failover_blackout_s is None
            t0 = time.monotonic()
            out_b = elected_round(eb, sched_b, t + 2.5)
            if probe and out_b is not None:
                # the failover blackout: wall time of the new leader's
                # FIRST completed scheduling round after the old leader
                # died (solver warm-up included — the persistent
                # compilation cache is what keeps this bounded across
                # real process restarts, tests/test_compilation_cache.py)
                failover_blackout_s = time.monotonic() - t0

        # exactly one scheduler acted
        assert out_a is None or out_b is None

        # -- invariants, every round ------------------------------------
        pods_on_bus = bus.list(Kind.POD)
        per_node_cpu = {name: 0 for name in NODES}
        for uid, pod in pods_on_bus.items():
            if pod.node_name is None:
                continue
            # 1. placement is sticky: no double placement, no silent move
            if uid in placements:
                assert placements[uid] == pod.node_name, (
                    f"round {i}: {uid} moved {placements[uid]} -> "
                    f"{pod.node_name} without an eviction"
                )
            else:
                placements[uid] = pod.node_name
            per_node_cpu[pod.node_name] += pod.requests.get(R.CPU, 0)

        # 2a. per-node assigned native-CPU requests fit allocatable
        for name, used in per_node_cpu.items():
            node = bus.get(Kind.NODE, name)
            assert used <= node.allocatable[R.CPU], (
                f"round {i}: node {name} over-committed {used}"
            )

        # 2b. no leaked holds in the ACTIVE scheduler's cache
        active = sched_a if not leader_killed else sched_b
        for uid, cached in active.cache.pods.items():
            if cached.node_name is not None:
                assert uid in pods_on_bus, (
                    f"round {i}: cache holds deleted pod {uid}"
                )

        # 3. quota used == assigned member pods' requests (both quotas,
        #    both schedulers' managers — the standby tracks via watches)
        for qname in ("team-a", "team-b"):
            want = _quota_used_by_pods(bus, qname)
            info = active.quota_manager.quotas.get(qname)
            if info is not None:
                got = np.asarray(info.used, dtype=np.int64)
                assert got[R.CPU] == want[0] and got[R.MEMORY] == want[1], (
                    f"round {i}: quota {qname} used {got} != pods {want}"
                )

    # -- post-soak: the failover actually happened and was fenced --------
    assert leader_killed
    # the new leader's first round completed within a bounded blackout
    # (warm-path bound; the cross-process cold path is bounded by the
    # persistent compilation cache, tests/test_compilation_cache.py)
    assert failover_blackout_s is not None
    assert failover_blackout_s < 10.0, (
        f"failover solver blackout {failover_blackout_s:.1f}s"
    )
    with pytest.raises(FencingError):
        ea.fenced(lambda: None)
    placed = [u for u, p in bus.list(Kind.POD).items()
              if p.node_name is not None]
    assert len(placed) > 40  # the soak genuinely placed a fleet

    # settle: one more agent tick so pods bound in the final round get
    # their cgroups materialized and actuated before the check
    for sim in sims.values():
        sim.step(100.0 + 30.0 * ROUNDS, {})

    # 4. cgroup consistency on every node at the end of the soak
    for name, sim in sims.items():
        for pod in bus.list(Kind.POD).values():
            if pod.node_name != name:
                continue
            uid_dir = "pod" + pod.uid.replace("/", "_")
            if pod.qos is QoSClass.LS:
                assert CPU_BVT_WARP_NS.read(
                    f"kubepods/burstable/{uid_dir}", sim.cfg) == "2"
            elif pod.qos is QoSClass.BE:
                assert CPU_BVT_WARP_NS.read(
                    f"kubepods/besteffort/{uid_dir}", sim.cfg) == "-1"


def test_scaled_soak_trees_reservations_migrations():
    """VERDICT r4 #8: the soak at fleet scale — 56 nodes in two
    quota-tree pools, reservations and migration jobs active in the
    loop, the same placement/fit/quota invariants every round PLUS
    quota-tree isolation (admission-injected tree affinity keeps every
    tree pod on its pool even while the descheduler drains hot nodes
    through reservation-first migrations)."""
    from koordinator_tpu.client.wiring import wire_descheduler, wire_pod_webhook
    from koordinator_tpu.descheduler import (
        Descheduler,
        LowNodeLoad,
        LowNodeLoadArgs,
        MigrationEvictor,
        NodePool,
        Profile,
    )
    from koordinator_tpu.quota.profile import QuotaProfile

    N_PER_POOL = 28
    ROUNDS_SCALED = 120
    bus = APIServer()
    manager = build_manager(ManagerConfig())
    wire_pod_webhook(bus, manager.mutating_webhook)
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    desch_loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="lnl", balance_plugins=[LowNodeLoad(
            LowNodeLoadArgs(node_pools=[NodePool(
                low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70},
            )])
        )])],
        evictor=MigrationEvictor(),
    ))

    # two quota trees, one node pool each
    for pool in ("a", "b"):
        bus.apply(Kind.QUOTA_PROFILE, f"pool-{pool}", QuotaProfile(
            name=f"pool-{pool}", quota_name=f"root-{pool}",
            tree_id=f"tree-{pool}", node_selector={"pool": pool},
        ))
        bus.apply(Kind.QUOTA, f"team-{pool}", QuotaSpec(
            name=f"team-{pool}", tree_id=f"tree-{pool}",
            min={R.CPU: 20000, R.MEMORY: 40960},
            max={R.CPU: 300000, R.MEMORY: 600000},
        ))
        for i in range(N_PER_POOL):
            name = f"{pool}{i}"
            bus.apply(Kind.NODE, name, NodeSpec(
                name=name, labels={"pool": pool},
                allocatable={R.CPU: NODE_CPU, R.MEMORY: NODE_MEM},
            ))

    rng = np.random.default_rng(77)
    placements = {}
    migrated_uids = set()
    live = []
    next_pod = 0
    jobs_seen = 0
    resv_seen = 0

    def publish_metrics(now):
        """Synthesized NodeMetric per node: usage tracks assigned
        requests; a rotating hot set reports extra load to trigger the
        rebalancer."""
        by_node = {}
        for pod in bus.list(Kind.POD).values():
            if pod.node_name is not None:
                by_node.setdefault(pod.node_name, []).append(pod)
        for name in list(bus.list(Kind.NODE)):
            on_node = by_node.get(name, [])
            cpu = sum(p.requests.get(R.CPU, 0) for p in on_node)
            hot = name in hot_nodes
            metric = NodeMetric(
                node_name=name,
                node_usage={
                    R.CPU: min(cpu + (12000 if hot else 500), NODE_CPU),
                    R.MEMORY: 2048,
                },
                pod_usages={
                    p.uid: {R.CPU: p.requests.get(R.CPU, 0),
                            R.MEMORY: p.requests.get(R.MEMORY, 0)}
                    for p in on_node
                },
                update_time=now,
            )
            bus.apply(Kind.NODE_METRIC, name, metric)

    for i in range(ROUNDS_SCALED):
        t = 100.0 + 30.0 * i
        hot_nodes = {f"a{(i // 10) % N_PER_POOL}", f"b{(i // 7) % N_PER_POOL}"}

        # churn: two arrivals a round, a deletion every 3rd
        for _ in range(2):
            pod = _mk_pod(next_pod, rng)
            next_pod += 1
            admitted, violations = manager.admit_pod(pod)
            assert not violations
            # admission injected the tree selector for the pod's quota
            assert admitted.node_selector == {
                "pool": "a" if admitted.quota == "team-a" else "b"
            }
            bus.apply(Kind.POD, admitted.uid, admitted)
            live.append(admitted.uid)
        if i % 3 == 2 and len(live) > 12:
            victim = live.pop(int(rng.integers(0, len(live) - 8)))
            bus.delete(Kind.POD, victim)
            placements.pop(victim, None)

        publish_metrics(t)
        scheduler.schedule_pending(now=t + 1)
        if i >= 10 and i % 5 == 0:
            migrated_uids.update(desch_loop.run_once(now=t + 2))
            scheduler.schedule_pending(now=t + 3)  # re-place migrants
        jobs_seen = max(jobs_seen, len(bus.list(Kind.MIGRATION_JOB)))
        resv_seen = max(resv_seen, len(bus.list(Kind.RESERVATION)))

        # -- invariants, every round ------------------------------------
        pods_on_bus = bus.list(Kind.POD)
        per_node_cpu = {}
        for uid, pod in pods_on_bus.items():
            if pod.node_name is None:
                continue
            prev = placements.get(uid)
            if prev is not None and prev != pod.node_name:
                # a placement may only change through a migration
                assert uid in migrated_uids, (
                    f"round {i}: {uid} moved {prev} -> {pod.node_name} "
                    "without a migration job"
                )
            placements[uid] = pod.node_name
            per_node_cpu[pod.node_name] = (
                per_node_cpu.get(pod.node_name, 0)
                + pod.requests.get(R.CPU, 0)
            )
            # quota-tree isolation: tree pods stay on tree nodes
            want_pool = "a" if pod.quota == "team-a" else "b"
            assert pod.node_name.startswith(want_pool), (
                f"round {i}: {uid} (quota {pod.quota}) escaped to "
                f"{pod.node_name}"
            )
        for name, used in per_node_cpu.items():
            node = bus.get(Kind.NODE, name)
            assert used <= node.allocatable[R.CPU]
        for qname in ("team-a", "team-b"):
            want = _quota_used_by_pods(bus, qname)
            info = scheduler.quota_manager.quotas.get(qname)
            if info is not None:
                got = np.asarray(info.used, dtype=np.int64)
                assert got[R.CPU] == want[0] and got[R.MEMORY] == want[1]

    # the loop genuinely exercised the machinery at scale
    placed = [u for u, p in bus.list(Kind.POD).items()
              if p.node_name is not None]
    assert len(placed) > 50
    assert jobs_seen >= 1, "no migration job was ever created"
    assert resv_seen >= 1, "no reservation was ever created"
    assert migrated_uids, "no pod was actually migrated"
