"""The north-star `--placement-backend=sidecar` loop (VERDICT r2 item 1).

Reference boundary: cmd/koord-scheduler/app/server.go:331-398 wires the
plugin backend behind the component config; here the same selection
routes PlacementModel's batched solves through the koord-solver sidecar
(service/), and the control plane survives sidecar restarts.
"""

import copy
import os

import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.client import APIServer, Kind, wire_scheduler
from koordinator_tpu.cmd.scheduler import SchedulerConfig, build_scheduler
from koordinator_tpu.cmd.solver import parse_address
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.service.client import RemoteSolver, SolverUnavailable
from koordinator_tpu.service.server import PlacementService


def _full_snapshot(now=100.0):
    """Quota + gang + reservation + node-selector extras in one solve."""
    nodes = [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: 16000, R.MEMORY: 32768},
                 labels={"zone": "a" if i % 2 == 0 else "b"})
        for i in range(6)
    ]
    metrics = {
        n.name: NodeMetric(node_name=n.name, node_usage={R.CPU: 500},
                           update_time=now - 1)
        for n in nodes
    }
    pending = [
        PodSpec(name="plain", requests={R.CPU: 2000}),
        PodSpec(name="quota1", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="quota2", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="g1", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="g2", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="zoned", requests={R.CPU: 1000},
                node_selector={"zone": "b"}),
        PodSpec(name="owner", labels={"app": "x"},
                requests={R.CPU: 2000}),
    ]
    return ClusterSnapshot(
        nodes=nodes,
        pods=[],
        pending_pods=pending,
        node_metrics=metrics,
        quotas={"t": QuotaSpec(name="t", min={R.CPU: 4000},
                               max={R.CPU: 50000})},
        gangs={"g": GangSpec(name="g", min_member=2)},
        reservations=[ReservationSpec(
            name="rx", node_name="n3", state=ReservationState.AVAILABLE,
            allocatable={R.CPU: 2000}, owner_labels={"app": "x"},
            allocate_once=True)],
        now=now,
    )


class TestRemoteSolverDifferential:
    def test_sidecar_matches_inprocess_full_features(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            local = PlacementModel()
            remote = PlacementModel(backend=RemoteSolver(addr))
            snap_a = _full_snapshot()
            snap_b = copy.deepcopy(snap_a)
            out_local = local.schedule(snap_a)
            out_remote = remote.schedule(snap_b)
            assert dict(out_local) == dict(out_remote)
            assert out_local.waiting == out_remote.waiting
            # the reservation epilogue ran identically on both sides
            ra = snap_a.reservations[0]
            rb = snap_b.reservations[0]
            assert ra.allocated == rb.allocated
            assert ra.state == rb.state
        finally:
            service.stop()


class TestNorthStarFlow:
    def test_webhook_to_sidecar_binding_with_restart(self, tmp_path):
        """Webhook-admitted pods flow bus -> scheduler -> sidecar solver
        -> binding; the sidecar dies and restarts mid-run and scheduling
        resumes warm (the whole point of the boundary)."""
        from koordinator_tpu.cmd.manager import ManagerConfig, build_manager

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()

        scheduler = build_scheduler(SchedulerConfig(
            placement_backend="sidecar", solver_address=addr))
        assert scheduler.model.backend is not None
        bus = APIServer()
        wire_scheduler(bus, scheduler)
        manager = build_manager(ManagerConfig())
        from koordinator_tpu.webhook.mutating import ClusterColocationProfile

        manager.mutating_webhook.update_profile(ClusterColocationProfile(
            name="colo", selector={"app": "batchjob"},
            qos_class=QoSClass.BE, priority=5500))

        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768,
                                    R.BATCH_CPU: 8000,
                                    R.BATCH_MEMORY: 16384}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=99.0))

        # admission: the mutating webhook translates the BE pod's native
        # requests into batch resources before it reaches the bus
        raw = PodSpec(name="be", labels={"app": "batchjob"},
                      requests={R.CPU: 2000, R.MEMORY: 1024})
        admitted, violations = manager.admit_pod(raw)
        assert violations == []
        assert admitted.qos == QoSClass.BE
        assert R.BATCH_CPU in admitted.requests
        bus.apply(Kind.POD, admitted.uid, admitted)

        out = scheduler.schedule_pending(now=100.0)
        assert out[admitted.uid] == "n0"

        # ---- kill the sidecar mid-run ----
        service.stop()
        os.unlink(addr)
        late = PodSpec(name="late", requests={R.CPU: 1000})
        bus.apply(Kind.POD, late.uid, late)
        with pytest.raises(SolverUnavailable):
            scheduler.schedule_pending(now=101.0)

        # ---- restart it in place: the control plane reconnects ----
        service2 = PlacementService(addr)
        service2.start()
        try:
            out = scheduler.schedule_pending(now=102.0)
            assert out[late.uid] == "n0"
            # earlier binding survived the outage
            assert scheduler.cache.pods[admitted.uid].node_name == "n0"
        finally:
            service2.stop()
            scheduler.model.backend.close()


class TestAddressParsing:
    def test_parse(self):
        assert parse_address("/tmp/x.sock") == "/tmp/x.sock"
        assert parse_address("127.0.0.1:9999") == ("127.0.0.1", 9999)
        assert parse_address(":9999") == ("127.0.0.1", 9999)


class _ScriptedSidecar:
    """A minimal frame server for retry tests: answers the first
    ``shed_first`` requests with a typed ``overloaded`` error, then
    real solves; records every decoded request."""

    def __init__(self, addr: str, shed_first: int = 0):
        import socket
        import threading

        from koordinator_tpu.service.admission import error_response
        from koordinator_tpu.service.codec import (
            decode_request,
            encode_response,
            read_frame,
            write_frame,
        )
        from koordinator_tpu.service.server import solve_from_request

        self.requests = []
        self._shed_first = shed_first
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(addr)
        self._sock.listen(4)
        self._sock.settimeout(0.2)

        def serve_conn(conn):
            stream = conn.makefile("rwb")
            try:
                while True:
                    payload = read_frame(stream)
                    if payload is None:
                        return
                    req = decode_request(payload)
                    self.requests.append(req)
                    if len(self.requests) <= self._shed_first:
                        resp = error_response(
                            "overloaded", "scripted shed"
                        )
                    else:
                        resp = solve_from_request(req)
                    write_frame(stream, encode_response(resp))
                    stream.flush()
            except (OSError, EOFError, ValueError):
                pass
            finally:
                stream.close()
                conn.close()

        def accept_loop():
            import socket as _socket

            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except (_socket.timeout, OSError):
                    continue
                threading.Thread(
                    target=serve_conn, args=(conn,), daemon=True
                ).start()

        self._thread = threading.Thread(target=accept_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._sock.close()


def _wire_problem(n_nodes=4, n_pods=5):
    """(state, batch, params, config) device inputs for solve_result."""
    import jax.numpy as jnp
    import numpy as np

    from koordinator_tpu.apis.extension import NUM_RESOURCES
    from koordinator_tpu.ops.binpack import (
        NodeState,
        PodBatch,
        ScoreParams,
        SolverConfig,
    )

    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.zeros_like(jnp.asarray(alloc)),
        usage=jnp.zeros_like(jnp.asarray(alloc)),
        prod_usage=jnp.zeros_like(jnp.asarray(alloc)),
        est_extra=jnp.zeros_like(jnp.asarray(alloc)),
        prod_base=jnp.zeros_like(jnp.asarray(alloc)),
        metric_fresh=jnp.ones(n_nodes, bool),
        schedulable=jnp.ones(n_nodes, bool),
    )
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = 1000
    batch = PodBatch.build(
        req=jnp.asarray(req), est=jnp.asarray((req * 85) // 100),
        is_prod=jnp.zeros(n_pods, bool),
        is_daemonset=jnp.zeros(n_pods, bool),
    )
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    params = ScoreParams(
        weights=jnp.asarray(weights),
        thresholds=jnp.asarray(thresholds),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, np.int32),
    )
    return state, batch, params, SolverConfig()


class TestRemoteSolverBackoff:
    """Satellite 2: jittered exponential backoff with a total-deadline
    cap for overloaded sheds AND unreachable sidecars — a slow/shedding
    sidecar can no longer hang a scheduler tick for the socket timeout."""

    def test_overloaded_retries_then_succeeds(self, tmp_path):
        import numpy as np

        addr = str(tmp_path / "scripted.sock")
        sidecar = _ScriptedSidecar(addr, shed_first=2)
        try:
            solver = RemoteSolver(
                addr, backoff_base_s=0.01, backoff_cap_s=0.05,
                retry_total_s=10.0,
            )
            result = solver.solve_result(*_wire_problem())
            assert (np.asarray(result.assign) >= 0).all()
            # two sheds + the success all rode ONE connection
            assert len(sidecar.requests) == 3
            solver.close()
        finally:
            sidecar.stop()

    def test_overloaded_exhausts_total_deadline_cap(self, tmp_path):
        import time as _time

        from koordinator_tpu.service.client import SolverOverloaded

        addr = str(tmp_path / "scripted.sock")
        sidecar = _ScriptedSidecar(addr, shed_first=10**6)
        try:
            solver = RemoteSolver(
                addr, backoff_base_s=0.02, backoff_cap_s=0.1,
                retry_total_s=0.3,
            )
            t0 = _time.monotonic()
            with pytest.raises(SolverOverloaded):
                solver.solve_result(*_wire_problem())
            assert _time.monotonic() - t0 < 2.0
            assert len(sidecar.requests) >= 2  # it did retry
            solver.close()
        finally:
            sidecar.stop()

    def test_deadline_and_lane_ride_the_wire(self, tmp_path):
        import numpy as np

        from koordinator_tpu.service.admission import LANE_BE

        addr = str(tmp_path / "scripted.sock")
        sidecar = _ScriptedSidecar(addr)
        try:
            solver = RemoteSolver(addr, deadline_s=5.0, lane="be")
            solver.solve_result(*_wire_problem())
            adm = sidecar.requests[0].admission
            assert adm is not None
            sent = float(np.asarray(adm["deadline_s"]).item())
            assert 0.0 < sent <= 5.0  # the REMAINING budget crossed
            assert int(np.asarray(adm["lane"]).item()) == LANE_BE
            solver.close()
        finally:
            sidecar.stop()

    def test_unreachable_bounded_by_total_deadline(self, tmp_path):
        import time as _time

        t0 = _time.monotonic()
        solver = RemoteSolver(
            str(tmp_path / "nowhere.sock"),
            backoff_base_s=0.01, retry_total_s=0.3,
        )
        with pytest.raises(SolverUnavailable):
            solver.solve_result(*_wire_problem())
        assert _time.monotonic() - t0 < 2.0

    def test_client_side_deadline_trumps_retries(self, tmp_path):
        from koordinator_tpu.service.client import SolverDeadlineExceeded

        solver = RemoteSolver(
            str(tmp_path / "nowhere.sock"),
            deadline_s=0.2, backoff_base_s=0.05,
        )
        with pytest.raises((SolverDeadlineExceeded, SolverUnavailable)):
            solver.solve_result(*_wire_problem())
