"""The north-star `--placement-backend=sidecar` loop (VERDICT r2 item 1).

Reference boundary: cmd/koord-scheduler/app/server.go:331-398 wires the
plugin backend behind the component config; here the same selection
routes PlacementModel's batched solves through the koord-solver sidecar
(service/), and the control plane survives sidecar restarts.
"""

import copy
import os

import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.client import APIServer, Kind, wire_scheduler
from koordinator_tpu.cmd.scheduler import SchedulerConfig, build_scheduler
from koordinator_tpu.cmd.solver import parse_address
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.service.client import RemoteSolver, SolverUnavailable
from koordinator_tpu.service.server import PlacementService


def _full_snapshot(now=100.0):
    """Quota + gang + reservation + node-selector extras in one solve."""
    nodes = [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: 16000, R.MEMORY: 32768},
                 labels={"zone": "a" if i % 2 == 0 else "b"})
        for i in range(6)
    ]
    metrics = {
        n.name: NodeMetric(node_name=n.name, node_usage={R.CPU: 500},
                           update_time=now - 1)
        for n in nodes
    }
    pending = [
        PodSpec(name="plain", requests={R.CPU: 2000}),
        PodSpec(name="quota1", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="quota2", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="g1", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="g2", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="zoned", requests={R.CPU: 1000},
                node_selector={"zone": "b"}),
        PodSpec(name="owner", labels={"app": "x"},
                requests={R.CPU: 2000}),
    ]
    return ClusterSnapshot(
        nodes=nodes,
        pods=[],
        pending_pods=pending,
        node_metrics=metrics,
        quotas={"t": QuotaSpec(name="t", min={R.CPU: 4000},
                               max={R.CPU: 50000})},
        gangs={"g": GangSpec(name="g", min_member=2)},
        reservations=[ReservationSpec(
            name="rx", node_name="n3", state=ReservationState.AVAILABLE,
            allocatable={R.CPU: 2000}, owner_labels={"app": "x"},
            allocate_once=True)],
        now=now,
    )


class TestRemoteSolverDifferential:
    def test_sidecar_matches_inprocess_full_features(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            local = PlacementModel()
            remote = PlacementModel(backend=RemoteSolver(addr))
            snap_a = _full_snapshot()
            snap_b = copy.deepcopy(snap_a)
            out_local = local.schedule(snap_a)
            out_remote = remote.schedule(snap_b)
            assert dict(out_local) == dict(out_remote)
            assert out_local.waiting == out_remote.waiting
            # the reservation epilogue ran identically on both sides
            ra = snap_a.reservations[0]
            rb = snap_b.reservations[0]
            assert ra.allocated == rb.allocated
            assert ra.state == rb.state
        finally:
            service.stop()


class TestNorthStarFlow:
    def test_webhook_to_sidecar_binding_with_restart(self, tmp_path):
        """Webhook-admitted pods flow bus -> scheduler -> sidecar solver
        -> binding; the sidecar dies and restarts mid-run and scheduling
        resumes warm (the whole point of the boundary)."""
        from koordinator_tpu.cmd.manager import ManagerConfig, build_manager

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()

        scheduler = build_scheduler(SchedulerConfig(
            placement_backend="sidecar", solver_address=addr))
        assert scheduler.model.backend is not None
        bus = APIServer()
        wire_scheduler(bus, scheduler)
        manager = build_manager(ManagerConfig())
        from koordinator_tpu.webhook.mutating import ClusterColocationProfile

        manager.mutating_webhook.update_profile(ClusterColocationProfile(
            name="colo", selector={"app": "batchjob"},
            qos_class=QoSClass.BE, priority=5500))

        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768,
                                    R.BATCH_CPU: 8000,
                                    R.BATCH_MEMORY: 16384}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=99.0))

        # admission: the mutating webhook translates the BE pod's native
        # requests into batch resources before it reaches the bus
        raw = PodSpec(name="be", labels={"app": "batchjob"},
                      requests={R.CPU: 2000, R.MEMORY: 1024})
        admitted, violations = manager.admit_pod(raw)
        assert violations == []
        assert admitted.qos == QoSClass.BE
        assert R.BATCH_CPU in admitted.requests
        bus.apply(Kind.POD, admitted.uid, admitted)

        out = scheduler.schedule_pending(now=100.0)
        assert out[admitted.uid] == "n0"

        # ---- kill the sidecar mid-run ----
        service.stop()
        os.unlink(addr)
        late = PodSpec(name="late", requests={R.CPU: 1000})
        bus.apply(Kind.POD, late.uid, late)
        with pytest.raises(SolverUnavailable):
            scheduler.schedule_pending(now=101.0)

        # ---- restart it in place: the control plane reconnects ----
        service2 = PlacementService(addr)
        service2.start()
        try:
            out = scheduler.schedule_pending(now=102.0)
            assert out[late.uid] == "n0"
            # earlier binding survived the outage
            assert scheduler.cache.pods[admitted.uid].node_name == "n0"
        finally:
            service2.stop()
            scheduler.model.backend.close()


class TestAddressParsing:
    def test_parse(self):
        assert parse_address("/tmp/x.sock") == "/tmp/x.sock"
        assert parse_address("127.0.0.1:9999") == ("127.0.0.1", 9999)
        assert parse_address(":9999") == ("127.0.0.1", 9999)
