"""Recommendation/analysis pipeline (VERDICT round-2 ask 6).

Reference: apis/analysis/v1alpha1/recommendation_types.go:55 — targets a
workload or pod selector; status carries recommended resources. The
controller computes status from the same decaying-histogram peaks the
koordlet prediction subsystem uses, and the webhook consumes it to
right-size pod requests from observed usage.
"""

from koordinator_tpu.apis.analysis import (
    CONDITION_NO_SAMPLES,
    CONDITION_READY,
    Recommendation,
    RecommendationTarget,
)
from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.client import APIServer, Kind
from koordinator_tpu.manager.recommendation import (
    RecommendationController,
    wire_recommendation,
)
from koordinator_tpu.webhook import PodMutatingWebhook

WORKLOAD = "Deployment/default/web"


def seed(bus, n_pods=3):
    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 32000, R.MEMORY: 65536}))
    for i in range(n_pods):
        bus.apply(Kind.POD, f"default/web-{i}", PodSpec(
            name=f"web-{i}", owner=WORKLOAD, node_name="n0",
            labels={"app": "web"},
            requests={R.CPU: 4000, R.MEMORY: 8192}))


def report(bus, t, cpu, mem, n_pods=3):
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0",
        node_usage={R.CPU: cpu * n_pods, R.MEMORY: mem * n_pods},
        pod_usages={
            f"default/web-{i}": {R.CPU: cpu, R.MEMORY: mem}
            for i in range(n_pods)
        },
        update_time=t,
    ))


class TestController:
    def test_no_samples_condition(self):
        bus = APIServer()
        c = RecommendationController(bus)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD)))
        assert c.run_once(now=10.0) == 1
        rec = bus.get(Kind.RECOMMENDATION, "web")
        assert rec.conditions[CONDITION_NO_SAMPLES] is True
        assert not rec.ready

    def test_peaks_become_status(self):
        """Pods requesting 4000m/8192Mi but using ~1000m/2048Mi get a
        recommendation near usage x safety margin (p95 cpu / p98 mem,
        +10% — predict_server semantics), far below the request."""
        bus = APIServer()
        c = RecommendationController(bus)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD)))
        seed(bus)
        for k in range(20):
            report(bus, t=float(k + 1), cpu=1000, mem=2048)
            c.observe(now=float(k + 1))
        assert c.reconcile(now=30.0) == 1
        rec = bus.get(Kind.RECOMMENDATION, "web")
        assert rec.ready and rec.conditions[CONDITION_READY]
        assert 1000 <= rec.recommended[R.CPU] <= 1400
        assert 2048 <= rec.recommended[R.MEMORY] <= 2600

    def test_selector_target_and_stale_metric_dedup(self):
        bus = APIServer()
        c = RecommendationController(bus)
        bus.apply(Kind.RECOMMENDATION, "by-label", Recommendation(
            name="by-label",
            target=RecommendationTarget(pod_selector={"app": "web"})))
        seed(bus, n_pods=1)
        report(bus, t=5.0, cpu=500, mem=1024, n_pods=1)
        assert c.observe(now=5.0) == 1
        # same update_time again: no new samples folded in
        assert c.observe(now=6.0) == 0
        report(bus, t=7.0, cpu=500, mem=1024, n_pods=1)
        assert c.observe(now=7.0) == 1

    def test_unmatched_pods_ignored(self):
        bus = APIServer()
        c = RecommendationController(bus)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload="Deployment/default/other")))
        seed(bus)
        report(bus, t=1.0, cpu=1000, mem=2048)
        assert c.observe(now=1.0) == 0


class TestFailoverSafety:
    def test_fresh_controller_does_not_clobber_ready_status(self):
        """Post-failover warm-up: a new leader's empty histogram bank
        must not overwrite a ready Recommendation a previous leader
        published (code-review regression)."""
        bus = APIServer()
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD),
            recommended={R.CPU: 1200}, update_time=5.0,
            conditions={CONDITION_READY: True}))
        fresh = RecommendationController(bus)
        assert fresh.reconcile(now=10.0) == 0
        rec = bus.get(Kind.RECOMMENDATION, "web")
        assert rec.ready and rec.recommended == {R.CPU: 1200}

    def test_preseeded_value_still_gains_ready_condition(self):
        """A Recommendation seeded with a recommended value but no
        conditions must become consumable once the controller computes
        the same value (code-review regression)."""
        bus = APIServer()
        c = RecommendationController(bus)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD),
            recommended={R.CPU: 550, R.MEMORY: 1123}))
        seed(bus, n_pods=1)
        for k in range(10):
            report(bus, t=float(k + 1), cpu=500, mem=1024, n_pods=1)
            c.observe(now=float(k + 1))
        assert c.reconcile(now=20.0) == 1
        assert bus.get(Kind.RECOMMENDATION, "web").ready

    def test_deposed_controller_publish_is_fenced(self):
        from koordinator_tpu.client.leaderelection import (
            FencingError,
            LeaderElector,
        )

        bus = APIServer()
        ea = LeaderElector(bus, "koord-manager", "a")
        eb = LeaderElector(bus, "koord-manager", "b")
        c = RecommendationController(bus, elector=ea)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD)))
        seed(bus, n_pods=1)
        report(bus, t=1.0, cpu=500, mem=1024, n_pods=1)
        ea.tick(0.0)
        c.observe(now=1.0)
        eb.tick(20.0)                 # failover: a deposed
        import pytest

        with pytest.raises(FencingError):
            c.reconcile(now=21.0)
        assert not bus.get(Kind.RECOMMENDATION, "web").ready


class TestWebhookConsumption:
    def test_pod_requests_right_sized_from_observed_usage(self):
        """The VERDICT done-criterion: a pod's requests get right-sized
        from observed usage in a bus test."""
        bus = APIServer()
        webhook = PodMutatingWebhook()
        controller = wire_recommendation(bus, webhook)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD)))
        seed(bus)
        for k in range(20):
            report(bus, t=float(k + 1), cpu=1000, mem=2048)
            controller.observe(now=float(k + 1))
        controller.reconcile(now=30.0)

        # a new replica arrives over-requesting 4 cores; admission sizes
        # it to the observed peak (and lifts no limits since none set)
        pod = PodSpec(name="web-new", owner=WORKLOAD,
                      requests={R.CPU: 4000, R.MEMORY: 8192})
        webhook.mutate(pod)
        assert 1000 <= pod.requests[R.CPU] <= 1400
        assert 2048 <= pod.requests[R.MEMORY] <= 2600

    def test_limits_grow_to_cover_request(self):
        bus = APIServer()
        webhook = PodMutatingWebhook()
        wire_recommendation(bus, webhook)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD),
            recommended={R.CPU: 3000}, update_time=1.0,
            conditions={CONDITION_READY: True}))
        pod = PodSpec(name="p", owner=WORKLOAD,
                      requests={R.CPU: 1000}, limits={R.CPU: 2000})
        webhook.mutate(pod)
        assert pod.requests[R.CPU] == 3000
        assert pod.limits[R.CPU] == 3000

    def test_not_ready_recommendation_leaves_pod_untouched(self):
        bus = APIServer()
        webhook = PodMutatingWebhook()
        wire_recommendation(bus, webhook)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD)))
        pod = PodSpec(name="p", owner=WORKLOAD, requests={R.CPU: 1000})
        webhook.mutate(pod)
        assert pod.requests[R.CPU] == 1000

    def test_only_requested_resources_sized(self):
        bus = APIServer()
        webhook = PodMutatingWebhook()
        wire_recommendation(bus, webhook)
        bus.apply(Kind.RECOMMENDATION, "web", Recommendation(
            name="web", target=RecommendationTarget(workload=WORKLOAD),
            recommended={R.CPU: 3000, R.MEMORY: 4096}, update_time=1.0,
            conditions={CONDITION_READY: True}))
        pod = PodSpec(name="p", owner=WORKLOAD, requests={R.CPU: 1000})
        webhook.mutate(pod)
        assert pod.requests[R.CPU] == 3000
        assert R.MEMORY not in pod.requests  # never invents a request
