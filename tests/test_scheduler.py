"""Scheduler framework tests: batch + incremental paths, reservations,
monitor/debug services."""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.scheduler import Scheduler


def _mk_scheduler(n_nodes=3, cpu=16000, mem=32768):
    s = Scheduler(cluster_total={R.CPU: n_nodes * cpu, R.MEMORY: n_nodes * mem})
    for i in range(n_nodes):
        s.add_node(
            NodeSpec(name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem})
        )
        s.update_node_metric(
            NodeMetric(
                node_name=f"n{i}", node_usage={R.CPU: 500}, update_time=99.0
            )
        )
    return s


def test_batched_round_commits_and_next_round_sees_state():
    s = _mk_scheduler(2)
    for i in range(2):
        s.add_pod(PodSpec(name=f"p{i}", requests={R.CPU: 6000, R.MEMORY: 4096}))
    out = s.schedule_pending(now=100.0)
    assert all(v is not None for v in out.values())
    # spreading: least-allocated puts them on different nodes
    assert len(set(out.values())) == 2

    # second round: a big pod that only fits because it sees prior commits
    s.add_pod(PodSpec(name="big", requests={R.CPU: 10000}))
    out2 = s.schedule_pending(now=101.0)
    assert out2["default/big"] is not None
    # third round: nothing pending
    assert s.schedule_pending(now=102.0) == {}


def test_incremental_path_binds():
    s = _mk_scheduler(3)
    s.add_pod(PodSpec(name="a", requests={R.CPU: 2000}))
    outcome = s.schedule_one("default/a", now=100.0)
    assert outcome.status == "bound" and outcome.node is not None
    assert "default/a" in s.cache.pods


def test_incremental_gang_waits_then_allows():
    s = _mk_scheduler(3)
    s.update_gang(GangSpec(name="g", min_member=2))
    s.add_pod(PodSpec(name="g0", gang="g", requests={R.CPU: 1000}))
    s.add_pod(PodSpec(name="g1", gang="g", requests={R.CPU: 1000}))
    o0 = s.schedule_one("default/g0", now=100.0)
    assert o0.status == "waiting"  # permit barrier
    o1 = s.schedule_one("default/g1", now=100.0)
    assert o1.status == "bound"


def test_reservation_held_for_owner():
    s = _mk_scheduler(1, cpu=10000)
    # reservation holds 8 cores for team=ml pods on the single node
    s.update_reservation(
        ReservationSpec(
            name="resv",
            requests={R.CPU: 8000},
            allocatable={R.CPU: 8000},
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
        )
    )
    # a non-owner pod asking 4 cores: only 2 cores unreserved -> unschedulable
    s.add_pod(PodSpec(name="other", requests={R.CPU: 4000}))
    out = s.schedule_pending(now=100.0)
    assert out["default/other"] is None

    # an owner pod asking 4 cores gets the reserved capacity
    s.add_pod(PodSpec(name="mlpod", requests={R.CPU: 4000}, labels={"team": "ml"}))
    outcome = s.schedule_one("default/mlpod", now=100.0)
    assert outcome.status == "bound" and outcome.node == "n0"
    # allocation recorded on the reservation
    resv = s.cache.reservations["resv"]
    assert resv.allocated.get(R.CPU) == 4000
    import koordinator_tpu.apis.extension as ext

    pod = s.cache.pods["default/mlpod"]
    assert pod.annotations.get(ext.ANNOTATION_RESERVATION_ALLOCATED) == "resv"


def test_quota_gates_incremental_path():
    s = _mk_scheduler(2)
    s.update_quota(QuotaSpec(name="t", min={R.CPU: 1000}, max={R.CPU: 3000}))
    s.add_pod(PodSpec(name="a", quota="t", requests={R.CPU: 3000}))
    s.add_pod(PodSpec(name="b", quota="t", requests={R.CPU: 1000}))
    assert s.schedule_one("default/a", now=100.0).status == "bound"
    out_b = s.schedule_one("default/b", now=100.0)
    assert out_b.status == "unschedulable"
    assert "quota" in out_b.reason


def test_monitor_and_debug_services():
    s = _mk_scheduler(1)
    s.debug.dump_scores = True
    s.add_pod(PodSpec(name="a", requests={R.CPU: 1000}))
    s.schedule_one("default/a", now=100.0)
    assert s.debug.scores and "n0" in s.debug.scores[0]["scores"]
    assert "Coscheduling" in s.services.names()
    # only the implicit root exists before any quota is registered
    assert list(s.services.query("ElasticQuota")) == ["root"]
    # the monitor is a span-fed watchdog now: an open tracer mark older
    # than the timeout reads as a stuck cycle. A FRESH tracer, not the
    # process-global one: marks leaked by unrelated earlier tests (or
    # left behind here) must not couple test outcomes
    from koordinator_tpu.obs.trace import SpanTracer
    from koordinator_tpu.scheduler.monitor import SchedulerMonitor

    tracer = SpanTracer()
    mon = SchedulerMonitor(tracer=tracer, log=lambda *a: None)
    tracer.mark_open("round:999")
    stuck = mon.check_stuck(now=tracer.now() + 99.0)
    assert "round:999" in stuck
    tracer.mark_closed("round:999")
    assert mon.check_stuck() == []


def test_batch_and_incremental_agree():
    def build():
        s = _mk_scheduler(4)
        rng = np.random.default_rng(3)
        for i in range(12):
            s.add_pod(
                PodSpec(
                    name=f"p{i}",
                    priority=int(rng.choice([9500, 5500])),
                    requests={
                        R.CPU: int(rng.choice([1000, 2000, 4000])),
                        R.MEMORY: int(rng.choice([1024, 4096])),
                    },
                )
            )
        return s

    s_batch = build()
    batch_out = dict(s_batch.schedule_pending(now=100.0))

    s_inc = build()
    from koordinator_tpu.state.cluster import schedule_order

    pending = list(s_inc.cache.pending.values())
    inc_out = {}
    for i in schedule_order(pending):
        uid = pending[i].uid
        outcome = s_inc.schedule_one(uid, now=100.0)
        inc_out[uid] = outcome.node
    assert batch_out == inc_out

class TestTransformerExtensionPoints:
    """Reference: frameworkext/interface.go:78-97 — AfterPreFilter,
    BeforeFilter, BeforeScore granularity (round-2 coverage item 4)."""

    def test_full_transformer_chain(self):
        import dataclasses

        from koordinator_tpu.apis.types import ClusterSnapshot
        from koordinator_tpu.scheduler.framework import (
            Plugin,
            SchedulingFramework,
            Status,
        )

        calls = []

        class Transformer(Plugin):
            name = "T"

            def before_pre_filter(self, state, snapshot, pod):
                calls.append("before_pre_filter")
                return False

            def after_pre_filter(self, state, snapshot, pod):
                calls.append("after_pre_filter")

            def before_filter(self, state, snapshot, pod, node):
                calls.append(f"before_filter:{node.name}")
                # substitute a pod view with a bigger request
                bigger = dataclasses.replace(
                    pod, requests={R.CPU: pod.requests[R.CPU] * 10}
                )
                return bigger, node

            def before_score(self, state, snapshot, pod, nodes):
                calls.append("before_score")
                # restrict scoring to n1
                return pod, [n for n in nodes if n.name == "n1"]

        class Fit(Plugin):
            name = "Fit"

            def filter(self, state, snapshot, pod, node):
                # sees the transformed 10x request: only big nodes pass
                if pod.requests[R.CPU] <= node.allocatable[R.CPU]:
                    return Status.success()
                return Status.unschedulable_("too big")

        snapshot = ClusterSnapshot(
            nodes=[
                NodeSpec(name="n0", allocatable={R.CPU: 20000}),
                NodeSpec(name="n1", allocatable={R.CPU: 20000}),
                NodeSpec(name="small", allocatable={R.CPU: 1000}),
            ],
        )
        fw = SchedulingFramework([Transformer(), Fit()])
        pod = PodSpec(name="p", requests={R.CPU: 2000})
        out = fw.schedule_one(snapshot, pod)
        # transformed request (20000) fits n0/n1 but not small;
        # before_score then restricts to n1
        assert out.status == "bound" and out.node == "n1"
        assert calls[0] == "before_pre_filter"
        assert "after_pre_filter" in calls
        assert any(c.startswith("before_filter:") for c in calls)
        assert "before_score" in calls
        # after_pre_filter ran before any filter
        assert calls.index("after_pre_filter") < calls.index("before_filter:n0")

    def test_after_pre_filter_runs_on_rejection(self):
        from koordinator_tpu.apis.types import ClusterSnapshot
        from koordinator_tpu.scheduler.framework import (
            Plugin,
            SchedulingFramework,
            Status,
        )

        seen = []

        class Gate(Plugin):
            name = "Gate"

            def pre_filter(self, state, snapshot, pod):
                return Status.unschedulable_("nope")

            def after_pre_filter(self, state, snapshot, pod):
                seen.append("after")

        fw = SchedulingFramework([Gate()])
        out = fw.schedule_one(
            ClusterSnapshot(nodes=[NodeSpec(name="n0")]),
            PodSpec(name="p"),
        )
        assert out.status == "unschedulable"
        assert seen == ["after"]


def test_node_selector_enforced_on_both_paths():
    """Required node selectors gate both the incremental fit Filter and
    the batched solver (round-2 review fix: eviction/reschedule loop)."""
    def mk():
        s = Scheduler()
        for name, zone in (("n0", "a"), ("n1", "b")):
            s.add_node(NodeSpec(name=name,
                                allocatable={R.CPU: 16000, R.MEMORY: 32768},
                                labels={"zone": zone}))
            s.update_node_metric(
                NodeMetric(node_name=name, node_usage={}, update_time=99.0)
            )
        return s

    sb = mk()
    sb.add_pod(PodSpec(name="pin-b", requests={R.CPU: 1000},
                       node_selector={"zone": "b"}))
    sb.add_pod(PodSpec(name="pin-c", requests={R.CPU: 1000},
                       node_selector={"zone": "c"}))
    out = sb.schedule_pending(now=100.0)
    assert out["default/pin-b"] == "n1"
    assert out["default/pin-c"] is None

    si = mk()
    si.add_pod(PodSpec(name="pin-b", requests={R.CPU: 1000},
                       node_selector={"zone": "b"}))
    assert si.schedule_one("default/pin-b", now=100.0).node == "n1"
