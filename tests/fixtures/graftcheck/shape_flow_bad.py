"""Seeded bucket-flow violations + adjacent clean shapes.

The bad functions each route a raw-dynamic count (len(), comprehension,
arithmetic over .shape) into a device-width sink; the clean functions
exercise the sanctioned idioms the rule must stay quiet on: a bucket
call, a bare aligned width, and the pad-remainder idiom.
"""

import jax
import jax.numpy as jnp


def fx_bucket(n, floor=8):
    """The fixture's registered bucket function."""
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def raw_len_zeros(pods):
    n = len(pods)
    return jnp.zeros(n, jnp.int32)


def raw_len_struct(pods):
    n = len(pods)
    return jax.ShapeDtypeStruct((n, 4), jnp.int32)


def raw_len_pad(a, pods):
    extra = len(pods)
    return jnp.pad(a, [(0, extra)])


def raw_comprehension_asarray(pods):
    return jnp.asarray([p.cpu for p in pods])


def raw_augassign_zeros(pods):
    # in-place arithmetic over a raw count stays raw: ``n += 1`` is
    # ``n = n + 1``, the same surface as the spelled-out form
    n = len(pods)
    n += 1
    return jnp.zeros(n, jnp.int32)


def raw_arith_shape(a):
    doubled = a.shape[0] * 2
    return jnp.zeros(doubled, jnp.int32)


def raw_via_helper(pods):
    # interprocedural: the raw len flows through a parameter
    return _make_axis(len(pods))


def _make_axis(count):
    return jnp.zeros(count, jnp.int32)


def clean_bucketed(pods):
    n = fx_bucket(len(pods))
    return jnp.zeros(n, jnp.int32)


def clean_aligned(a):
    # a width copied from an existing axis adds no new surface
    return jnp.zeros(a.shape[0], jnp.int32)


def clean_pad_remainder(a, pods):
    # the canonical pad idiom: bucket(n) - n stays bucketed
    n = len(pods)
    target = fx_bucket(n)
    pad = target - n
    return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))


def clean_constant():
    return jnp.zeros(64, jnp.int32)


def clean_augassign_constant():
    # constant arithmetic stays constant, in-place or not
    k = 4
    k += 60
    return jnp.zeros(k, jnp.int32)


def clean_nested_return(pods):
    # a nested def's raw return must summarize under the NESTED
    # function's key, never contaminate the enclosing summary: this
    # function returns a bucketed width, so its caller stays clean
    def helper(xs):
        return len(xs)

    _ = helper(pods)
    return fx_bucket(7)


def clean_nested_return_caller(pods):
    return jnp.zeros(clean_nested_return(pods), jnp.int32)
