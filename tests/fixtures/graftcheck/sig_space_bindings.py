"""Seeded signature-space / warm-coverage shapes: one declared and
adopted binding (clean), one undeclared binding, one adopted binding
whose statics fall outside the hashable registry, and one hot binding
never adopted (cold-on-every-recovery)."""

import jax

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.service.warmpool import WARM_POOL


def fx_solve(state, pods, params, config):
    return pods


def fx_orphan(state):
    return state


def fx_weird(state, pods, params, session):
    return pods


_jit_declared = DEVICE_OBS.jit("fx_declared", jax.jit(
    fx_solve, static_argnames=("config",), donate_argnums=()
))
WARM_POOL.adopt(_jit_declared, fx_solve, config_argpos=3)

# no BindingSpec anywhere: an unknown recompile surface
_jit_undeclared = DEVICE_OBS.jit("fx_undeclared", jax.jit(
    fx_orphan, donate_argnums=()
))

# adopted, but its static is not in the hashable-statics registry
_jit_weird = DEVICE_OBS.jit("fx_weird_statics", jax.jit(
    fx_weird, static_argnames=("session",), donate_argnums=()
))
WARM_POOL.adopt(_jit_weird, fx_weird, config_argpos=3)

# hot (in the narrowed scope) and never adopted: cold on every recovery
_jit_cold = DEVICE_OBS.jit("fx_cold", jax.jit(
    fx_solve, static_argnames=("config",), donate_argnums=()
))
