"""Seeded violations for the donation rule's warm-path checks (DESIGN
§19.2 / §21): a donating jit factory inside a no-donate module, and a
donating binding adopted into the warm pool. Both must flag."""

import jax


def f(x):
    return x + 1


# violation 1: a warm-path module's jit factory donates (and one that
# fails to declare donation at all would flag identically)
bad_warm_jit = jax.jit(f, static_argnums=(), donate_argnums=(0,))

# violation 2: a donating binding adopted into the pool — the §19.2
# replay bug shape, regardless of which module the adopt lives in
donating_solve = jax.jit(f, donate_argnums=(0,))


class _FakePool:
    def adopt(self, observed, fun, config_argpos):
        pass


POOL = _FakePool()
POOL.adopt(donating_solve, f, 0)
