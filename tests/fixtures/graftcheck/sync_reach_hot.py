"""Seeded cross-module sync leak, hot half — parsed by graftcheck's
self-test, never imported or executed. ``hot_schedule`` never syncs
locally; the leak is only visible interprocedurally."""

from tests.fixtures.graftcheck.sync_reach_helper import (
    clean_helper,
    middle_helper,
)


def hot_schedule(batch):
    staged = clean_helper(batch)
    return middle_helper(staged)           # VIOLATION: reaches device_get


def hot_clean(batch):
    return clean_helper(batch)             # no sync anywhere below
