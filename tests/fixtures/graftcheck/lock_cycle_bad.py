"""Seeded two-lock order cycle — parsed by graftcheck's self-test,
never imported or executed. ``CacheA`` acquires its lock then calls
into ``CacheB`` (which locks); ``CacheB`` does the reverse — a classic
AB/BA deadlock the per-class lock-discipline rule cannot see."""

import threading


class CacheB:
    def __init__(self, peer):
        self._lock = threading.Lock()
        self.peer = peer
        self.rows = {}

    def read_through(self, key):
        with self._lock:                       # B then (via peer) A
            return self.peer.direct_get(key)

    def direct_put(self, key, value):
        with self._lock:
            self.rows[key] = value


class CacheA:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = CacheB(self)
        self.rows = {}

    def write_through(self, key, value):
        with self._lock:                       # A then (via peer) B
            self.peer.direct_put(key, value)   # VIOLATION edge A->B

    def direct_get(self, key):
        with self._lock:                       # VIOLATION edge B->A
            return self.rows.get(key)
