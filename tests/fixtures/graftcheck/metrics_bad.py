"""Seeded metrics-hygiene violations beside the clean shapes: a served
registry with domain-declared labels (quiet), an unserved registry
(violation), an unknown label (violation), and a folded label whose
fold symbol exists (quiet) — the rule's fixture is self-contained so
the test can narrow its MetricsSpec to this file."""

from koordinator_tpu.metrics.registry import MergedGatherer, Registry

# annotated on purpose: the fold-symbol census must see AnnAssign
# module constants too (a type-annotation refactor must not read as
# "the fold was deleted")
OVERFLOW_USER: str = "_overflow"

SERVED = Registry("fx-served")
GOOD = SERVED.counter(
    "fx_good_total", "bounded enum label", label_names=("lane",),
)
FOLDED = SERVED.counter(
    "fx_folded_total", "folded label", label_names=("user",),
)
UNBOUNDED = SERVED.counter(
    "fx_unbounded_total", "hostile label", label_names=("pod_name",),
)

ORPHAN = Registry("fx-orphan")
LOST = ORPHAN.gauge("fx_lost", "registered but unscrapeable")


def _local_decoy():
    # a function-local name must never satisfy the fold-symbol check:
    # fold sentinels are module-level constants
    GONE = "_overflow"
    return GONE

# bare-Name argument on purpose: registries reach the mux as literal
# list elements in the repo, but a positional-args refactor must
# still count as served
_MUX = MergedGatherer(SERVED)
