"""Seeded donation-safety violations — parsed by graftcheck's
self-test, never imported or executed. Modeled on the PR 11
scatter-clobber: a donated buffer read after the donating dispatch."""

import jax
import jax.numpy as jnp

scatter_rows = jax.jit(
    lambda state, idx: state, donate_argnums=(0,), static_argnums=()
)


def read_after_donate(state, idx):
    out = scatter_rows(state, idx)
    return state + out                     # VIOLATION: clobbered read


def loop_redonate(state, idx):
    for i in range(4):
        out = scatter_rows(state, i)       # VIOLATION: re-donates stale
    return out


def safe_reassign(state, idx):
    state = scatter_rows(state, idx)       # killed at the call: safe
    return state


def safe_temporary(state, idx):
    return scatter_rows(jnp.asarray(state), idx)  # temp: dead anyway


class PinnedCache:
    """The pin protocol half: donating the possibly-pinned generation
    without the `is not pinned` guard is the exact PR 11 shape."""

    def __init__(self):
        self.state = None
        self._pinned = None

    def unguarded(self, idx):
        self.state = scatter_rows(self.state, idx)   # VIOLATION: no guard

    def guarded(self, idx, copied):
        if self.state is self._pinned:
            self.state = copied(self.state, idx)     # safe: copied path
        else:
            self.state = scatter_rows(self.state, idx)  # safe: guarded
