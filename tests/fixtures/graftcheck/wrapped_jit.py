"""Seeded fixtures for the instrumentation-wrapper jit-factory rules
(obs/device.py idiom: ``X = DEVICE_OBS.jit("name", jax.jit(f, ...))``)
— parsed by graftcheck's self-test, never imported or executed."""

import jax
import jax.numpy as jnp
import numpy as np

OBS = object()

# a wrapped binding IS a jit factory: declarations checked on the inner
# call (declared — no jit-hygiene violation), and the binding stays a
# device-value producer for the host-sync taint analysis
wrapped = OBS.jit("solve", jax.jit(
    lambda s: s * 2, static_argnums=(), donate_argnums=()
))

# VIOLATION (jit-hygiene): the INNER factory declares nothing — the
# wrapper must not launder an undeclared jit surface
bad_wrapped = OBS.jit("naked", jax.jit(lambda x: x + 1))


def hot(state):
    result = wrapped(jnp.asarray(state))
    return np.asarray(result)                # VIOLATION: host-sync


def churn(xs):
    # VIOLATION (jit-hygiene pass 2): per-call-varying scalar into a
    # WRAPPED jitted callable
    return wrapped(jnp.asarray(xs), len(xs))
