"""Seeded dead-import violations — parsed by graftcheck's self-test,
never imported or executed."""

import json                     # VIOLATION: never used
import os.path                  # VIOLATION: binds `os`, never used
from collections import OrderedDict, defaultdict  # OrderedDict VIOLATION

live = defaultdict(list)
