"""Seeded host-sync violations — parsed by graftcheck's self-test,
never imported or executed. Each marked line must be detected."""

import jax
import jax.numpy as jnp
import numpy as np

solve = jax.jit(lambda s: s * 2, static_argnums=(), donate_argnums=())


def hot_loop(state):
    scores = jnp.asarray(state)
    staged = jax.device_put(scores)
    result = solve(staged)
    host = jax.device_get(result)            # VIOLATION: device_get
    result.block_until_ready()               # VIOLATION: method barrier
    jax.block_until_ready(result)            # VIOLATION: free-fn barrier
    best = float(result[0])                  # VIOLATION: float() coercion
    count = int(scores.sum())                # VIOLATION: int() coercion
    flag = bool(result.any())                # VIOLATION: bool() coercion
    copied = np.asarray(result)              # VIOLATION: np.asarray
    return host, best, count, flag, copied


def match_hot(state, mode):
    result = solve(jnp.asarray(state))
    match mode:
        case "strict":
            return float(result[0])          # VIOLATION: inside match
        case _:
            return jax.device_get(result)    # VIOLATION: inside match


def cold_path(host_rows):
    # untainted: parameters start as host values, so none of these flag
    total = int(np.asarray(host_rows).sum())
    return float(total), bool(total)
