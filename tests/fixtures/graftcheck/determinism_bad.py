"""Seeded determinism-taint violations — parsed by graftcheck's
self-test, never imported or executed. Wall clock / RNG / set order
flowing into device values and wire frames."""

import os
import random
import time

import jax.numpy as jnp

from koordinator_tpu.service.codec import SolveRequest, encode_request


def clock_into_device():
    stamp = time.time()
    return jnp.asarray(stamp)              # VIOLATION: wall clock

def clock_into_wire(req):
    deadline = time.time() + 5.0
    return encode_request(deadline)        # VIOLATION: wall clock

def rng_into_wire():
    nonce = os.urandom(8)
    return SolveRequest(nonce)             # VIOLATION: urandom

def unseeded_draw_into_device():
    jitter = random.random()
    return jnp.asarray(jitter)             # VIOLATION: unseeded RNG

def set_order_into_device(names):
    pending = {"a", "b", "c"}
    return jnp.asarray([len(n) for n in pending])  # VIOLATION: set order

def clean_sorted(names):
    pending = {"a", "b", "c"}
    return jnp.asarray([len(n) for n in sorted(pending)])  # laundered

def clean_declared_input(now):
    return jnp.asarray(now)                # a declared model input

def clean_telemetry():
    at = time.time()
    return {"at": at}                      # telemetry, not a sink
