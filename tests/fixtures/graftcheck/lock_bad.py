"""Seeded lock-discipline violations — parsed by graftcheck's
self-test, never imported or executed."""

import threading


class RacyCache:
    def __init__(self):
        self._lock = threading.Lock()
        self.epoch = 0          # exempt: constructor
        self.rows = {}

    def good_mark(self, name):
        with self._lock:
            self.epoch += 1
            self.rows[name] = self.epoch

    def bad_mark(self, name):
        self.epoch += 1         # VIOLATION: write outside lock
        self.rows[name] = self.epoch  # VIOLATION x2: read + write outside

    def bad_read(self):
        return self.epoch       # VIOLATION: read outside lock

    def escaping_closure(self):
        with self._lock:
            # nested defs run later, after the lock is released
            def later():
                return self.rows  # VIOLATION: closure escapes the lock
            return later
