"""Seeded cross-module sync leak, helper half — parsed by graftcheck's
self-test, never imported or executed. The sync hides two calls deep in
a module no local-rule scope ever names."""

import jax


def deep_helper(values):
    # the buried sync: invisible to the per-module host-sync rule when
    # this module is outside HOT_MODULES
    return jax.device_get(values)          # VIOLATION target


def middle_helper(values):
    staged = [v for v in values]
    return deep_helper(staged)


def clean_helper(values):
    return [v * 2 for v in values]
