"""Seeded delta-parity violations — parsed by graftcheck's self-test,
never imported or executed. The pair must route row values through
``_row_helper``; the delta path here inlines the math instead."""

import numpy as np


def _row_helper(metric, scale):
    return metric * scale


def lower_full(snapshot):
    out = np.zeros((len(snapshot), 4))
    for i, metric in enumerate(snapshot):
        out[i] = _row_helper(metric, 2)
    return out


def lower_delta(snapshot, prev, dirty):
    for i in dirty:
        prev[i] = snapshot[i] * 2          # VIOLATION: inline arithmetic
        prev[i] += 1                       # VIOLATION: inline aug-arith
        prev[i] = np.maximum(prev[i], 0)   # VIOLATION: inline np.maximum
    # VIOLATION (coupling): lower_delta never calls _row_helper
    return prev
