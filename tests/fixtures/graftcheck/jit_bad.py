"""Seeded jit-hygiene violations — parsed by graftcheck's self-test,
never imported or executed."""

import functools

import jax
import jax.numpy as jnp


@jax.jit                                   # VIOLATION: bare decorator
def undeclared_step(x):
    return x + 1


# VIOLATION: declares neither static_arg* nor donate_arg*
naked = jax.jit(lambda x: x * 2)

# VIOLATION: partial form still needs both declarations
partial_naked = functools.partial(jax.jit)(lambda x: x - 1)

# ok: both surfaces declared (empty tuple IS a declaration)
declared = jax.jit(
    lambda x, n: x[:n], static_argnums=(1,), donate_argnums=()
)


def churn(xs):
    # VIOLATION: per-call-varying Python scalar into a jitted callable
    return declared(jnp.asarray(xs), len(xs))
