"""PVC informer + storage accounting (VERDICT r3 #6).

Oracles: statesinformer/impl/states_pvc.go (claim -> bound-PV map,
event handlers), qosmanager/plugins/blkio/blkio_reconcile.go:375-418
(BlockTypePodVolume resolution), collectors/nodestorageinfo +
states_nodemetric.go (storage accounting on the NodeMetric).
"""

import json

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import NodeSpec, PVCSpec, PodSpec
from koordinator_tpu.client import APIServer, Kind, wire_koordlet
from koordinator_tpu.koordlet.metriccache import (
    AggregationType,
    MetricCache,
    MetricKind,
)
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.statesinformer import (
    NodeMetricReporter,
    StatesInformer,
)
from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy
from koordinator_tpu.manager.sloconfig import BlockCfg, NodeSLOSpec


class TestPVCInformer:
    def test_upsert_and_remove(self):
        informer = StatesInformer()
        informer.upsert_pvc(PVCSpec(name="ns/claim-a", volume_name="pv-1"))
        assert informer.get_volume_name("ns/claim-a") == "pv-1"
        informer.upsert_pvc(PVCSpec(name="ns/claim-a", volume_name="pv-2"))
        assert informer.get_volume_name("ns/claim-a") == "pv-2"
        informer.remove_pvc("ns/claim-a")
        assert informer.get_volume_name("ns/claim-a") == ""

    def test_bus_watch_feeds_informer(self):
        bus = APIServer()
        informer = StatesInformer()
        wire_koordlet(bus, informer, "n0")
        bus.apply(Kind.PVC, "ns/claim-a",
                  PVCSpec(name="ns/claim-a", volume_name="pv-1"))
        assert informer.get_volume_name("ns/claim-a") == "pv-1"
        bus.delete(Kind.PVC, "ns/claim-a")
        assert informer.get_volume_name("ns/claim-a") == ""


class TestBlkioPodVolume:
    def test_pod_volume_block_resolves_to_device(self, tmp_path):
        from koordinator_tpu.koordlet.audit import Auditor
        from koordinator_tpu.koordlet.qosmanager.blkio import BlkIOReconcile
        from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.resourceexecutor.executor import (
            ensure_cgroup_dir,
        )
        from koordinator_tpu.koordlet.system.cgroup import SystemConfig

        informer = StatesInformer()
        informer.upsert_pvc(PVCSpec(name="ns/data-claim", volume_name="pv-7"))
        pod = PodMeta(
            "ls", "kubepods/burstable/podls", QoSClass.LS,
            volumes={"data": "ns/data-claim"},
        )
        informer.set_pods([pod])
        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                           proc_root=str(tmp_path / "proc"))
        for d in ("kubepods/burstable", "kubepods/besteffort",
                  pod.cgroup_dir):
            ensure_cgroup_dir(d, cfg)
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.ls.blkio = [BlockCfg(
            block_type="pod_volume", name="data", read_bps=1000000,
        )]
        ctx = QoSContext(
            metric_cache=MetricCache(),
            executor=ResourceUpdateExecutor(cfg, auditor=Auditor()),
            pod_provider=informer,
            system_config=cfg,
            node_slo=slo,
            volume_name_fn=informer.get_volume_name,
            volume_devices={"pv-7": "253:16"},
        )
        BlkIOReconcile().execute(ctx, now=0.0)
        throttle = (tmp_path / "cg" / "blkio" / pod.cgroup_dir /
                    "blkio.throttle.read_bps_device").read_text()
        assert throttle == "253:16 1000000"

    def test_unresolvable_volume_skipped(self, tmp_path):
        from koordinator_tpu.koordlet.audit import Auditor
        from koordinator_tpu.koordlet.qosmanager.blkio import BlkIOReconcile
        from koordinator_tpu.koordlet.qosmanager.framework import QoSContext
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.resourceexecutor.executor import (
            ensure_cgroup_dir,
        )
        from koordinator_tpu.koordlet.system.cgroup import SystemConfig

        informer = StatesInformer()  # no PVC known
        pod = PodMeta(
            "ls", "kubepods/burstable/podls", QoSClass.LS,
            volumes={"data": "ns/missing-claim"},
        )
        informer.set_pods([pod])
        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                           proc_root=str(tmp_path / "proc"))
        for d in ("kubepods/burstable", pod.cgroup_dir):
            ensure_cgroup_dir(d, cfg)
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.ls.blkio = [BlockCfg(
            block_type="pod_volume", name="data", read_bps=1000000,
        )]
        ctx = QoSContext(
            metric_cache=MetricCache(),
            executor=ResourceUpdateExecutor(cfg, auditor=Auditor()),
            pod_provider=informer,
            system_config=cfg,
            node_slo=slo,
            volume_name_fn=informer.get_volume_name,
            volume_devices={},
        )
        BlkIOReconcile().execute(ctx, now=0.0)  # must not raise
        path = (tmp_path / "cg" / "blkio" / pod.cgroup_dir /
                "blkio.throttle.read_bps_device")
        assert not path.exists() or path.read_text() == ""


class TestStorageAccounting:
    def test_reporter_carries_disk_usage_on_bus(self):
        """The done-criterion: volume/disk usage visible in the
        NodeMetric published on the bus."""
        bus = APIServer()
        informer = StatesInformer()
        informer.set_node(NodeSpec("n0", allocatable={R.CPU: 8000}))
        informer.set_pods([])
        informer.set_collect_policy(NodeMetricCollectPolicy(300, 60))
        mc = MetricCache()
        for t in range(10):
            mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 3000.0)
            mc.append(MetricKind.NODE_DISK_READ_BPS, {"dev": "vda"},
                      float(t), 2_000_000.0)
            mc.append(MetricKind.NODE_DISK_WRITE_BPS, {"dev": "vda"},
                      float(t), 500_000.0)
            mc.append(MetricKind.NODE_DISK_IO_UTIL, {"dev": "vda"},
                      float(t), 42.0)
        loop = wire_koordlet(bus, informer, "n0",
                             reporter=NodeMetricReporter(mc, informer))
        loop.report(now=10.0)
        published = bus.get(Kind.NODE_METRIC, "n0")
        assert published.disk_usages["vda"].read_bps == 2_000_000
        assert published.disk_usages["vda"].write_bps == 500_000
        assert published.disk_usages["vda"].io_util_pct == 42

    def test_label_values(self):
        mc = MetricCache()
        mc.append(MetricKind.NODE_DISK_READ_BPS, {"dev": "vda"}, 0.0, 1.0)
        mc.append(MetricKind.NODE_DISK_READ_BPS, {"dev": "sdb"}, 0.0, 1.0)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "x"}, 0.0, 1.0)
        assert mc.label_values(MetricKind.NODE_DISK_READ_BPS, "dev") == [
            "sdb", "vda"
        ]
