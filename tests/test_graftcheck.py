"""graftcheck: repo-wide invariant enforcement + per-rule self-tests.

Three layers of teeth, per ISSUE 2:

1. the repo itself must be clean: every rule over every module, with
   the checked-in allowlist (each entry justified AND still needed);
2. each rule must actually detect its seeded-violation fixture
   (``tests/fixtures/graftcheck/``) — a rule that silently stops
   firing is a lint hole, not a green build;
3. runtime teeth: a deliberately injected ``jax.device_get`` in the
   real ``models/placement.py`` source must fail the check, and a
   warmed steady-state churn tick must perform ZERO XLA recompiles
   (the ``xla_compiles`` fixture counts actual backend compilations
   via ``jax_log_compiles``).
"""

import ast
import json
from pathlib import Path

import pytest

from koordinator_tpu.analysis.graftcheck import (
    ModuleFile,
    default_rules,
    load_allowlist,
    load_module,
    run_checks,
)
from koordinator_tpu.analysis.graftcheck.engine import iter_repo_modules
from koordinator_tpu.analysis.graftcheck.rules import (
    DeadImportRule,
    DeltaParityRule,
    HostSyncRule,
    JitHygieneRule,
    LockDisciplineRule,
    LockSpec,
    ParitySpec,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "graftcheck"


def _fixture(name: str) -> ModuleFile:
    rel = f"tests/fixtures/graftcheck/{name}"
    return load_module(FIXTURES / name, rel)


# -- 1. the repo is clean (and the allowlist is honest) ----------------------

def test_repo_wide_clean():
    violations, suppressed = run_checks(
        iter_repo_modules(REPO), default_rules(),
        load_allowlist(REPO / "graftcheck.toml"),
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    # the allowlist is load-bearing: the intentional staging barriers
    # and read-back points exist and are suppressed, not absent
    assert suppressed, "allowlist suppressed nothing — entries are stale"


def test_every_allowlist_entry_has_reason():
    entries = load_allowlist(REPO / "graftcheck.toml")
    assert entries, "expected a non-empty allowlist"
    for entry in entries:
        assert entry.reason.strip(), (
            f"allowlist entry {entry.rule}@{entry.path} lacks a reason"
        )


# -- 2. each rule detects its seeded fixture ---------------------------------

def test_host_sync_fixture_detected():
    violations = HostSyncRule(scope=("*",)).check(
        _fixture("host_sync_bad.py")
    )
    symbols = {v.symbol for v in violations}
    assert symbols == {
        "jax.device_get", ".block_until_ready()", "jax.block_until_ready",
        "float()", "int()", "bool()", "np.asarray",
    }
    # parameters start untainted: the host-only path must NOT flag
    assert all(v.func != "cold_path" for v in violations)
    # py3.10 match statements are walked, not skipped
    match_hits = {v.symbol for v in violations if v.func == "match_hot"}
    assert match_hits == {"float()", "jax.device_get"}


def test_lock_discipline_fixture_detected():
    rule = LockDisciplineRule(specs=(LockSpec(
        path="tests/fixtures/graftcheck/lock_bad.py",
        class_name="RacyCache", lock="_lock", attrs=("epoch", "rows"),
    ),))
    violations = rule.check(_fixture("lock_bad.py"))
    by_func = {}
    for v in violations:
        by_func.setdefault(v.func, []).append(v.symbol)
    assert sorted(by_func) == [
        "RacyCache.bad_mark", "RacyCache.bad_read",
        "RacyCache.escaping_closure",
    ]
    assert sorted(by_func["RacyCache.bad_mark"]) == [
        "self.epoch", "self.epoch", "self.rows",
    ]
    assert by_func["RacyCache.escaping_closure"] == ["self.rows"]


def test_delta_parity_fixture_detected():
    rule = DeltaParityRule(specs=(ParitySpec(
        path="tests/fixtures/graftcheck/parity_bad.py",
        funcs=("lower_full", "lower_delta"),
        required_helpers=("_row_helper",),
    ),))
    violations = rule.check(_fixture("parity_bad.py"))
    assert all(v.func == "lower_delta" for v in violations)
    symbols = {v.symbol for v in violations}
    assert "Mult" in symbols          # inline arithmetic
    assert "Add" in symbols           # augmented arithmetic
    assert "np.maximum" in symbols    # inline value folding
    assert "_row_helper" in symbols   # missing shared-helper call


def test_jit_hygiene_fixture_detected():
    violations = JitHygieneRule(scope=("*",)).check(_fixture("jit_bad.py"))
    messages = [v.message for v in violations]
    assert sum("bare @" in m for m in messages) == 1
    assert sum("does not declare" in m for m in messages) == 2
    assert sum("per-call-varying" in m for m in messages) == 1
    # the fully-declared site must NOT flag
    assert all("declared(" not in m or "len(xs)" in m for m in messages)


def test_wrapped_jit_factory_recognized():
    """The obs/device.py instrumentation idiom — ``X = DEVICE_OBS.jit(
    "name", jax.jit(f, ...))`` — must behave exactly like a bare jit
    binding under both rules: the binding stays a device-value producer
    (host-sync), declaration completeness is checked on the INNER
    factory, and pass 2's varying-scalar check still covers the
    wrapped callable."""
    module = _fixture("wrapped_jit.py")
    sync = HostSyncRule(scope=("*",)).check(module)
    assert {(v.func, v.symbol) for v in sync} == {("hot", "np.asarray")}, (
        "wrapped binding lost (or over-gained) producer taint"
    )
    hygiene = JitHygieneRule(scope=("*",)).check(module)
    undeclared = [v for v in hygiene if "does not declare" in v.message]
    assert len(undeclared) == 1 and undeclared[0].line == 20, (
        "declaration completeness must be judged on the inner factory: "
        "exactly the naked inner jit flags"
    )
    varying = [v for v in hygiene if "per-call-varying" in v.message]
    assert len(varying) == 1 and varying[0].func == "churn"


def test_dead_import_fixture_detected():
    violations = DeadImportRule(scope=("*",)).check(
        _fixture("dead_import_bad.py")
    )
    assert {v.symbol for v in violations} == {"json", "os", "OrderedDict"}


# -- 3a. injected violation in the REAL hot path fails the check -------------

def test_injected_device_get_fails():
    """Seed a ``jax.device_get`` into the real models/placement.py solve
    path: the full rule set + the real allowlist must reject it (the
    allowlist entries are function+symbol scoped, so none can mask a
    new sync)."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    anchor = "batch = self.stage_pods(pod_arrays)"
    assert anchor in source
    injected = source.replace(
        anchor, anchor + "\n        _ = jax.device_get(batch.req)"
    )
    module = ModuleFile(
        path=path, tree=ast.parse(injected, filename=path), source=injected
    )
    allow = [
        e for e in load_allowlist(REPO / "graftcheck.toml")
        if e.path == path
    ]
    violations, _ = run_checks([module], default_rules(), allow)
    # the v3 census passes (signature-space/warm-coverage) legitimately
    # report registry mismatches against a ONE-module program — the
    # injected-sync property here is about the sync rules
    hits = [
        v for v in violations
        if v.rule in ("host-sync", "sync-reach")
    ]
    assert len(hits) == 1
    assert hits[0].symbol == "jax.device_get"
    assert hits[0].func == "PlacementModel.schedule_async"


# -- 3b. allowlist engine teeth ----------------------------------------------

def test_allowlist_entry_without_reason_is_violation(tmp_path):
    toml = tmp_path / "graftcheck.toml"
    toml.write_text(
        '[[allow]]\nrule = "host-sync"\n'
        'path = "koordinator_tpu/models/placement.py"\n'
        'func = "StagedStateCache.ensure"\n'
        'symbol = "jax.block_until_ready"\n'
    )
    module = load_module(
        REPO / "koordinator_tpu/models/placement.py",
        "koordinator_tpu/models/placement.py",
    )
    violations, _ = run_checks(
        [module], (HostSyncRule(scope=("*",)),), load_allowlist(toml)
    )
    assert any(v.rule == "allowlist-justification" for v in violations)


def test_stale_allowlist_entry_is_violation(tmp_path):
    toml = tmp_path / "graftcheck.toml"
    toml.write_text(
        '[[allow]]\nrule = "host-sync"\npath = "nonexistent.py"\n'
        'reason = "covers nothing"\n'
    )
    violations, _ = run_checks([], (), load_allowlist(toml))
    assert [v.rule for v in violations] == ["stale-allowlist"]


def test_allowlist_rejects_loose_syntax(tmp_path):
    toml = tmp_path / "graftcheck.toml"
    toml.write_text('[[allow]]\nrule = unquoted\n')
    with pytest.raises(ValueError, match="unsupported allowlist syntax"):
        load_allowlist(toml)
    toml.write_text('[[allow]]\nbadkey = "x"\n')
    with pytest.raises(ValueError, match="unknown allowlist key"):
        load_allowlist(toml)
    toml.write_text('[[allow]]\nrule = "host-sync"\n')
    with pytest.raises(ValueError, match="missing"):
        load_allowlist(toml)


def test_cli_json_clean(capsys):
    from koordinator_tpu.analysis.graftcheck.__main__ import main

    assert main(["--format=json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violation_count"] == 0
    assert payload["suppressed"], "expected allowlisted suppressions"


def test_cli_rule_filter(capsys):
    from koordinator_tpu.analysis.graftcheck.__main__ import main

    assert main(["--rule=dead-import", "--format=json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violation_count"] == 0


# -- 3c. runtime teeth: zero XLA recompiles on a warmed churn tick -----------

# the xla_compiles fixture lives in conftest.py: the pipelined tick
# path's recompile guard (tests/test_pipeline.py) shares it

def _churn_cluster():
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.scheduler.cache import SchedulerCache

    cpu, mem = ResourceName.CPU, ResourceName.MEMORY
    cache = SchedulerCache()
    for i in range(12):
        cache.add_node(NodeSpec(
            name=f"n{i}",
            allocatable={cpu: 32_000 + 100 * i, mem: 65_536},
        ))
    for j in range(6):
        cache.add_pod(PodSpec(
            name=f"pending{j}",
            requests={cpu: 500 + 10 * j, mem: 256},
        ))

    def tick(now: float):
        # steady-state churn: 3 nodes report fresh metrics, nothing else
        for i in (1, 4, 7):
            cache.update_node_metric(NodeMetric(
                node_name=f"n{i}",
                node_usage={cpu: 4_000 + int(now) % 100, mem: 8_192},
                update_time=now,
            ))
        return cache.snapshot(now=now)

    return tick


def test_warmed_churn_tick_zero_recompiles(xla_compiles):
    """The recompile guard the jit-hygiene rule is the static half of:
    after warmup, a steady-state churn tick (same dirty-row bucket,
    same pod bucket) runs entirely out of the jit caches — zero XLA
    compilations. A recompile here means a shape/bucket/static-arg
    leak on the hot path."""
    from koordinator_tpu.models.placement import PlacementModel

    tick = _churn_cluster()
    model = PlacementModel(use_pallas=False)
    now = 1_000.0
    for _ in range(3):  # cold compile + delta-path compile + margin
        model.schedule(tick(now))
        now += 30.0
    assert model.staged_cache.last_path == "delta"
    # the guard must not rot vacuous: warmup MUST have captured
    # compile records, or the logger hook no longer observes jax
    assert xla_compiles, "xla_compiles fixture captured no compilations"

    xla_compiles.clear()
    model.schedule(tick(now))
    assert model.staged_cache.last_path == "delta"
    assert xla_compiles == [], (
        "steady-state churn tick recompiled:\n" + "\n".join(xla_compiles)
    )
