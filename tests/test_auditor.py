"""The anti-entropy auditor (ISSUE 5): sweep cadence, the repair
ladder's drift-threshold boundary, deterministic parity-probe coverage,
and the run_loop FencingError forget path.

The chaos-level property (kill the leader, standby promotes, audits,
and finishes bit-identical) lives in tests/test_chaos.py; these are the
auditor's unit-level contracts.
"""

import itertools

import numpy as np
import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.leaderelection import LeaderElector
from koordinator_tpu.client.wiring import wire_scheduler
from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.auditor import StateAuditor
from koordinator_tpu.state.cluster import lower_node_rows, lower_nodes


def _wired(n_nodes=4, cpu=64000, mem=131072, elector_ids=()):
    """A bus + one wired scheduler (+ optional electors), seeded with
    nodes and fresh metrics."""
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    electors = [
        LeaderElector(bus, "koord-scheduler", ident, lease_duration=1.0)
        for ident in elector_ids
    ]
    wire_scheduler(bus, sched, elector=electors[0] if electors else None)
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}", node_usage={R.CPU: 100 * i},
            update_time=90.0))
    return bus, sched, electors


class TestSweepCadence:
    def test_promotion_sweep_once_per_acquisition(self):
        """The promotion sweep fires exactly once per lease acquisition
        — not once per round — and fires again on RE-acquisition."""
        bus, sched, (ea,) = _wired(elector_ids=("a",))
        aud = StateAuditor(sched, bus, interval_rounds=0)  # no periodic
        ea.on_started_leading = aud.note_promotion
        for t in range(5):
            assert ea.tick(0.5 * t)
            aud.on_round(now=0.5 * t)
        assert aud.status()["sweeps"] == {"promotion": 1}
        # deposed, then re-acquires: a SECOND promotion sweep
        eb = LeaderElector(bus, "koord-scheduler", "b", lease_duration=1.0)
        assert eb.tick(10.0)
        assert not ea.tick(10.1)
        eb.release()
        assert ea.tick(11.0)
        aud.on_round(now=11.0)
        assert aud.status()["sweeps"] == {"promotion": 2}

    def test_run_loop_promotion_then_periodic_cadence(self):
        """Through run_loop itself: round 1 runs the promotion sweep,
        then one periodic sweep every interval_rounds rounds."""
        bus, sched, (ea,) = _wired(elector_ids=("a",))
        aud = StateAuditor(sched, bus, interval_rounds=2)
        ea.on_started_leading = aud.note_promotion
        clock = itertools.count()
        run_loop(
            sched, SchedulerConfig(schedule_interval_seconds=0.0),
            elector=ea, auditor=aud, max_rounds=5,
            now_fn=lambda: 0.25 * next(clock), log=lambda *a: None,
        )
        # rounds: 1=promotion, 3=periodic, 5=periodic
        assert aud.status()["sweeps"] == {"promotion": 1, "periodic": 2}


class TestRepairLadder:
    def test_drift_threshold_boundary(self):
        """N-1 drifts repair targeted; N drifts trigger the full cache
        rebuild — the exact boundary, both sides."""
        bus, sched, _ = _wired(n_nodes=6)
        aud = StateAuditor(sched, bus, interval_rounds=0,
                           rebuild_threshold=3)
        # N-1 = 2 drifts: two nodes vanish from the cache with no event
        for name in ("n0", "n1"):
            sched.cache.nodes.pop(name)
        report = aud.sweep("manual", now=100.0)
        assert report["detections"] == {"cache-bus/missing-node": 2}
        assert report["repairs"] == {"targeted": 2}
        assert set(sched.cache.nodes) == {f"n{i}" for i in range(6)}
        # N = 3 drifts: the same corruption one wider → rebuild
        for name in ("n0", "n1", "n2"):
            sched.cache.nodes.pop(name)
        report = aud.sweep("manual", now=101.0)
        assert report["detections"] == {"cache-bus/missing-node": 3}
        assert report["repairs"] == {"cache-rebuild": 1}
        assert set(sched.cache.nodes) == {f"n{i}" for i in range(6)}
        assert set(sched.cache.node_metrics) == {
            f"n{i}" for i in range(6)
        }

    def test_orphan_assume_detected_and_dropped(self):
        bus, sched, _ = _wired()
        aud = StateAuditor(sched, bus, interval_rounds=0)
        sched.cache.assumed["ghost"] = 0.0
        report = aud.sweep("manual", now=1000.0)
        assert report["detections"] == {"cache-bus/orphan-assume": 1}
        assert report["repairs"] == {"targeted": 1}
        assert sched.cache.assumed == {}

    def test_resv_overcredit_clamped(self):
        """Accounting invariant: reservation credit above the reserved
        capacity is detected and clamped (with a tracker mark)."""
        bus, sched, _ = _wired()
        aud = StateAuditor(sched, bus, interval_rounds=0)
        resv = ReservationSpec(
            name="r0", node_name="n0", state=ReservationState.AVAILABLE,
            requests={R.CPU: 1000}, allocated={R.CPU: 4000}, ttl=0)
        bus.apply(Kind.RESERVATION, "r0", resv)
        epoch_before = sched.cache.delta_tracker.epoch
        report = aud.sweep("manual", now=100.0)
        assert report["detections"] == {"accounting/resv-overcredit": 1}
        assert report["repairs"] == {"targeted": 1}
        assert resv.allocated[R.CPU] == 1000
        assert sched.cache.delta_tracker.epoch > epoch_before
        # a second sweep is clean — the repair converged
        assert aud.sweep("manual", now=101.0)["detections"] == {}

    def test_gang_illegal_state_repaired(self):
        bus, sched, _ = _wired()
        aud = StateAuditor(sched, bus, interval_rounds=0)
        from koordinator_tpu.apis.types import GangSpec

        bus.apply(Kind.GANG, "g", GangSpec(name="g", min_member=2))
        record = sched.gang_manager.gangs["g"]
        record.children.add("p1")
        record.waiting.add("p1")
        record.bound.add("p1")        # waiting AND bound: illegal
        record.bound.add("stranger")  # not a child: illegal
        report = aud.sweep("manual", now=100.0)
        assert report["detections"] == {"accounting/gang-illegal-state": 1}
        assert record.waiting == set()      # bound wins the overlap
        assert record.bound == {"p1"}       # strangers dropped
        assert report["unrepaired"] == []

    def test_double_placed_pod_repaired_from_bus(self):
        bus, sched, _ = _wired()
        aud = StateAuditor(sched, bus, interval_rounds=0)
        pod = PodSpec(name="p", requests={R.CPU: 500}, node_name="n0")
        bus.apply(Kind.POD, pod.uid, pod)
        # corrupt: the same uid also lingers in pending
        sched.cache.pending[pod.uid] = pod
        report = aud.sweep("manual", now=100.0)
        assert "accounting/double-placed" in report["detections"]
        assert pod.uid not in sched.cache.pending
        assert sched.cache.pods[pod.uid].node_name == "n0"
        assert aud.sweep("manual", now=101.0)["detections"] == {}

    def test_truth_level_overcommit_is_loud_never_silent(self):
        """An invariant violation the ladder cannot repair (bus truth
        itself is overcommitted) is escalated to a rebuild, re-verified,
        and reported as unrepaired — never silently passed."""
        bus, sched, _ = _wired(n_nodes=1, cpu=1000)
        aud = StateAuditor(sched, bus, interval_rounds=0)
        for i in range(2):
            pod = PodSpec(name=f"p{i}", requests={R.CPU: 900},
                          node_name="n0")
            bus.apply(Kind.POD, pod.uid, pod)
        report = aud.sweep("manual", now=100.0)
        assert report["detections"] == {"accounting/node-overcommit": 1}
        assert report["repairs"] == {"cache-rebuild": 1}
        assert report["unrepaired"] == ["node-overcommit:n0"]
        # escalation memory: a rebuild provably cannot repair this, so
        # subsequent sweeps keep detecting+reporting WITHOUT paying an
        # O(cluster) rebuild every time
        report2 = aud.sweep("manual", now=101.0)
        assert report2["detections"] == {"accounting/node-overcommit": 1}
        assert report2["repairs"] == {}
        assert report2["unrepaired"] == ["node-overcommit:n0"]
        assert aud.status()["unrepairable"] == ["node-overcommit:n0"]
        # ...and re-arms the moment the violation heals
        for uid in list(sched.cache.pods):
            sched.cache.remove_pod(uid)
        for key in list(bus.list(Kind.POD)):
            bus.delete(Kind.POD, key)
        assert aud.sweep("manual", now=102.0)["unrepaired"] == []
        assert aud.status()["unrepairable"] == []


class TestParityProbe:
    def _staged(self, n_nodes=10):
        bus, sched, _ = _wired(n_nodes=n_nodes)
        pod = PodSpec(name="warm", requests={R.CPU: 500})
        bus.apply(Kind.POD, pod.uid, pod)
        sched.schedule_pending(now=100.0)  # populates the staged cache
        # settle: the warm bind marked its node dirty; a second (empty)
        # round re-lowers it so sweeps start from a clean generation
        sched.schedule_pending(now=100.0)
        return bus, sched

    def test_round_robin_covers_every_row_within_k_sweeps(self):
        """probe_rows=4 over 10 rows: ceil(10/4)=3 sweeps provably
        cover every row, in a deterministic round-robin order (no
        Date.now-style nondeterminism)."""
        bus, sched = self._staged(n_nodes=10)
        aud = StateAuditor(sched, bus, interval_rounds=0, probe_rows=4)
        names = sched.model.staged_cache.audit_view()[0].names
        probed = [
            aud.sweep("manual", now=100.0)["probe_rows"]
            for _ in range(3)
        ]
        assert probed[0] == names[0:4]
        assert probed[1] == names[4:8]
        assert probed[2] == names[8:10] + names[0:2]
        assert set().union(*map(set, probed)) == set(names)
        # and the cycle repeats identically
        assert aud.sweep("manual", now=100.0)["probe_rows"] == names[2:6]

    def test_desynced_row_detected_and_restaged(self):
        """A staged row drifted from truth with no tracker mark (host
        and device halves both) is caught by the probe and repaired by
        a forced full restage; the next solve is built from truth."""
        bus, sched = self._staged(n_nodes=6)
        aud = StateAuditor(sched, bus, interval_rounds=0, probe_rows=6)
        staged = sched.model.staged_cache
        arrays, state, _, _, _ = staged.audit_view()
        arrays.usage[2, 0] += 777
        staged.state = state._replace(usage=state.usage.at[2, 0].add(777))
        report = aud.sweep("manual", now=100.0)
        assert report["detections"] == {
            "device-parity/staged-host-drift": 1,
            "device-parity/staged-device-drift": 1,
        }
        assert report["repairs"] == {"full-restage": 1}
        assert staged.audit_view()[0] is None  # invalidated
        sched.schedule_pending(now=101.0)      # full restage from truth
        assert staged.last_path == "full"
        assert aud.sweep("manual", now=101.0)["detections"] == {}

    def test_dirty_rows_are_skipped_not_flagged(self):
        """Rows marked dirty since the staged generation are
        legitimately stale until the next solve — the probe skips them
        instead of crying drift."""
        bus, sched = self._staged(n_nodes=6)
        aud = StateAuditor(sched, bus, interval_rounds=0, probe_rows=6)
        # a real metric refresh through the bus: marked, not drift
        bus.apply(Kind.NODE_METRIC, "n3", NodeMetric(
            node_name="n3", node_usage={R.CPU: 9999}, update_time=100.5))
        report = aud.sweep("manual", now=100.5)
        assert report["detections"] == {}
        assert report["probe_skipped"] == 1
        assert "n3" not in report["probe_rows"]


class TestLowerNodeRowsParity:
    def test_matches_full_lowering_rows(self):
        """lower_node_rows == the same rows of lower_nodes, bit for bit
        (both route through the shared per-row helper registry)."""
        bus, sched, _ = _wired(n_nodes=5)
        for i in range(7):
            pod = PodSpec(name=f"p{i}", requests={R.CPU: 300 + i},
                          node_name=f"n{i % 5}")
            bus.apply(Kind.POD, pod.uid, pod)
        bus.apply(Kind.RESERVATION, "r0", ReservationSpec(
            name="r0", node_name="n1", state=ReservationState.AVAILABLE,
            requests={R.CPU: 2000}, ttl=0))
        snap = sched.cache.snapshot(now=120.0)
        full = lower_nodes(snap)
        names = ["n3", "n0", "n1"]
        rows = lower_node_rows(snap, names)
        for f, got in rows.items():
            for k, name in enumerate(names):
                i = full.names.index(name)
                np.testing.assert_array_equal(
                    got[k], getattr(full, f)[i],
                    err_msg=f"{f} row for {name} diverged")


class TestRebuildReleasesPermitHolds:
    def test_waiting_gang_pod_released_cleanly_on_rebuild(self):
        """A cache rebuild while a gang pod waits at Permit must fully
        release the local holds (quota used, gang waiting membership,
        the node hold) and return the pod to pending — a half-restore
        would leak quota accounting and double-allocate fine-grained
        holds on release."""
        from koordinator_tpu.apis.types import GangMode, GangSpec

        bus, sched, _ = _wired(n_nodes=2)
        bus.apply(Kind.QUOTA, "q", QuotaSpec(
            name="q", min={R.CPU: 10000}, max={R.CPU: 10000}))
        bus.apply(Kind.GANG, "g", GangSpec(
            name="g", min_member=2, mode=GangMode.NON_STRICT))
        pod = PodSpec(name="member", gang="g", quota="q",
                      requests={R.CPU: 1000})
        bus.apply(Kind.POD, pod.uid, pod)
        out = sched.schedule_pending(now=100.0)
        assert pod.uid in out.waiting          # held at the barrier
        assert pod.uid in sched._waiting
        info = sched.quota_registry.manager_for_quota("q").quotas["q"]
        assert info.used[int(R.CPU)] == 1000   # the hold is accounted

        aud = StateAuditor(sched, bus, interval_rounds=0,
                           rebuild_threshold=1)
        sched.cache.nodes.pop("n1")            # any drift -> rebuild
        report = aud.sweep("manual", now=101.0)
        assert report["repairs"] == {"cache-rebuild": 1}
        # the Permit hold was RELEASED, not half-restored
        assert sched._waiting == {}
        assert pod.uid in sched.cache.pending
        assert pod.node_name is None and not pod.waiting_permit
        assert info.used[int(R.CPU)] == 0      # no leaked accounting
        assert sched.gang_manager.gangs["g"].waiting == set()
        # the pod re-attempts (and re-waits, with fresh holds); the
        # rebuild re-created the quota record, so re-fetch it
        out2 = sched.schedule_pending(now=102.0)
        assert pod.uid in out2.waiting
        info = sched.quota_registry.manager_for_quota("q").quotas["q"]
        assert info.used[int(R.CPU)] == 1000


class TestOrphanPermitHold:
    def test_promotion_sweep_releases_dead_leaders_permit_hold(self):
        """A deposed leader's Permit-held gang pod (unpublished assume:
        the shared bus object carries node_name + waiting_permit) must
        be RELEASED back to pending by the promoted standby's sweep —
        adopting it as assigned would strand it with no holds and leak
        its capacity — while the live holder's own sweep treats the
        hold as healthy local state."""
        from koordinator_tpu.apis.types import GangMode, GangSpec

        bus = APIServer()
        sched_a = Scheduler(model=PlacementModel(use_pallas=False))
        sched_b = Scheduler(model=PlacementModel(use_pallas=False))
        wire_scheduler(bus, sched_a)
        wire_scheduler(bus, sched_b)
        for i in range(2):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={R.CPU: 8000, R.MEMORY: 16384}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}", node_usage={}, update_time=90.0))
        bus.apply(Kind.GANG, "g", GangSpec(
            name="g", min_member=2, mode=GangMode.NON_STRICT))
        pod = PodSpec(name="m1", gang="g", requests={R.CPU: 1000})
        bus.apply(Kind.POD, pod.uid, pod)
        out = sched_a.schedule_pending(now=100.0)
        assert pod.uid in out.waiting and pod.waiting_permit

        # the live holder's own auditor: the hold is NOT drift
        aud_a = StateAuditor(sched_a, bus, interval_rounds=0)
        assert aud_a.sweep("manual", now=100.5)["detections"] == {}

        # the leader dies; the standby promotes and audits
        aud_b = StateAuditor(sched_b, bus, interval_rounds=0)
        report = aud_b.sweep("promotion", now=101.0)
        assert report["detections"] == {
            "cache-bus/orphan-permit-hold": 1}
        assert report["repairs"] == {"targeted": 1}
        assert pod.node_name is None and not pod.waiting_permit
        assert pod.uid in sched_b.cache.pending
        # the gang completes under the new leader with full holds
        pod2 = PodSpec(name="m2", gang="g", requests={R.CPU: 1000})
        bus.apply(Kind.POD, pod2.uid, pod2)
        out2 = sched_b.schedule_pending(now=102.0)
        done = dict(out2) | dict(out2.waiting)
        assert done.get(pod.uid) and done.get(pod2.uid)
        assert aud_b.sweep("manual", now=102.5)["detections"] == {}


class TestFencingForget:
    def test_run_loop_forgets_assumed_on_mid_round_deposal(self):
        """Two electors on one bus (the satellite regression): the
        leader assumes a pod, then loses the lease before the round's
        fenced eviction; run_loop's FencingError path immediately
        forgets the assumed-but-unbound pod — no lingering assume, no
        leaked quota 'used', pod back in pending."""
        bus = APIServer()
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        ea = LeaderElector(bus, "koord-scheduler", "a", lease_duration=1.0)
        eb = LeaderElector(bus, "koord-scheduler", "b", lease_duration=1.0)
        wire_scheduler(bus, sched, elector=ea)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 10000, R.MEMORY: 64000}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=0.0))
        bus.apply(Kind.QUOTA, "a", QuotaSpec(
            name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        # a bound low-priority victim + a placeable pod + a preemptor
        # that cannot fit: the round assumes 'small', then the fenced
        # victim eviction for 'big' meets the stolen lease
        bus.apply(Kind.POD, "default/low", PodSpec(
            name="low", quota="a", priority=10,
            requests={R.CPU: 8000}, node_name="n0"))
        bus.apply(Kind.POD, "default/small", PodSpec(
            name="small", quota="a", priority=100,
            requests={R.CPU: 1000}))
        bus.apply(Kind.POD, "default/big", PodSpec(
            name="big", quota="a", priority=100,
            requests={R.CPU: 8000}))

        orig = sched.schedule_pending

        def steal_lease_mid_round(now=None):
            assert eb.tick(2.0)  # a's lease (renewed at 0) expired at 1
            return orig(now=now)

        sched.schedule_pending = steal_lease_mid_round
        rc = run_loop(
            sched, SchedulerConfig(schedule_interval_seconds=0.0),
            once=True, elector=ea, now_fn=lambda: 0.0,
            log=lambda *a: None,
        )
        assert rc == 1  # the round aborted on FencingError
        # the assume was forgotten, not left to expire
        assert sched.cache.assumed == {}
        assert "default/small" in sched.cache.pending
        assert sched.cache.pending["default/small"].node_name is None
        # quota 'used' leaked nothing: only the bound victim counts
        info = sched.quota_registry.manager_for_quota("a").quotas["a"]
        assert info.used[int(R.CPU)] == 8000
        # the victim was NOT evicted (the fenced write never applied)
        assert bus.get(Kind.POD, "default/low") is not None
        # a later re-election re-places 'small' exactly once
        sched.schedule_pending = orig
        eb.release()
        assert not ea.tick(4.0)  # first tick notices the deposal
        assert ea.tick(4.5)      # then re-acquires the released lease
        out = sched.schedule_pending(now=4.5)
        assert out["default/small"] == "n0"

    def test_fencing_forget_rolls_back_committed_reservation(self):
        """The aborted round's COMMITTED pod consumed a reservation:
        the forget must restore the credit (and an allocate_once
        reservation's AVAILABLE state) — the bind never published, so
        the new leader's re-placement would otherwise double-consume."""
        bus = APIServer()
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        ea = LeaderElector(bus, "koord-scheduler", "a", lease_duration=1.0)
        eb = LeaderElector(bus, "koord-scheduler", "b", lease_duration=1.0)
        wire_scheduler(bus, sched, elector=ea)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 10000, R.MEMORY: 64000}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=0.0))
        bus.apply(Kind.QUOTA, "a", QuotaSpec(
            name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        resv = ReservationSpec(
            name="r0", node_name="n0", state=ReservationState.AVAILABLE,
            requests={R.CPU: 2000}, owner_labels={"app": "x"},
            allocate_once=True, ttl=0)
        bus.apply(Kind.RESERVATION, "r0", resv)
        bus.apply(Kind.POD, "default/low", PodSpec(
            name="low", quota="a", priority=10,
            requests={R.CPU: 6000}, node_name="n0"))
        bus.apply(Kind.POD, "default/small", PodSpec(
            name="small", quota="a", priority=100,
            requests={R.CPU: 1000}, labels={"app": "x"}))
        bus.apply(Kind.POD, "default/big", PodSpec(
            name="big", quota="a", priority=100,
            requests={R.CPU: 9000}))

        orig = sched.schedule_pending

        def steal_lease_mid_round(now=None):
            assert eb.tick(2.0)
            return orig(now=now)

        sched.schedule_pending = steal_lease_mid_round
        rc = run_loop(
            sched, SchedulerConfig(schedule_interval_seconds=0.0),
            once=True, elector=ea, now_fn=lambda: 0.0,
            log=lambda *a: None,
        )
        assert rc == 1
        # 'small' was committed onto r0 mid-round, then the round
        # aborted: the consumption must be fully rolled back
        assert sched.cache.assumed == {}
        assert "default/small" in sched.cache.pending
        assert resv.allocated.get(R.CPU, 0) == 0
        assert resv.allocated_pod_uids == []
        assert resv.state is ReservationState.AVAILABLE
        assert sched._resv_inflight == {}

    def test_fencing_forget_covers_barrier_opened_gang_pods(self):
        """A gang whose Permit barrier opened IN the aborted round:
        open_permit keeps the assume until the publish confirms, so
        forget_assumed_unbound returns the whole gang — the previously
        waiting member included — to pending with its quota released."""
        from koordinator_tpu.apis.types import GangMode, GangSpec

        bus = APIServer()
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        ea = LeaderElector(bus, "koord-scheduler", "a", lease_duration=1.0)
        eb = LeaderElector(bus, "koord-scheduler", "b", lease_duration=1.0)
        wire_scheduler(bus, sched, elector=ea)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 10000, R.MEMORY: 64000}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=0.0))
        bus.apply(Kind.QUOTA, "a", QuotaSpec(
            name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
        bus.apply(Kind.GANG, "g", GangSpec(
            name="g", min_member=2, mode=GangMode.NON_STRICT))
        bus.apply(Kind.POD, "default/low", PodSpec(
            name="low", quota="a", priority=10,
            requests={R.CPU: 7000}, node_name="n0"))
        # round 1 (healthy): the first gang member waits at Permit
        assert ea.tick(0.0)
        bus.apply(Kind.POD, "default/m1", PodSpec(
            name="m1", gang="g", quota="a", priority=50,
            preemptible=False, requests={R.CPU: 1000}))
        out1 = sched.schedule_pending(now=0.0)
        assert "default/m1" in out1.waiting
        # round 2: the second member satisfies the gang (barrier opens
        # mid-round), then the preemptor's fenced eviction meets the
        # stolen lease
        bus.apply(Kind.POD, "default/m2", PodSpec(
            name="m2", gang="g", quota="a", priority=50,
            preemptible=False, requests={R.CPU: 1000}))
        bus.apply(Kind.POD, "default/big", PodSpec(
            name="big", quota="a", priority=100,
            requests={R.CPU: 8000}))
        orig = sched.schedule_pending

        def steal_lease_mid_round(now=None):
            assert eb.tick(2.0)
            return orig(now=now)

        sched.schedule_pending = steal_lease_mid_round
        rc = run_loop(
            sched, SchedulerConfig(schedule_interval_seconds=0.0),
            once=True, elector=ea, now_fn=lambda: 0.5,
            log=lambda *a: None,
        )
        assert rc == 1
        # the WHOLE gang was forgotten — m1 (barrier-opened) included
        assert sched.cache.assumed == {}
        assert "default/m1" in sched.cache.pending
        assert "default/m2" in sched.cache.pending
        for uid in ("default/m1", "default/m2"):
            assert sched.cache.pending[uid].node_name is None
            assert not sched.cache.pending[uid].waiting_permit
        info = sched.quota_registry.manager_for_quota("a").quotas["a"]
        assert info.used[int(R.CPU)] == 7000  # only the bound victim
        assert sched._waiting == {}
        record = sched.gang_manager.gangs["g"]
        assert record.waiting == set() and record.bound == set()
