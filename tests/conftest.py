"""Test harness: force an 8-device virtual CPU mesh before JAX import.

All sharding/multi-chip tests run on virtual CPU devices; the driver's
dryrun validates the same path. Must run before anything imports jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The ambient environment may preset JAX_PLATFORMS (e.g. a TPU tunnel);
# tests always run on the virtual CPU mesh, so force-override it. A
# site-level PJRT plugin may additionally have force-updated the
# jax_platforms config at interpreter start — undo that too.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
