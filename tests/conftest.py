"""Test harness: force an 8-device virtual CPU mesh before JAX import.

All sharding/multi-chip tests run on virtual CPU devices; the driver's
dryrun validates the same path. Must run before anything imports jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Isolate the suite from the user-global persistent compilation cache:
# cmd entry points enable it in-process (by design for production), and
# a shared on-disk cache would couple test runs to whatever any earlier
# crashed process left behind. The cache's own tests use tmp_path dirs.
os.environ.setdefault("KTPU_COMPILATION_CACHE_DIR", "")
# The ambient environment may preset JAX_PLATFORMS (e.g. a TPU tunnel);
# tests always run on the virtual CPU mesh, so force-override it. A
# site-level PJRT plugin may additionally have force-updated the
# jax_platforms config at interpreter start — undo that too.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402

#: module nodeids (e.g. "tests/test_chaos.py") that had tests
#: deselected this run (-k / -m / --deselect): the shape-flow
#: sentinel's non-vacuity teardown only fires on modules that ran
#: their full test set
_DESELECTED_MODULES = set()


def pytest_deselected(items):
    for item in items:
        _DESELECTED_MODULES.add(item.nodeid.split("::", 1)[0])


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Free compiled executables between test modules.

    Every XLA:CPU executable holds JIT code mappings; the full suite
    compiles thousands of programs and was hitting the kernel's
    vm.max_map_count (~65k mappings -> mmap failure -> segfault inside
    LLVM, measured r5). Cross-module cache reuse is negligible — each
    module compiles its own shapes — so clearing per module bounds the
    mapping count at a small runtime cost.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def lock_order_shim():
    """The runtime lock-order assertion shim (ISSUE 9): instruments
    every lock the static ``lock-order`` rule maps and verifies each
    observed acquisition embeds into the statically-derived order.
    Module-scoped: the chaos and pipeline suites opt in with an autouse
    wrapper so ALL their threads — coordinator, publisher, supervisor
    monitor, sidecar handlers, chaos proxies — run instrumented.
    Teardown asserts zero order violations and a non-vacuous run (the
    instrumented classes really were exercised)."""
    from koordinator_tpu.testing.lockorder import LockOrderShim

    shim = LockOrderShim.from_static_analysis().install()
    try:
        yield shim
    finally:
        report = shim.report()
        shim.uninstall()
        assert report["violations"] == [], (
            "runtime lock-order violations:\n"
            + "\n".join(map(str, report["violations"]))
        )
        assert report["acquisitions"] > 0, (
            "lock-order shim observed no acquisitions — the "
            "instrumentation no longer reaches the mapped locks"
        )


@pytest.fixture(scope="module")
def shape_flow_sentinel(request):
    """The runtime shape-flow sentinel (ISSUE 15, docs/DESIGN.md §23):
    derives the expected signature set from the SAME static analysis
    the ``signature-space`` rule runs and asserts every signature the
    DEVICE_OBS compile ring observes is inside it. The analysis build
    is memoized process-wide (testing/shapeflow.py), so module scope
    costs one build however many suites arm; the chaos and streaming
    suites opt in with an autouse per-test window wrapper so a
    structure change BETWEEN tests never smears into a false positive.
    Teardown asserts zero out-of-enumeration compiles always, and
    non-vacuity — compiles observed, enumeration covering live dims —
    only on a module that ran its FULL test set: a ``-k``/``-m``/
    nodeid selection of a few fake-clock tests legitimately compiles
    nothing, and erroring such a run would punish exactly the narrow
    reruns developers use. Partial selection is detected by
    deselection events against this module plus explicit nodeid args;
    tier-1's ``-m 'not slow'`` deselects nothing in the sentinel-armed
    modules, so the canonical run enforces non-vacuity."""
    from koordinator_tpu.testing.shapeflow import ShapeFlowSentinel

    sentinel = ShapeFlowSentinel.from_static_analysis()
    yield sentinel
    report = sentinel.report()
    assert report["violations"] == [], (
        "runtime shape-flow violations (out-of-enumeration compiles):\n"
        + "\n".join(map(str, report["violations"]))
    )
    assert report["enumerated_values"] > 0, (
        "shape-flow sentinel armed with an EMPTY enumeration"
    )
    module_id = request.node.nodeid
    nodeid_selected = any(
        "::" in str(a) for a in request.config.invocation_params.args
    )
    if nodeid_selected or module_id in _DESELECTED_MODULES:
        return
    assert report["windows_with_compiles"] > 0 \
        and report["dims_covered"] > 0, (
        f"shape-flow sentinel was vacuous: {report} — the suite no "
        f"longer exercises any enumerated compile signature"
    )


@pytest.fixture
def xla_compiles():
    """Counts actual backend compilations: with ``jax_log_compiles``
    on, jax logs one ``Compiling <name> ...`` record per XLA
    compilation (cache misses only — pjit cache hits don't log).
    Yields the live list of compile log messages; ``.clear()`` it after
    warmup. Shared by the graftcheck recompile guard
    (tests/test_graftcheck.py) and the pipelined tick path's
    steady-state guard (tests/test_pipeline.py)."""
    import logging

    logger = logging.getLogger("jax._src.interpreters.pxla")
    records = []

    class _Counter(logging.Handler):
        def emit(self, record):
            message = record.getMessage()
            if message.startswith("Compiling "):
                records.append(message)

    handler = _Counter()
    prev = jax.config.jax_log_compiles
    prev_level = logger.level
    jax.config.update("jax_log_compiles", True)
    logger.addHandler(handler)
    if logger.getEffectiveLevel() > logging.WARNING:
        logger.setLevel(logging.WARNING)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev_level)
        jax.config.update("jax_log_compiles", prev)
