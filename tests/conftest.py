"""Test harness: force an 8-device virtual CPU mesh before JAX import.

All sharding/multi-chip tests run on virtual CPU devices; the driver's
dryrun validates the same path. Must run before anything imports jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Isolate the suite from the user-global persistent compilation cache:
# cmd entry points enable it in-process (by design for production), and
# a shared on-disk cache would couple test runs to whatever any earlier
# crashed process left behind. The cache's own tests use tmp_path dirs.
os.environ.setdefault("KTPU_COMPILATION_CACHE_DIR", "")
# The ambient environment may preset JAX_PLATFORMS (e.g. a TPU tunnel);
# tests always run on the virtual CPU mesh, so force-override it. A
# site-level PJRT plugin may additionally have force-updated the
# jax_platforms config at interpreter start — undo that too.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Free compiled executables between test modules.

    Every XLA:CPU executable holds JIT code mappings; the full suite
    compiles thousands of programs and was hitting the kernel's
    vm.max_map_count (~65k mappings -> mmap failure -> segfault inside
    LLVM, measured r5). Cross-module cache reuse is negligible — each
    module compiles its own shapes — so clearing per module bounds the
    mapping count at a small runtime cost.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def lock_order_shim():
    """The runtime lock-order assertion shim (ISSUE 9): instruments
    every lock the static ``lock-order`` rule maps and verifies each
    observed acquisition embeds into the statically-derived order.
    Module-scoped: the chaos and pipeline suites opt in with an autouse
    wrapper so ALL their threads — coordinator, publisher, supervisor
    monitor, sidecar handlers, chaos proxies — run instrumented.
    Teardown asserts zero order violations and a non-vacuous run (the
    instrumented classes really were exercised)."""
    from koordinator_tpu.testing.lockorder import LockOrderShim

    shim = LockOrderShim.from_static_analysis().install()
    try:
        yield shim
    finally:
        report = shim.report()
        shim.uninstall()
        assert report["violations"] == [], (
            "runtime lock-order violations:\n"
            + "\n".join(map(str, report["violations"]))
        )
        assert report["acquisitions"] > 0, (
            "lock-order shim observed no acquisitions — the "
            "instrumentation no longer reaches the mapped locks"
        )


@pytest.fixture
def xla_compiles():
    """Counts actual backend compilations: with ``jax_log_compiles``
    on, jax logs one ``Compiling <name> ...`` record per XLA
    compilation (cache misses only — pjit cache hits don't log).
    Yields the live list of compile log messages; ``.clear()`` it after
    warmup. Shared by the graftcheck recompile guard
    (tests/test_graftcheck.py) and the pipelined tick path's
    steady-state guard (tests/test_pipeline.py)."""
    import logging

    logger = logging.getLogger("jax._src.interpreters.pxla")
    records = []

    class _Counter(logging.Handler):
        def emit(self, record):
            message = record.getMessage()
            if message.startswith("Compiling "):
                records.append(message)

    handler = _Counter()
    prev = jax.config.jax_log_compiles
    prev_level = logger.level
    jax.config.update("jax_log_compiles", True)
    logger.addHandler(handler)
    if logger.getEffectiveLevel() > logging.WARNING:
        logger.setLevel(logging.WARNING)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
        logger.setLevel(prev_level)
        jax.config.update("jax_log_compiles", prev)
