"""Actuation-edge hooks: device env inject, cpu-normalization quota
scaling, terway net-QoS config files (VERDICT r3 #3/#7).

Oracles: runtimehooks/hooks/gpu/gpu.go:51 (InjectContainerGPUEnv),
hooks/cpunormalization/cpu_normalization.go:79-171 (quota scaling +
isPodCPUShare), hooks/terwayqos/terwayqos.go (config generation,
parseNetQoS tiers, getPodPrio).
"""

import json
import math
import os

import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_CPU_NORMALIZATION_RATIO,
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_RESOURCE_STATUS,
    LABEL_QOS_CLASS,
    QoSClass,
)
from koordinator_tpu.apis.types import NodeSpec
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import (
    ensure_cgroup_dir,
)
from koordinator_tpu.koordlet.runtimehooks import (
    CPUNormalizationPlugin,
    DeviceEnvPlugin,
    HookRegistry,
    RuntimeHooks,
    RuntimeHookServer,
    TerwayQosPlugin,
    milli_cpu_to_quota,
)
from koordinator_tpu.koordlet.runtimehooks.terwayqos import (
    ANNOTATION_NET_QOS,
    NET_QOS_POLICY_KEY,
    NET_QOS_POLICY_TERWAY,
)
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.cgroup import CPU_CFS_QUOTA, SystemConfig
from koordinator_tpu.manager.sloconfig import NetworkQOS, NodeSLOSpec


def device_annotations(gpu_minors=(0, 2), rdma_vfs=("0000:81:00.2",)):
    return {
        ANNOTATION_DEVICE_ALLOCATED: json.dumps({
            "gpu": [{"minor": m, "resources": {}} for m in gpu_minors],
            "rdma": [{"minor": 0, "resources": {}, "vfs": list(rdma_vfs)}],
        })
    }


class TestDeviceEnvInject:
    def _server(self):
        registry = HookRegistry()
        DeviceEnvPlugin().register(registry)
        return RuntimeHookServer(registry)

    def test_allocated_pod_gets_env(self):
        pod = PodMeta(
            "p1", "kubepods/podp1", QoSClass.LSR,
            containers={"main": "kubepods/podp1/main"},
            annotations=device_annotations(),
        )
        resp = self._server().create_container(pod, "main", apply=False)
        assert resp.add_envs["TPU_VISIBLE_CHIPS"] == "0,2"
        assert resp.add_envs["NVIDIA_VISIBLE_DEVICES"] == "0,2"
        assert resp.add_envs["KOORDINATOR_RDMA_VFS"] == "0000:81:00.2"

    def test_no_allocation_no_env(self):
        pod = PodMeta("p2", "kubepods/podp2", QoSClass.LS,
                      containers={"main": "kubepods/podp2/main"})
        resp = self._server().create_container(pod, "main", apply=False)
        assert resp.add_envs is None

    def test_malformed_entries_skipped_not_raised(self):
        """A junk allocation entry must not fail container creation on
        the proxy/NRI path: skip it and inject the rest (ADVICE r4)."""
        pod = PodMeta(
            "p4", "kubepods/podp4", QoSClass.LSR,
            containers={"main": "kubepods/podp4/main"},
            annotations={ANNOTATION_DEVICE_ALLOCATED: json.dumps({
                "gpu": [{"minor": 0}, "not-a-dict", {"minor": "x"}],
                "rdma": ["nope", {"minor": 0, "vfs": ["0000:81:00.2"]}],
            })},
        )
        resp = self._server().create_container(pod, "main", apply=False)
        assert resp.add_envs["TPU_VISIBLE_CHIPS"] == "0"
        assert resp.add_envs["KOORDINATOR_RDMA_VFS"] == "0000:81:00.2"

    def test_injection_through_cri_proxy(self):
        """The NRI/proxy path: the env response merges into the container
        creation request the runtime actually sees — the allocator's
        output lands in the container (VERDICT r3 #3)."""
        from koordinator_tpu.runtimeproxy import (
            CRIRequest,
            RuntimeManagerCriServer,
        )

        class Backend:
            def __init__(self):
                self.requests = []

            def handle(self, request):
                self.requests.append(request)
                return {"ok": True}

            def list_pods(self):
                return []

        registry = HookRegistry()
        DeviceEnvPlugin().register(registry)
        backend = Backend()
        proxy = RuntimeManagerCriServer(
            RuntimeHookServer(registry), backend
        )
        pod = PodMeta(
            "p3", "kubepods/podp3", QoSClass.LSR,
            containers={"main": "kubepods/podp3/main"},
            annotations=device_annotations(gpu_minors=(1,)),
        )
        proxy.intercept(CRIRequest(method="RunPodSandbox", pod=pod))
        proxy.intercept(
            CRIRequest(method="CreateContainer", pod=pod, container="main")
        )
        forwarded = backend.requests[-1]
        assert forwarded.resources.add_envs["TPU_VISIBLE_CHIPS"] == "1"


def test_device_pod_scheduler_to_env_e2e():
    """The full actuation loop (VERDICT r3 #3 done-criterion): a
    device-requesting pod is placed by the scheduler, DeviceShare PreBind
    writes the allocation annotation, the koordlet-side projection turns
    the bound PodSpec into PodMeta, and the device hook injects the
    allocated minors into the container env at creation."""
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.device.cache import (
        DeviceEntry,
        DeviceResourceName as DR,
        DeviceType,
    )
    from koordinator_tpu.koordlet.statesinformer.reporters import (
        pod_meta_from_spec,
    )
    from koordinator_tpu.scheduler import Scheduler

    from koordinator_tpu.apis.extension import ResourceName as RN

    sched = Scheduler()
    sched.add_node(NodeSpec(name="n0", allocatable={
        RN.CPU: 16000, RN.MEMORY: 32768,
    }))
    sched.update_node_metric(NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0
    ))
    sched.update_node_devices("n0", [
        DeviceEntry(minor=i, device_type=DeviceType.GPU,
                    resources={DR.GPU_CORE: 100, DR.GPU_MEMORY: 16384,
                               DR.GPU_MEMORY_RATIO: 100},
                    numa_node=0, pcie_id="0")
        for i in range(2)
    ])
    pod = PodSpec(
        name="gpu-pod",
        requests={RN.CPU: 1000, RN.MEMORY: 1024},
        device_requests={DR.NVIDIA_GPU: 1},
    )
    sched.update_pod(pod)
    result = sched.schedule_pending(now=100.0)
    assert result["default/gpu-pod"] == "n0"
    bound = sched.cache.pods["default/gpu-pod"]
    assert ANNOTATION_DEVICE_ALLOCATED in bound.annotations

    registry = HookRegistry()
    DeviceEnvPlugin().register(registry)
    meta = pod_meta_from_spec(bound)
    resp = RuntimeHookServer(registry).create_container(
        meta, "main", apply=False
    )
    assert resp.add_envs["TPU_VISIBLE_CHIPS"] in ("0", "1")


class TestCPUNormalization:
    def _plugin(self, ratio):
        p = CPUNormalizationPlugin()
        node = NodeSpec(name="n0", annotations={
            ANNOTATION_CPU_NORMALIZATION_RATIO: str(ratio)
        })
        p.update_rule(node)
        return p

    def _pod_ctx(self, pod):
        from koordinator_tpu.koordlet.runtimehooks.protocol import PodContext

        return PodContext.from_meta(pod)

    def test_ls_pod_quota_scaled_ceil(self):
        p = self._plugin(1.3)
        pod = PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                      cpu_limit_mcpu=2000)
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us == math.ceil(
            milli_cpu_to_quota(2000) / 1.3
        )

    def test_container_quota_scaled(self):
        from koordinator_tpu.koordlet.runtimehooks.protocol import (
            ContainerContext,
        )

        p = self._plugin(2.0)
        pod = PodMeta(
            "ls", "kubepods/burstable/podls", QoSClass.LS,
            containers={"main": "kubepods/burstable/podls/main"},
            container_limits_mcpu={"main": 1000},
        )
        ctx = ContainerContext.from_meta(pod, "main")
        p.adjust_container_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us == math.ceil(
            milli_cpu_to_quota(1000) / 2.0
        )

    def test_ratio_removal_restores_spec_quota_once(self):
        """No kubelet re-asserts spec quotas here: removing the ratio
        writes the UNSCALED quota back for ONE pass (then the hook goes
        inert so it never fights cfs-quota-burst scale-ups)."""
        p = self._plugin(1.5)
        p.update_rule(NodeSpec(name="n0", annotations={}))  # removed
        pod = PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                      cpu_limit_mcpu=2000)
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us == milli_cpu_to_quota(2000)
        p.finish_restore()
        ctx2 = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx2)
        assert ctx2.response.cfs_quota_us is None  # steady state: inert

    def test_never_scaled_stays_inert(self):
        p = CPUNormalizationPlugin()
        p.update_rule(NodeSpec(name="n0", annotations={}))
        pod = PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                      cpu_limit_mcpu=2000)
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us is None

    def test_ratio_removal_restores_in_cgroupfs(self, tmp_path):
        """Shrink under ratio 2.0, then remove the annotation: the next
        reconcile writes the full spec quota back."""
        pod = PodMeta(
            "ls", "kubepods/burstable/podls", QoSClass.LS,
            containers={"main": "kubepods/burstable/podls/main"},
            cpu_limit_mcpu=4000,
            container_limits_mcpu={"main": 4000},
        )
        cfg = SystemConfig(
            cgroup_root=str(tmp_path / "cg"),
            proc_root=str(tmp_path / "proc"),
            terway_qos_root=str(tmp_path / "terway"),
        )
        for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort",
                  pod.cgroup_dir, pod.containers["main"]):
            ensure_cgroup_dir(d, cfg)
        executor = ResourceUpdateExecutor(cfg, auditor=Auditor())
        informer = StatesInformer()
        informer.set_pods([pod])
        RuntimeHooks(informer, executor)
        quota_file = os.path.join(
            cfg.cgroup_root, "cpu", pod.cgroup_dir, "cpu.cfs_quota_us"
        )
        informer.set_node(NodeSpec(name="n0", annotations={
            ANNOTATION_CPU_NORMALIZATION_RATIO: "2.0",
        }))
        assert open(quota_file).read() == str(
            math.ceil(milli_cpu_to_quota(4000) / 2.0)
        )
        informer.set_node(NodeSpec(name="n0", annotations={}))
        assert open(quota_file).read() == str(milli_cpu_to_quota(4000))

    def test_be_pod_excluded(self):
        p = self._plugin(1.5)
        pod = PodMeta("be", "kubepods/besteffort/podbe", QoSClass.BE,
                      cpu_limit_mcpu=2000)
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us is None

    def test_pinned_pod_excluded(self):
        p = self._plugin(1.5)
        pod = PodMeta(
            "pin", "kubepods/podpin", QoSClass.NONE, cpu_limit_mcpu=2000,
            annotations={
                ANNOTATION_RESOURCE_STATUS: json.dumps({"cpuset": [0, 1]})
            },
        )
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us is None

    def test_unlimited_pod_untouched(self):
        p = self._plugin(1.5)
        pod = PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS)
        ctx = self._pod_ctx(pod)
        p.adjust_pod_cfs_quota(ctx)
        assert ctx.response.cfs_quota_us is None

    def test_normalized_node_scales_quota_in_fake_cgroupfs(self, tmp_path):
        """End-to-end (VERDICT r3 #3 done-criterion): annotated node ->
        informer NODE callback -> reconcile writes the scaled quota into
        the fake cgroupfs for the LS pod."""
        pod = PodMeta(
            "ls", "kubepods/burstable/podls", QoSClass.LS,
            containers={"main": "kubepods/burstable/podls/main"},
            cpu_limit_mcpu=4000,
            container_limits_mcpu={"main": 4000},
        )
        cfg = SystemConfig(
            cgroup_root=str(tmp_path / "cg"),
            proc_root=str(tmp_path / "proc"),
            terway_qos_root=str(tmp_path / "terway"),
        )
        for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort",
                  pod.cgroup_dir, pod.containers["main"]):
            ensure_cgroup_dir(d, cfg)
        executor = ResourceUpdateExecutor(cfg, auditor=Auditor())
        informer = StatesInformer()
        informer.set_pods([pod])
        hooks = RuntimeHooks(informer, executor)
        informer.set_node(NodeSpec(name="n0", annotations={
            ANNOTATION_CPU_NORMALIZATION_RATIO: "1.6",
        }))
        want = str(math.ceil(milli_cpu_to_quota(4000) / 1.6))
        quota_file = os.path.join(
            cfg.cgroup_root, "cpu", pod.cgroup_dir, "cpu.cfs_quota_us"
        )
        assert open(quota_file).read() == want


class TestTerwayQos:
    def _slo(self, policy=NET_QOS_POLICY_TERWAY, total_bps=10_000_000_000):
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.policies[NET_QOS_POLICY_KEY] = policy
        slo.system_strategy.total_network_bandwidth_bps = total_bps
        slo.resource_qos_strategy.ls.network = NetworkQOS(
            enable=True, ingress_request=50, ingress_limit=100,
            egress_request=50, egress_limit=100,
        )
        slo.resource_qos_strategy.be.network = NetworkQOS(
            enable=True, ingress_request=10, ingress_limit=40,
            egress_request=10, egress_limit="2000000000",
        )
        return slo

    def test_node_config_tiers(self, tmp_path):
        plugin = TerwayQosPlugin(str(tmp_path))
        plugin.update_node_slo(self._slo())
        text = open(plugin.node_file).read()
        cfg = dict(
            line.split("=") for line in text.strip().splitlines()
        )
        total_bytes = 10_000_000_000 // 8
        assert int(cfg["hw_tx_bps_max"]) == total_bytes
        assert int(cfg["l1_rx_bps_min"]) == total_bytes // 2   # 50%
        assert int(cfg["l2_rx_bps_max"]) == total_bytes * 40 // 100
        # absolute bits/s string -> bytes
        assert int(cfg["l2_tx_bps_max"]) == 2_000_000_000 // 8

    def test_pod_config_prio_and_limits(self, tmp_path):
        plugin = TerwayQosPlugin(str(tmp_path))
        plugin.update_node_slo(self._slo())
        pods = [
            PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                    labels={LABEL_QOS_CLASS: QoSClass.LS.value},
                    annotations={ANNOTATION_NET_QOS: json.dumps(
                        {"ingressLimit": "800000000", "egressLimit": "400000000"}
                    )}),
            PodMeta("be", "kubepods/besteffort/podbe", QoSClass.BE),
            PodMeta("plain", "kubepods/podplain", QoSClass.NONE),
        ]
        plugin.update_pods(pods)
        data = json.loads(open(plugin.pod_file).read())
        assert data["ls"]["prio"] == 1
        assert data["ls"]["ingress_bandwidth"] == 100_000_000
        assert data["ls"]["egress_bandwidth"] == 50_000_000
        assert data["be"]["prio"] == 2       # kube besteffort tier
        assert data["plain"]["prio"] == 1    # guaranteed tier fallback

    def test_over_total_absolute_rejected_keeps_prior(self, tmp_path):
        """An absolute bits/s value above the node total is a parse
        error that rejects the whole rule update (reference
        parseQuantity); mapping it to 0 would silently mean 'no limit'
        (ADVICE r4)."""
        plugin = TerwayQosPlugin(str(tmp_path))
        plugin.update_node_slo(self._slo())
        before = open(plugin.node_file).read()
        bad = self._slo()
        bad.resource_qos_strategy.be.network = NetworkQOS(
            enable=True, ingress_request=10, ingress_limit=40,
            egress_request=10, egress_limit="20000000000",  # > 10G total
        )
        plugin.update_node_slo(bad)
        assert open(plugin.node_file).read() == before

    def test_disable_removes_files(self, tmp_path):
        plugin = TerwayQosPlugin(str(tmp_path))
        plugin.update_node_slo(self._slo())
        assert os.path.exists(plugin.node_file)
        plugin.update_node_slo(self._slo(policy="none"))
        assert not os.path.exists(plugin.node_file)
        assert not os.path.exists(plugin.pod_file)

    def test_wired_through_runtimehooks_callbacks(self, tmp_path):
        cfg = SystemConfig(
            cgroup_root=str(tmp_path / "cg"),
            proc_root=str(tmp_path / "proc"),
            terway_qos_root=str(tmp_path / "terway"),
        )
        for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
            ensure_cgroup_dir(d, cfg)
        executor = ResourceUpdateExecutor(cfg, auditor=Auditor())
        informer = StatesInformer()
        hooks = RuntimeHooks(informer, executor)
        informer.set_node_slo(self._slo())
        assert os.path.exists(hooks.terwayqos.node_file)
        informer.set_pods([
            PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                    containers={}),
        ])
        assert "ls" in json.loads(open(hooks.terwayqos.pod_file).read())
