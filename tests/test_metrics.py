"""Metrics registries (VERDICT weak item 6).

Reference: pkg/scheduler/metrics/, pkg/koordlet/metrics/ internal+external
registries + merged gather, pkg/descheduler/metrics/.
"""

import pytest

from koordinator_tpu.metrics import (
    Counter,
    Gauge,
    Histogram,
    MergedGatherer,
    Registry,
)


class TestPrimitives:
    def test_counter(self):
        c = Counter("hits_total", "hits", label_names=("code",))
        c.inc({"code": "200"})
        c.inc({"code": "200"}, amount=2)
        c.inc({"code": "500"})
        assert c.value({"code": "200"}) == 3
        with pytest.raises(ValueError):
            c.inc({"code": "200"}, amount=-1)
        with pytest.raises(ValueError):
            c.inc({"wrong": "x"})
        text = "\n".join(c.expose())
        assert 'hits_total{code="200"} 3' in text
        assert "# TYPE hits_total counter" in text

    def test_gauge(self):
        g = Gauge("pending", "")
        g.set(5)
        g.add(-2)
        assert g.value() == 3
        assert "pending 3" in "\n".join(g.expose())

    def test_histogram(self):
        h = Histogram("lat_seconds", "", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(55.55)
        text = "\n".join(h.expose())
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 2' in text
        assert 'lat_seconds_bucket{le="+Inf"} 4' in text
        assert "lat_seconds_count 4" in text

    def test_registry_and_merged_gather(self):
        internal = Registry("internal")
        external = Registry("external")
        internal.counter("a_total").inc()
        external.gauge("b").set(7)
        with pytest.raises(ValueError):
            internal.counter("a_total")  # duplicate
        merged = MergedGatherer([internal, external]).gather()
        assert "a_total 1" in merged and "b 7" in merged


class TestWiring:
    def test_scheduler_round_records_metrics(self):
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
        from koordinator_tpu.metrics.components import (
            BATCH_SOLVE_DURATION,
            SCHEDULING_ATTEMPTS,
        )
        from koordinator_tpu.scheduler import Scheduler

        scheduled0 = SCHEDULING_ATTEMPTS.value({"result": "scheduled"})
        solves0 = BATCH_SOLVE_DURATION.count()
        s = Scheduler()
        s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384}))
        s.update_node_metric(
            NodeMetric(node_name="n0", node_usage={}, update_time=99.0)
        )
        s.add_pod(PodSpec(name="a", requests={R.CPU: 1000}))
        s.schedule_pending(now=100.0)
        assert SCHEDULING_ATTEMPTS.value({"result": "scheduled"}) == scheduled0 + 1
        assert BATCH_SOLVE_DURATION.count() == solves0 + 1

    def test_executor_write_counter(self, tmp_path):
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )
        from koordinator_tpu.koordlet.resourceexecutor.executor import (
            CgroupUpdater,
            ensure_cgroup_dir,
        )
        from koordinator_tpu.koordlet.system.cgroup import SystemConfig
        from koordinator_tpu.metrics.components import CGROUP_WRITES

        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"))
        ensure_cgroup_dir("kubepods", cfg)
        ex = ResourceUpdateExecutor(cfg)
        before = CGROUP_WRITES.value({"resource": "cpu.shares"})
        ex.update(True, CgroupUpdater("cpu.shares", "kubepods", "1024"))
        assert CGROUP_WRITES.value({"resource": "cpu.shares"}) == before + 1
        # cache hit: no second write counted
        ex.update(True, CgroupUpdater("cpu.shares", "kubepods", "1024"))
        assert CGROUP_WRITES.value({"resource": "cpu.shares"}) == before + 1
