"""Pallas placement kernel: bit-identity vs the scan solver (interpret
mode — the TPU path is exercised by bench.py on hardware)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.ops.pallas_binpack import (
    pallas_schedule_batch,
    pallas_supported,
)


def _problem(n_nodes=96, n_pods=150, seed=0, stale_frac=0.2,
             unsched_frac=0.1, ds_frac=0.2, blocked_frac=0.1):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = rng.choice([4000, 16000, 64000], n_nodes)
    alloc[:, R.MEMORY] = rng.choice([8192, 32768], n_nodes)
    usage = (alloc * rng.uniform(0, 0.9, alloc.shape)).astype(np.int32)
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.asarray((alloc * rng.uniform(0, 0.3, alloc.shape)).astype(np.int32)),
        usage=jnp.asarray(usage),
        prod_usage=jnp.asarray(usage // 2),
        est_extra=jnp.asarray((usage // 4)),
        prod_base=jnp.asarray(usage // 3),
        metric_fresh=jnp.asarray(rng.uniform(size=n_nodes) > stale_frac),
        schedulable=jnp.asarray(rng.uniform(size=n_nodes) > unsched_frac),
    )
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([500, 1000, 4000, 100000], n_pods)
    req[:, R.MEMORY] = rng.choice([0, 1024, 4096], n_pods)
    pods = PodBatch.build(
        req=jnp.asarray(req),
        est=jnp.asarray((req * 85) // 100),
        is_prod=jnp.asarray(rng.uniform(size=n_pods) < 0.5),
        is_daemonset=jnp.asarray(rng.uniform(size=n_pods) < ds_frac),
        blocked=jnp.asarray(rng.uniform(size=n_pods) < blocked_frac),
    )
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = ScoreParams(
        weights=jnp.asarray(weights),
        thresholds=jnp.asarray(thresholds),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    return state, pods, params


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_identical_to_scan(seed):
    state, pods, params = _problem(seed=seed)
    config = SolverConfig()
    want_state, want = schedule_batch(state, pods, params, config)
    got_state, got = pallas_schedule_batch(
        state, pods, params, config, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for field in ("used_req", "est_extra", "prod_base"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got_state, field)),
            np.asarray(getattr(want_state, field)),
            err_msg=field,
        )


def test_supported_gate():
    state, pods, params = _problem()
    assert pallas_supported(params, SolverConfig())
    assert not pallas_supported(params, SolverConfig(score_according_prod=True))
    prod = params._replace(
        prod_thresholds=jnp.full(NUM_RESOURCES, 50, jnp.int32)
    )
    assert not pallas_supported(prod, SolverConfig())
    with pytest.raises(ValueError):
        pallas_schedule_batch(
            state, pods, params, SolverConfig(score_according_prod=True)
        )


def test_nonaligned_sizes():
    # N and P not multiples of 128 exercise the padding paths
    state, pods, params = _problem(n_nodes=33, n_pods=41, seed=3)
    config = SolverConfig()
    _, want = schedule_batch(state, pods, params, config)
    _, got = pallas_schedule_batch(
        state, pods, params, config, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_placement_model_pallas_path_identical():
    """PlacementModel routes eligible plain solves onto the kernel with
    identical end-to-end output (forced-on in interpret mode here)."""
    from koordinator_tpu.apis.types import ClusterSnapshot, NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.models.placement import PlacementModel

    def snap():
        return ClusterSnapshot(
            nodes=[
                NodeSpec(name=f"n{i}",
                         allocatable={R.CPU: 16000, R.MEMORY: 32768})
                for i in range(3)
            ],
            pending_pods=[
                PodSpec(name=f"p{i}", requests={R.CPU: 1000 + 500 * i})
                for i in range(5)
            ],
            node_metrics={
                f"n{i}": NodeMetric(node_name=f"n{i}", node_usage={},
                                    update_time=99.0)
                for i in range(3)
            },
            now=100.0,
        )

    model = PlacementModel(use_pallas=True)
    via_pallas = model.schedule(snap())
    via_scan = PlacementModel(use_pallas=False).schedule(snap())
    assert dict(via_pallas) == dict(via_scan)
    assert all(v is not None for v in via_pallas.values())
    # the kernel path was actually taken (no silent fallback)
    assert model.use_pallas


def test_model_pallas_breaker_not_tripped_by_empty_solves():
    """Zero-node / zero-pod snapshots route to the scan's shape early-out
    without permanently disabling the kernel (review fix)."""
    from koordinator_tpu.apis.types import ClusterSnapshot, PodSpec
    from koordinator_tpu.models.placement import PlacementModel

    model = PlacementModel(use_pallas=True)
    out = model.schedule(ClusterSnapshot(
        pending_pods=[PodSpec(name="p", requests={R.CPU: 100})]))
    assert out["default/p"] is None
    assert model.use_pallas  # breaker untouched
