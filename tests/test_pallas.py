"""Pallas placement kernel: bit-identity vs the scan solver (interpret
mode — the TPU path is exercised by bench.py on hardware)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.ops.pallas_binpack import (
    pallas_schedule_batch,
    pallas_supported,
)


def _problem(n_nodes=96, n_pods=150, seed=0, stale_frac=0.2,
             unsched_frac=0.1, ds_frac=0.2, blocked_frac=0.1):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = rng.choice([4000, 16000, 64000], n_nodes)
    alloc[:, R.MEMORY] = rng.choice([8192, 32768], n_nodes)
    usage = (alloc * rng.uniform(0, 0.9, alloc.shape)).astype(np.int32)
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.asarray((alloc * rng.uniform(0, 0.3, alloc.shape)).astype(np.int32)),
        usage=jnp.asarray(usage),
        prod_usage=jnp.asarray(usage // 2),
        est_extra=jnp.asarray((usage // 4)),
        prod_base=jnp.asarray(usage // 3),
        metric_fresh=jnp.asarray(rng.uniform(size=n_nodes) > stale_frac),
        schedulable=jnp.asarray(rng.uniform(size=n_nodes) > unsched_frac),
    )
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([500, 1000, 4000, 100000], n_pods)
    req[:, R.MEMORY] = rng.choice([0, 1024, 4096], n_pods)
    pods = PodBatch.build(
        req=jnp.asarray(req),
        est=jnp.asarray((req * 85) // 100),
        is_prod=jnp.asarray(rng.uniform(size=n_pods) < 0.5),
        is_daemonset=jnp.asarray(rng.uniform(size=n_pods) < ds_frac),
        blocked=jnp.asarray(rng.uniform(size=n_pods) < blocked_frac),
    )
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = ScoreParams(
        weights=jnp.asarray(weights),
        thresholds=jnp.asarray(thresholds),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    return state, pods, params


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_identical_to_scan(seed):
    state, pods, params = _problem(seed=seed)
    config = SolverConfig()
    want_state, want = schedule_batch(state, pods, params, config)
    got_state, got = pallas_schedule_batch(
        state, pods, params, config, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for field in ("used_req", "est_extra", "prod_base"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got_state, field)),
            np.asarray(getattr(want_state, field)),
            err_msg=field,
        )


def test_supported_gate():
    state, pods, params = _problem()
    assert pallas_supported(params, SolverConfig())
    assert not pallas_supported(params, SolverConfig(score_according_prod=True))
    prod = params._replace(
        prod_thresholds=jnp.full(NUM_RESOURCES, 50, jnp.int32)
    )
    assert not pallas_supported(prod, SolverConfig())
    with pytest.raises(ValueError):
        pallas_schedule_batch(
            state, pods, params, SolverConfig(score_according_prod=True)
        )


def test_nonaligned_sizes():
    # N and P not multiples of 128 exercise the padding paths
    state, pods, params = _problem(n_nodes=33, n_pods=41, seed=3)
    config = SolverConfig()
    _, want = schedule_batch(state, pods, params, config)
    _, got = pallas_schedule_batch(
        state, pods, params, config, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_placement_model_pallas_path_identical():
    """PlacementModel routes eligible plain solves onto the kernel with
    identical end-to-end output (forced-on in interpret mode here)."""
    from koordinator_tpu.apis.types import ClusterSnapshot, NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.models.placement import PlacementModel

    def snap():
        return ClusterSnapshot(
            nodes=[
                NodeSpec(name=f"n{i}",
                         allocatable={R.CPU: 16000, R.MEMORY: 32768})
                for i in range(3)
            ],
            pending_pods=[
                PodSpec(name=f"p{i}", requests={R.CPU: 1000 + 500 * i})
                for i in range(5)
            ],
            node_metrics={
                f"n{i}": NodeMetric(node_name=f"n{i}", node_usage={},
                                    update_time=99.0)
                for i in range(3)
            },
            now=100.0,
        )

    model = PlacementModel(use_pallas=True)
    via_pallas = model.schedule(snap())
    via_scan = PlacementModel(use_pallas=False).schedule(snap())
    assert dict(via_pallas) == dict(via_scan)
    assert all(v is not None for v in via_pallas.values())
    # the kernel path was actually taken (no silent fallback)
    assert model.use_pallas


def test_model_pallas_breaker_not_tripped_by_empty_solves():
    """Zero-node / zero-pod snapshots route to the scan's shape early-out
    without permanently disabling the kernel (review fix)."""
    from koordinator_tpu.apis.types import ClusterSnapshot, PodSpec
    from koordinator_tpu.models.placement import PlacementModel

    model = PlacementModel(use_pallas=True)
    out = model.schedule(ClusterSnapshot(
        pending_pods=[PodSpec(name="p", requests={R.CPU: 100})]))
    assert out["default/p"] is None
    assert model.use_pallas  # breaker untouched


def _quota_setup(state, pods, n_quota=7, seed=5, preempt_frac=0.3):
    """Tight quotas over the _problem pods: some groups exhaust runtime
    mid-batch so admission actually rejects."""
    from koordinator_tpu.ops.quota import QuotaState

    rng = np.random.default_rng(seed)
    n_pods = pods.req.shape[0]
    quota_id = rng.integers(-1, n_quota, n_pods).astype(np.int32)
    pods = pods._replace(
        quota_id=jnp.asarray(quota_id),
        non_preemptible=jnp.asarray(rng.uniform(size=n_pods) < preempt_frac),
    )
    total = np.asarray(state.alloc).astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mn[:, R.CPU] = total[R.CPU] // (4 * n_quota)
    mn[:, R.MEMORY] = total[R.MEMORY] // (4 * n_quota)
    mx[:, R.CPU] = total[R.CPU] // (n_quota + 2)
    mx[:, R.MEMORY] = total[R.MEMORY] // (n_quota + 2)
    req = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    pr = np.asarray(pods.req).astype(np.int64)
    for q in range(n_quota):
        req[q] = pr[quota_id == q].sum(axis=0)
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx,
        allow_lent=np.ones(n_quota, bool), total=total, child_request=req,
    )
    return pods, qstate


def _gang_setup(pods, n_gangs=9, seed=6):
    from koordinator_tpu.ops.gang import GangState

    rng = np.random.default_rng(seed)
    n_pods = pods.req.shape[0]
    gang_id = rng.integers(-1, n_gangs, n_pods).astype(np.int32)
    pods = pods._replace(gang_id=jnp.asarray(gang_id))
    sizes = [max(1, int((gang_id == g).sum())) for g in range(n_gangs)]
    gstate = GangState.build(
        min_member=[max(1, s - rng.integers(0, 2)) for s in sizes],
        bound_count=rng.integers(0, 2, n_gangs),
        strict=rng.uniform(size=n_gangs) < 0.6,
        group_id=[f"grp{g // 2}" for g in range(n_gangs)],  # shared groups
    )
    return pods, gstate


def _assert_result_identical(got, want):
    for field in ("assign", "commit", "waiting", "rejected", "raw_assign"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, field)),
            np.asarray(getattr(want, field)), err_msg=field)
    for field in ("used_req", "est_extra", "prod_base"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got.node_state, field)),
            np.asarray(getattr(want.node_state, field)), err_msg=field)
    if want.quota_state is not None:
        for field in ("used", "np_used"):
            np.testing.assert_array_equal(
                np.asarray(getattr(got.quota_state, field)),
                np.asarray(getattr(want.quota_state, field)), err_msg=field)


def _numa_setup(state, pods, seed=7, most=False):
    """NUMA arrays + aux: mixed node policies, mixed pod policies."""
    from koordinator_tpu.ops.binpack import NumaAux

    rng = np.random.default_rng(seed)
    n = state.alloc.shape[0]
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(
        numa_cap=jnp.asarray(cap), numa_free=jnp.asarray(free)
    )
    pods = pods._replace(
        has_numa_policy=jnp.asarray(
            rng.uniform(size=pods.req.shape[0]) < 0.4)
    )
    aux = NumaAux(node_policy=jnp.asarray(rng.uniform(size=n) < 0.5))
    return state, pods, aux


def _assert_numa_identical(got, want):
    _assert_result_identical(got, want)
    np.testing.assert_array_equal(
        np.asarray(got.numa_consumed), np.asarray(want.numa_consumed))
    np.testing.assert_array_equal(
        np.asarray(got.node_state.numa_free),
        np.asarray(want.node_state.numa_free))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("most", [False, True])
def test_numa_identical_to_scan(seed, most):
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    state, pods, aux = _numa_setup(state, pods, seed=seed + 7, most=most)
    config = SolverConfig(numa_most_allocated=most)
    want = solve_batch(state, pods, params, config, numa=aux)
    got = pallas_solve_batch(state, pods, params, config, numa_aux=aux,
                             interpret=True)
    _assert_numa_identical(got, want)
    assert int(np.asarray(want.numa_consumed).sum()) > 0


@pytest.mark.parametrize("seed", [0, 1])
def test_numa_quota_gang_identical_to_scan(seed):
    """The full kernel feature set at once: quota + gang + NUMA."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    pods, qstate = _quota_setup(state, pods, seed=seed + 5)
    pods, gstate = _gang_setup(pods, seed=seed + 6)
    state, pods, aux = _numa_setup(state, pods, seed=seed + 7)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, qstate, gstate,
                       numa=aux)
    got = pallas_solve_batch(state, pods, params, config, qstate, gstate,
                             numa_aux=aux, interpret=True)
    _assert_numa_identical(got, want)
    # gang rejections exercised the NUMA release path
    assert int(np.asarray(want.rejected).sum()) > 0


def test_quota_many_groups_identical_to_scan():
    """>128 quota groups exercises the multi-tile lane padding of the
    [R, Qp] quota layout (groups on lanes)."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=3)
    pods, qstate = _quota_setup(state, pods, n_quota=150, seed=9)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, qstate)
    got = pallas_solve_batch(state, pods, params, config, qstate,
                             interpret=True)
    _assert_result_identical(got, want)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_quota_identical_to_scan(seed):
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    pods, qstate = _quota_setup(state, pods, seed=seed + 5)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, qstate)
    got = pallas_solve_batch(state, pods, params, config, qstate,
                             interpret=True)
    _assert_result_identical(got, want)
    # the quota gate actually fired (otherwise this test proves nothing)
    assert int((np.asarray(want.assign) < 0).sum()) > 0


@pytest.mark.parametrize("seed", [0, 1])
def test_gang_identical_to_scan(seed):
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    pods, gstate = _gang_setup(pods, seed=seed + 7)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, None, gstate)
    got = pallas_solve_batch(state, pods, params, config, None, gstate,
                             interpret=True)
    _assert_result_identical(got, want)
    assert int(np.asarray(want.rejected).sum()) > 0  # gangs really gated


@pytest.mark.parametrize("seed", [0, 1])
def test_quota_and_gang_identical_to_scan(seed):
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    pods, qstate = _quota_setup(state, pods, seed=seed + 5)
    pods, gstate = _gang_setup(pods, seed=seed + 7)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, qstate, gstate)
    got = pallas_solve_batch(state, pods, params, config, qstate, gstate,
                             interpret=True)
    _assert_result_identical(got, want)


def test_model_quota_gang_pallas_path_identical():
    """PlacementModel routes quota+gang solves onto the kernel now —
    end-to-end schedule() identity incl. waiting pods."""
    from koordinator_tpu.apis.types import (
        ClusterSnapshot, GangSpec, NodeMetric, NodeSpec, PodSpec, QuotaSpec,
    )
    from koordinator_tpu.models.placement import PlacementModel

    def snap():
        return ClusterSnapshot(
            nodes=[NodeSpec(name=f"n{i}",
                            allocatable={R.CPU: 8000, R.MEMORY: 16384})
                   for i in range(4)],
            pending_pods=(
                [PodSpec(name=f"q{i}", quota="t", requests={R.CPU: 3000})
                 for i in range(4)]
                + [PodSpec(name=f"g{i}", gang="g", requests={R.CPU: 1000})
                   for i in range(3)]
                + [PodSpec(name="solo", requests={R.CPU: 500})]
            ),
            node_metrics={
                f"n{i}": NodeMetric(node_name=f"n{i}", node_usage={},
                                    update_time=99.0)
                for i in range(4)
            },
            quotas={"t": QuotaSpec(name="t", min={R.CPU: 3000},
                                   max={R.CPU: 6000})},
            gangs={"g": GangSpec(name="g", min_member=3)},
            now=100.0,
        )

    model = PlacementModel(use_pallas=True)
    via_pallas = model.schedule(snap())
    via_scan = PlacementModel(use_pallas=False).schedule(snap())
    assert dict(via_pallas) == dict(via_scan)
    assert via_pallas.waiting == via_scan.waiting
    # quota really capped: only 2 of 4 quota pods fit 6000/3000
    placed_q = [u for u, n in via_pallas.items()
                if n is not None and u.startswith("default/q")]
    assert len(placed_q) == 2
    assert model.use_pallas  # no silent fallback


def _resv_setup(state, pods, n_resv=11, seed=8, once_frac=0.4,
                match_frac=0.25):
    """Reservation tables over the _problem pods: holds big enough that
    the credit path flips some fit decisions, with allocate_once mixed
    in so remainder release is exercised."""
    from koordinator_tpu.ops.binpack import ResvArrays

    rng = np.random.default_rng(seed)
    n_nodes = state.alloc.shape[0]
    n_pods = pods.req.shape[0]
    node = rng.integers(0, n_nodes, n_resv).astype(np.int32)
    free = np.zeros((n_resv, NUM_RESOURCES), np.int32)
    free[:, R.CPU] = rng.integers(500, 100001, n_resv)
    free[:, R.MEMORY] = rng.integers(0, 8192, n_resv)
    match = rng.uniform(size=(n_pods, n_resv)) < match_frac
    return ResvArrays(
        node=jnp.asarray(node),
        free=jnp.asarray(free),
        allocate_once=jnp.asarray(rng.uniform(size=n_resv) < once_frac),
        match=jnp.asarray(match),
    )


def _assert_resv_identical(got, want):
    _assert_result_identical(got, want)
    for field in ("resv_free", "resv_vstar", "resv_delta"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, field)),
            np.asarray(getattr(want, field)), err_msg=field)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_resv_identical_to_scan(seed):
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    resv = _resv_setup(state, pods, seed=seed + 8)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, resv=resv)
    got = pallas_solve_batch(state, pods, params, config, resv=resv,
                             interpret=True)
    _assert_resv_identical(got, want)
    # reservations really consumed (else the credit matmul is untested)
    assert int((np.asarray(want.resv_vstar) >= 0).sum()) > 0
    assert not np.array_equal(
        np.asarray(want.resv_free), np.asarray(resv.free))


@pytest.mark.parametrize("seed", [0, 1])
def test_resv_gang_identical_to_scan(seed):
    """Gang rejections release reservation consumption — the epilogue's
    segment-sum restore must match the scan's."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    resv = _resv_setup(state, pods, seed=seed + 8)
    pods, gstate = _gang_setup(pods, seed=seed + 7)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, None, gstate,
                       resv=resv)
    got = pallas_solve_batch(state, pods, params, config, None, gstate,
                             resv=resv, interpret=True)
    _assert_resv_identical(got, want)
    rej_consumed = (np.asarray(want.rejected)
                    & (np.asarray(want.resv_vstar) >= 0))
    assert rej_consumed.sum() > 0  # the restore path really ran


@pytest.mark.parametrize("seed", [0, 1])
def test_resv_quota_gang_numa_identical_to_scan(seed):
    """EVERY kernel feature fused at once: quota admission + strict
    gangs + NUMA scoring/consumption + reservation credit/consumption."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(seed=seed)
    pods, qstate = _quota_setup(state, pods, seed=seed + 5)
    pods, gstate = _gang_setup(pods, seed=seed + 6)
    state, pods, aux = _numa_setup(state, pods, seed=seed + 7)
    resv = _resv_setup(state, pods, seed=seed + 8)
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, qstate, gstate,
                       resv=resv, numa=aux)
    got = pallas_solve_batch(state, pods, params, config, qstate, gstate,
                             numa_aux=aux, resv=resv, interpret=True)
    _assert_numa_identical(got, want)
    _assert_resv_identical(got, want)


def test_resv_multi_tile_and_gate():
    """129 reservations exercise the second lane tile (Vp=256); 257
    overflows the exactness bound and must raise."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import (
        pallas_resv_supported,
        pallas_solve_batch,
    )

    state, pods, params = _problem(seed=4)
    config = SolverConfig()
    resv = _resv_setup(state, pods, n_resv=129, seed=12, match_frac=0.1)
    want = solve_batch(state, pods, params, config, resv=resv)
    got = pallas_solve_batch(state, pods, params, config, resv=resv,
                             interpret=True)
    _assert_resv_identical(got, want)

    assert pallas_resv_supported(256, 5000)
    assert not pallas_resv_supported(257, 5000)
    assert not pallas_resv_supported(256, 20000)  # one-hot VMEM gate
    assert not pallas_resv_supported(0, 5000)  # empty: pass resv=None
    big = _resv_setup(state, pods, n_resv=257, seed=13)
    with pytest.raises(ValueError):
        pallas_solve_batch(state, pods, params, config, resv=big,
                           interpret=True)


def test_resv_credit_flips_fit():
    """A pod that does NOT fit on any node by raw used_req fits via a
    matched reservation's credited hold — the hi/lo credit matmul must
    discount exactly (transformer.go restore semantics)."""
    from koordinator_tpu.ops.binpack import ResvArrays, solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    n_nodes = 5
    alloc = np.full((n_nodes, NUM_RESOURCES), 0, np.int32)
    alloc[:, R.CPU] = 8000
    alloc[:, R.MEMORY] = 16384
    used = alloc.copy()  # every node fully held
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.asarray(used),
        usage=jnp.zeros_like(jnp.asarray(alloc)),
        prod_usage=jnp.zeros_like(jnp.asarray(alloc)),
        est_extra=jnp.zeros_like(jnp.asarray(alloc)),
        prod_base=jnp.zeros_like(jnp.asarray(alloc)),
        metric_fresh=jnp.ones(n_nodes, bool),
        schedulable=jnp.ones(n_nodes, bool),
    )
    req = np.zeros((2, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = 2000
    pods = PodBatch.build(
        req=jnp.asarray(req), est=jnp.asarray(req),
        is_prod=jnp.zeros(2, bool), is_daemonset=jnp.zeros(2, bool),
    )
    free = np.zeros((1, NUM_RESOURCES), np.int32)
    free[0, R.CPU] = 4000
    free[0, R.MEMORY] = 4096
    resv = ResvArrays(
        node=jnp.asarray(np.array([3], np.int32)),
        free=jnp.asarray(free),
        allocate_once=jnp.asarray([False]),
        match=jnp.asarray(np.ones((2, 1), bool)),
    )
    params = ScoreParams(
        weights=jnp.asarray(np.array([1, 1] + [0] * (NUM_RESOURCES - 2),
                                     np.int32)),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    config = SolverConfig()
    want = solve_batch(state, pods, params, config, resv=resv)
    got = pallas_solve_batch(state, pods, params, config, resv=resv,
                             interpret=True)
    _assert_resv_identical(got, want)
    # both pods land on the reserved node through the credit
    np.testing.assert_array_equal(np.asarray(got.assign), [3, 3])
    np.testing.assert_array_equal(
        np.asarray(got.resv_free)[0, R.CPU], 0)  # 2x2000 consumed


def test_resv_score_budget_gate():
    """A reservation table whose credit could overflow the packed
    argmax's 15-bit score budget must be rejected (rides the scan);
    normal tables pass."""
    from koordinator_tpu.ops.binpack import ResvArrays, solve_batch
    from koordinator_tpu.ops.pallas_binpack import (
        pallas_resv_score_safe,
        pallas_solve_batch,
    )

    state, pods, params = _problem(seed=5)
    ok_resv = _resv_setup(state, pods, seed=15)
    assert pallas_resv_score_safe(ok_resv.node, ok_resv.free, state.alloc)

    # ~325x the smallest node's allocatable as matched free => the fit
    # term alone could exceed 32767
    n_nodes = state.alloc.shape[0]
    small = int(np.asarray(state.alloc)[:, R.CPU].min())
    free = np.zeros((1, NUM_RESOURCES), np.int32)
    free[0, R.CPU] = small * 330
    node = int(np.asarray(state.alloc)[:, R.CPU].argmin())
    bad = ResvArrays(
        node=jnp.asarray(np.array([node], np.int32)),
        free=jnp.asarray(free),
        allocate_once=jnp.asarray([False]),
        match=jnp.asarray(np.ones((pods.req.shape[0], 1), bool)),
    )
    assert not pallas_resv_score_safe(bad.node, bad.free, state.alloc)
    with pytest.raises(ValueError):
        pallas_solve_batch(state, pods, params, SolverConfig(), resv=bad,
                           interpret=True)
    # the scan handles it fine (the contract the router falls back to)
    solve_batch(state, pods, params, SolverConfig(), resv=bad)


@pytest.mark.parametrize("seed", [0, 3])
def test_resv_onehot_hoist_identical(seed):
    """A caller-cached resv_node_onehot must be byte-for-byte the
    operand the kernel derives itself: solves with and without the
    hoisted one-hot are identical (the per-solve rebuild it replaces
    was ADVICE r5 low #3)."""
    from koordinator_tpu.ops.pallas_binpack import (
        pallas_solve_batch,
        resv_node_onehot,
    )

    state, pods, params = _problem(seed=seed)
    config = SolverConfig()
    resv = _resv_setup(state, pods, seed=seed + 8)
    onehot = resv_node_onehot(resv.node, int(state.alloc.shape[0]))
    want = pallas_solve_batch(state, pods, params, config, resv=resv,
                              interpret=True)
    got = pallas_solve_batch(state, pods, params, config, resv=resv,
                             interpret=True, resv_onehot=onehot)
    _assert_resv_identical(got, want)
    assert int((np.asarray(want.resv_vstar) >= 0).sum()) > 0


@pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="hardware MXU precision semantics only exist on TPU",
)
def test_resv_credit_precision_on_hardware():
    """TPU-gated (ADVICE r5 high): the reservation credit matmul runs
    on the REAL MXU (interpret=False) and must still reproduce the scan
    bit-for-bit. Without precision=HIGHEST the default f32 dot rounds
    operands toward bfloat16 and the hi/lo integer partials corrupt —
    interpret-mode CI (exact f32) can never catch that, so this is the
    only test standing between the kernel and silent hardware
    divergence."""
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    state, pods, params = _problem(n_nodes=256, n_pods=192, seed=5)
    config = SolverConfig()
    # free remainders chosen to need all 16 low bits AND the high half:
    # any mantissa rounding in the dot shifts the reconstructed credit
    resv = _resv_setup(state, pods, n_resv=31, seed=13,
                       match_frac=0.5)
    want = solve_batch(state, pods, params, config, resv=resv)
    got = pallas_solve_batch(state, pods, params, config, resv=resv,
                             interpret=False)
    _assert_resv_identical(got, want)
    assert int((np.asarray(want.resv_vstar) >= 0).sum()) > 0
