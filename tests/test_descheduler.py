"""Descheduler tests: classification op, LowNodeLoad, anomaly debounce,
migration controller + arbitrator (mirrors reference
low_node_load_test.go / controller_test.go / arbitrator_test.go)."""

import numpy as np
import jax.numpy as jnp
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    MigrationPhase,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.descheduler import (
    Arbitrator,
    BasicDetector,
    Descheduler,
    DirectEvictor,
    EvictionLimiter,
    LowNodeLoad,
    LowNodeLoadArgs,
    MigrationController,
    MigrationEvictor,
    NodePool,
    Profile,
)
from koordinator_tpu.descheduler.anomaly import State
from koordinator_tpu.ops.rebalance import classify_nodes, threshold_quantities

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


def pvec(d):
    v = np.full(NUM_RESOURCES, -1, dtype=np.int64)
    for k, val in d.items():
        v[int(k)] = val
    return v


def classify(usage, alloc, low_d, high_d, active, sched,
             use_deviation=False):
    """threshold_quantities + classify_nodes, the way the plugin runs."""
    low_q, high_q, mask = threshold_quantities(
        usage, alloc, pvec(low_d), pvec(high_d), np.asarray(active),
        use_deviation=use_deviation,
    )
    return classify_nodes(
        jnp.asarray(usage), jnp.asarray(low_q), jnp.asarray(high_q),
        jnp.asarray(mask), jnp.asarray(active), jnp.asarray(sched),
    )


class TestClassifyOp:
    def test_basic_classification(self):
        alloc = np.tile(np.array([[0] * NUM_RESOURCES]), (3, 1))
        alloc[:, CPU] = 10000
        usage = np.zeros_like(alloc)
        usage[0, CPU] = 1000   # 10% → low
        usage[1, CPU] = 5000   # 50% → neither
        usage[2, CPU] = 9000   # 90% → high
        v = classify(usage, alloc, {CPU: 30}, {CPU: 70},
                     np.ones(3, bool), np.ones(3, bool))
        assert list(np.asarray(v.low)) == [True, False, False]
        assert list(np.asarray(v.high)) == [False, False, True]

    def test_under_requires_all_over_requires_any(self):
        alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
        alloc[0, CPU] = 10000
        alloc[0, MEM] = 1000
        usage = np.zeros_like(alloc)
        usage[0, CPU] = 1000   # under cpu low
        usage[0, MEM] = 900    # over mem high
        v = classify(usage, alloc, {CPU: 30, MEM: 30}, {CPU: 70, MEM: 70},
                     np.ones(1, bool), np.ones(1, bool))
        assert not bool(np.asarray(v.low)[0])
        assert bool(np.asarray(v.high)[0])

    def test_deviation_mode(self):
        alloc = np.zeros((2, NUM_RESOURCES), dtype=np.int64)
        alloc[:, CPU] = 10000
        usage = np.zeros_like(alloc)
        usage[0, CPU] = 2000  # 20%
        usage[1, CPU] = 8000  # 80%  avg=50
        v = classify(usage, alloc, {CPU: 10}, {CPU: 10},
                     np.ones(2, bool), np.ones(2, bool), use_deviation=True)
        # thresholds become low=40%, high=60%
        assert list(np.asarray(v.low)) == [True, False]
        assert list(np.asarray(v.high)) == [False, True]

    def test_stale_node_inactive(self):
        alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
        alloc[0, CPU] = 10000
        usage = np.zeros_like(alloc)
        usage[0, CPU] = 9900
        v = classify(usage, alloc, {CPU: 30}, {CPU: 70},
                     np.zeros(1, bool), np.ones(1, bool))
        assert not bool(np.asarray(v.high)[0])

    def test_float_threshold_rounding_matches_reference(self):
        """resourceThreshold is int64(float64(pct)*0.01*float64(cap)) —
        0.29*100 truncates to 28 in float64, NOT the integer 29."""
        alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
        alloc[0, CPU] = 100
        usage = np.zeros_like(alloc)
        low_q, high_q, _ = threshold_quantities(
            usage, alloc, pvec({CPU: 29}), pvec({CPU: 29}),
            np.ones(1, bool),
        )
        assert int(low_q[0, CPU]) == int(0.29 * 100.0)  # 28, not 29
        assert int(low_q[0, CPU]) == 28

    def test_memory_always_participates(self):
        """newThresholds appends memory to resourceNames always: with
        only a cpu threshold set, memory usage above capacity still
        flags the node overutilized (fill = 100%)."""
        alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
        alloc[0, CPU] = 10000
        alloc[0, MEM] = 1000
        usage = np.zeros_like(alloc)
        usage[0, MEM] = 1500   # above 100% of capacity
        v = classify(usage, alloc, {CPU: 30}, {CPU: 70},
                     np.ones(1, bool), np.ones(1, bool))
        assert bool(np.asarray(v.high)[0])
        # but a non-thresholded, non-memory resource never triggers
        usage2 = np.zeros_like(alloc)
        usage2[0, ResourceName.GPU] = 99999
        v2 = classify(usage2, alloc, {CPU: 30}, {CPU: 70},
                      np.ones(1, bool), np.ones(1, bool))
        assert not bool(np.asarray(v2.high)[0])


class TestAnomalyDetector:
    def test_debounce(self):
        det = BasicDetector("n", consecutive_abnormalities=2)
        assert det.mark(False) == State.OK
        assert det.mark(False) == State.OK
        assert det.mark(False) == State.ANOMALY

    def test_normal_resets_streak(self):
        det = BasicDetector("n", consecutive_abnormalities=2)
        det.mark(False)
        det.mark(False)
        det.mark(True)
        assert det.mark(False) == State.OK


def make_cluster(n_nodes=4, overloaded=(0,), underloaded=(2, 3)):
    nodes, pods, metrics = [], [], {}
    for i in range(n_nodes):
        name = f"node-{i}"
        nodes.append(NodeSpec(name=name, allocatable={CPU: 10000, MEM: 10000}))
        if i in overloaded:
            usage = {CPU: 9000, MEM: 5000}
            for j in range(3):
                pods.append(PodSpec(
                    name=f"app-{i}-{j}", node_name=name,
                    requests={CPU: 2000, MEM: 1000},
                ))
            metrics[name] = NodeMetric(
                node_name=name, node_usage=usage, update_time=100.0,
                pod_usages={
                    f"default/app-{i}-{j}": {CPU: 2500, MEM: 1200}
                    for j in range(3)
                },
            )
        else:
            usage = {CPU: 5000 if i in underloaded else 6000, MEM: 2000}
            if i in underloaded:
                usage = {CPU: 2000, MEM: 1000}
            metrics[name] = NodeMetric(
                node_name=name, node_usage=usage, update_time=100.0
            )
    return ClusterSnapshot(nodes=nodes, pods=pods, node_metrics=metrics,
                          now=120.0)


class TestLowNodeLoad:
    def test_evicts_from_overloaded(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
        )]))
        evictor = DirectEvictor()
        desch = Descheduler([Profile("p", balance_plugins=[plugin])], evictor)
        evicted = desch.run_once(snapshot)
        assert evicted  # pods moved off node-0
        assert all(p.node_name is None for p in evicted)

    def test_stops_when_under_threshold(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
        )]))
        evictor = DirectEvictor()
        plugin.balance(snapshot, evictor)
        # 9000 usage, threshold 7000: one pod (2500) → 6500 under
        assert len(evictor.evicted) == 1

    def test_no_low_nodes_no_eviction(self):
        snapshot = make_cluster(underloaded=())
        # make every other node mid-loaded (not under 30%)
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
        )]))
        evictor = DirectEvictor()
        plugin.balance(snapshot, evictor)
        assert evictor.evicted == []

    def test_anomaly_debounce_delays_eviction(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
            consecutive_abnormalities=2,
        )]))
        evictor = DirectEvictor()
        plugin.balance(snapshot, evictor)
        assert evictor.evicted == []  # first observation: debounced
        plugin.balance(snapshot, evictor)
        assert evictor.evicted == []  # streak=2, needs > 2
        plugin.balance(snapshot, evictor)
        assert evictor.evicted       # third consecutive → anomaly

    def test_max_per_node_enforced(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 20, MEM: 90},  # wants to evict a lot
        )]))
        evictor = DirectEvictor(EvictionLimiter(max_per_node=1))
        plugin.balance(snapshot, evictor)
        assert len(evictor.evicted) <= 1

    def test_high_only_threshold_detects_overload(self):
        alloc = np.zeros((2, NUM_RESOURCES), dtype=np.int64)
        alloc[:, CPU] = 10000
        usage = np.zeros_like(alloc)
        usage[0, CPU] = 9500
        v = classify(usage, alloc, {MEM: 60}, {CPU: 70},
                     np.ones(2, bool), np.ones(2, bool))
        assert bool(np.asarray(v.high)[0])

    def test_flapping_node_not_anomalous(self):
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
            consecutive_abnormalities=2,
        )]))
        evictor = DirectEvictor()
        for spike in (True, False, True, False, True):
            snapshot = make_cluster()
            if not spike:
                snapshot.node_metrics["node-0"].node_usage = {
                    CPU: 5000, MEM: 5000
                }
            plugin.balance(snapshot, evictor)
        # spikes were never consecutive → debounce holds
        assert evictor.evicted == []

    def test_stale_metric_skips_node(self):
        snapshot = make_cluster()
        snapshot.node_metrics["node-0"].update_time = -1000.0
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
        )]))
        evictor = DirectEvictor()
        plugin.balance(snapshot, evictor)
        assert evictor.evicted == []

    def test_eviction_limit_respected(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            # very low high threshold → wants to evict everything
            high_thresholds={CPU: 10, MEM: 90},
        )]))
        evictor = DirectEvictor(EvictionLimiter(max_per_cycle=1))
        plugin.balance(snapshot, evictor)
        assert len(evictor.evicted) <= 1


class TestMigration:
    def place(self, snapshot, reservation):
        # trivially place on the emptiest node
        return "node-3"

    def test_reservation_first_migration(self):
        snapshot = make_cluster()
        plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
            low_thresholds={CPU: 30, MEM: 30},
            high_thresholds={CPU: 70, MEM: 70},
        )]))
        evictor = MigrationEvictor()
        plugin.balance(snapshot, evictor)
        assert evictor.jobs
        controller = MigrationController(self.place)
        controller.reconcile(snapshot, evictor.jobs)
        done = [j for j in evictor.jobs if j.phase == MigrationPhase.SUCCEEDED]
        assert done
        assert snapshot.reservations  # capacity reserved before eviction
        assert snapshot.reservations[0].node_name == "node-3"
        # evicted pod requeued as pending
        assert any(p.uid == done[0].pod_uid for p in snapshot.pending_pods)

    def test_unplaceable_reservation_stays_pending(self):
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        pod = snapshot.pods[0]
        evictor.evict(snapshot, pod, reason="test")
        controller = MigrationController(lambda s, r: None)
        controller.reconcile(snapshot, evictor.jobs)
        assert evictor.jobs[0].phase == MigrationPhase.PENDING
        assert pod.node_name is not None  # NOT evicted without capacity

    def test_job_ttl_fails(self):
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        evictor.evict(snapshot, snapshot.pods[0], reason="test")
        evictor.jobs[0].create_time = snapshot.now - 1000
        controller = MigrationController(self.place)
        controller.reconcile(snapshot, evictor.jobs)
        assert evictor.jobs[0].phase == MigrationPhase.FAILED

    def test_duplicate_job_suppressed(self):
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        assert evictor.evict(snapshot, snapshot.pods[0], reason="a")
        assert not evictor.evict(snapshot, snapshot.pods[0], reason="b")

    def test_arbitrator_workload_limit(self):
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        for pod in snapshot.pods[:3]:  # same workload app-0-*
            pod.labels["workload"] = "app"
            evictor.evict(snapshot, pod, reason="t")
        arb = Arbitrator(max_migrating_per_workload=1)
        admitted = arb.arbitrate(evictor.jobs, snapshot, [])
        assert len(admitted) == 1

    def test_migrated_pod_can_consume_reservation(self):
        from koordinator_tpu.scheduler.plugins.reservation import (
            reservation_matches_pod,
        )
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        pod = snapshot.pods[0]
        evictor.evict(snapshot, pod, reason="t")
        controller = MigrationController(self.place)
        controller.reconcile(snapshot, evictor.jobs)
        resv = snapshot.reservations[0]
        assert reservation_matches_pod(resv, pod)
        other = PodSpec(name="other")
        assert not reservation_matches_pod(resv, other)
        assert resv.expiration_time is not None

    def test_arbitrator_sorts_by_creation_time(self):
        snapshot = make_cluster()
        evictor = MigrationEvictor()
        for pod in snapshot.pods[:2]:
            evictor.evict(snapshot, pod, reason="t")
        evictor.jobs[0].create_time = 50.0
        evictor.jobs[1].create_time = 10.0
        admitted = Arbitrator().arbitrate(evictor.jobs, snapshot, [])
        assert admitted[0] is evictor.jobs[1]


class TestK8sCompatPlugins:
    """Reference: pkg/descheduler/framework/plugins/kubernetes/ adaptors."""

    def _snapshot(self):
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec

        return ClusterSnapshot(
            nodes=[
                NodeSpec(name="n0", allocatable={R.CPU: 16000},
                         labels={"zone": "a"}),
                NodeSpec(name="n1", allocatable={R.CPU: 16000},
                         labels={"zone": "b"}),
            ],
            pods=[
                PodSpec(name="aff-ok", node_name="n0",
                        node_selector={"zone": "a"}),
                PodSpec(name="aff-bad", node_name="n1",
                        node_selector={"zone": "a"}),
                PodSpec(name="restarty", node_name="n0", restart_count=150),
                PodSpec(name="dup-1", node_name="n0",
                        owner="ReplicaSet/default/web"),
                PodSpec(name="dup-2", node_name="n0",
                        owner="ReplicaSet/default/web"),
                PodSpec(name="dup-3", node_name="n1",
                        owner="ReplicaSet/default/web"),
            ],
        )

    def test_node_affinity_violation_evicted(self):
        from koordinator_tpu.descheduler.framework import DirectEvictor
        from koordinator_tpu.descheduler.kubernetes import (
            RemovePodsViolatingNodeAffinity,
        )

        snap = self._snapshot()
        evictor = DirectEvictor()
        RemovePodsViolatingNodeAffinity().deschedule(snap, evictor)
        assert [p.name for p in evictor.evicted] == ["aff-bad"]

    def test_too_many_restarts(self):
        from koordinator_tpu.descheduler.framework import DirectEvictor
        from koordinator_tpu.descheduler.kubernetes import (
            RemovePodsHavingTooManyRestarts,
        )

        snap = self._snapshot()
        evictor = DirectEvictor()
        RemovePodsHavingTooManyRestarts(pod_restart_threshold=100).deschedule(
            snap, evictor
        )
        assert [p.name for p in evictor.evicted] == ["restarty"]

    def test_remove_duplicates_keeps_one_per_node(self):
        from koordinator_tpu.descheduler.framework import DirectEvictor
        from koordinator_tpu.descheduler.kubernetes import RemoveDuplicates

        snap = self._snapshot()
        evictor = DirectEvictor()
        RemoveDuplicates().deschedule(snap, evictor)
        # dup-1/dup-2 share (owner, n0): one evicted; dup-3 alone on n1 stays
        assert [p.name for p in evictor.evicted] == ["dup-2"]


def test_workload_of_prefers_owner_reference():
    from koordinator_tpu.apis.types import PodSpec
    from koordinator_tpu.descheduler.migration import _workload_of

    pod = PodSpec(name="web-abc12", owner="ReplicaSet/default/web")
    assert _workload_of(pod) == "ReplicaSet/default/web"
    # fallback heuristics unchanged for owner-less pods
    assert _workload_of(PodSpec(name="solo")) == "default/solo"
