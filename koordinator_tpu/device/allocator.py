"""Device request normalization + the Autopilot allocator.

Semantics oracle: pkg/scheduler/plugins/deviceshare/
{utils.go (resource combination validation/normalization),
devicehandler_gpu.go, devicehandler_default.go,
device_allocator.go (AutopilotAllocator :61, jointAllocate :286,
defaultAllocateDevices :392, allocateVF :464),
numa_topology.go (deviceTopologyGuide), scoring.go}.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.apis.types import selector_matches as _matches
from koordinator_tpu.device.cache import (
    DeviceResourceName,
    DeviceResources,
    DeviceType,
    NodeDevice,
    VirtualFunction,
    fits,
    is_zero,
)

MAX_NODE_SCORE = 100


class DeviceUnschedulable(Exception):
    """Allocation impossible on this node (maps to Unschedulable status)."""


# ---------------------------------------------------------------------------
# request normalization (reference: utils.go DeviceResourceFlags /
# ValidDeviceResourceCombinations / ResourceCombinationsMapper)
# ---------------------------------------------------------------------------

_GPU_NAMES = (
    DeviceResourceName.NVIDIA_GPU,
    DeviceResourceName.KOORD_GPU,
    DeviceResourceName.GPU_CORE,
    DeviceResourceName.GPU_MEMORY,
    DeviceResourceName.GPU_MEMORY_RATIO,
)

_PERCENTAGE_NAMES = {
    DeviceResourceName.KOORD_GPU,
    DeviceResourceName.GPU_CORE,
    DeviceResourceName.GPU_MEMORY_RATIO,
    DeviceResourceName.RDMA,
    DeviceResourceName.FPGA,
}


def _validate_percentage(v: int) -> bool:
    """>100 must be a whole-device multiple (reference: utils.go
    ValidatePercentageResource)."""
    return not (v > 100 and v % 100 != 0)


def normalize_device_requests(
    requests: Dict[DeviceResourceName, int],
) -> Dict[DeviceType, DeviceResources]:
    """Validate the resource-name combination and normalize to per-type
    requests in canonical names (GPU → gpu-core/gpu-memory[-ratio]).

    Reference: utils.go ValidateDeviceRequest + ConvertDeviceRequest:
    nvidia.com/gpu N → core=ratio=N*100; koordinator/gpu P → core=ratio=P;
    gpu-core+gpu-memory[-ratio] kept as-is; bare gpu-memory[-ratio] kept.
    """
    for name, v in requests.items():
        if name in _PERCENTAGE_NAMES and not _validate_percentage(v):
            raise DeviceUnschedulable(f"invalid percentage request {name}={v}")

    gpu_names = frozenset(n for n in _GPU_NAMES if requests.get(n, 0) > 0)
    out: Dict[DeviceType, DeviceResources] = {}
    if gpu_names:
        valid = {
            frozenset({DeviceResourceName.NVIDIA_GPU}),
            frozenset({DeviceResourceName.KOORD_GPU}),
            frozenset({DeviceResourceName.GPU_MEMORY}),
            frozenset({DeviceResourceName.GPU_MEMORY_RATIO}),
            frozenset({DeviceResourceName.GPU_CORE, DeviceResourceName.GPU_MEMORY}),
            frozenset(
                {DeviceResourceName.GPU_CORE, DeviceResourceName.GPU_MEMORY_RATIO}
            ),
        }
        if gpu_names not in valid:
            raise DeviceUnschedulable(
                f"invalid GPU resource combination {sorted(n.value for n in gpu_names)}"
            )
        if DeviceResourceName.NVIDIA_GPU in gpu_names:
            n = requests[DeviceResourceName.NVIDIA_GPU]
            out[DeviceType.GPU] = {
                DeviceResourceName.GPU_CORE: n * 100,
                DeviceResourceName.GPU_MEMORY_RATIO: n * 100,
            }
        elif DeviceResourceName.KOORD_GPU in gpu_names:
            p = requests[DeviceResourceName.KOORD_GPU]
            out[DeviceType.GPU] = {
                DeviceResourceName.GPU_CORE: p,
                DeviceResourceName.GPU_MEMORY_RATIO: p,
            }
        else:
            out[DeviceType.GPU] = {
                n: requests[n] for n in gpu_names
            }
    if requests.get(DeviceResourceName.RDMA, 0) > 0:
        out[DeviceType.RDMA] = {
            DeviceResourceName.RDMA: requests[DeviceResourceName.RDMA]
        }
    if requests.get(DeviceResourceName.FPGA, 0) > 0:
        out[DeviceType.FPGA] = {
            DeviceResourceName.FPGA: requests[DeviceResourceName.FPGA]
        }
    return out


# ---------------------------------------------------------------------------
# hints / joint-allocate specs (reference: apis/extension/device_share.go
# DeviceAllocateHints / DeviceJointAllocate)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceHint:
    selector: Optional[Dict[str, str]] = None      # device label equality
    vf_selector: Optional[Dict[str, str]] = None   # require a VF; match labels
    allocate_strategy: str = ""  # "ApplyForAll" | "RequestsAsCount" | ""
    exclusive_policy: str = ""   # "DeviceLevel" | "PCIeLevel" | ""

    @property
    def must_allocate_vf(self) -> bool:
        return self.vf_selector is not None


@dataclasses.dataclass
class JointAllocate:
    device_types: List[DeviceType] = dataclasses.field(default_factory=list)
    required_scope: str = ""  # "SamePCIe" or ""


@dataclasses.dataclass
class DeviceAllocation:
    minor: int
    resources: DeviceResources
    vf_bus_ids: List[str] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# handlers (reference: devicehandler_gpu.go / devicehandler_default.go)
# ---------------------------------------------------------------------------


def _calc_gpu(
    node_device: NodeDevice, requests: DeviceResources, hint: Optional[DeviceHint]
) -> Tuple[DeviceResources, int]:
    total = node_device.device_total.get(DeviceType.GPU, {})
    if not total:
        raise DeviceUnschedulable("Insufficient gpu devices")
    healthy = next((r for r in total.values() if r and not is_zero(r)), None)
    if healthy is None:
        raise DeviceUnschedulable("no healthy GPU Devices")
    requests = dict(requests)
    # fill the missing one of memory/ratio from per-device total memory
    # (reference: devicehandler_gpu.go fillGPUTotalMem)
    total_mem = healthy.get(DeviceResourceName.GPU_MEMORY, 0)
    if DeviceResourceName.GPU_MEMORY in requests:
        if total_mem:
            requests[DeviceResourceName.GPU_MEMORY_RATIO] = (
                requests[DeviceResourceName.GPU_MEMORY] * 100 // total_mem
            )
    else:
        requests[DeviceResourceName.GPU_MEMORY] = (
            requests.get(DeviceResourceName.GPU_MEMORY_RATIO, 0) * total_mem // 100
        )

    ratio = requests.get(DeviceResourceName.GPU_MEMORY_RATIO, 0)
    if ratio > 100 and ratio % 100 == 0:
        count = ratio // 100
        requests = {
            DeviceResourceName.GPU_CORE: requests.get(DeviceResourceName.GPU_CORE, 0)
            // count,
            DeviceResourceName.GPU_MEMORY: requests[DeviceResourceName.GPU_MEMORY]
            // count,
            DeviceResourceName.GPU_MEMORY_RATIO: ratio // count,
        }
        return requests, count
    return requests, 1


def _calc_default(
    device_type: DeviceType,
    resource_name: DeviceResourceName,
    node_device: NodeDevice,
    requests: DeviceResources,
    hint: Optional[DeviceHint],
) -> Tuple[DeviceResources, int]:
    total = node_device.device_total.get(device_type, {})
    if not total:
        raise DeviceUnschedulable(f"Insufficient {device_type.value} devices")
    quantity = requests.get(resource_name, 0)
    if quantity > 100 and quantity % 100 == 0:
        count = quantity // 100
        return {resource_name: quantity // count}, count
    if hint is not None:
        if hint.allocate_strategy == "ApplyForAll":
            count = sum(
                1
                for e in node_device.device_infos.get(device_type, [])
                if _matches(hint.selector, e.labels)
                and not is_zero(node_device.device_total[device_type].get(e.minor, {}))
            )
            if count == 0:
                raise DeviceUnschedulable(
                    f"Insufficient {device_type.value} devices"
                )
            return dict(requests), count
        if hint.allocate_strategy == "RequestsAsCount":
            per_device = 100 if hint.exclusive_policy == "DeviceLevel" else 1
            return {resource_name: per_device}, quantity
    return dict(requests), 1


def calc_requests_and_count(
    node_device: NodeDevice,
    pod_requests: Dict[DeviceType, DeviceResources],
    hints: Dict[DeviceType, DeviceHint],
) -> Tuple[Dict[DeviceType, DeviceResources], Dict[DeviceType, int]]:
    """Per-instance request + desired instance count per device type
    (reference: device_allocator.go:160 calcRequestsAndCountByDeviceType)."""
    requests_per_instance: Dict[DeviceType, DeviceResources] = {}
    desired_count: Dict[DeviceType, int] = {}
    for device_type, requests in pod_requests.items():
        if is_zero(requests):
            continue
        hint = hints.get(device_type)
        if device_type == DeviceType.GPU:
            req, count = _calc_gpu(node_device, requests, hint)
        elif device_type == DeviceType.RDMA:
            req, count = _calc_default(
                device_type, DeviceResourceName.RDMA, node_device, requests, hint
            )
        else:
            req, count = _calc_default(
                device_type, DeviceResourceName.FPGA, node_device, requests, hint
            )
        requests_per_instance[device_type] = req
        desired_count[device_type] = count
    return requests_per_instance, desired_count


# ---------------------------------------------------------------------------
# scoring (reference: scoring.go + device_resources.go scoreDevices)
# ---------------------------------------------------------------------------


def _score_device(
    requests: DeviceResources,
    total: DeviceResources,
    free: DeviceResources,
    scorer: str,
) -> int:
    score_sum, weight_sum = 0, 0
    for r in requests:
        cap = total.get(r, 0)
        used = cap - free.get(r, 0) + requests[r]
        if cap == 0 or used > cap:
            s = 0
        elif scorer == "MostAllocated":
            s = used * MAX_NODE_SCORE // cap
        else:
            s = (cap - used) * MAX_NODE_SCORE // cap
        score_sum += s
        weight_sum += 1
    return score_sum // weight_sum if weight_sum else 0


# ---------------------------------------------------------------------------
# the allocator
# ---------------------------------------------------------------------------


class AutopilotAllocator:
    """Hint/topology-aware multi-device allocator (reference:
    device_allocator.go AutopilotAllocator)."""

    def __init__(
        self,
        node_device: NodeDevice,
        pod_requests: Dict[DeviceType, DeviceResources],
        hints: Optional[Dict[DeviceType, DeviceHint]] = None,
        joint_allocate: Optional[JointAllocate] = None,
        numa_affinity: Optional[int] = None,  # bitmask over NUMA nodes
        scorer: str = "LeastAllocated",
        required_minors: Optional[Dict[DeviceType, Set[int]]] = None,
        preferred_minors: Optional[Dict[DeviceType, Set[int]]] = None,
    ):
        self.node_device = node_device
        self.hints = hints or {}
        self.joint_allocate = joint_allocate
        self.numa_affinity = numa_affinity
        self.scorer = scorer
        self.required = required_minors or {}
        self.preferred = preferred_minors or {}
        self.requests_per_instance, self.desired_count = calc_requests_and_count(
            node_device, pod_requests, self.hints
        )
        for device_type in self.requests_per_instance:
            hint = self.hints.get(device_type)
            if hint is not None and hint.must_allocate_vf:
                if not any(
                    e.vfs for e in node_device.device_infos.get(device_type, [])
                ):
                    raise DeviceUnschedulable(
                        f"Insufficient {device_type.value} VirtualFunctions"
                    )

    # -- candidate minors after NUMA affinity + selector filtering
    # (reference: device_allocator.go:134 filterNodeDevice) ----------------
    def _candidate_minors(self, device_type: DeviceType) -> List[int]:
        hint = self.hints.get(device_type)
        minors = []
        for e in self.node_device.device_infos.get(device_type, []):
            if self.numa_affinity is not None and not (
                self.numa_affinity >> e.numa_node
            ) & 1:
                continue
            if hint is not None and not _matches(hint.selector, e.labels):
                continue
            minors.append(e.minor)
        return minors

    def allocate(self) -> Dict[DeviceType, List[DeviceAllocation]]:
        """Full allocation: joint allocate first, then remaining types
        (reference: device_allocator.go:94 Allocate)."""
        allocations: Dict[DeviceType, List[DeviceAllocation]] = {}
        if self.joint_allocate and self.joint_allocate.device_types:
            allocations = self._try_joint_allocate()
        for device_type in self.requests_per_instance:
            if device_type in allocations:
                continue
            allocs = self._allocate_device_type(
                device_type,
                self.desired_count.get(device_type, 1),
                preferred_pcies=None,
                minors=self._candidate_minors(device_type),
            )
            if allocs:
                allocations[device_type] = allocs
        if not any(allocations.values()):
            raise DeviceUnschedulable(
                "Insufficient "
                + ", ".join(t.value for t in self.requests_per_instance)
                + " devices"
            )
        return allocations

    def score(self) -> int:
        """Node-level device score (reference: device_allocator.go:507)."""
        final = 0
        for device_type, requests in self.requests_per_instance.items():
            total = self.node_device.device_total.get(device_type, {})
            free = self.node_device.free(device_type)
            if not total:
                continue
            agg_total: DeviceResources = {}
            agg_free: DeviceResources = {}
            for minor in total:
                for k, v in total[minor].items():
                    agg_total[k] = agg_total.get(k, 0) + v
                for k, v in free.get(minor, {}).items():
                    agg_free[k] = agg_free.get(k, 0) + v
            final += _score_device(requests, agg_total, agg_free, self.scorer)
        return final

    # -- joint allocation (reference: :188 tryJointAllocate,
    # :210 allocateByTopology) ---------------------------------------------
    def _try_joint_allocate(self) -> Dict[DeviceType, List[DeviceAllocation]]:
        joint = self.joint_allocate
        primary = joint.device_types[0]
        secondary = joint.device_types[1:]
        desired = self.desired_count.get(primary, 0)
        if desired == 0:
            return {}

        # 1) one PCIe switch with enough free primary devices
        for pcie, minors in self._free_by_pcie(primary):
            if len(minors) >= desired:
                try:
                    allocs = self._joint_allocate_group(
                        primary, secondary, {pcie}, minors=None
                    )
                except DeviceUnschedulable:
                    continue
                if allocs:
                    return allocs
        # 2) one NUMA node, preferring its PCIes
        for node, pcies, minors in self._free_by_numa_node(primary):
            if len(minors) >= desired:
                try:
                    allocs = self._joint_allocate_group(
                        primary, secondary, pcies, minors=None
                    )
                except DeviceUnschedulable:
                    continue
                if allocs:
                    return allocs
        # same-PCIe scope must be satisfied by the grouped attempts above
        if joint.required_scope == "SamePCIe":
            raise DeviceUnschedulable("node(s) Joint-Allocate rules not met")
        # 3) whole machine, preferring any NUMA-grouped PCIes
        all_pcies: Set[str] = set()
        for _, pcies, _ in self._free_by_numa_node(primary):
            all_pcies |= pcies
        allocs = self._joint_allocate_group(primary, secondary, all_pcies, minors=None)
        if allocs:
            return allocs
        raise DeviceUnschedulable("node(s) Joint-Allocate rules not met")

    def _joint_allocate_group(
        self,
        primary: DeviceType,
        secondary: Sequence[DeviceType],
        preferred_pcies: Set[str],
        minors: Optional[List[int]],
    ) -> Dict[DeviceType, List[DeviceAllocation]]:
        """(reference: :286 jointAllocate — primary first, secondaries ride
        the primary's PCIes)."""
        primary_allocs = self._allocate_device_type(
            primary,
            self.desired_count.get(primary, 1),
            preferred_pcies=preferred_pcies,
            minors=self._candidate_minors(primary),
        )
        if not primary_allocs:
            return {}
        result = {primary: primary_allocs}
        primary_pcies = {
            self.node_device.entry(primary, a.minor).pcie_id
            for a in primary_allocs
        }
        for device_type in secondary:
            # only types the pod actually requested ride along
            if device_type not in self.requests_per_instance:
                continue
            if (
                self.joint_allocate is not None
                and self.joint_allocate.required_scope == "SamePCIe"
            ):
                # one secondary device per primary PCIe, pinned to it so the
                # distribution cannot clump on one switch
                allocs = []
                for pcie in sorted(primary_pcies):
                    on_pcie = [
                        m
                        for m in self._candidate_minors(device_type)
                        if self.node_device.entry(device_type, m).pcie_id == pcie
                    ]
                    allocs.extend(
                        self._allocate_device_type(
                            device_type, 1, preferred_pcies={pcie},
                            minors=on_pcie, exclude=[a.minor for a in allocs],
                        )
                    )
            else:
                allocs = self._allocate_device_type(
                    device_type,
                    1,
                    preferred_pcies=primary_pcies,
                    minors=self._candidate_minors(device_type),
                )
            if allocs:
                result[device_type] = allocs
        if self.joint_allocate.required_scope == "SamePCIe":
            self._validate_same_pcie(result, primary, secondary)
        return result

    def _validate_same_pcie(self, result, primary, secondary) -> None:
        """(reference: :255 validateJointAllocation)."""
        def pcies(device_type):
            return {
                self.node_device.entry(device_type, a.minor).pcie_id
                for a in result.get(device_type, [])
            }

        primary_pcies = pcies(primary)
        for device_type in secondary:
            if pcies(device_type) != primary_pcies:
                raise DeviceUnschedulable(
                    "node(s) Device Joint-Allocate rules violation"
                )

    def _free_by_pcie(self, device_type: DeviceType) -> List[Tuple[str, List[int]]]:
        """PCIe id → minors with any free capacity, sorted for determinism
        (reference: numa_topology.go deviceTopologyGuide
        freeNodeDevicesInPCIe)."""
        free = self.node_device.free(device_type)
        candidates = set(self._candidate_minors(device_type))
        groups: Dict[Tuple[int, str], List[int]] = {}
        for e in self.node_device.device_infos.get(device_type, []):
            if e.minor in candidates and not is_zero(free.get(e.minor, {})) and fits(
                self.requests_per_instance.get(device_type, {}), free.get(e.minor, {})
            ):
                groups.setdefault((e.numa_node, e.pcie_id), []).append(e.minor)
        return [
            (pcie, sorted(minors))
            for (_, pcie), minors in sorted(groups.items())
        ]

    def _free_by_numa_node(
        self, device_type: DeviceType
    ) -> List[Tuple[int, Set[str], List[int]]]:
        """NUMA node → (pcies, free minors) (reference: numa_topology.go
        freeNodeDevicesInNode)."""
        free = self.node_device.free(device_type)
        candidates = set(self._candidate_minors(device_type))
        groups: Dict[int, Tuple[Set[str], List[int]]] = {}
        for e in self.node_device.device_infos.get(device_type, []):
            if e.minor in candidates and not is_zero(free.get(e.minor, {})) and fits(
                self.requests_per_instance.get(device_type, {}), free.get(e.minor, {})
            ):
                pcies, minors = groups.setdefault(e.numa_node, (set(), []))
                pcies.add(e.pcie_id)
                minors.append(e.minor)
        return [
            (node, pcies, sorted(minors))
            for node, (pcies, minors) in sorted(groups.items())
        ]

    # -- per-type allocation (reference: :392 defaultAllocateDevices) ------
    def _allocate_device_type(
        self,
        device_type: DeviceType,
        desired_count: int,
        preferred_pcies: Optional[Set[str]],
        minors: List[int],
        exclude: Sequence[int] = (),
    ) -> List[DeviceAllocation]:
        requests = self.requests_per_instance.get(device_type, {})
        # preferred PCIes only steer the ordering; the pod gets exactly the
        # count it asked for (the reference inflates maxDesiredCount by
        # len(preferredPCIEs), device_allocator.go:361-370, which can grant
        # devices beyond the request — treated as unintended here)
        desired_count = max(desired_count, 1)
        max_desired = desired_count
        minors = [m for m in minors if m not in set(exclude)]
        free = self.node_device.free(device_type)
        total = self.node_device.device_total.get(device_type, {})
        hint = self.hints.get(device_type)
        required = self.required.get(device_type, set())
        preferred_minors = self.preferred.get(device_type, set())

        # score each candidate minor, best first; stable-prefer preferred
        # PCIes then preferred (reservation) minors (reference: :415-417)
        def sort_key(minor):
            e = self.node_device.entry(device_type, minor)
            in_pcie = (
                0 if preferred_pcies and e and e.pcie_id in preferred_pcies else 1
            )
            in_preferred = 0 if minor in preferred_minors else 1
            score = _score_device(
                requests, total.get(minor, {}), free.get(minor, {}), self.scorer
            )
            return (in_pcie, in_preferred, -score, minor)

        allocations: List[DeviceAllocation] = []
        for minor in sorted(minors, key=sort_key):
            if required and minor not in required:
                continue
            f = free.get(minor, {})
            if is_zero(f) or not fits(requests, f):
                continue
            alloc = DeviceAllocation(minor=minor, resources=dict(requests))
            if hint is not None and hint.must_allocate_vf:
                vf = self._allocate_vf(device_type, minor, hint.vf_selector)
                if vf is None:
                    continue
                alloc.vf_bus_ids = [vf.bus_id]
            allocations.append(alloc)
            if len(allocations) == max_desired:
                break
        if len(allocations) < desired_count:
            raise DeviceUnschedulable(
                f"Insufficient {device_type.value} devices"
            )
        return allocations

    def _allocate_vf(
        self, device_type: DeviceType, minor: int, vf_selector
    ) -> Optional[VirtualFunction]:
        """First free VF by bus id (reference: :464 allocateVF)."""
        entry = self.node_device.entry(device_type, minor)
        if entry is None:
            return None
        allocated = self.node_device.vf_allocations.get(device_type, {}).get(
            minor, set()
        )
        remaining = [
            vf
            for vf in entry.vfs
            if _matches(vf_selector, vf.labels) and vf.bus_id not in allocated
        ]
        if not remaining:
            return None
        return min(remaining, key=lambda vf: vf.bus_id)
