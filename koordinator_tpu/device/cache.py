"""Per-node device inventory + free/used accounting.

Semantics oracle: pkg/scheduler/plugins/deviceshare/device_cache.go
(nodeDevice: deviceTotal/deviceFree/deviceUsed keyed device type → minor →
resources, vfAllocations) and apis/scheduling/v1alpha1/device_types.go
(DeviceInfo topology: socket/node/PCIe). Quantities are ints: percentage
shares (100 == one whole device) and MiB for device memory.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set


class DeviceType(str, enum.Enum):
    GPU = "gpu"
    RDMA = "rdma"
    FPGA = "fpga"


class DeviceResourceName(str, enum.Enum):
    """Device resource dimensions (reference: apis/extension/
    device_share.go resource names)."""

    NVIDIA_GPU = "nvidia.com/gpu"        # whole devices
    KOORD_GPU = "koordinator/gpu"        # percent of one device
    GPU_CORE = "gpu-core"                # percent
    GPU_MEMORY = "gpu-memory"            # MiB
    GPU_MEMORY_RATIO = "gpu-memory-ratio"  # percent
    RDMA = "rdma"                        # percent
    FPGA = "fpga"                        # percent


#: sparse device resource amounts
DeviceResources = Dict[DeviceResourceName, int]


def add_resources(a: DeviceResources, b: DeviceResources) -> DeviceResources:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def sub_resources(a: DeviceResources, b: DeviceResources) -> DeviceResources:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) - v
    return out


def fits(request: DeviceResources, available: DeviceResources) -> bool:
    return all(available.get(k, 0) >= v for k, v in request.items())


def is_zero(res: DeviceResources) -> bool:
    return all(v == 0 for v in res.values())


@dataclasses.dataclass
class VirtualFunction:
    """An SR-IOV virtual function (reference: device_types.go
    VirtualFunction)."""

    bus_id: str
    minor: int = 0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DeviceEntry:
    """One device instance on a node (reference: device_types.go
    DeviceInfo)."""

    minor: int
    device_type: DeviceType = DeviceType.GPU
    resources: DeviceResources = dataclasses.field(default_factory=dict)
    # topology (reference: DeviceTopology socket/node/pcie)
    socket_id: int = 0
    numa_node: int = 0
    pcie_id: str = "0"
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    vfs: List[VirtualFunction] = dataclasses.field(default_factory=list)
    health: bool = True


class NodeDevice:
    """All devices of one node with free/used accounting (reference:
    device_cache.go nodeDevice)."""

    def __init__(self, node_name: str, entries: Sequence[DeviceEntry] = ()):
        self.node_name = node_name
        self.device_infos: Dict[DeviceType, List[DeviceEntry]] = {}
        self.device_total: Dict[DeviceType, Dict[int, DeviceResources]] = {}
        self.device_used: Dict[DeviceType, Dict[int, DeviceResources]] = {}
        # pod uid -> device type -> [(minor, resources, vf bus ids)]
        self.allocations: Dict[str, Dict[DeviceType, List]] = {}
        # device type -> minor -> allocated VF bus ids
        self.vf_allocations: Dict[DeviceType, Dict[int, Set[str]]] = {}
        for e in entries:
            self.add_entry(e)

    def add_entry(self, entry: DeviceEntry) -> None:
        self.device_infos.setdefault(entry.device_type, []).append(entry)
        total = self.device_total.setdefault(entry.device_type, {})
        # unhealthy devices stay in the inventory with zero resources
        # (reference: device_cache.go updateCacheUsed healthy handling)
        total[entry.minor] = dict(entry.resources) if entry.health else {}
        self.device_used.setdefault(entry.device_type, {}).setdefault(
            entry.minor, {}
        )

    def free(self, device_type: DeviceType) -> Dict[int, DeviceResources]:
        out: Dict[int, DeviceResources] = {}
        for minor, total in self.device_total.get(device_type, {}).items():
            used = self.device_used.get(device_type, {}).get(minor, {})
            out[minor] = {k: v - used.get(k, 0) for k, v in total.items()}
        return out

    def entry(self, device_type: DeviceType, minor: int) -> Optional[DeviceEntry]:
        for e in self.device_infos.get(device_type, []):
            if e.minor == minor:
                return e
        return None

    # -- commit / rollback (reference: device_cache.go updateCacheUsed) ----
    def apply(self, pod_uid: str, allocations: Dict[DeviceType, List]) -> None:
        if pod_uid in self.allocations:
            return
        self.allocations[pod_uid] = allocations
        for device_type, allocs in allocations.items():
            used = self.device_used.setdefault(device_type, {})
            vf_alloc = self.vf_allocations.setdefault(device_type, {})
            for alloc in allocs:
                u = used.setdefault(alloc.minor, {})
                for k, v in alloc.resources.items():
                    u[k] = u.get(k, 0) + v
                for bus_id in alloc.vf_bus_ids:
                    vf_alloc.setdefault(alloc.minor, set()).add(bus_id)

    def release(self, pod_uid: str) -> None:
        allocations = self.allocations.pop(pod_uid, None)
        if not allocations:
            return
        for device_type, allocs in allocations.items():
            used = self.device_used.get(device_type, {})
            vf_alloc = self.vf_allocations.get(device_type, {})
            for alloc in allocs:
                u = used.get(alloc.minor, {})
                for k, v in alloc.resources.items():
                    u[k] = u.get(k, 0) - v
                for bus_id in alloc.vf_bus_ids:
                    vf_alloc.get(alloc.minor, set()).discard(bus_id)


class NodeDeviceCache:
    """node name → NodeDevice (reference: device_cache.go
    nodeDeviceCache)."""

    def __init__(self):
        self.nodes: Dict[str, NodeDevice] = {}

    def update_node(self, node_name: str, entries: Sequence[DeviceEntry]) -> None:
        self.nodes[node_name] = NodeDevice(node_name, entries)

    def get(self, node_name: str) -> Optional[NodeDevice]:
        return self.nodes.get(node_name)
