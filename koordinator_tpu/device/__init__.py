"""Device (GPU/RDMA/FPGA) partial + multi-device allocation.

TPU-native rebuild of the reference's DeviceShare plugin
(pkg/scheduler/plugins/deviceshare/): per-node device inventories with
PCIe/NUMA topology, percentage-share device resources, virtual-function
allocation, and PCIe/NUMA joint allocation. Per-node minor counts are tiny
(≤16), so allocation runs host-side; the node fan-out stays in the batched
solver.
"""

from koordinator_tpu.device.cache import (  # noqa: F401
    DeviceResourceName,
    DeviceType,
    NodeDevice,
    NodeDeviceCache,
    VirtualFunction,
)
from koordinator_tpu.device.allocator import (  # noqa: F401
    AutopilotAllocator,
    DeviceAllocation,
    DeviceHint,
    DeviceUnschedulable,
    JointAllocate,
    normalize_device_requests,
)
