"""koord-runtime-proxy: the CRI interposer between kubelet and the
container runtime.

Reference: pkg/runtimeproxy/ (SURVEY.md §2.5, §3.5) —
``server/cri/criserver.go`` intercepts the resource-relevant CRI calls,
runs the koordlet RuntimeHookServer pre/post, merges the hook response
into the runtime request, and transparently forwards everything else;
``store/`` keeps pod/container metadata across calls (rebuilt from the
backend on startup, the failOver path); ``config`` failure policy decides
whether hook errors fail the CRI call.
"""

from koordinator_tpu.runtimeproxy.criserver import (  # noqa: F401
    BackendRuntime,
    CRIRequest,
    CRIResponse,
    RuntimeManagerCriServer,
    RuntimeProxyStore,
)
