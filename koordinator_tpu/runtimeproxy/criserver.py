"""The CRI interposer server.

Reference: pkg/runtimeproxy/server/cri/criserver.go —
``InterceptRuntimeRequest`` (:125-170): for hooked service types, run the
pre-hook, merge the hook's resource response into the request, forward to
the backend runtime, then run the post-hook; unknown methods flow through
the TransparentHandler untouched (:89-94). ``failOver`` (:79) rebuilds
the store from the backend's live pods/containers when the proxy
restarts. The hook failure policy (config.go:24-33) decides whether a
hook error fails the CRI call (Fail) or forwards unmodified (Ignore).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Protocol

from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.runtimehooks.hooks import FailurePolicy
from koordinator_tpu.koordlet.runtimehooks.protocol import Resources
from koordinator_tpu.koordlet.runtimehooks.server import RuntimeHookServer


@dataclasses.dataclass
class CRIRequest:
    """One CRI call: typed method + the pod/container it concerns.

    ``resources`` carries the request's linux resource parameters; the
    interposer overlays the hook response onto it before forwarding (the
    reference mutates the protobuf request in place).
    """

    method: str                      # e.g. "RunPodSandbox"
    pod: Optional[PodMeta] = None
    container: Optional[str] = None  # container name
    resources: Resources = dataclasses.field(default_factory=Resources)
    payload: Dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CRIResponse:
    request: CRIRequest
    backend_response: object = None
    hook_response: Optional[Resources] = None


class BackendRuntime(Protocol):
    """The real runtime behind the proxy (containerd/dockerd stand-in)."""

    def handle(self, request: CRIRequest) -> object: ...

    def list_pods(self) -> List[PodMeta]: ...


class RuntimeProxyStore:
    """Pod/container metadata across calls (store/store.go): the hook
    stages after RunPodSandbox need the sandbox's annotations/cgroup
    parent, which later CRI calls don't repeat."""

    def __init__(self):
        self.pods: Dict[str, PodMeta] = {}

    def record_pod(self, pod: PodMeta) -> None:
        self.pods[pod.uid] = pod

    def pod(self, uid: str) -> Optional[PodMeta]:
        return self.pods.get(uid)

    def delete_pod(self, uid: str) -> None:
        self.pods.pop(uid, None)


#: method -> (pre-forward runner, post-forward runner) names on
#: RuntimeHookServer; stop/post hooks run AFTER the runtime acted
_HOOKED = {
    "RunPodSandbox": ("run_pod_sandbox", None),
    "StopPodSandbox": (None, "stop_pod_sandbox"),
    "CreateContainer": ("create_container", None),
    "StartContainer": ("start_container", "post_start_container"),
    "UpdateContainerResources": ("update_container_resources", None),
    "StopContainer": (None, "stop_container"),
}

_POD_METHODS = {"RunPodSandbox", "StopPodSandbox"}


class RuntimeManagerCriServer:
    """The interposer: hooked methods go pre-hook → backend → bookkeeping;
    everything else passes through transparently."""

    def __init__(
        self,
        hook_server: RuntimeHookServer,
        backend: BackendRuntime,
        failure_policy: FailurePolicy = FailurePolicy.IGNORE,
    ):
        self.hook_server = hook_server
        self.backend = backend
        self.failure_policy = failure_policy
        self.store = RuntimeProxyStore()

    # -- startup (criserver.go:79 failOver) ---------------------------------

    def fail_over(self) -> int:
        """Rebuild the store from the backend's live pods after a proxy
        restart; returns how many pods were recovered."""
        count = 0
        for pod in self.backend.list_pods():
            self.store.record_pod(pod)
            count += 1
        return count

    # -- interception --------------------------------------------------------

    def intercept(self, request: CRIRequest) -> CRIResponse:
        """The gRPC unary interceptor equivalent
        (InterceptRuntimeRequest :125)."""
        runners = _HOOKED.get(request.method)
        if runners is None:
            # TransparentHandler: forward untouched (:89-94)
            return CRIResponse(
                request=request, backend_response=self.backend.handle(request)
            )

        pod = request.pod
        if pod is None and request.payload.get("pod_uid"):
            pod = self.store.pod(request.payload["pod_uid"])
        if pod is None:
            return CRIResponse(
                request=request, backend_response=self.backend.handle(request)
            )

        pre_name, post_name = runners
        hook_response: Optional[Resources] = None

        def run_hook(name: str) -> Optional[Resources]:
            # the PROXY's failure policy governs, regardless of the hook
            # server's own default (hooks must surface errors to us)
            try:
                runner = getattr(self.hook_server, name)
                if request.method in _POD_METHODS:
                    return runner(pod, apply=False, policy=FailurePolicy.FAIL)
                return runner(
                    pod, request.container or "", apply=False,
                    policy=FailurePolicy.FAIL,
                )
            except Exception:
                if self.failure_policy is FailurePolicy.FAIL:
                    raise
                return None  # Ignore: forward unmodified

        if pre_name is not None:
            # pre-hooks mutate the request before the runtime sees it
            hook_response = run_hook(pre_name)
            if hook_response is not None:
                self._merge(request, hook_response)

        backend_response = self.backend.handle(request)

        # bookkeeping after the runtime accepted the call
        if request.method == "RunPodSandbox":
            self.store.record_pod(pod)
        elif request.method == "StopPodSandbox":
            self.store.delete_pod(pod.uid)

        if post_name is not None:
            # post hooks run after the runtime acted; they never block
            # the already-completed call
            try:
                hook_response = run_hook(post_name) or hook_response
            except Exception:
                pass

        return CRIResponse(
            request=request,
            backend_response=backend_response,
            hook_response=hook_response,
        )

    @staticmethod
    def _merge(request: CRIRequest, response: Resources) -> None:
        """Overlay the hook's resource response onto the request (the
        reference's updateResource on the protobuf LinuxContainerResources)."""
        for field in dataclasses.fields(Resources):
            value = getattr(response, field.name)
            if value is not None:
                setattr(request.resources, field.name, value)
