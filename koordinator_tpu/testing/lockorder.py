"""Runtime lock-order assertion shim: the dynamic half of graftcheck's
``lock-order`` rule.

The static pass (analysis/graftcheck/rules/lock_order.py) proves the
mapped locks' acquisition graph is acyclic over every path the call
graph can name. This shim closes the gap static resolution can't: it
instruments the SAME mapped locks at runtime and verifies every
observed acquisition embeds into the statically-derived order — under
the full chaos suite (six wire fault kinds, state sabotage,
kill-the-leader) and the pipelined churn, where every thread the
process owns (coordinator, publisher, gate executor, sidecar handlers,
debug mux, supervisor monitor) runs concurrently.

Mechanics:

- :meth:`LockOrderShim.install` wraps each mapped class's ``__init__``
  so new instances get an order-checking lock proxy, and wraps the
  process singletons (TRACER, FLIGHT, DEVICE_OBS) that predate the
  install. :meth:`uninstall` restores the constructors and disables
  recording (already-wrapped instances keep working, silently).
- each thread keeps a stack of held mapped locks. Acquiring lock B
  while holding A records the edge A→B and checks that
  ``static ∪ observed`` stays acyclic — an inversion of any known
  order is recorded as a violation (with both lock names, the thread,
  and the acquisition stack), never raised mid-test: the chaos
  properties keep running and the fixture asserts ``violations == []``
  at teardown.
- reentrancy is per-INSTANCE: re-acquiring an RLock you already hold
  (SchedulerCache, StateAuditor) is legal and records nothing; nesting
  two different instances of the same class IS an edge (label→label, a
  self-loop) and therefore a violation — non-reentrant cross-instance
  nesting is a real deadlock shape.

Zero third-party deps; safe to import without jax.
"""

from __future__ import annotations

import importlib
import threading
from typing import Dict, List, Sequence, Set, Tuple

#: process singletons created at import time, before any install():
#: (module, attribute, lock attr)
_SINGLETON_LOCKS = (
    ("koordinator_tpu.obs.trace", "TRACER", "_lock"),
    ("koordinator_tpu.obs.flight", "FLIGHT", "_lock"),
    ("koordinator_tpu.obs.device", "DEVICE_OBS", "_lock"),
    ("koordinator_tpu.obs.device", "DEVICE_OBS", "_profile_io_lock"),
)


class _CheckedLock:
    """A lock proxy recording acquisition order into the shim."""

    __slots__ = ("_inner", "label", "_shim")

    def __init__(self, inner, label: str, shim: "LockOrderShim"):
        self._inner = inner
        self.label = label
        self._shim = shim

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._shim._note_acquire(self)
        return got

    def release(self) -> None:
        self._shim._note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name):
        # Condition-backed locks (AdmissionGate) reach wait/notify/
        # notify_all through the proxy; a Condition's wait-side
        # release+reacquire never acquires OTHER locks on this thread,
        # so the held-stack bookkeeping stays sound
        return getattr(self._inner, name)


class LockOrderShim:
    """Instrument the mapped locks; verify the static order holds."""

    def __init__(self, static_edges: Sequence[Tuple[str, str]],
                 lock_map: Sequence[Tuple[str, str, str]]):
        """``static_edges``: (held label, acquired label) pairs from
        the static pass. ``lock_map``: (module dotted path, class name,
        lock attr) for every mapped lock."""
        self.static_edges = set(static_edges)
        self.lock_map = tuple(lock_map)
        self.violations: List[dict] = []
        self.observed_edges: Set[Tuple[str, str]] = set()
        self.acquisitions = 0
        self.enabled = False
        self._adj: Dict[str, Set[str]] = {}
        for a, b in self.static_edges:
            if a != b:
                self._adj.setdefault(a, set()).add(b)
        self._tls = threading.local()
        self._graph_lock = threading.Lock()
        self._patched: List[Tuple[type, object]] = []
        self._wrapped_singletons: List[Tuple[object, str, object]] = []

    # -- instrumentation -----------------------------------------------------

    @classmethod
    def from_static_analysis(cls) -> "LockOrderShim":
        """Build the shim from the SAME program analysis the static
        rule runs — the declared order is derived, never hand-copied."""
        from pathlib import Path

        from koordinator_tpu.analysis.graftcheck.callgraph import (
            build_program,
            module_dotted,
        )
        from koordinator_tpu.analysis.graftcheck.engine import (
            iter_repo_modules,
        )
        from koordinator_tpu.analysis.graftcheck.rules import LOCK_NODES
        from koordinator_tpu.analysis.graftcheck.rules.lock_order import (
            build_lock_graph,
        )
        from koordinator_tpu.analysis.graftcheck.__main__ import (
            find_repo_root,
        )

        root = find_repo_root(Path(__file__).resolve())
        program = build_program(list(iter_repo_modules(root)))
        edges, _ = build_lock_graph(program, LOCK_NODES)
        return cls(
            static_edges=[(e.held, e.acquired) for e in edges],
            lock_map=[
                (module_dotted(ln.path), ln.class_name, ln.lock)
                for ln in LOCK_NODES
            ],
        )

    def install(self) -> "LockOrderShim":
        self.enabled = True
        by_class: Dict[Tuple[str, str], List[str]] = {}
        for dotted, class_name, lock in self.lock_map:
            by_class.setdefault((dotted, class_name), []).append(lock)
        for (dotted, class_name), locks in by_class.items():
            module = importlib.import_module(dotted)
            cls = getattr(module, class_name)
            orig_init = cls.__init__
            shim = self

            def make_init(orig, cname, lock_attrs):
                def __init__(self_obj, *args, **kwargs):
                    orig(self_obj, *args, **kwargs)
                    for attr in lock_attrs:
                        inner = getattr(self_obj, attr, None)
                        if inner is not None and not isinstance(
                            inner, _CheckedLock
                        ):
                            setattr(self_obj, attr, _CheckedLock(
                                inner, f"{cname}.{attr}", shim
                            ))
                return __init__

            cls.__init__ = make_init(orig_init, class_name, locks)
            self._patched.append((cls, orig_init))
        for dotted, name, attr in _SINGLETON_LOCKS:
            try:
                module = importlib.import_module(dotted)
                obj = getattr(module, name)
            except (ImportError, AttributeError):
                continue
            inner = getattr(obj, attr, None)
            if inner is None or isinstance(inner, _CheckedLock):
                continue
            label = f"{type(obj).__name__}.{attr}"
            setattr(obj, attr, _CheckedLock(inner, label, self))
            self._wrapped_singletons.append((obj, attr, inner))
        return self

    def uninstall(self) -> None:
        self.enabled = False
        for cls, orig_init in self._patched:
            cls.__init__ = orig_init
        self._patched.clear()
        for obj, attr, inner in self._wrapped_singletons:
            current = getattr(obj, attr, None)
            if isinstance(current, _CheckedLock):
                setattr(obj, attr, inner)
        self._wrapped_singletons.clear()

    def __enter__(self) -> "LockOrderShim":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- order checking ------------------------------------------------------

    def _held(self) -> List[_CheckedLock]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, lock: _CheckedLock) -> None:
        stack = self._held()
        if not self.enabled:
            stack.append(lock)
            return
        self.acquisitions += 1
        reentrant = any(held is lock for held in stack)
        if not reentrant:
            # RLock reentry on the same instance records no edge; the
            # stack entry is still pushed so releases stay balanced
            for held in stack:
                self._check_edge(held.label, lock.label, stack)
        stack.append(lock)

    def _note_release(self, lock: _CheckedLock) -> None:
        stack = self._held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    def _check_edge(self, held: str, acquired: str,
                    stack: List[_CheckedLock]) -> None:
        edge = (held, acquired)
        with self._graph_lock:
            if edge in self.observed_edges:
                return
            if held == acquired:
                # two INSTANCES of the same class nested — a cross-
                # instance deadlock shape the per-class graph models as
                # a self-loop
                self.violations.append({
                    "held": held, "acquired": acquired,
                    "thread": threading.current_thread().name,
                    "kind": "same-class-nesting",
                    "stack": [l.label for l in stack],
                })
                self.observed_edges.add(edge)
                return
            # would acquired -> ... -> held complete a cycle through
            # the combined static+observed graph?
            if self._reaches(acquired, held):
                self.violations.append({
                    "held": held, "acquired": acquired,
                    "thread": threading.current_thread().name,
                    "kind": "order-inversion",
                    "stack": [l.label for l in stack],
                })
            self.observed_edges.add(edge)
            self._adj.setdefault(held, set()).add(acquired)

    def _reaches(self, src: str, dst: str) -> bool:
        seen: Set[str] = set()
        work = [src]
        while work:
            node = work.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            work.extend(self._adj.get(node, ()))
        return False

    # -- reporting -----------------------------------------------------------

    def report(self) -> dict:
        with self._graph_lock:
            return {
                "acquisitions": self.acquisitions,
                "observed_edges": sorted(self.observed_edges),
                "violations": list(self.violations),
            }
