"""Seeded open-loop arrival traces for the streaming serving mode.

The continuous-arrival bench (leg ``18_streaming_arrival``) and the
streaming property/chaos tests need *scenario diversity without
hand-written scenarios*: a pod stream whose shape — diurnal load
swings, heavy-tailed request sizes, burst storms, gang waves — is
drawn from a seeded generator, so every scenario is reproducible
forever from ``(kind, seed, rate, duration)`` alone. Same determinism
contract as :mod:`koordinator_tpu.testing.chaos`: the TRACE is the
deterministic artifact (same seed → same arrivals, byte for byte);
what the scheduler does with it is the property under test.

An :class:`ArrivalTrace` is a time-sorted list of :class:`Arrival`
rows — relative timestamps (seconds from trace start), a pod name, a
QoS lane, resource requests, and an optional gang — that a driver
replays against a clock: the bench paces submissions on the wall
clock (open loop: arrivals never wait for the scheduler), the
property tests step a fake clock through the same timestamps.

Generators:

- :func:`diurnal_trace` — a non-homogeneous Poisson process whose
  rate swings sinusoidally between ``low_frac`` and 1.0 of the peak
  rate: the compressed day/night cycle a global user base produces.
- :func:`heavy_tail_trace` — Poisson arrivals with Pareto-distributed
  request sizes (many small pods, a heavy tail of large ones) and a
  small fraction of system-lane pods: the multi-workload mix.
- :func:`burst_storm_trace` — a baseline trickle plus scheduled
  storms: ``burst_pods`` arrivals packed into a few milliseconds
  (a deployment rollout / failover herd). The adaptive trigger's
  watermark must absorb these into few dispatches.
- :func:`gang_wave_trace` — waves of gang members arriving together
  on a cadence over a solo-pod baseline: the all-or-nothing batch
  workloads whose Permit barrier spans rounds.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.apis.extension import QoSClass

#: lane mix (system, ls, be) used when a generator does not override it
_DEFAULT_LANE_MIX = (0.05, 0.65, 0.30)

_QOS_BY_LANE = {
    "system": QoSClass.SYSTEM,
    "ls": QoSClass.LS,
    "be": QoSClass.BE,
}


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One pod arrival: ``at`` is seconds from trace start."""

    at: float
    name: str
    lane: str  # system | ls | be
    cpu: int   # millicores
    memory: int  # MiB
    gang: Optional[str] = None

    @property
    def qos(self) -> QoSClass:
        return _QOS_BY_LANE[self.lane]


@dataclasses.dataclass(frozen=True)
class ArrivalTrace:
    """A time-sorted arrival sequence plus its provenance."""

    kind: str
    seed: int
    duration_s: float
    rate_pods_per_s: float
    arrivals: Tuple[Arrival, ...]

    def __len__(self) -> int:
        return len(self.arrivals)

    def __iter__(self):
        return iter(self.arrivals)


def _lane(rng: random.Random, mix=_DEFAULT_LANE_MIX) -> str:
    x = rng.random()
    if x < mix[0]:
        return "system"
    if x < mix[0] + mix[1]:
        return "ls"
    return "be"


def _small_pod(rng: random.Random) -> Tuple[int, int]:
    """The baseline request shape: 200-2000 mcpu, 128-2048 MiB."""
    return rng.randrange(200, 2000), rng.randrange(128, 2048)


def _finish(kind: str, seed: int, duration_s: float, rate: float,
            rows: List[Arrival]) -> ArrivalTrace:
    rows.sort(key=lambda a: (a.at, a.name))
    return ArrivalTrace(
        kind=kind, seed=seed, duration_s=duration_s,
        rate_pods_per_s=rate, arrivals=tuple(rows),
    )


def diurnal_trace(seed: int, duration_s: float = 10.0,
                  rate_pods_per_s: float = 200.0,
                  low_frac: float = 0.2,
                  cycles: float = 1.0) -> ArrivalTrace:
    """Sinusoidal-rate Poisson arrivals: the instantaneous rate swings
    between ``low_frac * rate`` and ``rate`` over ``cycles`` full
    day-cycles compressed into ``duration_s`` (thinning method, so the
    process is exactly non-homogeneous Poisson)."""
    rng = random.Random(f"diurnal:{seed}")
    rows: List[Arrival] = []
    t, i = 0.0, 0
    peak = max(1e-9, rate_pods_per_s)
    while True:
        t += rng.expovariate(peak)  # candidate at the peak rate
        if t >= duration_s:
            break
        phase = 2.0 * math.pi * cycles * t / duration_s
        frac = low_frac + (1.0 - low_frac) * 0.5 * (1 - math.cos(phase))
        if rng.random() > frac:
            continue  # thinned: off-peak hours
        cpu, mem = _small_pod(rng)
        rows.append(Arrival(
            at=t, name=f"d{seed}p{i}", lane=_lane(rng), cpu=cpu,
            memory=mem,
        ))
        i += 1
    return _finish("diurnal", seed, duration_s, rate_pods_per_s, rows)


def heavy_tail_trace(seed: int, duration_s: float = 10.0,
                     rate_pods_per_s: float = 200.0,
                     tail_alpha: float = 1.3,
                     cpu_cap: int = 16000) -> ArrivalTrace:
    """Poisson arrivals whose request sizes follow a (capped) Pareto:
    the p50 pod is small, the p99 pod is an order of magnitude larger
    — the mix that makes tail latency a packing problem, not only a
    queueing one."""
    rng = random.Random(f"heavy-tail:{seed}")
    rows: List[Arrival] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(max(1e-9, rate_pods_per_s))
        if t >= duration_s:
            break
        # capped Pareto over [200, cpu_cap] millicores; memory scales
        cpu = min(cpu_cap, int(200 * rng.paretovariate(tail_alpha)))
        mem = min(32768, max(128, cpu))
        rows.append(Arrival(
            at=t, name=f"h{seed}p{i}", lane=_lane(rng), cpu=cpu,
            memory=mem,
        ))
        i += 1
    return _finish("heavy-tail", seed, duration_s, rate_pods_per_s, rows)


def burst_storm_trace(seed: int, duration_s: float = 10.0,
                      rate_pods_per_s: float = 50.0,
                      bursts: int = 3, burst_pods: int = 64,
                      burst_span_s: float = 0.005) -> ArrivalTrace:
    """A baseline Poisson trickle plus ``bursts`` storms: each packs
    ``burst_pods`` arrivals into ``burst_span_s`` at seeded instants
    (never in the first or last tenth of the trace, so a mid-storm
    fault injection has runway on both sides)."""
    rng = random.Random(f"burst-storm:{seed}")
    rows: List[Arrival] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(max(1e-9, rate_pods_per_s))
        if t >= duration_s:
            break
        cpu, mem = _small_pod(rng)
        rows.append(Arrival(
            at=t, name=f"b{seed}p{i}", lane=_lane(rng), cpu=cpu,
            memory=mem,
        ))
        i += 1
    for b in range(bursts):
        at0 = rng.uniform(0.1 * duration_s, 0.9 * duration_s)
        for j in range(burst_pods):
            cpu, mem = _small_pod(rng)
            rows.append(Arrival(
                at=at0 + rng.uniform(0.0, burst_span_s),
                name=f"b{seed}s{b}p{j}",
                # storms skew latency-sensitive: the rollout herd
                lane="ls" if rng.random() < 0.8 else "be",
                cpu=cpu, memory=mem,
            ))
    return _finish("burst-storm", seed, duration_s, rate_pods_per_s,
                   rows)


def gang_wave_trace(seed: int, duration_s: float = 10.0,
                    rate_pods_per_s: float = 50.0,
                    waves: int = 4, gang_size: int = 4,
                    wave_span_s: float = 0.002) -> ArrivalTrace:
    """Solo-pod baseline plus ``waves`` gang waves: each wave is one
    gang's ``gang_size`` members arriving within ``wave_span_s`` —
    the co-scheduled batch jobs whose Permit barrier must bridge
    adaptively-fired rounds."""
    rng = random.Random(f"gang-wave:{seed}")
    rows: List[Arrival] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(max(1e-9, rate_pods_per_s))
        if t >= duration_s:
            break
        cpu, mem = _small_pod(rng)
        rows.append(Arrival(
            at=t, name=f"g{seed}p{i}", lane=_lane(rng), cpu=cpu,
            memory=mem,
        ))
        i += 1
    for w in range(waves):
        at0 = rng.uniform(0.05 * duration_s, 0.95 * duration_s)
        for j in range(gang_size):
            rows.append(Arrival(
                at=at0 + rng.uniform(0.0, wave_span_s),
                name=f"g{seed}w{w}m{j}", lane="ls",
                cpu=800, memory=256, gang=f"wave{seed}-{w}",
            ))
    return _finish("gang-wave", seed, duration_s, rate_pods_per_s, rows)


#: the convergence scenario's load regimes (DESIGN §25): time-dilation
#: factors applied to ONE seeded trace — same pods, same order, same
#: relative shape, 3 sustained-rate points
REGIMES: Dict[str, float] = {
    "low": 0.25,
    "mid": 1.0,
    "saturating": 4.0,
}


def regime_scale(trace: ArrivalTrace, regime) -> ArrivalTrace:
    """Replay ONE seeded trace at another load regime without
    re-deriving seeds: a time-dilation by ``factor`` (a
    :data:`REGIMES` name or a float) divides every arrival timestamp
    and the duration by the factor, multiplying the sustained rate —
    the pod SEQUENCE (names, lanes, sizes, gangs, relative shape) is
    byte-identical across regimes, so a controller property like
    "converges at low/mid/saturating" is tested against the same
    workload, not three different random draws."""
    factor = REGIMES[regime] if isinstance(regime, str) else float(regime)
    if factor <= 0:
        raise ValueError(f"regime factor must be positive: {factor}")
    label = regime if isinstance(regime, str) else f"x{factor:g}"
    if factor == 1.0:
        scaled = trace.arrivals
    else:
        scaled = tuple(
            dataclasses.replace(a, at=a.at / factor)
            for a in trace.arrivals
        )
    return ArrivalTrace(
        kind=f"{trace.kind}@{label}",
        seed=trace.seed,
        duration_s=trace.duration_s / factor,
        rate_pods_per_s=trace.rate_pods_per_s * factor,
        arrivals=scaled,
    )


#: generator registry: scenario diversity is data-driven — benches and
#: tests iterate this instead of hand-picking scenarios
TRACE_KINDS: Dict[str, object] = {
    "diurnal": diurnal_trace,
    "heavy-tail": heavy_tail_trace,
    "burst-storm": burst_storm_trace,
    "gang-wave": gang_wave_trace,
}


def make_trace(kind: str, seed: int, **kwargs) -> ArrivalTrace:
    """Build a trace by kind name (see :data:`TRACE_KINDS`)."""
    try:
        gen = TRACE_KINDS[kind]
    except KeyError:
        raise ValueError(
            f"unknown arrival trace kind {kind!r}; "
            f"one of {sorted(TRACE_KINDS)}"
        ) from None
    return gen(seed, **kwargs)


def trace_pods(trace: ArrivalTrace, gang_min_member: Optional[int] = None):
    """Materialize a trace's arrivals as ``(at, PodSpec)`` pairs (and
    the gang specs it references, as ``{name: GangSpec}``) — the bus
    objects a driver applies. Import-light: apis.types only."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import GangMode, GangSpec, PodSpec

    gangs: Dict[str, object] = {}
    pairs = []
    for a in trace:
        if a.gang and a.gang not in gangs:
            size = gang_min_member
            if size is None:
                size = sum(1 for x in trace if x.gang == a.gang)
            gangs[a.gang] = GangSpec(
                name=a.gang, min_member=size, mode=GangMode.NON_STRICT,
            )
        pairs.append((a.at, PodSpec(
            name=a.name,
            requests={ResourceName.CPU: a.cpu, ResourceName.MEMORY: a.memory},
            qos=a.qos, gang=a.gang,
        )))
    return pairs, gangs
