"""Deterministic fault injection for the solver wire boundary.

The failure-domain layer (service/supervisor.py, service/failover.py,
the typed codec errors) exists to survive exactly the failures no unit
test used to produce: torn frames, bytes flipped on the wire, stalls
past the deadline, connections reset mid-solve, the sidecar SIGKILLed
mid-request, the per-connection delta base silently lost. This module
produces them ON DEMAND and DETERMINISTICALLY:

- :class:`FaultSchedule` maps request ordinals to fault kinds — either
  scripted explicitly (the property tests pin specific scenarios to
  specific ticks) or generated from a seed.
- :class:`ChaosProxy` sits between a :class:`PlacementClient`/
  :class:`RemoteSolver` and the sidecar, speaking the plain framed
  protocol (no shared-secret mode), forwarding frames verbatim except
  where the schedule names a fault.
- :class:`InProcessSidecar` wraps a :class:`PlacementService` in a
  subprocess-like handle (``poll``/``kill``/``pid``) so
  :class:`SolverSupervisor` can supervise — and chaos tests can
  SIGKILL-and-restart — a sidecar without paying a fresh JAX import
  per respawn. The jit cache survives in-process restarts, which is
  fine: the properties under test are protocol/state-machine
  properties, not cold-start cost.

The determinism contract is the SCHEDULE, not the interleaving: which
retry hits which ordinal can shift with timing, but every injected
fault leads to a typed, recoverable outcome, so the chaos property
tests assert path-independent facts (every tick completed; final
placements and node accounting bit-identical to a fault-free run).
"""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import Callable, Dict, Optional

from koordinator_tpu.service.codec import read_frame, write_frame

#: every fault kind the proxy can inject
FAULT_KINDS = (
    "torn-response",     # half the response frame, then a hard close
    "corrupt-response",  # response payload bytes flipped (frame intact)
    "stall",             # response delayed past the client's deadline
    "reset-request",     # client connection reset after the request
    "kill-server",       # kill_fn() fired mid-request (sidecar SIGKILL)
    "drop-base",         # upstream connection swapped: delta base lost
)

#: state-corruption fault kinds (injected by :class:`StateSaboteur`
#: into a live scheduler's caches rather than onto the wire) — the
#: drift classes the runtime auditor (scheduler/auditor.py) exists to
#: detect and repair
STATE_FAULT_KINDS = (
    "corrupt-cache-cell",   # a cached pod's placement silently rewritten
    "orphan-assume",        # an assume entry with no pod behind it
    "desync-staged-row",    # truth mutated WITHOUT a delta-tracker mark
)

#: executable-store corruption kinds (applied by :func:`sabotage_store`
#: to the AOT warm pool's on-disk entries, docs/DESIGN.md §21) — every
#: one must surface as a TYPED WarmEntryError + counted reject +
#: quarantine, then degrade to cold compile; never a crash and never a
#: stale-executable solve
#: device-memory-pressure fault kinds (injected by :class:`HBMSaboteur`
#: into the process-wide working-set manager, docs/DESIGN.md §26) —
#: every one must degrade through the typed demote→retry ladder with
#: counted outcomes; never a crashed tick, never a silently dropped
#: solve, and final placements bit-identical to a fault-free run
HBM_FAULT_KINDS = (
    "alloc-fail-stage",        # RESOURCE_EXHAUSTED at the next staging
    "alloc-fail-scatter",      # RESOURCE_EXHAUSTED at the next scatter
    "budget-squeeze-mid-churn",  # budget transiently halved: forced
                                 # demotions under live multi-tenant load
)

#: eviction-storm fault kinds (driven by the chaos arbitration suite
#: against a live scheduler + migration arbiter, docs/DESIGN.md §27) —
#: every one must pass through the MigrationArbiter: no declared budget
#: exceeded in any window, every over-budget request deferred with a
#: typed + counted refusal (never dropped silently), no eviction
#: cascade, and final placements + node accounting bit-identical to a
#: fault-free control arm
EVICTION_STORM_FAULT_KINDS = (
    "rebalance-wave",           # a LoadAware Balance sweep fired mid-run
    "preemption-storm",         # a wave of unique-fit LS arrivals, each
                                # placing only by evicting a BE resident
    "budget-squeeze-mid-wave",  # arbiter budget transiently tightened
                                # against already-admitted evictions
)

WARM_POOL_FAULT_KINDS = (
    "truncated-entry",          # torn write: the file ends mid-payload
    "bitflipped-entry",         # bit rot: bytes flipped under the header
    "stale-host-fingerprint",   # store copied from another machine: the
                                # embedded host fingerprint is foreign
    "torn-concurrent-write",    # two unsynchronized writers interleaved:
                                # head from one write, tail from another
    "wrong-magic",              # foreign/stale file where an entry should be
    "oversize-entry",           # corrupt/hostile length: GB-claiming header
)

def sabotage_store(store_dir: str, kind: str, seed: int = 0,
                   manifest: bool = False):
    """Deterministically corrupt one AOT warm-pool store file under
    ``store_dir`` (the newest ``.exec`` entry in sorted order, or the
    manifest with ``manifest=True``). Returns the path corrupted, or
    None when the store holds no target. Same seed → same bytes
    flipped, forever — the warm-pool fuzz tests and the chaos
    restart-storm share this one implementation."""
    import os
    import struct

    if kind not in WARM_POOL_FAULT_KINDS:
        raise ValueError(f"unknown store fault kind: {kind!r}")
    targets = []
    for root, _dirs, files in os.walk(store_dir):
        for name in files:
            if manifest and name == "warm_manifest.bin":
                targets.append(os.path.join(root, name))
            elif not manifest and name.endswith(".exec"):
                targets.append(os.path.join(root, name))
    if not targets:
        return None
    path = sorted(targets)[-1]
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    rng = random.Random(seed)
    if kind == "truncated-entry":
        raw = raw[: max(8, len(raw) // 2)]
    elif kind == "bitflipped-entry":
        # flip bytes PAST the framed header so the fingerprint check —
        # not the magic check — is what must catch it
        start = min(len(raw) - 1, 64)
        for _ in range(max(1, len(raw) // 4096)):
            i = rng.randrange(start, len(raw))
            raw[i] ^= 0xFF
    elif kind == "stale-host-fingerprint":
        # a VALIDLY framed entry whose embedded provenance names a
        # different machine — the copied-store/baked-container-image
        # shape that dodges the host-scoped directory layout. Only the
        # load-time provenance check can catch this one: the frame
        # digest is recomputed, so it verifies clean.
        import pickle

        from koordinator_tpu.utils.compilation_cache import (
            frame_payload,
            unframe_payload,
        )

        try:
            record = pickle.loads(unframe_payload(bytes(raw)))
            host, version, payload, trees = record
        except Exception:
            return None  # not a v2 entry (e.g. the manifest): no target
        body = pickle.dumps(
            ("x86_64-deadbeef0000", version, payload, trees)
        )
        raw = bytearray(frame_payload(body))
    elif kind == "torn-concurrent-write":
        # two writers' interleaved output: the header + head of one
        # write, the tail of another (simulated by splicing the file's
        # own head over its tail) — framing intact, fingerprint wrong
        half = max(64, len(raw) // 2)
        raw = raw[:half] + raw[len(raw) - half: len(raw) - half // 2] \
            + raw[half + half // 2:]
        if len(raw) < 64:
            raw = raw + b"\x00" * 64
    elif kind == "wrong-magic":
        raw[:8] = b"NOTKOORD"
    elif kind == "oversize-entry":
        # keep the real magic, claim an absurd payload length
        raw[8:16] = struct.pack(">Q", 1 << 62)
    with open(path, "wb") as f:
        f.write(bytes(raw))
    return path


class FaultSchedule:
    """Request ordinal (0-based, global across connections) → fault.

    ``events`` pins faults explicitly; :meth:`generate` derives a
    schedule from a seed. Ordinals are counted by the proxy in arrival
    order, so a single-threaded scheduler loop sees a reproducible
    mapping from schedule to wire behavior. State-corruption kinds
    (:data:`STATE_FAULT_KINDS`) share the same schedule machinery but
    are executed by :class:`StateSaboteur` against tick ordinals."""

    def __init__(self, events: Optional[Dict[int, str]] = None):
        self.events = dict(events or {})
        for kind in self.events.values():
            if (
                kind not in FAULT_KINDS
                and kind not in STATE_FAULT_KINDS
                and kind not in HBM_FAULT_KINDS
                and kind not in EVICTION_STORM_FAULT_KINDS
            ):
                raise ValueError(f"unknown fault kind: {kind!r}")

    @classmethod
    def generate(cls, seed: int, n_requests: int, rate: float = 0.2,
                 kinds=FAULT_KINDS, start: int = 0) -> "FaultSchedule":
        """A seeded schedule over ``[start, start+n_requests)``: each
        ordinal independently faulted with probability ``rate``, kind
        drawn uniformly. Same seed → same schedule, forever."""
        rng = random.Random(seed)
        events: Dict[int, str] = {}
        for i in range(start, start + n_requests):
            if rng.random() < rate:
                events[i] = kinds[rng.randrange(len(kinds))]
        return cls(events)

    def fault_for(self, ordinal: int) -> Optional[str]:
        return self.events.get(ordinal)


def _connect(address):
    family = socket.AF_UNIX if isinstance(address, str) else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.connect(address)
    return sock


class ChaosProxy:
    """A frame-level proxy injecting :class:`FaultSchedule` faults.

    One thread per client connection; the upstream connection is opened
    lazily (and re-opened after ``drop-base``/upstream death, so the
    client keeps its connection while the server's per-connection delta
    base vanishes — the forced-base-loss scenario). If the upstream is
    unreachable when a client connects, the client connection is closed
    immediately: :func:`~koordinator_tpu.service.supervisor.
    connection_probe`'s hold-open check then correctly reports the
    BACKEND dead even though the proxy itself still accepts."""

    def __init__(self, listen_address, upstream_address,
                 schedule: Optional[FaultSchedule] = None,
                 kill_fn: Optional[Callable[[], None]] = None,
                 stall_s: float = 1.0, corrupt_seed: int = 0):
        self.listen_address = listen_address
        self.upstream_address = upstream_address
        self.schedule = schedule or FaultSchedule()
        self.kill_fn = kill_fn
        self.stall_s = stall_s
        self._corrupt_rng = random.Random(corrupt_seed)
        self._lock = threading.Lock()
        self.requests_seen = 0
        self.faults_injected: Dict[str, int] = {}
        self._stop = threading.Event()
        family = (socket.AF_UNIX if isinstance(listen_address, str)
                  else socket.AF_INET)
        self._sock = socket.socket(family, socket.SOCK_STREAM)
        self._sock.bind(listen_address)
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ChaosProxy":
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="chaos-proxy"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._sock.close()

    # -- internals -----------------------------------------------------------

    def _next_ordinal(self) -> int:
        with self._lock:
            ordinal = self.requests_seen
            self.requests_seen += 1
            return ordinal

    def _record(self, kind: str) -> None:
        with self._lock:
            self.faults_injected[kind] = (
                self.faults_injected.get(kind, 0) + 1
            )

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # a dead backend must look dead THROUGH the proxy: refuse
            # (close) the client connection when upstream won't accept
            try:
                upstream = _connect(self.upstream_address)
            except OSError:
                conn.close()
                continue
            threading.Thread(
                target=self._serve, args=(conn, upstream), daemon=True
            ).start()

    def _serve(self, conn: socket.socket, upstream: socket.socket) -> None:
        client_stream = conn.makefile("rwb")
        up_stream = upstream.makefile("rwb")

        def close_all():
            for closeable in (client_stream, up_stream, conn, upstream):
                try:
                    closeable.close()
                except OSError:
                    pass

        def hard_reset():
            # RST instead of FIN where the platform allows: the client
            # must handle an ABRUPT death, not a polite shutdown
            try:
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00",
                )
            except OSError:
                pass
            close_all()

        try:
            while not self._stop.is_set():
                try:
                    payload = read_frame(client_stream)
                except (EOFError, ValueError, OSError):
                    return close_all()
                if payload is None:
                    return close_all()
                fault = self.schedule.fault_for(self._next_ordinal())
                if fault == "reset-request":
                    self._record(fault)
                    return hard_reset()
                if fault == "kill-server":
                    self._record(fault)
                    if self.kill_fn is not None:
                        self.kill_fn()
                    return hard_reset()
                if fault == "drop-base":
                    # swap the upstream connection: the server's
                    # per-connection NodeStateCache dies with it while
                    # the CLIENT connection lives on — the next delta
                    # request meets delta-base-mismatch
                    self._record(fault)
                    try:
                        up_stream.close()
                        upstream.close()
                    except OSError:
                        pass
                    try:
                        upstream = _connect(self.upstream_address)
                        up_stream = upstream.makefile("rwb")
                    except OSError:
                        return hard_reset()
                try:
                    write_frame(up_stream, payload)
                    up_stream.flush()
                    response = read_frame(up_stream)
                except (EOFError, ValueError, OSError):
                    return hard_reset()  # backend died mid-solve
                if response is None:
                    return hard_reset()
                if fault == "stall":
                    self._record(fault)
                    time.sleep(self.stall_s)
                elif fault == "torn-response":
                    self._record(fault)
                    try:
                        # length prefix + half the payload, then RST:
                        # the client sees TruncatedFrame
                        import struct

                        client_stream.write(
                            struct.pack(">I", len(response))
                        )
                        client_stream.write(response[: len(response) // 2])
                        client_stream.flush()
                    except OSError:
                        pass
                    return hard_reset()
                elif fault == "corrupt-response":
                    self._record(fault)
                    corrupted = bytearray(response)
                    for _ in range(max(1, len(corrupted) // 256)):
                        i = self._corrupt_rng.randrange(len(corrupted))
                        corrupted[i] ^= 0xFF
                    response = bytes(corrupted)
                try:
                    write_frame(client_stream, response)
                    client_stream.flush()
                except OSError:
                    return close_all()
        finally:
            close_all()


class StateSaboteur:
    """Deterministic *state* corruption: the drift classes the runtime
    auditor (scheduler/auditor.py) detects and repairs, injected into a
    live scheduler the same way :class:`ChaosProxy` injects wire faults
    — a :class:`FaultSchedule` maps tick ordinals to
    :data:`STATE_FAULT_KINDS`, ``inject(tick)`` executes the scheduled
    fault (seeded target selection; same seed → same victims, forever):

    - ``corrupt-cache-cell``: a cached assigned pod is silently replaced
      by a copy claiming a different node — the cache now disagrees with
      bus truth with no event to heal it (auditor: ``stale-pod``).
    - ``orphan-assume``: an assume entry appears with no pod behind it —
      the lingering-assume class a crashed round can leave (auditor:
      ``orphan-assume``).
    - ``desync-staged-row``: one staged node row (host arrays AND the
      device half, when staged) is bumped away from typed truth with NO
      delta-tracker mark — the missed-mark / corrupted-scatter class
      only the device↔host parity probe can see (auditor:
      ``staged-host-drift`` / ``staged-device-drift``). Typed truth is
      NOT touched, so a corrupted-then-repaired run stays bit-identical
      to a fault-free one.

    ``inject`` returns the fault kind applied (None when nothing was
    scheduled or its precondition — an assigned pod, a staged row —
    does not hold yet); ``injected`` counts per kind and ``log`` keeps
    ``(tick, kind, detail)`` for assertions."""

    def __init__(self, schedule: FaultSchedule, scheduler, seed: int = 0):
        self.schedule = schedule
        self.scheduler = scheduler
        self._rng = random.Random(seed)
        self.injected: Dict[str, int] = {}
        self.log: list = []

    def inject(self, tick: int) -> Optional[str]:
        kind = self.schedule.fault_for(tick)
        if kind is None or kind not in STATE_FAULT_KINDS:
            return None
        detail = getattr(self, "_" + kind.replace("-", "_"))()
        if detail is None:
            return None  # precondition unmet — nothing corrupted
        self.injected[kind] = self.injected.get(kind, 0) + 1
        self.log.append((tick, kind, detail))
        return kind

    # -- fault implementations ----------------------------------------------

    def _corrupt_cache_cell(self) -> Optional[str]:
        import dataclasses

        cache = self.scheduler.cache
        nodes = sorted(cache.nodes)
        if len(nodes) < 2:
            return None
        candidates = sorted(
            uid for uid, pod in cache.pods.items()
            if pod.node_name is not None
            and not getattr(pod, "waiting_permit", False)
        )
        if not candidates:
            return None
        uid = candidates[self._rng.randrange(len(candidates))]
        pod = cache.pods[uid]
        others = [n for n in nodes if n != pod.node_name]
        wrong = others[self._rng.randrange(len(others))]
        # a COPY, so the shared bus object keeps the true placement:
        # exactly the cache-forgot-an-event drift shape
        cache.pods[uid] = dataclasses.replace(pod, node_name=wrong)
        return f"{uid}:{pod.node_name}->{wrong}"

    def _orphan_assume(self) -> Optional[str]:
        cache = self.scheduler.cache
        uid = f"__ghost__{self._rng.randrange(1 << 30)}"
        cache.assumed[uid] = 0.0  # ancient: expired by any TTL
        return uid

    def _desync_staged_row(self) -> Optional[str]:
        model = getattr(self.scheduler, "model", None)
        staged = getattr(model, "staged_cache", None)
        if staged is None:
            return None
        arrays, state, tracker, seen_epoch, _now = staged.audit_view()
        if arrays is None or tracker is None:
            return None
        dirty = set(tracker.dirty_since(seen_epoch))
        cache = self.scheduler.cache
        candidates = [
            name for name in arrays.names
            if name not in dirty and name in cache.node_metrics
        ]
        if not candidates:
            return None
        name = candidates[self._rng.randrange(len(candidates))]
        i = arrays.names.index(name)
        # drift the staged row away from truth on BOTH halves, no
        # tracker mark: typed truth stays intact (a fault-free run is
        # still the reference), but nothing event-driven will ever
        # re-lower this row — only the parity probe can see it
        arrays.usage[i, 0] += 777
        if state is not None:
            staged.state = state._replace(
                usage=state.usage.at[i, 0].add(777)
            )
        return name


class HBMSaboteur:
    """Deterministic *device-memory-pressure* injection: the allocation
    failures and budget squeezes the working-set manager
    (state/workingset.py, docs/DESIGN.md §26) exists to absorb, driven
    by the same :class:`FaultSchedule` machinery as
    :class:`StateSaboteur` — a schedule maps tick ordinals to
    :data:`HBM_FAULT_KINDS`, ``inject(tick)`` executes the scheduled
    fault against the process singleton:

    - ``alloc-fail-stage`` / ``alloc-fail-scatter``: arm one injected
      ``RESOURCE_EXHAUSTED`` at the named boundary — the NEXT staging
      (or scatter) raises before any device work runs, forcing the
      typed demote→retry ladder. The retried callable executes exactly
      once, so the landed solve is bit-identical to a fault-free one.
    - ``budget-squeeze-mid-churn``: the HBM budget is transiently
      halved and enforced — residents demote (BE lanes first) under
      live load, then the budget is restored; subsequent touches
      restage on demand.

    ``inject`` returns the kind applied (None when nothing scheduled);
    ``injected`` counts per kind and ``log`` keeps ``(tick, kind,
    detail)`` for assertions."""

    def __init__(self, schedule: FaultSchedule, manager=None, seed: int = 0):
        from koordinator_tpu.state.workingset import WORKING_SET

        self.schedule = schedule
        self.manager = manager if manager is not None else WORKING_SET
        self._rng = random.Random(seed)
        self.injected: Dict[str, int] = {}
        self.log: list = []

    def inject(self, tick: int) -> Optional[str]:
        kind = self.schedule.fault_for(tick)
        if kind is None or kind not in HBM_FAULT_KINDS:
            return None
        detail = getattr(self, "_" + kind.replace("-", "_"))()
        if detail is None:
            return None
        self.injected[kind] = self.injected.get(kind, 0) + 1
        self.log.append((tick, kind, detail))
        return kind

    # -- fault implementations ----------------------------------------------

    def _alloc_fail_stage(self) -> Optional[str]:
        self.manager.arm_fault("stage")
        return "armed:stage"

    def _alloc_fail_scatter(self) -> Optional[str]:
        self.manager.arm_fault("scatter")
        return "armed:scatter"

    def _budget_squeeze_mid_churn(self) -> Optional[str]:
        demoted = self.manager.squeeze(0.5)
        return f"squeezed:demoted={demoted}"


class InProcessSidecar:
    """A :class:`PlacementService` behind a subprocess-like handle.

    ``SolverSupervisor``'s ``spawn_fn`` returns one of these in tests
    and the bench outage leg: ``kill()`` severs every live connection
    and stops serving (the observable behavior of SIGKILL at the wire),
    ``poll()`` reports the exit code, and a respawn is a fresh
    ``InProcessSidecar`` on the same address — milliseconds instead of
    a subprocess's cold JAX import, with the solve jit cache shared
    (restart cost is not what these tests measure)."""

    _next_pid = [1000]

    def __init__(self, address, warm_restored: Optional[bool] = None,
                 **service_kwargs):
        from koordinator_tpu.service.server import PlacementService

        self._service = PlacementService(address, **service_kwargs)
        self._service.start()
        self._returncode: Optional[int] = None
        self._lock = threading.Lock()
        InProcessSidecar._next_pid[0] += 1
        self.pid = InProcessSidecar._next_pid[0]
        #: the handle-borne warm/cold restore outcome SolverSupervisor's
        #: default ``warm_outcome_fn`` reads (None = undecided): chaos
        #: tests and the bench set it to exercise the probe-budget
        #: split without a debug mux round trip
        self.warm_restored = warm_restored

    def poll(self) -> Optional[int]:
        with self._lock:
            return self._returncode

    def kill(self) -> None:
        with self._lock:
            if self._returncode is not None:
                return
            self._returncode = -9
        self._service.stop()

    terminate = kill

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        return self.poll()

    @property
    def service(self):
        return self._service


def preemption_storm(seed: int, n_nodes: int = 24,
                     residents_per_node: int = 4,
                     n_arrivals: int = 12,
                     quota: Optional[str] = None):
    """Seeded preemption-storm world: every node packed tight with
    low-priority preemptible BE residents, then a wave of higher-priority
    LS arrivals sized so plain fit fails — each can only place by
    evicting. Drives the joint place+evict solve's compile signatures
    (``preempt_solve`` / ``preempt_solve_scan`` / ``defrag_repack``)
    under the chaos suite's runtime sentinel and the storm bench leg.
    Same seed → same storm, forever.

    Returns ``(nodes, residents, arrivals)`` as typed specs; residents
    carry ``node_name`` (pre-assigned), arrivals are pending. With
    ``quota`` set, every pod shares that quota group, arming the
    ElasticQuota reprieve gate."""
    from koordinator_tpu.apis.extension import (
        PriorityClass,
        QoSClass,
        ResourceName,
    )
    from koordinator_tpu.apis.types import NodeSpec, PodSpec

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    rng = random.Random(seed)
    nodes, residents, arrivals = [], [], []
    for i in range(n_nodes):
        nodes.append(NodeSpec(
            name=f"storm-n{i}",
            allocatable={CPU: 16000, MEM: 65536},
        ))
        for j in range(residents_per_node):
            # residents fill the node: per-resident share with a little
            # jitter, leaving no room for an arrival without eviction
            residents.append(PodSpec(
                name=f"storm-be-{i}-{j}",
                node_name=f"storm-n{i}",
                requests={
                    CPU: 16000 // residents_per_node,
                    MEM: rng.randrange(
                        49152 // residents_per_node,
                        65536 // residents_per_node + 1,
                    ),
                },
                qos=QoSClass.BE,
                priority=rng.randrange(100, 400),
                quota=quota,
                assign_time=float(rng.randrange(0, 1000)),
            ))
    for k in range(n_arrivals):
        # an arrival needs more than any single resident frees — the
        # minimal victim set is >1 pod, so reprieve ordering matters
        arrivals.append(PodSpec(
            name=f"storm-ls-{k}",
            requests={
                CPU: (16000 // residents_per_node) * 2,
                MEM: (49152 // residents_per_node) * 2,
            },
            qos=QoSClass.LS,
            priority_class=PriorityClass.PROD,
            priority=rng.randrange(5000, 9000),
            quota=quota,
        ))
    return nodes, residents, arrivals


def eviction_storm_world(seed: int, n_nodes: int = 12,
                         base_cpu: int = 4000, base_mem: int = 8192,
                         step: int = 64):
    """Seeded UNIQUE-FIT eviction-storm world for the arbitration
    chaos suite (docs/DESIGN.md §27, :data:`EVICTION_STORM_FAULT_KINDS`).

    Node ``i`` allocates ``(base_cpu + i*step, base_mem + (N-1-i)*step)``
    — a two-resource staircase where arrival ``i`` requests EXACTLY
    node ``i``'s shape, so it fits node ``i`` and no other (every
    ``j < i`` is CPU-short, every ``j > i`` is memory-short). Each node
    starts filled by exactly one preemptible BE resident of the same
    shape. Consequences, by construction:

    - every LS arrival has exactly one feasible node and exactly one
      victim there, so the FINAL placement set is order-independent —
      deferrals and budget squeezes reshuffle WHEN evictions land,
      never WHERE, which is what lets the property test demand
      bit-identical final placements against the fault-free arm;
    - an evicted BE resident fits nowhere else while the storm is in
      flight (its unique node is being taken by its arrival), so the
      world cannot cascade by geometry: any observed cascade is an
      arbitration bug, not storm noise.

    Resident priorities are seeded jitter (the arbiter must not depend
    on them); arrival priorities strictly dominate. Returns
    ``(nodes, residents, arrivals)``; residents carry ``node_name``."""
    from koordinator_tpu.apis.extension import (
        PriorityClass,
        QoSClass,
        ResourceName,
    )
    from koordinator_tpu.apis.types import NodeSpec, PodSpec

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    rng = random.Random(seed)
    nodes, residents, arrivals = [], [], []
    for i in range(n_nodes):
        cpu = base_cpu + i * step
        mem = base_mem + (n_nodes - 1 - i) * step
        nodes.append(NodeSpec(
            name=f"evstorm-n{i}",
            allocatable={CPU: cpu, MEM: mem},
        ))
        residents.append(PodSpec(
            name=f"evstorm-be-{i}",
            node_name=f"evstorm-n{i}",
            requests={CPU: cpu, MEM: mem},
            qos=QoSClass.BE,
            priority=rng.randrange(100, 400),
            assign_time=float(rng.randrange(0, 1000)),
        ))
        arrivals.append(PodSpec(
            name=f"evstorm-ls-{i}",
            requests={CPU: cpu, MEM: mem},
            qos=QoSClass.LS,
            priority_class=PriorityClass.PROD,
            priority=5000 + rng.randrange(0, 4000),
        ))
    return nodes, residents, arrivals
