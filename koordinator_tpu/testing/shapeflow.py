"""Runtime shape-flow sentinel: the dynamic half of graftcheck v3's
signature-space pass (docs/DESIGN.md §23) — the same shape the
lock-order shim gives the lock-order rule.

The static pass (analysis/graftcheck/rules/shape_flow.py) enumerates,
per ``DEVICE_OBS.jit`` binding, the finite set of axis values the
bucket family can produce under the config bounds. This sentinel
closes the gap static resolution can't: it reads the SAME enumeration
(derived from the same program analysis — never hand-copied) and
asserts, against the device observatory's live compile ring, that
every signature a real workload actually compiles is inside it.

Mechanics:

- :meth:`begin_window` marks the compile-ring sequence;
  :meth:`verify_window` reads the entries the window produced and
  checks them. The warmed chaos and streaming suites run every test in
  its own window (autouse fixtures), so a structure change BETWEEN
  tests (a new world size) never smears into a false positive.
- per window, observed signatures group by ``(fn, pytree structure,
  leaf count)``. A leaf dimension that is CONSTANT across the window's
  signatures is structural (node width, feature columns — quasi-static
  axes the static pass declares as such). A dimension that VARIES is a
  live recompile axis and every observed value must be a member of the
  binding's enumerated bucket images — a varying value outside them is
  exactly an unbounded-signature-surface storm in progress.
- a compile from a binding the enumeration doesn't know is itself a
  violation: an undeclared hot jit appeared at runtime.
- violations are recorded, never raised mid-test (the suites keep
  running; the fixture asserts ``violations == []`` at teardown), and
  the report carries non-vacuity counters: windows with compiles,
  dimensions checked, dimensions covered by the enumeration — so "the
  sentinel passed" can never mean "the sentinel watched nothing".

Importing this module needs jax only transitively (the observatory);
building the enumeration imports the live bucket functions — the same
dependency surface the static rule already carries.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple


#: from_static_analysis results memoized per BINDING_SPECS tuple: the
#: whole-repo program build costs seconds and is a pure function of
#: the source tree + registry, so one process builds it once however
#: many suites arm sentinels (a monkeypatched registry is a different
#: key and rebuilds — the refuses-to-arm property stays live)
_STATIC_CACHE: Dict[object, Tuple[dict, dict, dict]] = {}


class ShapeFlowSentinel:
    """Assert observed compile signatures stay inside the statically
    enumerated signature space."""

    def __init__(self, allowed: Dict[str, Set[int]],
                 structural: Optional[Dict[str, Sequence[str]]] = None,
                 axis_images: Optional[
                     Dict[str, Tuple[frozenset, ...]]] = None):
        """``allowed``: binding name -> union of its enumerated axis
        values. ``structural``: binding -> declared quasi-static axis
        names (report detail only; the constant-within-window check is
        positional). ``axis_images``: binding -> per-axis image sets —
        when present, a varying position's value set must additionally
        fit inside ONE axis's image (union membership alone would let
        one axis's values launder another's: the config-capped raw
        lane range covers every small integer)."""
        self.allowed = {k: set(v) for k, v in allowed.items()}
        self.structural = dict(structural or {})
        self.axis_images = {
            k: tuple(frozenset(s) for s in v)
            for k, v in (axis_images or {}).items()
        }
        self.violations: List[dict] = []
        self.windows = 0
        self.windows_with_compiles = 0
        self.observed_compiles = 0
        self.dims_checked = 0
        self.dims_covered = 0
        self._lock = threading.Lock()
        self._mark = 0

    # -- construction --------------------------------------------------------

    @classmethod
    def from_static_analysis(cls) -> "ShapeFlowSentinel":
        """Build from the SAME program analysis the static rule runs —
        the enumeration is derived, never hand-copied. The build is
        memoized per registry tuple (pure function of the source
        tree), so repeated arming across suites costs one analysis."""
        from pathlib import Path

        from koordinator_tpu.analysis.graftcheck.__main__ import (
            find_repo_root,
        )
        from koordinator_tpu.analysis.graftcheck.callgraph import (
            build_program,
        )
        from koordinator_tpu.analysis.graftcheck.engine import (
            iter_repo_modules,
        )
        from koordinator_tpu.analysis.graftcheck import rules as _rules

        specs = _rules.BINDING_SPECS
        cached = _STATIC_CACHE.get(specs)
        if cached is not None:
            allowed, structural, axis_images = cached
            return cls(allowed=allowed, structural=structural,
                       axis_images=axis_images)

        root = find_repo_root(Path(__file__).resolve())
        program = build_program(list(iter_repo_modules(root)))
        rule = _rules.SignatureSpaceRule(specs=specs)
        findings = rule.check_program(program)
        if findings:
            raise AssertionError(
                "signature-space enumeration is not clean; the "
                "sentinel refuses to arm from a broken registry:\n"
                + "\n".join(v.format() for v in findings)
            )
        allowed: Dict[str, Set[int]] = {}
        structural: Dict[str, Sequence[str]] = {}
        axis_images: Dict[str, Tuple[frozenset, ...]] = {}
        for name, entry in rule.last_space.items():
            values: Set[int] = set()
            for axis in entry["axes"]:
                values.update(axis["values"])
            allowed[name] = values
            structural[name] = tuple(entry["structural_axes"])
            axis_images[name] = tuple(
                frozenset(axis["values"]) for axis in entry["axes"]
            )
        _STATIC_CACHE[specs] = (allowed, structural, axis_images)
        return cls(allowed=allowed, structural=structural,
                   axis_images=axis_images)

    # -- windows -------------------------------------------------------------

    def begin_window(self) -> None:
        from koordinator_tpu.obs.device import DEVICE_OBS

        _, seq = DEVICE_OBS.compile_ring()
        with self._lock:
            self._mark = seq
            self.windows += 1

    def verify_window(self) -> None:
        """Check every compile the window produced; record violations
        (never raise — teardown asserts)."""
        from koordinator_tpu.obs.device import DEVICE_OBS

        with self._lock:
            mark = self._mark
        entries, _ = DEVICE_OBS.compile_ring(mark)
        self.check_entries(
            [(e["fn"], e["key"][1]) for e in entries if "key" in e]
        )

    # -- the check (pure; unit-testable without a live observatory) ----------

    @staticmethod
    def _leaf_dims(sig) -> Optional[Tuple[Tuple[int, ...], ...]]:
        """The array-leaf shape tuples of one observed signature
        (``_signature`` leaves: arrays as (shape, dtype), statics by
        value — only shape-like leaves carry dims)."""
        try:
            leaves = sig[1]
        except Exception:
            return None
        shapes = []
        for leaf in leaves:
            if (
                isinstance(leaf, tuple) and len(leaf) == 2
                and isinstance(leaf[0], tuple)
                and all(isinstance(d, int) for d in leaf[0])
            ):
                shapes.append(tuple(leaf[0]))
        return tuple(shapes)

    def check_entries(self, entries: Sequence[Tuple[str, object]]) -> None:
        """``entries``: (fn_name, signature) pairs from one window."""
        if not entries:
            return
        with self._lock:
            self.windows_with_compiles += 1
            self.observed_compiles += len(entries)
        #: (fn, treedef repr, n leaves) -> list of dim matrices
        groups: Dict[Tuple, List] = {}
        for fn, sig in entries:
            if fn not in self.allowed:
                with self._lock:
                    self.violations.append({
                        "kind": "unknown-binding", "fn": fn,
                        "detail": (
                            "compile observed from a binding the "
                            "static enumeration does not declare"
                        ),
                    })
                continue
            dims = self._leaf_dims(sig)
            if dims is None:
                continue
            try:
                tree = repr(sig[0])
            except Exception:
                tree = "?"
            groups.setdefault((fn, tree, len(dims)), []).append(dims)
        for (fn, _tree, _n), dim_sets in groups.items():
            allowed = self.allowed[fn]
            # positionally align: dimension (leaf i, axis j) across the
            # window's signatures; constant positions are structural,
            # varying positions must live inside the enumeration
            positions: Dict[Tuple[int, int], Set[int]] = {}
            for dims in dim_sets:
                for i, shape in enumerate(dims):
                    for j, d in enumerate(shape):
                        positions.setdefault((i, j), set()).add(d)
            for (i, j), values in sorted(positions.items()):
                if len(values) <= 1:
                    # constant within the window: structural. Still
                    # counts toward coverage when the enumeration
                    # names it — the "actually exercised" signal: the
                    # static image describes live signatures, not just
                    # hypothetical ones.
                    d = next(iter(values))
                    if d in allowed:
                        with self._lock:
                            self.dims_covered += 1
                    continue
                with self._lock:
                    self.dims_checked += len(values)
                for d in sorted(values):
                    if d in allowed:
                        with self._lock:
                            self.dims_covered += 1
                    else:
                        with self._lock:
                            self.violations.append({
                                "kind": "out-of-enumeration",
                                "fn": fn, "leaf": i, "axis": j,
                                "value": d,
                                "varying": sorted(values),
                                "detail": (
                                    "a VARYING axis value outside the "
                                    "enumerated bucket images — an "
                                    "unbounded recompile surface in "
                                    "progress"
                                ),
                            })
                # one position is ONE semantic axis: beyond union
                # membership, the varying set must fit a single axis's
                # image — otherwise one axis's values launder
                # another's (the config-capped raw lane range covers
                # every small integer)
                images = self.axis_images.get(fn)
                if images and values <= allowed and not any(
                        values <= img for img in images):
                    with self._lock:
                        self.violations.append({
                            "kind": "axis-inconsistent",
                            "fn": fn, "leaf": i, "axis": j,
                            "varying": sorted(values),
                            "detail": (
                                "the varying values are each inside "
                                "SOME enumerated image but no single "
                                "axis's image contains them all — a "
                                "surface drifting across axis "
                                "identities"
                            ),
                        })

    # -- reporting -----------------------------------------------------------

    def report(self) -> dict:
        with self._lock:
            return {
                "windows": self.windows,
                "windows_with_compiles": self.windows_with_compiles,
                "observed_compiles": self.observed_compiles,
                "dims_checked": self.dims_checked,
                "dims_covered": self.dims_covered,
                "enumerated_bindings": len(self.allowed),
                "enumerated_values": sum(
                    len(v) for v in self.allowed.values()
                ),
                # which axes the registry declares quasi-static per
                # binding — the report's explanation for why a
                # constant-within-window dimension outside every
                # bucket image is still legitimate
                "structural_axes": {
                    k: list(v) for k, v in self.structural.items()
                },
                "violations": list(self.violations),
            }
