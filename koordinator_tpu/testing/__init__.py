"""Shared synthetic-problem builders and identity assertions.

Used by the test suite, ``bench.py``, and the driver's
``dryrun_multichip`` evidence run — library code, so the multichip
artifact does not depend on the tests/ tree being shipped.
"""

from __future__ import annotations

import numpy as np


def example_problem(n_nodes, n_pods, seed=0):
    """The standard random placement problem: (NodeState, PodBatch,
    ScoreParams) with mixed node sizes, 0-50% ambient usage, and
    cpu+memory thresholds — the flagship bench/test workload shape."""
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import NodeState, PodBatch, ScoreParams

    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), dtype=np.int32)
    alloc[:, ResourceName.CPU] = rng.choice([16000, 32000, 64000], n_nodes)
    alloc[:, ResourceName.MEMORY] = rng.choice([32768, 65536], n_nodes)
    usage = (alloc * rng.uniform(0, 0.5, alloc.shape)).astype(np.int32)
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.zeros_like(jnp.asarray(alloc)),
        usage=jnp.asarray(usage),
        prod_usage=jnp.asarray(usage // 2),
        est_extra=jnp.zeros_like(jnp.asarray(alloc)),
        prod_base=jnp.asarray(usage // 2),
        metric_fresh=jnp.ones(n_nodes, bool),
        schedulable=jnp.ones(n_nodes, bool),
    )
    req = np.zeros((n_pods, NUM_RESOURCES), dtype=np.int32)
    req[:, ResourceName.CPU] = rng.choice([500, 1000, 2000], n_pods)
    req[:, ResourceName.MEMORY] = rng.choice([1024, 2048], n_pods)
    est = (req * 85) // 100
    pods = PodBatch.build(
        req=jnp.asarray(req),
        est=jnp.asarray(est),
        is_prod=jnp.asarray(rng.uniform(size=n_pods) < 0.5),
        is_daemonset=jnp.zeros(n_pods, bool),
    )
    weights = np.zeros(NUM_RESOURCES, dtype=np.int32)
    weights[ResourceName.CPU] = 1
    weights[ResourceName.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, dtype=np.int32)
    thresholds[ResourceName.CPU] = 65
    thresholds[ResourceName.MEMORY] = 95
    params = ScoreParams(
        weights=jnp.asarray(weights),
        thresholds=jnp.asarray(thresholds),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    return state, pods, params


def full_feature_problem(n_nodes, n_pods, n_quota, n_gangs, n_resv, seed):
    """Quota + gang + NUMA + reservation inputs at the given shape
    (shared by the sharded-identity tests and the driver dryrun)."""
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import NumaAux, ResvArrays
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.ops.quota import QuotaState

    state, pods, params = example_problem(n_nodes, n_pods, seed=seed)
    rng = np.random.default_rng(seed)
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(numa_cap=jnp.asarray(cap),
                           numa_free=jnp.asarray(free))
    gang_id = np.full(n_pods, -1, np.int32)
    gang_id[: n_gangs * 8] = np.repeat(np.arange(n_gangs, dtype=np.int32), 8)
    pods = pods._replace(
        quota_id=jnp.asarray(rng.integers(0, n_quota, n_pods).astype(np.int32)),
        gang_id=jnp.asarray(gang_id),
        has_numa_policy=jnp.asarray(rng.uniform(size=n_pods) < 0.4),
        non_preemptible=jnp.asarray(rng.uniform(size=n_pods) < 0.3),
    )
    total = cap.astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mn[:, ResourceName.CPU] = total[ResourceName.CPU] // (2 * n_quota)
    mn[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // (2 * n_quota)
    mx[:, ResourceName.CPU] = total[ResourceName.CPU] // 8
    mx[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // 8
    qid = np.asarray(pods.quota_id)
    req_np = np.asarray(pods.req).astype(np.int64)
    child_request = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    np.add.at(child_request, qid, req_np)
    quota_state = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=child_request,
    )
    gang_state = GangState.build(min_member=[8] * n_gangs)
    numa_aux = NumaAux(
        node_policy=jnp.asarray(rng.uniform(size=n_nodes) < 0.5)
    )
    node_of = rng.integers(0, n_nodes, n_resv).astype(np.int32)
    rfree = np.zeros((n_resv, NUM_RESOURCES), np.int32)
    rfree[:, ResourceName.CPU] = rng.integers(500, 4000, n_resv)
    rfree[:, ResourceName.MEMORY] = rng.integers(500, 4000, n_resv)
    match = np.zeros((n_pods, n_resv), bool)
    for v in range(n_resv):
        lo = (v * 16) % max(n_pods - 16, 1)
        match[lo:lo + 16, v] = True
    resv = ResvArrays(
        node=jnp.asarray(node_of), free=jnp.asarray(rfree),
        allocate_once=jnp.asarray(rng.uniform(size=n_resv) < 0.5),
        match=jnp.asarray(match),
    )
    return state, pods, params, quota_state, gang_state, numa_aux, resv


def assert_full_identity(sharded, single, n_devices=8):
    """Bit-identity of a sharded full-feature SolveResult against the
    single-device one, across every mutated carry."""
    np.testing.assert_array_equal(
        np.asarray(sharded.assign), np.asarray(single.assign)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.commit), np.asarray(single.commit)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.used_req),
        np.asarray(single.node_state.used_req),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.numa_free),
        np.asarray(single.node_state.numa_free),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.quota_state.used),
        np.asarray(single.quota_state.used),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.resv_free), np.asarray(single.resv_free)
    )
    assert len(sharded.node_state.used_req.devices()) == n_devices
    assert int(np.asarray(sharded.commit).sum()) > 0


def example_resv(n_resv, n_nodes, n_pods, seed=9):
    """A random-but-seeded reservation table (shared by the sharded
    kernel tests and the driver dryrun so the two can't drift)."""
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import ResvArrays

    rng = np.random.default_rng(seed)
    free = np.zeros((n_resv, NUM_RESOURCES), np.int32)
    free[:, ResourceName.CPU] = rng.integers(500, 60000, n_resv)
    free[:, ResourceName.MEMORY] = rng.integers(0, 8192, n_resv)
    return ResvArrays(
        node=jnp.asarray(rng.integers(0, n_nodes, n_resv).astype(np.int32)),
        free=jnp.asarray(free),
        allocate_once=jnp.asarray(rng.uniform(size=n_resv) < 0.4),
        match=jnp.asarray(rng.uniform(size=(n_pods, n_resv)) < 0.3),
    )


def churn_world(n_nodes, *, assigned_per_node=2, seed=42,
                with_tracker=False):
    """The seeded typed churn world shared by bench legs 9/14 and the
    sharded-staging tests: ``n_nodes`` uniform nodes, ``assigned_per_node
    * n_nodes`` randomly-bound pods, full metric coverage at t=10, a
    snapshot at now=20 with an optional :class:`ClusterDeltaTracker`.
    One definition so the three churn harnesses can never drift apart
    in workload shape. Returns ``(snapshot, tracker)``."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.state.cluster import ClusterDeltaTracker

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    rng = np.random.default_rng(seed)
    nodes = [
        NodeSpec(name=f"n{i}", allocatable={CPU: 64000, MEM: 131072})
        for i in range(n_nodes)
    ]
    pods = []
    for j in range(assigned_per_node * n_nodes):
        node_i = int(rng.integers(0, n_nodes))
        pods.append(PodSpec(
            name=f"a{j}", node_name=f"n{node_i}", assign_time=5.0,
            requests={CPU: int(rng.integers(200, 2000)),
                      MEM: int(rng.integers(128, 2048))},
        ))
    metrics = {
        f"n{i}": NodeMetric(
            node_name=f"n{i}",
            node_usage={CPU: int(rng.integers(500, 30000)),
                        MEM: int(rng.integers(512, 65536))},
            update_time=10.0,
        )
        for i in range(n_nodes)
    }
    tracker = ClusterDeltaTracker() if with_tracker else None
    snap = ClusterSnapshot(
        nodes=nodes, pods=pods, pending_pods=[],
        node_metrics=metrics, now=20.0, delta_tracker=tracker,
    )
    return snap, tracker


def churn_tick_events(snap, tracker, rng, *, dirty, pending, t, now):
    """One churn tick's mutation stream, applied in place: ``dirty``
    random nodes get a fresh NodeMetric (pod_usages preserved, tracker
    marked) and a ``pending``-pod wave lands in ``snap.pending_pods``;
    ``snap.now`` advances to ``now``. Returns ``{uid: pod}`` of the
    wave for :func:`fold_churn_binds`. The rng draw ORDER is the
    contract — bench legs and tests replaying the same seed must see
    identical worlds."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, PodSpec

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = len(snap.nodes)
    for i in rng.choice(n_nodes, dirty, replace=False):
        name = snap.nodes[int(i)].name
        old = snap.node_metrics[name]
        snap.node_metrics[name] = NodeMetric(
            node_name=name,
            node_usage={CPU: int(rng.integers(500, 30000)),
                        MEM: int(rng.integers(512, 65536))},
            update_time=now,
            pod_usages=old.pod_usages,
        )
        if tracker is not None:
            tracker.mark_node(name)
    snap.pending_pods = [
        PodSpec(
            name=f"t{t}p{j}",
            requests={CPU: int(rng.integers(200, 1500)),
                      MEM: int(rng.integers(128, 1024))},
        )
        for j in range(pending)
    ]
    snap.now = now
    return {p.uid: p for p in snap.pending_pods}


def fold_churn_binds(snap, tracker, result, by_uid, now):
    """Fold one tick's committed placements back into the world: the
    placed pods become assigned pods (tracker marked per node) so the
    next tick's lowering sees them."""
    for uid, node in result.items():
        if node is not None:
            pod = by_uid[uid]
            pod.node_name = node
            pod.assign_time = now
            snap.pods.append(pod)
            if tracker is not None:
                tracker.mark_node(node)
