"""Leader election over the bus: lease + fencing tokens.

Reference: every koordinator binary is leader-elected through a
client-go resource lock before its loops start
(cmd/koord-scheduler/app/server.go:226-252 LeaderCallbacks,
cmd/koord-manager/main.go:123-126 LeaderElection options). The HTTP
lease machinery reduces, on the in-process bus, to a Lease object whose
acquisition is an atomic read-modify-write under the store lock
(``APIServer.transact``).

Two deliberate strengthenings over the reference (which inherits
client-go's known weakness that a paused leader can still write after
losing the lease):

- every change of holder increments a **fencing token**; components
  route leader-gated bus mutations through :meth:`LeaderElector.fenced`
  which re-validates holder+token under the store lock, so a deposed
  leader's in-flight writes raise :class:`FencingError` instead of
  double-applying;
- time is injected (``now`` parameters) so failover is deterministic
  under test — no wall-clock sleeps.

The callback shape mirrors the reference: ``on_started_leading`` /
``on_stopped_leading``; losing the lease is fatal for the loop that was
gated on it (the reference exits the process; run loops here stop).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from koordinator_tpu.client.bus import APIServer, Kind


class FencingError(RuntimeError):
    """A leader-gated write carried a stale fencing token (the writer
    lost the lease between deciding and applying)."""


@dataclasses.dataclass
class Lease:
    """The coordination object (reference: coordination/v1 Lease as used
    by client-go resourcelock)."""

    holder: str
    acquire_time: float
    renew_time: float
    duration_seconds: float
    #: monotonic across holder changes — the fencing token
    token: int = 0

    def expired(self, now: float) -> bool:
        return now >= self.renew_time + self.duration_seconds


#: reference defaults (client-go leaderelection.LeaderElectionConfig)
LEASE_DURATION = 15.0
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 2.0


class LeaderElector:
    """Acquire/renew loop for one identity on one lease.

    Drive with :meth:`tick` (idempotent, safe at any cadence; production
    loops call it every ``retry_period``). Like client-go, a deposed
    leader may still observe ``is_leader() == True`` until its next tick
    (the zombie window between losing the lease and noticing) —
    ``is_leader`` is advisory. The HARD guarantee is :meth:`fenced`: at
    most one identity's fenced writes succeed per lease token, checked
    under the store lock, so a zombie's write raises :class:`FencingError`
    instead of double-applying (tests/test_concurrency.py drives 16
    electors from real threads to hold this).
    """

    def __init__(
        self,
        bus: APIServer,
        lease_name: str,
        identity: str,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.bus = bus
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._token: Optional[int] = None
        self._last_renew: Optional[float] = None

    # -- state ---------------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leading

    @property
    def token(self) -> Optional[int]:
        """The fencing token of the currently held lease (None while
        standby)."""
        return self._token

    # -- the election step ---------------------------------------------------

    def tick(self, now: float) -> bool:
        """One acquire-or-renew step; returns ``is_leader()`` after."""
        if self._leading:
            self._renew(now)
        else:
            self._try_acquire(now)
        return self._leading

    def _try_acquire(self, now: float) -> None:
        def txn():
            lease = self.bus.get(Kind.LEASE, self.lease_name)
            if lease is not None and not lease.expired(now) \
                    and lease.holder != self.identity:
                return None  # held by a live peer
            token = 1 if lease is None else (
                lease.token if lease.holder == self.identity
                else lease.token + 1
            )
            new = Lease(
                holder=self.identity,
                acquire_time=now,
                renew_time=now,
                duration_seconds=self.lease_duration,
                token=token,
            )
            self.bus.apply(Kind.LEASE, self.lease_name, new)
            return new

        got = self.bus.transact(txn)
        if got is not None:
            self._leading = True
            self._token = got.token
            self._last_renew = now
            if self.on_started_leading:
                self.on_started_leading()

    def _renew(self, now: float) -> None:
        def txn():
            lease = self.bus.get(Kind.LEASE, self.lease_name)
            if lease is None or lease.holder != self.identity \
                    or lease.token != self._token:
                return False  # deposed: someone re-acquired
            self.bus.apply(Kind.LEASE, self.lease_name, dataclasses.replace(
                lease, renew_time=now,
            ))
            return True

        last = self._last_renew if self._last_renew is not None else now
        if now - last > self.renew_deadline:
            # could not renew within the deadline: give up leadership
            # even if the lease object still names us (clock-skew safety,
            # mirrors client-go's renew-deadline semantics)
            self._demote()
            return
        if self.bus.transact(txn):
            self._last_renew = now
        else:
            self._demote()

    def _demote(self) -> None:
        self._leading = False
        self._token = None
        self._last_renew = None
        if self.on_stopped_leading:
            self.on_stopped_leading()

    # -- fenced writes -------------------------------------------------------

    def fenced(self, fn: Callable[[], object]) -> object:
        """Run a bus mutation only if this elector STILL holds the lease
        (checked under the store lock). Raises :class:`FencingError`
        otherwise — the caller's round aborts instead of double-applying
        a deposed leader's decision."""
        token = self._token

        def txn():
            lease = self.bus.get(Kind.LEASE, self.lease_name)
            if (
                token is None
                or lease is None
                or lease.holder != self.identity
                or lease.token != token
            ):
                raise FencingError(
                    f"{self.identity} lost lease {self.lease_name!r}"
                )
            return fn()

        return self.bus.transact(txn)

    def release(self) -> None:
        """Voluntarily step down (graceful shutdown): expire the lease in
        place so a standby can take over without waiting out the
        duration. The lease object is KEPT (holder cleared, token
        preserved) — deleting it would reset the token sequence to 1 and
        let a later holder reuse an old token, breaking the fencing
        tokens' monotonicity that external consumers order by."""
        def txn():
            lease = self.bus.get(Kind.LEASE, self.lease_name)
            if lease is not None and lease.holder == self.identity \
                    and lease.token == self._token:
                self.bus.apply(Kind.LEASE, self.lease_name, dataclasses.replace(
                    lease, holder="", renew_time=float("-inf"),
                ))

        if self._leading:
            self.bus.transact(txn)
            self._demote()
