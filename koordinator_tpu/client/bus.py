"""Typed object store + watch fan-out (the apiserver stand-in).

The reference's generated clientset/informer/lister stack (pkg/client,
6.5k LoC of codegen) reduces, for an in-process control plane, to: a
store keyed (kind, name), ``apply``/``delete`` mutations, ``get``/
``list`` reads, and ``watch`` subscriptions that replay existing objects
then receive every subsequent event — informer semantics without the
HTTP/CRD machinery.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Dict, List, Optional


class Kind(str, enum.Enum):
    """Object kinds on the bus (the CRD groups of SURVEY.md §2.6)."""

    NODE = "Node"
    POD = "Pod"
    NODE_METRIC = "NodeMetric"
    NODE_SLO = "NodeSLO"
    QUOTA = "ElasticQuota"
    QUOTA_PROFILE = "ElasticQuotaProfile"
    GANG = "PodGroup"
    RESERVATION = "Reservation"
    DEVICE = "Device"
    NODE_RESOURCE_TOPOLOGY = "NodeResourceTopology"
    MIGRATION_JOB = "PodMigrationJob"
    LEASE = "Lease"
    RECOMMENDATION = "Recommendation"
    PVC = "PersistentVolumeClaim"


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


#: watch callback: (event type, name, object)
WatchFn = Callable[[EventType, str, object], None]


class APIServer:
    """The bus. Watch callbacks run synchronously on the mutating thread
    while the (reentrant) lock is held, so event order matches store
    order exactly — the deterministic equivalent of informer delivery.
    Callbacks may re-enter the bus from the same thread (the manager loop
    PATCHes nodes from inside a reconcile)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[Kind, Dict[str, object]] = {k: {} for k in Kind}
        self._watchers: Dict[Kind, List[WatchFn]] = {k: [] for k in Kind}

    # -- mutations -----------------------------------------------------------

    def apply(self, kind: Kind, name: str, obj: object) -> None:
        with self._lock:
            existed = name in self._objects[kind]
            self._objects[kind][name] = obj
            event = EventType.MODIFIED if existed else EventType.ADDED
            for fn in list(self._watchers[kind]):
                fn(event, name, obj)

    def delete(self, kind: Kind, name: str) -> None:
        with self._lock:
            obj = self._objects[kind].pop(name, None)
            if obj is None:
                return
            for fn in list(self._watchers[kind]):
                fn(EventType.DELETED, name, obj)

    def transact(self, fn: Callable[[], object]) -> object:
        """Run ``fn`` atomically under the store lock (``fn`` may call
        get/apply/delete reentrantly) — the compare-and-swap primitive
        leader election builds its lease acquisition on."""
        with self._lock:
            return fn()

    # -- reads ---------------------------------------------------------------

    def get(self, kind: Kind, name: str) -> Optional[object]:
        with self._lock:
            return self._objects[kind].get(name)

    def list(self, kind: Kind) -> Dict[str, object]:
        with self._lock:
            return dict(self._objects[kind])

    # -- watch (informer semantics: replay, then live events) ----------------

    def watch(self, kind: Kind, fn: WatchFn) -> None:
        with self._lock:
            for name, obj in list(self._objects[kind].items()):
                fn(EventType.ADDED, name, obj)
            self._watchers[kind].append(fn)
