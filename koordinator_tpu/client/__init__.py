"""The in-process API-server bus: typed objects, watch/list, component
wiring.

Reference: SURVEY.md §1 — "the API server is the only cross-process bus":
the five components never talk to each other directly; they watch and
patch CRDs (pkg/client generated clientsets/informers/listers). Here the
bus is a typed object store with synchronous watch fan-out
(:class:`APIServer`) plus the informer-style adapters that subscribe each
component (scheduler, manager, koordlet reporter) to the kinds it
consumes and publish what it produces.
"""

from koordinator_tpu.client.bus import APIServer, Kind  # noqa: F401
from koordinator_tpu.client.wiring import (  # noqa: F401
    wire_descheduler,
    wire_koordlet,
    wire_manager,
    wire_scheduler,
)
