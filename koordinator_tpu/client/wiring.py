"""Informer adapters: subscribe components to the bus.

Each wire_* mirrors the reference component's informer registrations
(cmd/*/main.go + eventhandlers): the scheduler consumes
Node/Pod/NodeMetric/Quota/PodGroup/Reservation/Device/NRT — with a
DeleteFunc for every kind — and the manager consumes NodeMetric (+ pods
via the snapshot) and PATCHes Node allocatable back onto the bus.
"""

from __future__ import annotations

from koordinator_tpu.client.bus import APIServer, EventType, Kind


def wire_scheduler(bus: APIServer, scheduler) -> None:
    """Subscribe a Scheduler to every kind it consumes (the reference's
    informer factory in cmd/koord-scheduler/app/server.go + frameworkext
    eventhandlers)."""

    def on_node(event, name, node):
        if event is EventType.DELETED:
            scheduler.remove_node(name)
        else:
            scheduler.add_node(node)

    def on_pod(event, name, pod):
        if event is EventType.DELETED:
            scheduler.remove_pod(pod)
        else:
            # update_pod handles both first-sight and refresh without
            # re-running quota/gang registration for status-only changes
            scheduler.update_pod(pod)

    def updater(update_fn, delete_fn):
        def on_event(event, name, obj):
            if event is EventType.DELETED:
                delete_fn(name)
            else:
                update_fn(obj)

        return on_event

    bus.watch(Kind.NODE, on_node)
    bus.watch(Kind.POD, on_pod)
    bus.watch(
        Kind.NODE_METRIC,
        updater(scheduler.update_node_metric, scheduler.remove_node_metric),
    )
    bus.watch(
        Kind.QUOTA, updater(scheduler.update_quota, scheduler.remove_quota)
    )
    bus.watch(Kind.GANG, updater(scheduler.update_gang, scheduler.remove_gang))
    bus.watch(
        Kind.RESERVATION,
        updater(scheduler.update_reservation, scheduler.remove_reservation),
    )

    def on_nrt(event, name, topology):
        if event is EventType.DELETED:
            from koordinator_tpu.numa.manager import TopologyOptions

            scheduler.update_node_topology(name, TopologyOptions())
        else:
            scheduler.update_node_topology(name, topology)

    def on_device(event, name, entries):
        scheduler.update_node_devices(
            name, [] if event is EventType.DELETED else entries
        )

    bus.watch(Kind.NODE_RESOURCE_TOPOLOGY, on_nrt)
    bus.watch(Kind.DEVICE, on_device)


class ManagerLoop:
    """The slo-controller noderesource reconcile loop over the bus
    (SURVEY.md §3.3): NodeMetric + pods in, Node allocatable PATCH out."""

    def __init__(self, bus: APIServer, controller):
        self.bus = bus
        self.controller = controller

    def reconcile(self, now: float) -> int:
        """One pass; returns how many nodes were synced back to the bus."""
        from koordinator_tpu.apis.types import ClusterSnapshot

        nodes = list(self.bus.list(Kind.NODE).values())
        pods = [
            p for p in self.bus.list(Kind.POD).values()
            if getattr(p, "node_name", None) is not None
        ]
        snapshot = ClusterSnapshot(
            nodes=nodes,
            pods=pods,
            node_metrics=self.bus.list(Kind.NODE_METRIC),
            now=now,
        )
        updates = self.controller.reconcile_all(snapshot)
        synced = 0
        for update, node in zip(updates, snapshot.nodes):
            if update.synced:
                # the reference PATCHes Node.status.allocatable; here the
                # mutated NodeSpec is re-applied, fanning out to watchers
                self.bus.apply(Kind.NODE, node.name, node)
                synced += 1
        return synced


def wire_manager(bus: APIServer, controller=None) -> ManagerLoop:
    from koordinator_tpu.manager.noderesource import NodeResourceController

    return ManagerLoop(bus, controller or NodeResourceController())
