"""Informer adapters: subscribe components to the bus.

Each wire_* mirrors the reference component's informer registrations
(cmd/*/main.go + eventhandlers): the scheduler consumes
Node/Pod/NodeMetric/Quota/PodGroup/Reservation/Device/NRT — with a
DeleteFunc for every kind — and the manager consumes NodeMetric (+ pods
via the snapshot) and PATCHes Node allocatable back onto the bus.
"""

from __future__ import annotations

import dataclasses

from koordinator_tpu.client.bus import APIServer, EventType, Kind
from koordinator_tpu.obs.trace import TRACER


def transform_node(node):
    """Scheduler-side node transform (reference: pkg/util/transformer/
    node_transformer.go TransformNodeWithNodeReservation +
    util.TrimNodeAllocatableByNodeReservation, node.go:121-150): subtract
    the node-reservation annotation's resources from allocatable before
    the scheduler's cache sees the node. Only the Default apply policy
    trims (ReservedCPUsOnly reserves cores without shrinking schedulable
    totals); malformed annotations leave the node untouched. Returns a
    COPY when trimming — the in-process bus shares objects, and other
    watchers (the manager's overcommit math reads the annotation itself)
    must keep the raw view.
    """
    from koordinator_tpu.apis.extension import (
        ResourceName,
        parse_node_reservation,
    )

    spec = parse_node_reservation(node.annotations)
    if spec is None or spec["apply_policy"] not in ("", "Default"):
        return node
    cpu, mem = spec["cpu"], spec["memory"]
    if cpu <= 0 and mem <= 0:
        return node
    alloc = dict(node.allocatable)
    if cpu > 0:
        alloc[ResourceName.CPU] = max(
            alloc.get(ResourceName.CPU, 0) - cpu, 0
        )
    if mem > 0:
        alloc[ResourceName.MEMORY] = max(
            alloc.get(ResourceName.MEMORY, 0) - mem, 0
        )
    return dataclasses.replace(node, allocatable=alloc)


def _updater(update_fn, delete_fn):
    """Bus watch adapter: DELETED events dispatch by name, everything
    else by object."""

    def on_event(event, name, obj):
        if event is EventType.DELETED:
            delete_fn(name)
        else:
            update_fn(obj)

    return on_event


def wire_scheduler(bus: APIServer, scheduler, elector=None) -> None:
    """Subscribe a Scheduler to every kind it consumes (the reference's
    informer factory in cmd/koord-scheduler/app/server.go + frameworkext
    eventhandlers). With ``elector`` (a LeaderElector), leader-gated bus
    mutations (victim eviction) are fenced: a deposed leader's in-flight
    eviction raises FencingError instead of double-applying."""

    def on_node(event, name, node):
        if event is EventType.DELETED:
            scheduler.remove_node(name)
        else:
            # informer-level node transform: trim allocatable by the
            # node-reservation annotation before the scheduler sees it
            scheduler.add_node(transform_node(node))

    # bus key per pod uid: conventionally identical, but eviction must
    # delete the key the pod was actually applied under
    pod_bus_name = {}

    def on_pod(event, name, pod):
        if event is EventType.DELETED:
            pod_bus_name.pop(pod.uid, None)
            scheduler.remove_pod(pod)
        else:
            pod_bus_name[pod.uid] = name
            # update_pod handles both first-sight and refresh without
            # re-running quota/gang registration for status-only changes
            scheduler.update_pod(pod)

    updater = _updater

    bus.watch(Kind.NODE, on_node)
    bus.watch(Kind.POD, on_pod)
    bus.watch(
        Kind.NODE_METRIC,
        updater(scheduler.update_node_metric, scheduler.remove_node_metric),
    )
    bus.watch(
        Kind.QUOTA, updater(scheduler.update_quota, scheduler.remove_quota)
    )
    bus.watch(Kind.GANG, updater(scheduler.update_gang, scheduler.remove_gang))
    bus.watch(
        Kind.RESERVATION,
        updater(scheduler.update_reservation, scheduler.remove_reservation),
    )

    def on_nrt(event, name, topology):
        if event is EventType.DELETED:
            from koordinator_tpu.numa.manager import TopologyOptions

            scheduler.update_node_topology(name, TopologyOptions())
        else:
            scheduler.update_node_topology(name, topology)

    def on_device(event, name, entries):
        scheduler.update_node_devices(
            name, [] if event is EventType.DELETED else entries
        )

    bus.watch(Kind.NODE_RESOURCE_TOPOLOGY, on_nrt)
    bus.watch(Kind.DEVICE, on_device)

    # bindings must be PUBLISHED through the bus (the reference's Bind
    # goes to the API server) so node agents and controllers observe
    # them: re-apply each newly committed pod after the round. The
    # resulting MODIFIED event re-enters update_pod, which handles
    # refreshes of assigned pods idempotently.
    inner_schedule = scheduler.schedule_pending

    def publish_result(out):
        t0 = TRACER.now()
        published = 0
        for uid, node in out.items():
            if node is None:
                continue
            key = pod_bus_name.get(uid, uid)
            pod = bus.get(Kind.POD, key)
            if pod is not None and getattr(pod, "node_name", None) == node:
                bus.apply(Kind.POD, key, pod)
                # the bind is now observable on the bus: confirm the
                # assume (the reference's finishBinding on the bind
                # confirmation). Confirm ONLY what actually published —
                # everything left in cache.assumed is exactly the
                # unpublished in-flight state a FencingError abort must
                # forget and the auditor's lingering-assume check hunts;
                # a skipped publish (the pod vanished or was replaced
                # mid-round) must stay forgettable.
                scheduler.cache.finish_binding(uid)
                # the bind is observable: close the pod's timeline
                # (observes scheduler_pod_e2e_seconds by QoS lane)
                scheduler.timelines.published(uid)
                published += 1
        TRACER.emit("publish", cat="publish", t0=t0,
                    args={"published": published})

    def schedule_and_publish(now=None, trigger=None):
        out = inner_schedule(now=now, trigger=trigger)
        # watchdog mark: the serial loop publishes inline (the
        # pipelined path opens its own mark from the publisher
        # worker), so without this a publish wedged on a half-open
        # connection wedges the loop with zero open marks and the
        # stuck-publish watchdog never fires
        rid = getattr(scheduler, "last_round_id", None)
        if rid is None:
            rid = TRACER.round_id
        TRACER.mark_open(f"publish:{rid}", round_id=rid)
        try:
            publish_result(out)
        finally:
            TRACER.mark_closed(f"publish:{rid}")
        return out

    scheduler.schedule_pending = schedule_and_publish
    # the pipelined loop bypasses the blocking wrapper above (it splits
    # the round across threads) and publishes through this instead,
    # from the publisher worker
    scheduler.publish_result = publish_result

    # preemption victims must be evicted THROUGH the bus (the reference
    # deletes them via the API server) so koordlet/manager/descheduler
    # observe the eviction; the DELETED event re-enters remove_pod
    def _evict(pod):
        def do():
            bus.delete(Kind.POD, pod_bus_name.get(pod.uid, pod.uid))

        if elector is not None:
            elector.fenced(do)
        else:
            do()

    scheduler.evict_pod_fn = _evict


def wire_pod_webhook(bus: APIServer, webhook) -> None:
    """Feed the pod mutating webhook's quota-tree registries from the
    bus (ElasticQuota + ElasticQuotaProfile watches) so admission can
    inject multi-quota-tree node affinity
    (multi_quota_tree_affinity.go's Client reads, informer-fed here)."""

    bus.watch(
        Kind.QUOTA, _updater(webhook.update_quota, webhook.remove_quota)
    )
    bus.watch(
        Kind.QUOTA_PROFILE,
        _updater(webhook.update_quota_profile, webhook.remove_quota_profile),
    )


def snapshot_from_bus(bus: APIServer, now: float, with_reservations=False):
    """Assigned-pod cluster snapshot from the bus (shared by the manager
    and descheduler loops)."""
    from koordinator_tpu.apis.types import ClusterSnapshot

    return ClusterSnapshot(
        nodes=list(bus.list(Kind.NODE).values()),
        # Permit-held gang members (waiting_permit) are assumed but not
        # bound: the manager must not count them as running and the
        # descheduler must never pick one as a migration victim
        pods=[
            p for p in bus.list(Kind.POD).values()
            if getattr(p, "node_name", None) is not None
            and not getattr(p, "waiting_permit", False)
        ],
        node_metrics=bus.list(Kind.NODE_METRIC),
        reservations=(
            list(bus.list(Kind.RESERVATION).values())
            if with_reservations
            else []
        ),
        now=now,
    )


class ManagerLoop:
    """The slo-controller noderesource reconcile loop over the bus
    (SURVEY.md §3.3): NodeMetric + pods in, Node allocatable PATCH out."""

    def __init__(self, bus: APIServer, controller, elector=None,
                 nodeslo=None):
        self.bus = bus
        self.controller = controller
        self.elector = elector
        #: optional NodeSLO renderer (manager/nodeslo.py) — when set,
        #: reconcile also publishes each node's rendered NodeSLO on the
        #: bus (the nodeslo_controller.go loop) for koordlets to consume
        self.nodeslo = nodeslo

    def reconcile(self, now: float) -> int:
        """One pass; returns how many nodes were synced back to the bus."""
        import dataclasses

        snapshot = snapshot_from_bus(self.bus, now)
        # the controller mutates synced nodes' allocatable in place;
        # reconcile over COPIES so a fenced-off (deposed) or failed
        # write-back leaks nothing into the shared bus objects — the
        # reference's PATCH has the same all-or-nothing property
        snapshot = dataclasses.replace(snapshot, nodes=[
            dataclasses.replace(n, allocatable=dict(n.allocatable))
            for n in snapshot.nodes
        ])
        updates = self.controller.reconcile_all(snapshot)
        synced = 0
        for update, node in zip(updates, snapshot.nodes):
            if update.synced:
                # the reference PATCHes Node.status.allocatable; here the
                # mutated NodeSpec is re-applied, fanning out to watchers.
                # Leader-elected managers fence the PATCH: a deposed
                # instance must not overwrite the new leader's numbers.
                if self.elector is not None:
                    self.elector.fenced(
                        lambda n=node: self.bus.apply(Kind.NODE, n.name, n)
                    )
                else:
                    self.bus.apply(Kind.NODE, node.name, node)
                synced += 1
        if self.nodeslo is not None:
            for node in snapshot.nodes:
                spec = self.nodeslo.render(node.name, node.labels)
                if spec != self.bus.get(Kind.NODE_SLO, node.name):
                    # fenced like the Node PATCH above: a deposed
                    # manager must not overwrite the leader's render
                    if self.elector is not None:
                        self.elector.fenced(lambda n=node.name, s=spec:
                                            self.bus.apply(Kind.NODE_SLO, n, s))
                    else:
                        self.bus.apply(Kind.NODE_SLO, node.name, spec)
        return synced


def wire_manager(bus: APIServer, controller=None, elector=None,
                 nodeslo=None) -> ManagerLoop:
    from koordinator_tpu.manager.noderesource import NodeResourceController

    return ManagerLoop(bus, controller or NodeResourceController(), elector,
                       nodeslo)


class KoordletLoop:
    """One node agent on the bus (the koordlet side of §1's layer map):
    consumes its Node, its node's pods, and its rendered NodeSLO through
    watches — the informer then fans those into runtimehooks/qosmanager —
    and reports NodeMetric status back (states_nodemetric.go sync)."""

    def __init__(self, bus: APIServer, informer, node_name: str,
                 reporter=None, pod_meta_fn=None,
                 topology_reporter=None, device_reporter=None):
        from koordinator_tpu.koordlet.statesinformer import pod_meta_from_spec

        self.bus = bus
        self.informer = informer
        self.node_name = node_name
        self.reporter = reporter
        #: optional NRT / Device CR reporters (statesinformer reporters
        #: built with koordlet_report_sinks(bus) as their sinks)
        self.topology_reporter = topology_reporter
        self.device_reporter = device_reporter
        self._meta_fn = pod_meta_fn or pod_meta_from_spec
        self._pods = {}

        def on_node(event, name, node):
            if name == node_name and event is not EventType.DELETED:
                informer.set_node(node)

        def on_slo(event, name, slo):
            if name == node_name and event is not EventType.DELETED:
                informer.set_node_slo(slo)

        def on_pod(event, name, pod):
            # a gang member waiting at the Permit barrier is assumed
            # (node_name set in the scheduler cache) but NOT bound: the
            # agent must not run it (the reference keeps WaitOnPermit
            # assumptions out of the API server)
            mine = (
                getattr(pod, "node_name", None) == node_name
                and not getattr(pod, "waiting_permit", False)
            )
            if event is EventType.DELETED or not mine:
                if self._pods.pop(pod.uid, None) is None:
                    return  # never ours: don't rebuild the pod list
            else:
                self._pods[pod.uid] = pod
            informer.set_pods(
                [self._meta_fn(p) for p in self._pods.values()]
            )

        def on_pvc(event, name, pvc):
            # claim -> bound-PV map for the blkio pod-volume resolution
            # (reference: states_pvc.go event handlers)
            if event is EventType.DELETED:
                informer.remove_pvc(name)
            else:
                informer.upsert_pvc(pvc)

        bus.watch(Kind.NODE, on_node)
        bus.watch(Kind.NODE_SLO, on_slo)
        bus.watch(Kind.POD, on_pod)
        bus.watch(Kind.PVC, on_pvc)

    def pods(self):
        return list(self._pods.values())

    def report(self, now: float):
        """Aggregate the metric cache into a NodeMetric and publish it
        (requires a NodeMetricReporter); NRT/Device reporters, when
        wired, publish through their own bus sinks."""
        if (self.reporter is None and self.topology_reporter is None
                and self.device_reporter is None):
            raise RuntimeError(
                "wire_koordlet was built without any reporter; pass "
                "reporter=/topology_reporter=/device_reporter="
            )
        metric = None
        if self.reporter is not None:
            metric = self.reporter.report(now)
            if metric is not None:
                self.bus.apply(Kind.NODE_METRIC, self.node_name, metric)
        if self.topology_reporter is not None:
            self.topology_reporter.sync()
        if self.device_reporter is not None:
            self.device_reporter.sync()
        return metric


def koordlet_report_sinks(bus: APIServer):
    """(topology_sink, device_sink) publishing the NodeResourceTopology
    and Device CRs on the bus — the ``report`` callbacks the
    statesinformer reporters take (the scheduler's NUMA manager and
    device cache consume them through wire_scheduler's watches)."""
    return (
        lambda name, options: bus.apply(
            Kind.NODE_RESOURCE_TOPOLOGY, name, options
        ),
        lambda name, entries: bus.apply(Kind.DEVICE, name, list(entries)),
    )


def wire_koordlet(bus: APIServer, informer, node_name: str, reporter=None,
                  pod_meta_fn=None, topology_reporter=None,
                  device_reporter=None) -> KoordletLoop:
    return KoordletLoop(bus, informer, node_name, reporter, pod_meta_fn,
                        topology_reporter, device_reporter)


class DeschedulerLoop:
    """The descheduling cycle over the bus (SURVEY.md §3.4): classify and
    emit PodMigrationJobs, reconcile them reservation-first — the
    destination is found by the SAME batched solver the scheduler runs
    (the reference creates a Reservation CR and lets koord-scheduler bind
    it) — then the eviction flows back as a Pod re-apply so every wired
    component observes the move."""

    def __init__(self, bus: APIServer, descheduler, place_model=None,
                 elector=None):
        from koordinator_tpu.descheduler.migration import MigrationController
        from koordinator_tpu.models.placement import PlacementModel

        if not hasattr(descheduler.evictor, "jobs"):
            # a direct evictor would mutate shared pod objects without
            # any bus event — only the migration evictor is coherent here
            raise TypeError(
                "DeschedulerLoop requires a MigrationEvictor (jobs-based) "
                "evictor; direct eviction bypasses the bus"
            )
        self.bus = bus
        self.descheduler = descheduler
        self._model = place_model or PlacementModel()
        self.controller = MigrationController(self._place)
        #: leader-elected deployments verify the lease before the
        #: mutation phase (evictions/reservations must not double-apply)
        self.elector = elector

    def _place(self, snapshot, reservation):
        """Reservation placement through the batched solver: the probe is
        the VICTIM pod's shape (requests, devices, selector, QoS) so the
        reserved node can actually host it after the eviction."""
        import dataclasses

        from koordinator_tpu.apis.types import ClusterSnapshot, PodSpec

        victim = None
        if reservation.owner_pod_uids:
            victim = next(
                (p for p in snapshot.pods
                 if p.uid == reservation.owner_pod_uids[0]), None,
            )
        if victim is not None:
            probe = dataclasses.replace(
                victim,
                name=f"__resv__{reservation.name}",
                uid=f"__resv__{reservation.name}",
                node_name=None,
                gang=None,
                quota=None,  # reservation capacity is not quota-gated
            )
        else:
            probe = PodSpec(
                name=f"__resv__{reservation.name}",
                uid=f"__resv__{reservation.name}",  # is_reserve_pod marker
                requests=dict(reservation.requests),
            )
        # the probe's __resv__ uid marks it a reserve pod: it never
        # MATCHES reservations (is_reserve_pod), but existing
        # reservations stay in the snapshot so their capacity holds
        # still count against the nodes. Nodes go through the SAME
        # node-reservation trim the scheduler's informer applies — a
        # destination probe that over-estimated a reserved node's
        # capacity would create a Reservation the scheduler can never
        # bind, looping the migration.
        out = self._model.schedule(ClusterSnapshot(
            nodes=[transform_node(n) for n in snapshot.nodes],
            pods=snapshot.pods,
            pending_pods=[probe],
            node_metrics=snapshot.node_metrics,
            reservations=snapshot.reservations,
            now=snapshot.now,
        ))
        return out.get(probe.uid)

    def run_once(self, now: float):
        from koordinator_tpu.apis.types import MigrationPhase

        snapshot = snapshot_from_bus(self.bus, now, with_reservations=True)
        pre_assign = {p.uid: p.node_name for p in snapshot.pods}
        pre_resv = {r.name for r in snapshot.reservations}
        # bus key per pod uid (conventionally identical): deletes and
        # re-applies must address the key the pod was applied under
        key_of = {p.uid: k for k, p in self.bus.list(Kind.POD).items()}
        self.descheduler.run_once(snapshot)
        evictor = self.descheduler.evictor
        jobs = list(evictor.jobs)
        migrated = []
        if jobs:
            # the reconcile COMPUTE (state machine + placement probes —
            # slow) runs outside any lock; every bus WRITE runs in one
            # fenced transaction so a leader deposed between compute and
            # apply raises FencingError with nothing half-applied
            self.controller.reconcile(snapshot, jobs)

            def apply_mutations():
                # reservation deltas only (blanket re-applies would grow
                # bus traffic and resurrect GC'd reservations)
                post = {r.name: r for r in snapshot.reservations}
                for name in pre_resv - set(post):
                    self.bus.delete(Kind.RESERVATION, name)
                for name, resv in post.items():
                    if name not in pre_resv:
                        self.bus.apply(Kind.RESERVATION, name, resv)
                for job in jobs:
                    self.bus.apply(Kind.MIGRATION_JOB, job.name, job)
                for pod in snapshot.pending_pods:
                    # the reference EVICTS (deletes) the pod and the
                    # workload recreates it. The controller already
                    # cleared node_name on the shared object, so restore
                    # it for the DELETE — the scheduler's release path
                    # (quota used, NUMA/device holds) keys off the
                    # assigned state.
                    pod.node_name = pre_assign.get(pod.uid)
                    self.bus.delete(Kind.POD, key_of.get(pod.uid, pod.uid))
                    pod.node_name = None
                    self.bus.apply(Kind.POD, key_of.get(pod.uid, pod.uid), pod)
                    migrated.append(pod.uid)

            if self.elector is not None:
                try:
                    self.elector.fenced(apply_mutations)
                except Exception:
                    # undo the controller's in-place victim mutation so
                    # the shared bus objects stay consistent with the
                    # (never-applied) eviction, and DISCARD the advanced
                    # jobs — a re-elected leader must re-detect, not
                    # publish phantom SUCCEEDED migrations
                    for pod in snapshot.pending_pods:
                        pod.node_name = pre_assign.get(pod.uid)
                    evictor.jobs = [
                        j for j in evictor.jobs
                        if j.phase in (MigrationPhase.PENDING,
                                       MigrationPhase.RUNNING)
                    ]
                    raise
            else:
                apply_mutations()
            # completed jobs leave the dedup window
            evictor.jobs = [
                j for j in evictor.jobs
                if j.phase in (MigrationPhase.PENDING, MigrationPhase.RUNNING)
            ]
        return migrated


def wire_descheduler(bus: APIServer, descheduler, place_model=None,
                     elector=None) -> DeschedulerLoop:
    return DeschedulerLoop(bus, descheduler, place_model, elector)
