"""LowNodeLoad: balance actual utilization across the pool.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/
{low_node_load.go, utilization_util.go} (see SURVEY.md A.7): classify
nodes by *real* utilization (NodeMetric) against low/high thresholds —
underutilized iff below all lows, overutilized iff above any high —
debounce with the anomaly detector, then evict the heaviest pods from
overutilized nodes while the destination pool has headroom. The
classification runs as one vectorized pass over the (nodes × resources)
matrix (``ops.rebalance.classify_nodes``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.apis.types import resources_to_vector, selector_matches
from koordinator_tpu.descheduler.anomaly import BasicDetector, State
from koordinator_tpu.descheduler.framework import BalancePlugin, Evictor
from koordinator_tpu.ops.rebalance import classify_nodes


@dataclasses.dataclass
class NodePool:
    """One node pool's thresholds (reference: LowNodeLoadNodePool)."""

    name: str = "default"
    # resource -> percent; missing resource = never triggers
    low_thresholds: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 45, ResourceName.MEMORY: 60}
    )
    high_thresholds: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 65, ResourceName.MEMORY: 80}
    )
    use_deviation_thresholds: bool = False
    node_selector: Optional[Dict[str, str]] = None
    resource_weights: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 1, ResourceName.MEMORY: 1}
    )
    # anomaly debounce (reference: LoadAnomalyCondition)
    consecutive_abnormalities: int = 1


@dataclasses.dataclass
class LowNodeLoadArgs:
    """Plugin args (reference: apis/config LowNodeLoadArgs)."""

    node_pools: Sequence[NodePool] = dataclasses.field(
        default_factory=lambda: [NodePool()]
    )
    paused: bool = False
    dry_run: bool = False
    node_fit: bool = True
    number_of_nodes: int = 0
    node_metric_expiration_seconds: Optional[float] = 180.0
    # pod filter: which pods are candidates for eviction at all
    pod_filter: Optional[Callable[[PodSpec], bool]] = None


def _percent_vec(thresholds: Dict[ResourceName, int]) -> np.ndarray:
    vec = np.full(NUM_RESOURCES, -1, dtype=np.int64)
    for r, p in thresholds.items():
        vec[int(r)] = p
    return vec


class LowNodeLoad(BalancePlugin):
    name = "LowNodeLoad"

    def __init__(self, args: Optional[LowNodeLoadArgs] = None):
        self.args = args or LowNodeLoadArgs()
        self.detectors: Dict[str, BasicDetector] = {}

    # -- usage gathering (reference: utilization_util.go getNodeUsage) -----
    def _gather(self, pool: NodePool, snapshot: ClusterSnapshot,
                processed: set):
        nodes: List[NodeSpec] = []
        for node in snapshot.nodes:
            if node.name in processed:
                continue
            if not selector_matches(pool.node_selector, node.labels):
                continue
            nodes.append(node)
        usage = np.zeros((len(nodes), NUM_RESOURCES), dtype=np.int64)
        alloc = np.zeros((len(nodes), NUM_RESOURCES), dtype=np.int64)
        fresh = np.zeros(len(nodes), dtype=bool)
        schedulable = np.zeros(len(nodes), dtype=bool)
        expiry = self.args.node_metric_expiration_seconds
        for i, node in enumerate(nodes):
            alloc[i] = resources_to_vector(node.allocatable)
            schedulable[i] = not node.unschedulable
            metric = snapshot.node_metrics.get(node.name)
            if metric is None:
                continue
            if expiry is not None and snapshot.now - metric.update_time > expiry:
                continue
            fresh[i] = True
            usage[i] = resources_to_vector(metric.node_usage)
        return nodes, usage, alloc, fresh, schedulable

    # -- the Balance extension point (reference: low_node_load.go:134) -----
    def balance(self, snapshot: ClusterSnapshot, evictor: Evictor) -> None:
        if self.args.paused:
            return
        processed: set = set()
        for pool in self.args.node_pools:
            self._process_pool(pool, snapshot, evictor, processed)

    def _process_pool(self, pool: NodePool, snapshot: ClusterSnapshot,
                      evictor: Evictor, processed: set) -> None:
        nodes, usage, alloc, fresh, schedulable = self._gather(
            pool, snapshot, processed
        )
        if not nodes:
            return
        verdict = classify_nodes(
            jnp.asarray(usage),
            jnp.asarray(alloc),
            jnp.asarray(_percent_vec(pool.low_thresholds)),
            jnp.asarray(_percent_vec(pool.high_thresholds)),
            jnp.asarray(fresh),
            jnp.asarray(schedulable),
            use_deviation=pool.use_deviation_thresholds,
        )
        low = np.asarray(verdict.low)
        high = np.asarray(verdict.high)
        over_res = np.asarray(verdict.over_resource)
        high_q = np.asarray(verdict.high_quantity)

        source_idx = [i for i in np.flatnonzero(high)]
        for i in source_idx:
            processed.add(nodes[i].name)
        # a normal observation breaks mid-load nodes' abnormal streaks so
        # non-consecutive spikes don't accumulate (the reference expires
        # streaks via the detector cache timeout; an explicit normal mark
        # is the equivalent debounce)
        high_names = {nodes[i].name for i in source_idx}
        for i in range(len(nodes)):
            if fresh[i] and nodes[i].name not in high_names:
                det = self.detectors.get(nodes[i].name)
                if det is not None:
                    det.mark(True)
        if not source_idx:
            return

        # anomaly debounce (reference: :258 filterRealAbnormalNodes)
        abnormal_idx = []
        for i in source_idx:
            det = self.detectors.get(nodes[i].name)
            if det is None:
                det = self.detectors[nodes[i].name] = BasicDetector(
                    nodes[i].name,
                    consecutive_abnormalities=pool.consecutive_abnormalities,
                )
            if (
                pool.consecutive_abnormalities <= 1
                or det.mark(False) == State.ANOMALY
            ):
                abnormal_idx.append(i)
        if not abnormal_idx:
            return

        low_idx = list(np.flatnonzero(low))
        for i in low_idx:
            det = self.detectors.get(nodes[i].name)
            if det is not None:
                det.reset()
        if not low_idx:
            return
        if len(low_idx) <= self.args.number_of_nodes:
            return
        if len(low_idx) == len(nodes):
            return

        # destination headroom: Σ over low nodes of (high threshold − usage),
        # tracked only on thresholded resources (the reference's
        # resourceNames set — union of low and high threshold names,
        # utilization_util.go newThresholds)
        thresholded = (
            (_percent_vec(pool.low_thresholds) >= 0)
            | (_percent_vec(pool.high_thresholds) >= 0)
        )
        available = np.zeros(NUM_RESOURCES, dtype=np.int64)
        for i in low_idx:
            available += high_q[i] - usage[i]

        weights = np.zeros(NUM_RESOURCES, dtype=np.int64)
        for r, w in pool.resource_weights.items():
            weights[int(r)] = w

        # heaviest source nodes first (reference: sortNodesByUsage desc)
        def node_score(i):
            cap = np.maximum(alloc[i], 1)
            pct = usage[i] * 100 // cap
            wsum = max(int(weights.sum()), 1)
            return int((pct * weights).sum() // wsum)

        abnormal_idx.sort(key=node_score, reverse=True)
        # one pass over the pod list, not one per source node
        pods_by_node: Dict[str, List[PodSpec]] = {}
        for pod in snapshot.pods:
            if pod.node_name:
                pods_by_node.setdefault(pod.node_name, []).append(pod)
        low_arr = np.asarray(low_idx, dtype=np.int64)
        for i in abnormal_idx:
            self._evict_from_node(
                pool, snapshot, evictor, nodes[i],
                pods_by_node.get(nodes[i].name, []), usage[i], high_q[i],
                over_res[i], available, thresholded, weights,
                alloc, usage, low_arr,
            )

    def _pod_usage(self, snapshot, pod) -> np.ndarray:
        metric = snapshot.node_metrics.get(pod.node_name or "")
        if metric is not None and pod.uid in metric.pod_usages:
            return resources_to_vector(metric.pod_usages[pod.uid])
        return resources_to_vector(pod.requests)

    def _evict_from_node(
        self, pool, snapshot, evictor, node, node_pods, node_usage,
        node_high_q, node_over, available, thresholded, weights, alloc,
        usage, low_arr,
    ) -> None:
        removable = []
        for pod in node_pods:
            if pod.is_daemonset:
                continue
            if self.args.pod_filter is not None and not self.args.pod_filter(pod):
                continue
            if not evictor.filter(pod):
                continue
            if self.args.node_fit and not self._fits_any(
                pod, alloc, usage, low_arr
            ):
                continue
            removable.append(pod)
        if not removable:
            return

        # evict biggest consumers of the *overused* resources first
        # (reference: sortPodsOnOneOverloadedNode — weights zeroed for
        # resources the node is not overusing)
        over_weights = np.where(node_over, weights, 0)
        cap = np.maximum(resources_to_vector(node.allocatable), 1)
        wsum = max(int(over_weights.sum()), 1)

        def pod_score(pod):
            u = self._pod_usage(snapshot, pod)
            return int((u * 100 // cap * over_weights).sum() // wsum)

        removable.sort(key=pod_score, reverse=True)
        for pod in removable:
            # stop once the node is back under every high threshold or the
            # destination headroom is gone (reference: continueEvictionCond)
            if not ((node_usage > node_high_q).any()):
                det = self.detectors.get(node.name)
                if det is not None:
                    det.reset()
                return
            if (available[thresholded] <= 0).any():
                return
            if not evictor.evict(snapshot, pod, reason=(
                f"node {node.name} over-utilized"
            )):
                continue
            u = self._pod_usage(snapshot, pod)
            available -= u
            node_usage -= u

    def _fits_any(self, pod, alloc, usage, low_arr) -> bool:
        """nodeFit gate (reference: nodeutil.PodFitsAnyNode): some
        underutilized node has headroom for the pod's request."""
        if low_arr.size == 0:
            return False
        req = resources_to_vector(pod.requests)
        fits = (usage[low_arr] + req[None, :]) <= alloc[low_arr]
        return bool(fits.all(axis=1).any())
