"""LowNodeLoad: balance actual utilization across the pool.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/
{low_node_load.go:134-326, utilization_util.go} (see SURVEY.md A.7):
classify nodes by *real* utilization (NodeMetric) against low/high
thresholds — underutilized iff below all lows, overutilized iff above
any high — debounce with the anomaly detector, then evict the heaviest
pods from overutilized nodes while the destination pool has headroom.
The classification runs as one vectorized pass over the
(nodes × resources) matrix (``ops.rebalance``), threshold resolution in
reference-exact float64; victim ordering uses the full PodSorter chain
(``descheduler.sorter``).

Design note (getNodeUsage, utilization_util.go:132-191): the reference
recomposes node usage as systemUsage + Σ podUsage from the NodeMetric
CR. Our ``NodeMetric.node_usage`` is reported by the koordlet as exactly
that total, so the plugin reads it directly — same quantity, one hop
shorter. Pods without a metric entry behave as in the reference: they
can still be evicted, but decrement neither the node usage nor the
destination headroom (:339-352).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.apis.types import resources_to_vector, selector_matches
from koordinator_tpu.descheduler.anomaly import BasicDetector, State
from koordinator_tpu.descheduler.framework import BalancePlugin, Evictor
from koordinator_tpu.descheduler.sorter import (
    pod_sort_key_from_static,
    pod_sort_static,
    resource_usage_score,
)
from koordinator_tpu.ops.rebalance import classify_nodes, threshold_quantities


@dataclasses.dataclass
class NodePool:
    """One node pool's thresholds (reference: LowNodeLoadNodePool)."""

    name: str = "default"
    # resource -> percent; missing resource = never triggers
    low_thresholds: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 45, ResourceName.MEMORY: 60}
    )
    high_thresholds: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 65, ResourceName.MEMORY: 80}
    )
    use_deviation_thresholds: bool = False
    node_selector: Optional[Dict[str, str]] = None
    resource_weights: Dict[ResourceName, int] = dataclasses.field(
        default_factory=lambda: {ResourceName.CPU: 1, ResourceName.MEMORY: 1}
    )
    # anomaly debounce (reference: LoadAnomalyCondition)
    consecutive_abnormalities: int = 1


@dataclasses.dataclass
class LowNodeLoadArgs:
    """Plugin args (reference: apis/config LowNodeLoadArgs)."""

    node_pools: Sequence[NodePool] = dataclasses.field(
        default_factory=lambda: [NodePool()]
    )
    paused: bool = False
    dry_run: bool = False
    node_fit: bool = True
    number_of_nodes: int = 0
    node_metric_expiration_seconds: Optional[float] = 180.0
    # pod filter: which pods are candidates for eviction at all
    pod_filter: Optional[Callable[[PodSpec], bool]] = None
    # eviction-sweep backend: "host" walks nodes/pods in Python
    # (reference-shaped, the bit-parity oracle); "device" runs the
    # ordered sweep as one lax.scan over the flattened candidate list
    # (ops.rebalance.run_balance_sweep); "verify" runs the device sweep
    # and asserts its decision stream bit-equal to a pure-host replica
    # before applying anything
    backend: str = "host"


def _percent_vec(thresholds: Dict[ResourceName, int]) -> np.ndarray:
    vec = np.full(NUM_RESOURCES, -1, dtype=np.int64)
    for r, p in thresholds.items():
        vec[int(r)] = p
    return vec


class LowNodeLoad(BalancePlugin):
    name = "LowNodeLoad"

    def __init__(self, args: Optional[LowNodeLoadArgs] = None):
        self.args = args or LowNodeLoadArgs()
        self.detectors: Dict[str, BasicDetector] = {}
        #: dry-run mode: the would-be evictions of the last balance pass,
        #: in order (the reference logs them; this is the queryable form)
        self.last_proposals: List = []
        #: per-snapshot pod cache (see _process_pool); initialized here
        #: so direct _process_pool calls work too
        self._sweep_cache: Dict[str, tuple] = {}
        self._cache_snapshot = None

    # -- usage gathering (reference: utilization_util.go getNodeUsage) -----
    def _gather(self, pool: NodePool, snapshot: ClusterSnapshot,
                processed: set):
        nodes: List[NodeSpec] = []
        for node in snapshot.nodes:
            if node.name in processed:
                continue
            if not selector_matches(pool.node_selector, node.labels):
                continue
            nodes.append(node)
        usage = np.zeros((len(nodes), NUM_RESOURCES), dtype=np.int64)
        alloc = np.zeros((len(nodes), NUM_RESOURCES), dtype=np.int64)
        fresh = np.zeros(len(nodes), dtype=bool)
        schedulable = np.zeros(len(nodes), dtype=bool)
        expiry = self.args.node_metric_expiration_seconds
        for i, node in enumerate(nodes):
            alloc[i] = resources_to_vector(node.allocatable)
            schedulable[i] = not node.unschedulable
            metric = snapshot.node_metrics.get(node.name)
            if metric is None:
                continue
            if expiry is not None and snapshot.now - metric.update_time > expiry:
                continue
            fresh[i] = True
            usage[i] = resources_to_vector(metric.node_usage)
        return nodes, usage, alloc, fresh, schedulable

    # -- the Balance extension point (reference: low_node_load.go:134) -----
    def balance(self, snapshot: ClusterSnapshot, evictor: Evictor) -> None:
        if self.args.paused:
            return
        if self.args.backend not in ("host", "device", "verify"):
            raise ValueError(
                f"unknown rebalance backend {self.args.backend!r} "
                "(expected host | device | verify)"
            )
        self.last_proposals = []
        try:
            processed: set = set()
            for pool in self.args.node_pools:
                self._process_pool(pool, snapshot, evictor, processed)
        finally:
            # release the per-snapshot cache so a finished (or
            # never-again-invoked) plugin doesn't pin pod data
            self._sweep_cache = {}
            self._cache_snapshot = None

    def _pod_cached(self, pod) -> tuple:
        """(pod_sort_static prefix, request vector) for this sweep."""
        ent = self._sweep_cache.get(pod.uid)
        if ent is None:
            ent = (pod_sort_static(pod), resources_to_vector(pod.requests))
            self._sweep_cache[pod.uid] = ent
        return ent

    def _process_pool(self, pool: NodePool, snapshot: ClusterSnapshot,
                      evictor: Evictor, processed: set) -> None:
        # pod cache: uid -> (static sort prefix, request vector). Pod
        # specs are immutable for a given snapshot object, so the
        # static key parts and the request lowering are computed once
        # per pod instead of once per comparator/filter call; a NEW
        # snapshot (direct _process_pool callers included) resets it.
        if self._cache_snapshot is not snapshot:
            self._sweep_cache = {}
            self._cache_snapshot = snapshot
        nodes, usage, alloc, fresh, schedulable = self._gather(
            pool, snapshot, processed
        )
        if not nodes:
            return
        low_q, high_q, res_mask = threshold_quantities(
            usage, alloc,
            _percent_vec(pool.low_thresholds),
            _percent_vec(pool.high_thresholds),
            fresh,
            use_deviation=pool.use_deviation_thresholds,
        )
        verdict = classify_nodes(
            usage, low_q, high_q, res_mask, fresh, schedulable
        )
        low = verdict.low
        high = verdict.high

        source_idx = [i for i in np.flatnonzero(high)]
        for i in source_idx:
            processed.add(nodes[i].name)
        # a normal observation breaks mid-load nodes' abnormal streaks so
        # non-consecutive spikes don't accumulate (the reference expires
        # streaks via the detector cache timeout; an explicit normal mark
        # is the equivalent debounce)
        high_names = {nodes[i].name for i in source_idx}
        for i in range(len(nodes)):
            if fresh[i] and nodes[i].name not in high_names:
                det = self.detectors.get(nodes[i].name)
                if det is not None:
                    det.mark(True)
        if not source_idx:
            return

        # anomaly debounce (reference: :258 filterRealAbnormalNodes)
        abnormal_idx = []
        for i in source_idx:
            det = self.detectors.get(nodes[i].name)
            if det is None:
                det = self.detectors[nodes[i].name] = BasicDetector(
                    nodes[i].name,
                    consecutive_abnormalities=pool.consecutive_abnormalities,
                )
            if (
                pool.consecutive_abnormalities <= 1
                or det.mark(False) == State.ANOMALY
            ):
                abnormal_idx.append(i)
        if not abnormal_idx:
            return

        low_idx = list(np.flatnonzero(low))
        for i in low_idx:
            det = self.detectors.get(nodes[i].name)
            if det is not None:
                det.reset()
        if not low_idx:
            return
        if len(low_idx) <= self.args.number_of_nodes:
            return
        if len(low_idx) == len(nodes):
            return

        # destination headroom: Σ over low nodes of (high threshold −
        # usage), tracked on the participating resourceNames only
        # (evictPodsFromSourceNodes:247-267)
        available = np.zeros(NUM_RESOURCES, dtype=np.int64)
        for i in low_idx:
            available += high_q[i] - usage[i]

        weights = np.zeros(NUM_RESOURCES, dtype=np.int64)
        for r, w in pool.resource_weights.items():
            weights[int(r)] = w
        # the reference scorer iterates the node usage map, whose keys
        # are exactly resourceNames — weights outside that set never
        # contribute to score or weight-sum
        weights = np.where(res_mask, weights, 0)

        # heaviest source nodes first (reference: sortNodesByUsage desc,
        # sorter.ResourceUsageScorer — weighted mean of 1000-scale
        # mostRequestedScore over resourceNames)
        res_idx = [int(r) for r in np.flatnonzero(res_mask)]

        def node_score(i):
            u = {r: int(usage[i][r]) for r in res_idx}
            a = {r: int(alloc[i][r]) for r in res_idx}
            w = {r: int(weights[r]) for r in res_idx}
            return resource_usage_score(u, a, w)

        abnormal_idx.sort(key=node_score, reverse=True)
        # one pass over the pod list, not one per source node
        pods_by_node: Dict[str, List[PodSpec]] = {}
        for pod in snapshot.pods:
            if pod.node_name:
                pods_by_node.setdefault(pod.node_name, []).append(pod)
        low_arr = np.asarray(low_idx, dtype=np.int64)
        fits_any = _FitProbe(alloc[low_arr] - usage[low_arr])
        if self.args.backend in ("device", "verify"):
            self._sweep_device(
                pool, snapshot, evictor, nodes, abnormal_idx,
                pods_by_node, usage, high_q, available, res_mask,
                weights, fits_any,
                verify=(self.args.backend == "verify"),
            )
        else:
            for i in abnormal_idx:
                self._evict_from_node(
                    pool, snapshot, evictor, nodes[i],
                    pods_by_node.get(nodes[i].name, []), usage[i],
                    high_q[i], available, res_mask, weights, fits_any,
                )
        # one normal observation on every abnormal node at the end of
        # the pass (reference: tryMarkNodesAsNormal)
        for i in abnormal_idx:
            det = self.detectors.get(nodes[i].name)
            if det is not None:
                det.mark(True)

    def _pod_metric(self, snapshot, node, pod):
        """The pod's metric ResourceList from the SOURCE NODE's metric
        map, or None when absent (reference nodeInfo.podMetrics lookup
        :338-341 — keyed off the node being drained, so eviction
        clearing pod.node_name cannot orphan the lookup)."""
        metric = snapshot.node_metrics.get(node.name)
        if metric is not None and pod.uid in metric.pod_usages:
            return metric.pod_usages[pod.uid]
        return None

    def _removable_sorted(
        self, pool, snapshot, evictor, node, node_pods, node_usage,
        node_high_q, res_mask, weights, fits_any,
    ) -> List[PodSpec]:
        """The candidate head both backends share: filter evictable
        pods and order them under the full PodSorter chain. Keeping it
        one function is what makes host/device parity structural — the
        backends can only disagree about the sequential walk, which the
        parity suite pins."""
        removable = []
        for pod in node_pods:
            if pod.is_daemonset:
                continue
            if self.args.pod_filter is not None and not self.args.pod_filter(pod):
                continue
            if not evictor.filter(pod):
                continue
            if self.args.node_fit and not fits_any(self._pod_cached(pod)[1]):
                continue
            removable.append(pod)
        if not removable:
            return removable

        # evict biggest consumers of the *overused* resources first,
        # under the full PodSorter chain (priority class, priority, QoS,
        # costs, usage desc, creation) — sortPodsOnOneOverloadedNode:
        # weights restricted to resources the node is overusing
        over = (node_usage > node_high_q) & res_mask
        over_weights = {
            ResourceName(r): int(weights[r]) for r in np.flatnonzero(over)
        }
        removable.sort(key=lambda pod: pod_sort_key_from_static(
            self._pod_cached(pod)[0],
            self._pod_metric(snapshot, node, pod), node.allocatable,
            over_weights,
        ))
        return removable

    def _evict_from_node(
        self, pool, snapshot, evictor, node, node_pods, node_usage,
        node_high_q, available, res_mask, weights, fits_any,
    ) -> None:
        removable = self._removable_sorted(
            pool, snapshot, evictor, node, node_pods, node_usage,
            node_high_q, res_mask, weights, fits_any,
        )
        for pod in removable:
            # stop once the node is back under every high threshold or the
            # destination headroom is gone (reference: continueEvictionCond)
            if not ((node_usage > node_high_q) & res_mask).any():
                det = self.detectors.get(node.name)
                if det is not None:
                    det.reset()
                return
            if (available[res_mask] <= 0).any():
                return
            if self.args.dry_run:
                # reference evictPods dry-run branch: log instead of
                # evicting, but keep the sweep's accounting identical so
                # the proposals match what a live run would do
                self.last_proposals.append(pod)
            elif not evictor.evict(snapshot, pod, reason=(
                f"node {node.name} over-utilized"
            )):
                continue
            pod_metric = self._pod_metric(snapshot, node, pod)
            if pod_metric is None:
                # evicted, but with no metric there is nothing to
                # subtract (reference evictPods:339-341 continue)
                continue
            u = resources_to_vector(pod_metric)
            available -= np.where(res_mask, u, 0)
            node_usage -= np.where(res_mask, u, 0)

    # -- the device backend (docs/DESIGN.md §27) ---------------------------
    def _sweep_device(
        self, pool, snapshot, evictor, nodes, abnormal_idx, pods_by_node,
        usage, high_q, available, res_mask, weights, fits_any,
        verify=False,
    ) -> None:
        """Run the ordered eviction walk as one scan over the flattened
        candidate list (ops.rebalance). Host preprocessing — node score
        order, per-node removable filter + PodSorter order — is the
        SAME code as the host backend; only the sequential
        check/evict/subtract walk moves to the device. Evictor refusals
        (including arbiter deferrals) feed back as a ``blocked`` mask
        and the scan re-runs: a refusal can only change decisions at or
        after its own index, so the applied prefix stays valid and the
        walk resumes in place — worst case one re-scan per refusal."""
        from koordinator_tpu.ops.rebalance import (
            SweepBatch,
            replay_sweep_host,
            run_balance_sweep,
        )

        cand_pods: List[PodSpec] = []
        cand_nodes: List[NodeSpec] = []
        rows = {"start": [], "u0": [], "hq": [], "m": [], "hm": []}
        segments = []  # (node, first candidate index, end index)
        for i in abnormal_idx:
            node = nodes[i]
            removable = self._removable_sorted(
                pool, snapshot, evictor, node,
                pods_by_node.get(node.name, []), usage[i], high_q[i],
                res_mask, weights, fits_any,
            )
            first = len(cand_pods)
            for j, pod in enumerate(removable):
                cand_pods.append(pod)
                cand_nodes.append(node)
                rows["start"].append(j == 0)
                rows["u0"].append(usage[i])
                rows["hq"].append(high_q[i])
                pod_metric = self._pod_metric(snapshot, node, pod)
                rows["hm"].append(pod_metric is not None)
                rows["m"].append(
                    np.zeros(NUM_RESOURCES, dtype=np.int64)
                    if pod_metric is None
                    else resources_to_vector(pod_metric)
                )
            segments.append((node, first, len(cand_pods)))
        k = len(cand_pods)
        if k == 0:
            return
        batch = SweepBatch(
            node_start=np.asarray(rows["start"], bool),
            usage0=np.stack(rows["u0"]).astype(np.int64),
            high_q=np.stack(rows["hq"]).astype(np.int64),
            metric=np.stack(rows["m"]).astype(np.int64),
            has_metric=np.asarray(rows["hm"], bool),
            valid=np.ones(k, bool),
        )
        blocked = np.zeros(k, bool)

        def run_sweep():
            got = run_balance_sweep(batch, available, res_mask, blocked)
            if verify:
                want = replay_sweep_host(batch, available, res_mask, blocked)
                for name, a, b in zip(("propose", "over", "avail_ok"),
                                      got, want):
                    if not np.array_equal(a, b):
                        raise RuntimeError(
                            "rebalance verify backend: device sweep "
                            f"{name} stream diverged from the host "
                            f"replica at candidates "
                            f"{np.flatnonzero(a != b).tolist()}"
                        )
            return got

        propose, over, avail_ok = run_sweep()
        applied = np.zeros(k, bool)
        idx = 0
        while idx < k:
            if not propose[idx] or applied[idx]:
                idx += 1
                continue
            pod = cand_pods[idx]
            if self.args.dry_run:
                self.last_proposals.append(pod)
                applied[idx] = True
                idx += 1
            elif evictor.evict(snapshot, pod, reason=(
                f"node {cand_nodes[idx].name} over-utilized"
            )):
                applied[idx] = True
                idx += 1
            else:
                blocked[idx] = True
                propose, over, avail_ok = run_sweep()
        # detector resets, replayed from the decision streams: the host
        # walk resets a node's detector iff the first candidate that
        # stops the walk on that node stops it via the under-threshold
        # check (over == False, checked BEFORE headroom exhaustion)
        for node, first, end in segments:
            for j in range(first, end):
                if not over[j]:
                    det = self.detectors.get(node.name)
                    if det is not None:
                        det.reset()
                    break
                if not avail_ok[j]:
                    break
        # reproduce the host's in-place pool accounting (nothing after
        # the sweep reads it today, but the contract is bit-parity of
        # state, not just of decisions)
        for j in np.flatnonzero(applied & batch.has_metric):
            available -= np.where(res_mask, batch.metric[j], 0)


class _FitProbe:
    """nodeFit gate (reference: nodeutil.PodFitsAnyNode): some
    underutilized node has headroom for the pod's request.

    Exact, with two O(R) screens before the O(low_nodes × R) scan:
    a pod whose request exceeds the columnwise max headroom fits
    nowhere, and a pod that fits the single emptiest node needs no
    scan — at bench shape (~5k low nodes) that removes ~99% of the
    full scans without changing any answer."""

    def __init__(self, headroom: np.ndarray):
        self.headroom = headroom
        if headroom.size:
            self.col_max = headroom.max(axis=0)
            # anchor: row maximizing the columnwise-normalized minimum
            # headroom (any anchor is correct; this one catches most)
            norm = headroom / np.maximum(self.col_max, 1)[None, :]
            self.anchor = headroom[int(np.argmax(norm.min(axis=1)))]

    def __call__(self, req: np.ndarray) -> bool:
        if not self.headroom.size:
            return False
        if (req > self.col_max).any():
            return False
        if (req <= self.anchor).all():
            return True
        return bool((req[None, :] <= self.headroom).all(axis=1).any())
