"""k8s-compat descheduler plugins.

Reference: pkg/descheduler/framework/plugins/kubernetes/ — the upstream
sigs-descheduler strategies adapted into the koord descheduler framework
(plugin.go:85-120 registers RemoveDuplicates,
RemovePodsHavingTooManyRestarts, RemovePodsViolatingNodeAffinity via the
adaptor). Each is a Deschedule plugin: scan the snapshot, evict
violators through the shared evictor (which enforces limits and the
migration/direct mode).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.apis.types import ClusterSnapshot, PodSpec, selector_matches
from koordinator_tpu.descheduler.framework import DeschedulePlugin, Evictor


class RemovePodsViolatingNodeAffinity(DeschedulePlugin):
    """Evict pods whose required node selector no longer matches their
    node's labels (upstream removepodsviolatingnodeaffinity with
    requiredDuringSchedulingIgnoredDuringExecution)."""

    name = "RemovePodsViolatingNodeAffinity"

    def deschedule(self, snapshot: ClusterSnapshot, evictor: Evictor) -> None:
        nodes = {node.name: node for node in snapshot.nodes}
        for pod in list(snapshot.pods):
            if pod.node_name is None or not pod.node_selector:
                continue
            node = nodes.get(pod.node_name)
            if node is None:
                continue
            if not selector_matches(pod.node_selector, node.labels):
                evictor.evict(snapshot, pod, reason=self.name)


@dataclasses.dataclass
class RemovePodsHavingTooManyRestarts(DeschedulePlugin):
    """Evict pods whose summed container restarts exceed the threshold
    (upstream removepodshavingtoomanyrestarts; default 100)."""

    pod_restart_threshold: int = 100
    name = "RemovePodsHavingTooManyRestarts"

    def deschedule(self, snapshot: ClusterSnapshot, evictor: Evictor) -> None:
        for pod in list(snapshot.pods):
            if pod.node_name is None:
                continue
            if pod.restart_count >= self.pod_restart_threshold:
                evictor.evict(snapshot, pod, reason=self.name)


class RemoveDuplicates(DeschedulePlugin):
    """Evict excess same-owner replicas sharing one node, keeping one per
    (owner, node) (upstream removeduplicates: duplicates are pods of one
    controller colocated on a node)."""

    name = "RemoveDuplicates"

    def deschedule(self, snapshot: ClusterSnapshot, evictor: Evictor) -> None:
        groups: Dict[tuple, List[PodSpec]] = {}
        for pod in snapshot.pods:
            if pod.node_name is None or pod.owner is None:
                continue
            groups.setdefault((pod.owner, pod.node_name), []).append(pod)
        for (_owner, _node), pods in sorted(groups.items()):
            if len(pods) <= 1:
                continue
            # keep the first by name; evict the rest
            for pod in sorted(pods, key=lambda p: p.name)[1:]:
                evictor.evict(snapshot, pod, reason=self.name)
