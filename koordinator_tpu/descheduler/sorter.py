"""Pod/node eviction-order comparators for the descheduler.

Semantics oracle: pkg/descheduler/utils/sorter/{pod.go, scorer.go,
helper.go}. The reference sorts with a chain of comparators under
``sort.Sort`` (MultiSorter); each comparator is a total preorder, so the
whole chain collapses into one sort key per pod — which is how it's
expressed here. Eviction order (ascending, first = evicted first):

1. Koordinator PriorityClass (free < batch < mid < prod < none)
2. numeric k8s priority (lower first)
3. Kubernetes QoS class (besteffort < burstable < guaranteed)
4. Koordinator QoS class (BE < LS < LSR < LSE/SYSTEM < NONE)
5. pod deletion cost annotation (lower first)
6. koordinator eviction cost annotation (lower first)
7. usage score, descending (heavier first; pods with no usage metric
   sort after every metered pod — sorter/pod.go:109-113 cmpBool under
   Reverse)
8. creation time, newest first

The reference's ``sort.Sort``/``sort.Slice`` are unstable, so full-tie
order is arbitrary there; both this module and the rebalance oracle
determinize full ties by input order (Python stable sort), which is one
valid refinement of the reference's unspecified order.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

from koordinator_tpu.apis.extension import PriorityClass, QoSClass
from koordinator_tpu.apis.types import PodSpec

#: sorter/pod.go koordPriorityClassOrder
KOORD_PRIORITY_ORDER: Mapping[PriorityClass, int] = {
    PriorityClass.NONE: 5,
    PriorityClass.PROD: 4,
    PriorityClass.MID: 3,
    PriorityClass.BATCH: 2,
    PriorityClass.FREE: 1,
}

#: sorter/pod.go koordQoSClassOrder
KOORD_QOS_ORDER: Mapping[QoSClass, int] = {
    QoSClass.NONE: 5,
    QoSClass.SYSTEM: 4,
    QoSClass.LSE: 4,
    QoSClass.LSR: 3,
    QoSClass.LS: 2,
    QoSClass.BE: 1,
}

#: k8s PodQOSClass order: guaranteed 3, burstable 2, besteffort 1
_KUBE_GUARANTEED, _KUBE_BURSTABLE, _KUBE_BESTEFFORT = 3, 2, 1

ANNOTATION_DELETION_COST = "controller.kubernetes.io/pod-deletion-cost"
ANNOTATION_EVICTION_COST = "koordinator.sh/eviction-cost"


def kube_qos_order(pod: PodSpec) -> int:
    """Kubernetes QoS class from requests/limits (qos.GetPodQOS):
    guaranteed iff requests == limits and BOTH cpu and memory are
    limited; besteffort iff no requests and no limits; else
    burstable."""
    from koordinator_tpu.apis.extension import ResourceName

    reqs = {k: v for k, v in pod.requests.items() if v}
    lims = {k: v for k, v in pod.limits.items() if v}
    if not reqs and not lims:
        return _KUBE_BESTEFFORT
    if (reqs == lims
            and lims.get(ResourceName.CPU)
            and lims.get(ResourceName.MEMORY)):
        return _KUBE_GUARANTEED
    return _KUBE_BURSTABLE


def _annotation_cost(pod: PodSpec, key: str) -> int:
    """Strict int cost parse (extension.GetEvictionCost:69-84 /
    k8s GetDeletionCostFromPodAnnotations): leading '+'/zeros invalid,
    malformed -> 0."""
    value = pod.annotations.get(key)
    if not value:
        return 0
    first_ok = value[0] == "-" or value == "0" or "1" <= value[0] <= "9"
    if not first_ok:
        return 0
    try:
        return int(value)
    except ValueError:
        return 0


def most_requested_score(requested: int, capacity: int) -> int:
    """sorter/scorer.go mostRequestedScore: min(requested, cap)*1000//cap,
    zero capacity scores 0."""
    if capacity == 0:
        return 0
    if requested > capacity:
        requested = capacity
    return requested * 1000 // capacity


def resource_usage_score(
    usage: Mapping, allocatable: Mapping, weights: Mapping
) -> int:
    """sorter/scorer.go ResourceUsageScorer: weighted mean of
    mostRequestedScore over the resources PRESENT IN THE USAGE MAP —
    absent resources contribute neither score nor weight, so pods
    metered on different resource sets normalize differently, exactly
    like the reference."""
    score = 0
    weight_sum = 0
    for r, q in usage.items():
        w = int(weights.get(r, 0))
        score += most_requested_score(int(q), int(allocatable.get(r, 0))) * w
        weight_sum += w
    if weight_sum == 0:
        return 0
    return score // weight_sum


def pod_sort_static(pod: PodSpec) -> Tuple:
    """The node-independent prefix of the PodSorter chain (everything
    but the usage score) — computable once per pod per sweep and cached
    by callers that sort the same pod set against many nodes."""
    return (
        KOORD_PRIORITY_ORDER.get(
            pod.priority_class or PriorityClass.NONE, 5
        ),
        pod.priority,
        kube_qos_order(pod),
        KOORD_QOS_ORDER.get(pod.qos, 5),
        _annotation_cost(pod, ANNOTATION_DELETION_COST),
        _annotation_cost(pod, ANNOTATION_EVICTION_COST),
        -pod.creation_time,
    )


def pod_sort_key_from_static(
    static: Tuple,
    pod_usage: Optional[Mapping],
    node_allocatable: Mapping,
    weights: Mapping,
) -> Tuple:
    """Assemble the full ascending key from a cached
    :func:`pod_sort_static` prefix plus the node-dependent usage score.

    ``pod_usage`` is the pod's metric ResourceList (None = no metric,
    which sorts after all metered pods)."""
    if pod_usage is None:
        usage_key = (1, 0)
    else:
        usage_key = (
            0, -resource_usage_score(pod_usage, node_allocatable, weights)
        )
    # the usage score slots in just before the creation-time tail
    return static[:-1] + (usage_key, static[-1])


def pod_sort_key(
    pod: PodSpec,
    pod_usage: Optional[Mapping],
    node_allocatable: Mapping,
    weights: Mapping,
) -> Tuple:
    """The full PodSorter comparator chain as one ascending key."""
    return pod_sort_key_from_static(
        pod_sort_static(pod), pod_usage, node_allocatable, weights
    )
