"""Consecutive-observation anomaly detector.

Semantics oracle: pkg/descheduler/framework/plugins/loadaware/anomaly/
(BasicDetector): a node is flagged anomalous only after strictly more
than N consecutive abnormal observations, and returns to normal after
strictly more than M consecutive normal ones (debounce against
utilization flapping, low_node_load.go:258 filterRealAbnormalNodes).
"""

from __future__ import annotations

import enum
import time


class State(enum.Enum):
    OK = "ok"
    ANOMALY = "anomaly"


class BasicDetector:
    def __init__(
        self,
        name: str,
        consecutive_abnormalities: int = 1,
        consecutive_normalities: int = 1,
        timeout: float = 0.0,
        clock=time.monotonic,
    ):
        self.name = name
        self.consecutive_abnormalities = consecutive_abnormalities
        self.consecutive_normalities = consecutive_normalities
        self.timeout = timeout
        self.clock = clock
        self.abnormal_streak = 0
        self.normal_streak = 0
        self.state = State.OK
        self.last_mark = clock()

    def mark(self, normal: bool) -> State:
        now = self.clock()
        if self.timeout and now - self.last_mark > self.timeout:
            self.reset()
        self.last_mark = now
        if normal:
            self.normal_streak += 1
            self.abnormal_streak = 0
            if (
                self.state == State.ANOMALY
                and self.normal_streak > self.consecutive_normalities
            ):
                self.state = State.OK
        else:
            self.abnormal_streak += 1
            self.normal_streak = 0
            if self.abnormal_streak > self.consecutive_abnormalities:
                self.state = State.ANOMALY
        return self.state

    def reset(self) -> None:
        self.abnormal_streak = 0
        self.normal_streak = 0
        self.state = State.OK
