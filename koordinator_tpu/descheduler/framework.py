"""Descheduler plugin framework + evictors.

Semantics oracle: pkg/descheduler/framework/types.go (DeschedulePlugin /
BalancePlugin / Evictor), framework/runtime/framework.go (profile
execution order: all Deschedule plugins, then all Balance plugins),
pkg/descheduler/evictions/ (policy-group limits: per cycle / namespace /
node), descheduler.go (interval loop).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    MigrationPhase,
    PodMigrationJob,
    PodSpec,
)


class DeschedulePlugin:
    """Point-fix plugins: look at individual policy violations."""

    name = "DeschedulePlugin"

    def deschedule(self, snapshot: ClusterSnapshot, evictor: "Evictor") -> None:
        raise NotImplementedError


class BalancePlugin:
    """Distribution plugins: rebalance load across the pool."""

    name = "BalancePlugin"

    def balance(self, snapshot: ClusterSnapshot, evictor: "Evictor") -> None:
        raise NotImplementedError


@dataclasses.dataclass
class EvictionLimiter:
    """Eviction budget (reference: evictions/evictions.go policy groups +
    arbitrator group limits). None = unlimited."""

    max_per_cycle: Optional[int] = None
    max_per_node: Optional[int] = None
    max_per_namespace: Optional[int] = None

    def __post_init__(self):
        self._cycle = 0
        self._per_node: Dict[str, int] = {}
        self._per_namespace: Dict[str, int] = {}

    def reset_cycle(self) -> None:
        self._cycle = 0
        self._per_node.clear()
        self._per_namespace.clear()

    def allow(self, pod: PodSpec) -> bool:
        if self.max_per_cycle is not None and self._cycle >= self.max_per_cycle:
            return False
        node = pod.node_name or ""
        if (
            self.max_per_node is not None
            and self._per_node.get(node, 0) >= self.max_per_node
        ):
            return False
        if (
            self.max_per_namespace is not None
            and self._per_namespace.get(pod.namespace, 0) >= self.max_per_namespace
        ):
            return False
        return True

    def note(self, node: str, namespace: str) -> None:
        self._cycle += 1
        self._per_node[node] = self._per_node.get(node, 0) + 1
        self._per_namespace[namespace] = self._per_namespace.get(namespace, 0) + 1


class Evictor:
    """Evictor protocol (reference: framework/types.go Evictor).

    ``arbiter`` optionally routes every eviction through the migration
    arbiter (control/migration.py, docs/DESIGN.md §27) under the given
    source label — a standalone descheduler run then obeys the same
    disruption budgets as the scheduler-integrated sweep. A deferral
    surfaces as the protocol's existing refusal (``evict`` returns
    False); the typed reason lands in the arbiter's ring + metrics."""

    def __init__(self, limiter: Optional[EvictionLimiter] = None,
                 arbiter=None, arbiter_source: str = "rebalance"):
        self.limiter = limiter or EvictionLimiter()
        self.evicted: List[PodSpec] = []
        self.arbiter = arbiter
        self.arbiter_source = arbiter_source

    def filter(self, pod: PodSpec) -> bool:
        """Whether this pod may be evicted at all."""
        return True

    def evict(self, snapshot: ClusterSnapshot, pod: PodSpec, reason: str = "") -> bool:
        if not self.limiter.allow(pod):
            return False
        if self.arbiter is not None:
            from koordinator_tpu.obs.timeline import lane_of

            verdict = self.arbiter.request(
                self.arbiter_source, pod.node_name, [pod.uid],
                lanes=[lane_of(pod)], gangs=[pod.gang],
            )
            if not verdict.apply or not verdict.admitted:
                return False
        # capture the accounting keys before _do_evict mutates the pod
        node, namespace = pod.node_name or "", pod.namespace
        if not self._do_evict(snapshot, pod, reason):
            return False
        self.limiter.note(node, namespace)
        self.evicted.append(pod)
        from koordinator_tpu.metrics.components import PODS_EVICTED

        PODS_EVICTED.inc({"strategy": reason or "unknown", "node": node})
        return True

    def _do_evict(self, snapshot, pod, reason) -> bool:
        raise NotImplementedError


class DirectEvictor(Evictor):
    """Immediate eviction: remove the pod from its node in the snapshot
    (reference: evictions.go direct API eviction path)."""

    def _do_evict(self, snapshot, pod, reason) -> bool:
        # identity-based removal: dataclass == would deep-compare every
        # field against the whole pod list
        snapshot.pods[:] = [p for p in snapshot.pods if p is not pod]
        pod.node_name = None
        pod.annotations["descheduler.evicted-reason"] = reason
        return True


class MigrationEvictor(Evictor):
    """Reservation-first eviction: emit a PodMigrationJob instead of
    evicting inline (reference: evictor/migration controller handoff,
    pkg/descheduler/controllers/migration/evictor/)."""

    def __init__(self, limiter: Optional[EvictionLimiter] = None):
        super().__init__(limiter)
        self.jobs: List[PodMigrationJob] = []
        self._seq = 0

    def _do_evict(self, snapshot, pod, reason) -> bool:
        # one active job per pod (reference: migration controller dedup)
        for job in self.jobs:
            if job.pod_uid == pod.uid and job.phase in (
                MigrationPhase.PENDING,
                MigrationPhase.RUNNING,
            ):
                return False
        self._seq += 1
        self.jobs.append(
            PodMigrationJob(
                name=f"migrate-{self._seq}-{pod.name}",
                pod_uid=pod.uid,
                reason=reason,
                create_time=snapshot.now,
            )
        )
        return True


@dataclasses.dataclass
class Profile:
    """One descheduling profile (reference: apis/config DeschedulerProfile)."""

    name: str
    deschedule_plugins: Sequence[DeschedulePlugin] = ()
    balance_plugins: Sequence[BalancePlugin] = ()


class Descheduler:
    """Runs profiles every interval (reference: descheduler.go:46)."""

    def __init__(
        self,
        profiles: Sequence[Profile],
        evictor: Evictor,
        descheduling_interval: float = 120.0,
    ):
        self.profiles = list(profiles)
        self.evictor = evictor
        self.descheduling_interval = descheduling_interval
        self.last_run = 0.0

    def run_once(self, snapshot: ClusterSnapshot) -> List[PodSpec]:
        """One descheduling cycle: every profile's Deschedule plugins,
        then its Balance plugins (reference: framework/runtime/
        framework.go RunDeschedulePlugins/RunBalancePlugins order)."""
        from koordinator_tpu.metrics.components import DESCHEDULE_LOOP_DURATION

        started = time.monotonic()
        self.evictor.limiter.reset_cycle()
        before = len(self.evictor.evicted)
        for profile in self.profiles:
            for plugin in profile.deschedule_plugins:
                plugin.deschedule(snapshot, self.evictor)
            for plugin in profile.balance_plugins:
                plugin.balance(snapshot, self.evictor)
        DESCHEDULE_LOOP_DURATION.observe(time.monotonic() - started)
        return self.evictor.evicted[before:]

    def maybe_run(self, snapshot: ClusterSnapshot, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        if now - self.last_run < self.descheduling_interval:
            return []
        self.last_run = now
        return self.run_once(snapshot)
