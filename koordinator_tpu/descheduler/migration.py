"""PodMigrationJob controller + arbitrator: reservation-first migration.

Semantics oracle: pkg/descheduler/controllers/migration/controller.go
(Reconcile :218, doMigrate :241, createReservation :763, evictPod :661 —
capacity is reserved on a destination node *before* the pod is evicted,
so migration never loses capacity) and controllers/migration/arbitrator/
{arbitrator.go, sort.go, filter.go} (candidate ordering + group limits).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    MigrationPhase,
    PodMigrationJob,
    PodSpec,
    ReservationSpec,
    ReservationState,
)


def _workload_of(pod: PodSpec) -> str:
    """Group key for per-workload limits (reference: arbitrator sort.go
    getJobControllerOfPod — the controller owner reference). Pods without
    an owner fall back to a label or the pod-name-stem heuristic."""
    if pod.owner:
        return pod.owner
    if "workload" in pod.labels:
        return pod.labels["workload"]
    base = pod.name.rsplit("-", 1)[0] if "-" in pod.name else pod.name
    return f"{pod.namespace}/{base}"


@dataclasses.dataclass
class Arbitrator:
    """Serializes + gates candidate migrations (reference:
    arbitrator.go:52 Arbitrator, :198 doOnceArbitrate)."""

    max_migrating_per_node: Optional[int] = None
    max_migrating_per_namespace: Optional[int] = None
    max_migrating_per_workload: Optional[int] = None

    def arbitrate(
        self,
        jobs: List[PodMigrationJob],
        snapshot: ClusterSnapshot,
        migrating: List[PodMigrationJob],
    ) -> List[PodMigrationJob]:
        """Order pending jobs and admit those within group limits.

        Sort: creation time, then fewest in-flight migrations of the same
        workload first, then workload grouping (reference: sort.go
        SortJobsByCreationTime/SortJobsByMigratingNum/SortJobsByController).
        """
        pods = {p.uid: p for p in snapshot.pods}
        in_flight_nodes: Dict[str, int] = {}
        in_flight_ns: Dict[str, int] = {}
        in_flight_workload: Dict[str, int] = {}
        for job in migrating:
            pod = pods.get(job.pod_uid)
            if pod is None:
                continue
            in_flight_nodes[pod.node_name or ""] = (
                in_flight_nodes.get(pod.node_name or "", 0) + 1
            )
            in_flight_ns[pod.namespace] = in_flight_ns.get(pod.namespace, 0) + 1
            in_flight_workload[_workload_of(pod)] = (
                in_flight_workload.get(_workload_of(pod), 0) + 1
            )

        def sort_key(job):
            pod = pods.get(job.pod_uid)
            workload = _workload_of(pod) if pod else ""
            return (
                job.create_time,
                in_flight_workload.get(workload, 0),
                workload,
                job.name,
            )

        admitted: List[PodMigrationJob] = []
        for job in sorted(jobs, key=sort_key):
            pod = pods.get(job.pod_uid)
            if pod is None:
                job.phase = MigrationPhase.FAILED
                job.reason = "pod not found"
                continue
            node = pod.node_name or ""
            ns = pod.namespace
            workload = _workload_of(pod)
            if (
                self.max_migrating_per_node is not None
                and in_flight_nodes.get(node, 0) >= self.max_migrating_per_node
            ):
                continue
            if (
                self.max_migrating_per_namespace is not None
                and in_flight_ns.get(ns, 0) >= self.max_migrating_per_namespace
            ):
                continue
            if (
                self.max_migrating_per_workload is not None
                and in_flight_workload.get(workload, 0)
                >= self.max_migrating_per_workload
            ):
                continue
            in_flight_nodes[node] = in_flight_nodes.get(node, 0) + 1
            in_flight_ns[ns] = in_flight_ns.get(ns, 0) + 1
            in_flight_workload[workload] = in_flight_workload.get(workload, 0) + 1
            admitted.append(job)
        return admitted


class MigrationController:
    """PodMigrationJob state machine (reference: migration/controller.go).

    Pending → (arbitrate) → create Reservation → wait bound → evict pod →
    Succeeded; TTL exceeded → Failed. ``place_reservation`` is the
    scheduler handoff: given the stand-in reservation spec, return the
    destination node (the reference creates a Reservation CR and lets
    koord-scheduler bind it, controller.go:763 + :587
    waitForPodBindReservation).
    """

    def __init__(
        self,
        place_reservation: Callable[
            [ClusterSnapshot, ReservationSpec], Optional[str]
        ],
        arbitrator: Optional[Arbitrator] = None,
    ):
        self.place_reservation = place_reservation
        self.arbitrator = arbitrator or Arbitrator()

    def reconcile(
        self, snapshot: ClusterSnapshot, jobs: List[PodMigrationJob]
    ) -> None:
        pods = {p.uid: p for p in snapshot.pods}

        # expire overdue jobs first (reference: controller.go job TTL)
        for job in jobs:
            if job.phase in (MigrationPhase.PENDING, MigrationPhase.RUNNING):
                if snapshot.now - job.create_time > job.ttl:
                    job.phase = MigrationPhase.FAILED
                    job.reason = "migration job timeout"
                    self._cleanup_reservation(snapshot, job)

        running = [j for j in jobs if j.phase == MigrationPhase.RUNNING]
        pending = [
            j for j in jobs if j.phase == MigrationPhase.PENDING and not j.paused
        ]
        for job in self.arbitrator.arbitrate(pending, snapshot, running):
            pod = pods[job.pod_uid]
            reservation = ReservationSpec(
                name=f"reserve-{job.name}",
                requests=dict(pod.requests),
                owner_pod_uids=[pod.uid],
                expiration_time=snapshot.now + job.ttl,
            )
            node = self.place_reservation(snapshot, reservation)
            if node is None:
                continue  # stays Pending; retried next reconcile
            reservation.node_name = node
            reservation.state = ReservationState.AVAILABLE
            snapshot.reservations.append(reservation)
            tracker = getattr(snapshot, "delta_tracker", None)
            if tracker is not None:
                tracker.mark_node(node)
            job.reservation_name = reservation.name
            job.phase = MigrationPhase.RUNNING

        for job in jobs:
            if job.phase != MigrationPhase.RUNNING:
                continue
            pod = pods.get(job.pod_uid)
            if pod is None:
                job.phase = MigrationPhase.FAILED
                job.reason = "pod disappeared"
                self._cleanup_reservation(snapshot, job)
                continue
            # capacity reserved → safe to evict (reference: evictPod :661)
            pod.node_name = None
            snapshot.pods[:] = [p for p in snapshot.pods if p is not pod]
            snapshot.pending_pods.append(pod)
            job.phase = MigrationPhase.SUCCEEDED

    def _cleanup_reservation(self, snapshot, job) -> None:
        if not job.reservation_name:
            return
        snapshot.reservations = [
            r for r in snapshot.reservations if r.name != job.reservation_name
        ]
