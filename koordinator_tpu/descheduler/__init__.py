"""Descheduler: load-aware rebalancing + reservation-first migration.

TPU-native rebuild of the reference pkg/descheduler/: its own plugin
framework (Deschedule/Balance extension points), the LowNodeLoad balance
plugin (node classification vectorized over the whole pool via
``ops.rebalance``), the PodMigrationJob controller (reservation-first
migrate state machine) and the arbitrator (sort + group-limit filters).
"""

from koordinator_tpu.descheduler.framework import (  # noqa: F401
    BalancePlugin,
    DeschedulePlugin,
    Descheduler,
    DirectEvictor,
    EvictionLimiter,
    MigrationEvictor,
    Profile,
)
from koordinator_tpu.descheduler.anomaly import BasicDetector  # noqa: F401
from koordinator_tpu.descheduler.loadaware import (  # noqa: F401
    LowNodeLoad,
    LowNodeLoadArgs,
    NodePool,
)
from koordinator_tpu.descheduler.migration import (  # noqa: F401
    Arbitrator,
    MigrationController,
)
