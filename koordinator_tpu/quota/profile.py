"""ElasticQuotaProfile → per-node-pool quota trees (quota-controller).

Rebuild of /root/reference/pkg/quota-controller/profile/
profile_controller.go:69-214: each profile selects a node pool by label,
sums its allocatable into the tree total, and materialises/updates the
tree's ROOT quota: ``min = pool total`` (masked to the profile's resource
keys), ``max = unbounded``, carrying the pool total and a stable tree id
derived from the profile name.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import (
    QuotaSpec,
    Resources,
    selector_matches,
)

#: max quota placeholder (reference: math.MaxInt64/2000)
UNBOUNDED = (2**63 - 1) // 2000


@dataclasses.dataclass
class QuotaProfile:
    """An ElasticQuotaProfile (apis/quota/v1alpha1)."""

    name: str
    quota_name: str
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    resource_keys: Sequence[ResourceName] = (
        ResourceName.CPU,
        ResourceName.MEMORY,
    )
    tree_id: str = ""  # generated from the profile name when empty
    quota_labels: Dict[str, str] = dataclasses.field(default_factory=dict)

    def effective_tree_id(self) -> str:
        if self.tree_id:
            return self.tree_id
        # profile_controller.go:100 hash(namespace/name)
        return hashlib.sha1(self.name.encode()).hexdigest()[:12]


class QuotaProfileController:
    """Reconciles profiles into tree-root QuotaSpecs on the scheduler."""

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self.profiles: Dict[str, QuotaProfile] = {}

    def update_profile(self, profile: QuotaProfile) -> None:
        self.profiles[profile.name] = profile

    def remove_profile(self, name: str) -> None:
        self.profiles.pop(name, None)

    def sync(self) -> None:
        """One reconcile pass over every profile (Reconcile :80-214)."""
        for profile in self.profiles.values():
            self._reconcile(profile)

    def _reconcile(self, profile: QuotaProfile) -> None:
        total: Resources = {}
        for node in self.scheduler.cache.nodes.values():
            if not selector_matches(profile.node_selector, node.labels):
                continue
            for r, v in node.allocatable.items():
                total[r] = total.get(r, 0) + v
        mn: Resources = {}
        mx: Resources = {}
        for key in profile.resource_keys:
            mn[key] = total.get(key, 0)
            mx[key] = UNBOUNDED
        self.scheduler.update_quota(
            QuotaSpec(
                name=profile.quota_name,
                parent=None,  # tree root
                min=mn,
                max=mx,
                is_parent=True,
                tree_id=profile.effective_tree_id(),
                total_resource=dict(total),
            )
        )
