"""Multi-quota-tree registry: one GroupQuotaManager per tree.

Reference: pkg/scheduler/plugins/elasticquota/quota_handler.go
(GetOrCreateGroupQuotaManagerForTree :143, GetGroupQuotaManagerForTree
:172, quota→tree routing via the quota-tree-id label). Trees are created
on demand; the default (empty id) tree spans the whole cluster, while
profile-created trees carry their node pool's total resource on their
root quota (quota-controller, profile_controller.go).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from koordinator_tpu.apis.types import QuotaSpec, resources_to_vector
from koordinator_tpu.quota.core import GroupQuotaManager


class QuotaTreeRegistry:
    """Routes quotas to per-tree managers (the plugin's
    groupQuotaManagersForQuotaTree map)."""

    def __init__(self, cluster_total=None):
        self.default = GroupQuotaManager(cluster_total=cluster_total or {})
        self.trees: Dict[str, GroupQuotaManager] = {"": self.default}
        #: quota name -> tree id (the reference's quotaToTreeMap)
        self.quota_tree: Dict[str, str] = {}

    def manager_for_tree(self, tree_id: str) -> GroupQuotaManager:
        mgr = self.trees.get(tree_id)
        if mgr is None:
            mgr = GroupQuotaManager()
            self.trees[tree_id] = mgr
        return mgr

    def manager_for_quota(self, quota_name: Optional[str]) -> GroupQuotaManager:
        if not quota_name:
            return self.default
        return self.manager_for_tree(self.quota_tree.get(quota_name, ""))

    def update_quota(self, spec: QuotaSpec) -> None:
        old_tree = self.quota_tree.get(spec.name)
        carry = None
        if old_tree is not None and old_tree != spec.tree_id:
            # moved trees: withdraw the quota's propagated accounting from
            # the old ancestors, then re-add under the new manager with
            # its live request/used carried over
            old = self.trees.get(old_tree)
            if old is not None:
                info = old.quotas.get(spec.name)
                if info is not None:
                    carry = (
                        info.child_request.copy(),
                        info.non_preemptible_request.copy(),
                        info.used.copy(),
                        info.non_preemptible_used.copy(),
                    )
                    self._shift_accounting(old, spec.name, carry, sign=-1)
                old.quotas.pop(spec.name, None)
                old._rebuild_children()
        self.quota_tree[spec.name] = spec.tree_id
        mgr = self.manager_for_tree(spec.tree_id)
        if spec.total_resource is not None and (
            spec.parent is None or spec.parent == "root"
        ):
            # only tree ROOTS carry the node pool total (profile
            # controller); non-root totals are ignored so a stale spec
            # can't clobber the tree total
            mgr.cluster_total = resources_to_vector(spec.total_resource)
        mgr.update_quota(spec)
        if carry is not None:
            self._shift_accounting(mgr, spec.name, carry, sign=+1)

    @staticmethod
    def _shift_accounting(mgr: GroupQuotaManager, name: str, carry, sign: int) -> None:
        """Add/subtract a quota's live accounting along ``mgr``'s ancestry
        (tree-move migration): preemptible request/used go through the
        manager's propagation; the non-preemptible components propagate
        unchanged, so they shift by plain ancestry walk."""
        child_request, np_request, used, np_used = carry
        mgr.add_request(name, sign * child_request)
        mgr.add_used(name, sign * used)
        for anc in mgr._ancestry(name):
            anc.non_preemptible_request = np.maximum(
                anc.non_preemptible_request + sign * np_request, 0
            )
            anc.non_preemptible_used = np.maximum(
                anc.non_preemptible_used + sign * np_used, 0
            )

    def remove_quota(self, name: str) -> None:
        """Quota deleted: withdraw its propagated request/used from the
        old ancestors (the tree-move withdraw), then drop the node."""
        tree_id = self.quota_tree.pop(name, "")
        mgr = self.trees.get(tree_id)
        if mgr is None:
            return
        info = mgr.quotas.get(name)
        if info is not None:
            self._shift_accounting(
                mgr,
                name,
                (
                    info.child_request.copy(),
                    info.non_preemptible_request.copy(),
                    info.used.copy(),
                    info.non_preemptible_used.copy(),
                ),
                sign=-1,
            )
            mgr.quotas.pop(name, None)
            mgr._rebuild_children()

    def items(self) -> Iterable[Tuple[str, GroupQuotaManager]]:
        return self.trees.items()
