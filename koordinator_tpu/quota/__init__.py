"""Hierarchical elastic quota: fair sharing of cluster capacity.

TPU-native rebuild of the reference's ElasticQuota plugin core
(pkg/scheduler/plugins/elasticquota/core/): a tree of quota groups with
min/max/shared-weight semantics, per-resource water-filling redistribution
of unused capacity, and admission gating.

Two implementations with one semantics:
- ``quota.core``: the host control-plane manager (exact reference
  semantics; Python ints == Go int64, float64 where the reference uses it).
- ``ops.quota``: the device path used inside the batched solver — the same
  water-filling as a fixed-point ``lax.while_loop`` over ``[Q, R]``
  arrays with host-normalized weights (exact int32 arithmetic).
"""

from koordinator_tpu.quota.core import (  # noqa: F401
    GroupQuotaManager,
    QuotaInfo,
    water_filling,
)
