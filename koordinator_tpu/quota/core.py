"""Host control-plane quota manager with exact reference semantics.

Reference: pkg/scheduler/plugins/elasticquota/core/
  - runtime_quota_calculator.go:111-186 (redistribution + iteration)
  - group_quota_manager.go:184-328 (request propagation, runtime refresh)
  - plugin.go:210-255 (admission; SURVEY.md A.3/A.4)

All vectors are numpy int64 ``[R]`` in canonical units; the weighted
redistribution delta uses float64 half-up rounding exactly like the Go
path (``int64(float64(w)*float64(T)/float64(W) + 0.5)``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from koordinator_tpu.apis.extension import NUM_RESOURCES
from koordinator_tpu.apis.types import QuotaSpec, resources_to_vector

#: Well-known quota group names (reference: apis/extension/constants.go).
ROOT_QUOTA = "root"
SYSTEM_QUOTA = "system"
DEFAULT_QUOTA = "default"


def water_filling(
    total: int,
    request: Sequence[int],
    min_: Sequence[int],
    guarantee: Sequence[int],
    weight: Sequence[int],
    allow_lent: Sequence[bool],
    *,
    exact_rational: bool = False,
) -> List[int]:
    """One resource dimension's runtime redistribution.

    Reference: runtime_quota_calculator.go:111-186. Each group first gets
    ``min(autoScaleMin, request)`` where ``autoScaleMin = max(min,
    guarantee)``; non-lent groups keep ``autoScaleMin`` even when their
    request is below it; groups requesting more become "adjustable" and the
    remaining capacity is distributed iteratively in proportion to shared
    weight, clamping at request and re-pooling surplus until exhausted.

    ``exact_rational=True`` replaces the reference's float64 delta with the
    exact rational round-half-up — the semantics used by the device path
    (see ops/quota.py); the two differ only on float64 rounding artifacts.
    """
    n = len(request)
    runtime = [0] * n
    adjustable = []
    total_weight = 0
    remaining = int(total)
    for i in range(n):
        auto_min = max(int(min_[i]), int(guarantee[i]))
        if request[i] > auto_min:
            adjustable.append(i)
            total_weight += int(weight[i])
            runtime[i] = auto_min
        elif allow_lent[i]:
            runtime[i] = int(request[i])
        else:
            runtime[i] = auto_min
        remaining -= runtime[i]

    while remaining > 0 and total_weight > 0 and adjustable:
        still = []
        still_weight = 0
        surplus = 0
        for i in adjustable:
            w = int(weight[i])
            if exact_rational:
                delta = (2 * w * remaining + total_weight) // (2 * total_weight)
            else:
                delta = int(math.floor(float(w) * float(remaining) / float(total_weight) + 0.5))
            runtime[i] += delta
            if runtime[i] < request[i]:
                still.append(i)
                still_weight += w
            else:
                surplus += runtime[i] - int(request[i])
                runtime[i] = int(request[i])
        if surplus <= 0 or not still:
            break
        adjustable, total_weight, remaining = still, still_weight, surplus
    return runtime


@dataclasses.dataclass
class QuotaInfo:
    """One quota group's live accounting state."""

    spec: QuotaSpec
    min: np.ndarray
    max: np.ndarray
    guaranteed: np.ndarray         # spec.guaranteed as a vector
    shared_weight: np.ndarray      # defaults to max
    request: np.ndarray            # own + child limited requests
    child_request: np.ndarray
    non_preemptible_request: np.ndarray
    used: np.ndarray
    non_preemptible_used: np.ndarray
    runtime: np.ndarray
    children: List[str]

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def parent(self) -> str:
        return self.spec.parent or ROOT_QUOTA

    @property
    def limited_request(self) -> np.ndarray:
        return np.minimum(self.request, self.max)


def _zeros() -> np.ndarray:
    return np.zeros(NUM_RESOURCES, dtype=np.int64)


class GroupQuotaManager:
    """The hierarchical quota tree: request/used accounting + runtime refresh.

    Reference: group_quota_manager.go. The reference maintains per-parent
    incremental calculators with versioning; at control-plane scale a full
    root→leaf recomputation per refresh is equivalent and simpler — the
    observable runtime values match.
    """

    def __init__(
        self,
        cluster_total: Optional[Dict] = None,
        exact_rational: bool = False,
    ):
        self.quotas: Dict[str, QuotaInfo] = {}
        self.cluster_total = resources_to_vector(cluster_total or {})
        self.exact_rational = exact_rational
        root = QuotaSpec(name=ROOT_QUOTA, parent=None, is_parent=True)
        self._insert(root)

    # -- tree maintenance ---------------------------------------------------

    def _insert(self, spec: QuotaSpec) -> QuotaInfo:
        mn = resources_to_vector(spec.min)
        mx = resources_to_vector(spec.max)
        guarantee = resources_to_vector(spec.guaranteed)
        weight = (
            resources_to_vector(spec.shared_weight)
            if spec.shared_weight is not None
            else mx.copy()
        )
        info = QuotaInfo(
            spec=spec,
            min=mn,
            max=mx,
            guaranteed=guarantee,
            shared_weight=weight,
            request=_zeros(),
            child_request=_zeros(),
            non_preemptible_request=_zeros(),
            used=_zeros(),
            non_preemptible_used=_zeros(),
            runtime=_zeros(),
            children=[],
        )
        self.quotas[spec.name] = info
        return info

    def update_quota(self, spec: QuotaSpec) -> None:
        """Add or reconfigure a quota group (UpdateQuota equivalent)."""
        existing = self.quotas.get(spec.name)
        if existing is not None:
            carry = existing
            info = self._insert(spec)
            info.request = carry.request
            info.child_request = carry.child_request
            info.non_preemptible_request = carry.non_preemptible_request
            info.used = carry.used
            info.non_preemptible_used = carry.non_preemptible_used
            info.children = carry.children
        else:
            self._insert(spec)
        self._rebuild_children()

    def _rebuild_children(self) -> None:
        for info in self.quotas.values():
            info.children = []
        for name, info in self.quotas.items():
            if name == ROOT_QUOTA:
                continue
            parent = self.quotas.get(info.parent)
            if parent is not None:
                parent.children.append(name)

    def _ancestry(self, name: str) -> List[QuotaInfo]:
        """[self, parent, ..., root] (getCurToAllParentGroupQuotaInfo)."""
        chain = []
        cur = self.quotas.get(name)
        while cur is not None:
            chain.append(cur)
            if cur.name == ROOT_QUOTA:
                break
            cur = self.quotas.get(cur.parent)
        return chain

    # -- accounting ---------------------------------------------------------

    def add_request(
        self, name: str, delta: np.ndarray, non_preemptible: bool = False
    ) -> None:
        """Propagate a request delta up the tree
        (recursiveUpdateGroupTreeWithDeltaRequest, group_quota_manager.go:184).

        At every level: ChildRequest accumulates the delta (for the leaf,
        pods are its "children"); Request is rewritten as ChildRequest
        floored at min for non-lent groups; the delta handed to the parent
        is the change in the group's max-limited request. The
        non-preemptible delta adds unchanged at every ancestor.
        """
        chain = self._ancestry(name)
        d = np.asarray(delta, dtype=np.int64)
        npd = d if non_preemptible else np.zeros_like(d)
        for info in chain:
            old_limited = info.limited_request
            info.non_preemptible_request = np.maximum(
                info.non_preemptible_request + npd, 0
            )
            if info.name == ROOT_QUOTA:
                # only the root keeps the plain accumulated request; every
                # other level rewrites request from child_request below
                info.request = np.maximum(info.request + d, 0)
                return
            info.child_request = np.maximum(info.child_request + d, 0)
            real = info.child_request.copy()
            if not info.spec.allow_lent_resource:
                real = np.maximum(real, info.min)
            info.request = real
            d = info.limited_request - old_limited

    def add_used(
        self, name: str, delta: np.ndarray, non_preemptible: bool = False
    ) -> None:
        """used += delta on the group and all ancestors
        (updateGroupDeltaUsedNoLock, group_quota_manager.go:228)."""
        d = np.asarray(delta, dtype=np.int64)
        for info in self._ancestry(name):
            info.used = np.maximum(info.used + d, 0)
            if non_preemptible:
                info.non_preemptible_used = np.maximum(
                    info.non_preemptible_used + d, 0
                )

    # -- runtime ------------------------------------------------------------

    def _available_total(self) -> np.ndarray:
        """Cluster total minus system/default groups' used
        (totalResourceExceptSystemAndDefaultUsed)."""
        total = self.cluster_total.copy()
        for special in (SYSTEM_QUOTA, DEFAULT_QUOTA):
            info = self.quotas.get(special)
            if info is not None:
                total = total - info.used
        return total

    def refresh_runtime(self, name: str) -> Optional[np.ndarray]:
        """Runtime of ``name`` after a root→leaf refresh along its ancestry
        (refreshRuntimeNoLock, group_quota_manager.go:266-328)."""
        info = self.quotas.get(name)
        if info is None:
            return None
        if name == ROOT_QUOTA:
            return self._available_total()
        if name in (SYSTEM_QUOTA, DEFAULT_QUOTA):
            return info.max.copy()

        chain = self._ancestry(name)  # [self ... root]
        total = self._available_total()
        for info in reversed(chain):
            if info.name == ROOT_QUOTA:
                continue
            parent = self.quotas[info.parent]
            self._redistribute_children(parent, total)
            total = info.runtime
        return np.minimum(self.quotas[name].runtime, self.quotas[name].max)

    def _scaled_mins(
        self, children: List[QuotaInfo], total: np.ndarray
    ) -> np.ndarray:
        """[C,R] per-child min after proportional scaling (reference:
        scale_minquota_when_over_root_res.go:99-160 getScaledMinQuota).

        On dimensions where Σ sibling mins exceeds ``total``, scaling-
        enabled children share the remainder after non-scaling children's
        mins are guaranteed first, proportionally to their original min:
        ``scaled = (total - disable_sum)+ * min / enable_sum``.
        """
        mins = np.stack([c.min for c in children])
        enable = np.array(
            [c.spec.enable_min_quota_scale for c in children], dtype=bool
        )
        if not enable.any():
            return mins
        enable_sum = mins[enable].sum(axis=0)
        disable_sum = mins[~enable].sum(axis=0) if (~enable).any() else np.zeros_like(total)
        over = (enable_sum + disable_sum) > total  # [R] dims needing scale
        if not over.any():
            return mins
        scaled = mins.copy()
        avail = np.maximum(total - disable_sum, 0)
        for i, c in enumerate(children):
            if not enable[i]:
                continue
            for r in np.nonzero(over)[0]:
                if avail[r] <= 0:
                    scaled[i, r] = 0
                elif enable_sum[r] > 0:
                    scaled[i, r] = int(
                        float(avail[r]) * float(mins[i, r]) / float(enable_sum[r])
                    )
        return scaled

    def _redistribute_children(self, parent: QuotaInfo, total: np.ndarray) -> None:
        """Run the per-dimension water-filling over ``parent``'s children."""
        children = [
            self.quotas[c]
            for c in parent.children
            if c not in (SYSTEM_QUOTA, DEFAULT_QUOTA)
        ]
        if not children:
            return
        request = np.stack([c.limited_request for c in children])
        min_ = self._scaled_mins(children, total)  # scaled when oversubscribed
        guarantee = np.stack([c.guaranteed for c in children])
        weight = np.stack([c.shared_weight for c in children])
        allow = [c.spec.allow_lent_resource for c in children]
        for r in range(NUM_RESOURCES):
            runtimes = water_filling(
                int(total[r]),
                request[:, r],
                min_[:, r],
                guarantee[:, r],
                weight[:, r],
                allow,
                exact_rational=self.exact_rational,
            )
            for c, rt in zip(children, runtimes):
                c.runtime[r] = rt

    # -- admission (SURVEY.md A.3) -----------------------------------------

    def can_admit(
        self,
        name: str,
        pod_request: np.ndarray,
        non_preemptible: bool = False,
        check_parents: bool = False,
    ) -> bool:
        """PreFilter admission: ``used + podReq <= runtime`` on the pod's
        requested dimensions; non-preemptible pods additionally against min
        (plugin.go:210-255)."""
        info = self.quotas.get(name)
        if info is None:
            return True
        req = np.asarray(pod_request, dtype=np.int64)
        dims = req > 0
        runtime = self.refresh_runtime(name)
        if runtime is None:
            return True
        if np.any((info.used + req)[dims] > runtime[dims]):
            return False
        if non_preemptible and np.any(
            (info.non_preemptible_used + req)[dims] > info.min[dims]
        ):
            return False
        if check_parents and info.parent != ROOT_QUOTA and info.parent in self.quotas:
            return self.can_admit(
                info.parent, pod_request, non_preemptible=False, check_parents=True
            )
        return True

    # -- convenience --------------------------------------------------------

    def assume_pod(
        self, name: str, pod_request: np.ndarray, non_preemptible: bool = False
    ) -> None:
        self.add_request(name, pod_request, non_preemptible)
        self.add_used(name, pod_request, non_preemptible)

    def forget_pod(
        self, name: str, pod_request: np.ndarray, non_preemptible: bool = False
    ) -> None:
        self.add_request(name, -np.asarray(pod_request), non_preemptible)
        self.add_used(name, -np.asarray(pod_request), non_preemptible)
