"""Per-component metric sets.

Reference metric names: pkg/scheduler/metrics/metrics.go,
pkg/koordlet/metrics/{metrics,common,resource_summary,cpu_suppress,...}.go
(internal vs external registries), pkg/descheduler/metrics/metrics.go.
"""

from __future__ import annotations

from koordinator_tpu.metrics.registry import Registry

# -- koord-scheduler (pkg/scheduler/metrics) --------------------------------

SCHEDULER_METRICS = Registry("koord-scheduler")
SCHEDULING_ATTEMPTS = SCHEDULER_METRICS.counter(
    "scheduler_schedule_attempts_total",
    "Scheduling attempts by result",
    label_names=("result",),  # scheduled | unschedulable | error | nominated
)
E2E_SCHEDULING_DURATION = SCHEDULER_METRICS.histogram(
    "scheduler_e2e_scheduling_duration_seconds",
    "End-to-end scheduling latency per pod/batch",
)
PENDING_PODS = SCHEDULER_METRICS.gauge(
    "scheduler_pending_pods", "Pods waiting to be scheduled",
)
BATCH_SOLVE_DURATION = SCHEDULER_METRICS.histogram(
    "scheduler_batched_solve_duration_seconds",
    "Device solve wall-clock per batched round (the jax-tpu backend)",
)
PREEMPTION_ATTEMPTS = SCHEDULER_METRICS.counter(
    "scheduler_preemption_attempts_total",
    "PostFilter preemption attempts",
)
PREEMPT_VICTIMS = SCHEDULER_METRICS.counter(
    "scheduler_preempt_victims_total",
    "Joint place+evict victim flow per outcome: candidates the solve "
    "chose (selected), candidates the reprieve loop spared (reprieved), "
    "victims actually evicted (evicted)",
    label_names=("outcome",),  # selected | reprieved | evicted
)
DEFRAG_DRAINS = SCHEDULER_METRICS.counter(
    "scheduler_defrag_drains_total",
    "Headroom-repack drains applied (pods evicted to restore a "
    "gang-sized hole)",
)
MIGRATION_REQUESTS = SCHEDULER_METRICS.counter(
    "scheduler_migration_requests_total",
    "Eviction victims presented to the migration arbiter, per source",
    label_names=("source",),  # preemption | defrag | rebalance | workingset
)
MIGRATION_ADMITTED = SCHEDULER_METRICS.counter(
    "scheduler_migration_admitted_total",
    "Victims the arbiter admitted within the declared disruption "
    "budgets (working-set demotions count here too: undeferrable)",
    label_names=("source",),  # preemption | defrag | rebalance | workingset
)
MIGRATION_DEFERRALS = SCHEDULER_METRICS.counter(
    "scheduler_migration_deferrals_total",
    "Victims deferred by the arbiter, per typed refusal reason — the "
    "never-dropped-silently contract (docs/DESIGN.md §27)",
    # reason: cooldown | round-budget | node-budget | tenant-budget |
    #         gang-min-available
    label_names=("source", "reason"),
)
DEFRAG_DECISIONS = SCHEDULER_METRICS.counter(
    "scheduler_defrag_decisions_total",
    "Closed-loop defrag controller decisions, per triggering signal",
    label_names=("signal",),  # frag-over
)
GANG_REJECTIONS = SCHEDULER_METRICS.counter(
    "scheduler_gang_rejections_total",
    "Gang-group rejections (strict failures + WaitTime expiry)",
)

# -- failure domains (service/failover.py + service/supervisor.py) ----------
# These live in the SCHEDULER registry: the failover state machine and
# the sidecar supervisor both run in the control-plane process, and the
# operator watching "is my scheduler placing pods?" needs them on the
# same scrape as the round metrics (docs/DESIGN.md §13).

ROUNDS_SKIPPED = SCHEDULER_METRICS.counter(
    "scheduler_rounds_skipped_total",
    "Scheduling rounds skipped outright (solver outage, no failover)",
    label_names=("reason",),  # solver-unavailable
)
SOLVER_DEGRADED = SCHEDULER_METRICS.gauge(
    "scheduler_solver_degraded",
    "1 while the failover backend answers solves in-process",
)
SOLVER_FAILOVERS = SCHEDULER_METRICS.counter(
    "scheduler_solver_failovers_total",
    "Failover state-machine flips",
    label_names=("direction",),  # to-degraded | to-remote
)
SOLVER_LOCAL_SOLVES = SCHEDULER_METRICS.counter(
    "scheduler_solver_local_solves_total",
    "Solves answered by the in-process fallback instead of the sidecar",
    label_names=("mode",),  # local-fallback | local-degraded
)
SUPERVISOR_RESTARTS = SCHEDULER_METRICS.counter(
    "solver_supervisor_restarts_total",
    "Sidecar restarts performed by the supervisor",
    label_names=("reason",),  # crashed | hung | down
)
SUPERVISOR_RESPAWN_WARM = SCHEDULER_METRICS.counter(
    "solver_supervisor_respawn_warm_total",
    "Supervised child (re)spawns that warm-restored from the AOT pool "
    "— probed on the tight warm ready grace instead of the "
    "cold-compile allowance (docs/DESIGN.md §21)",
)
SUPERVISOR_UP = SCHEDULER_METRICS.gauge(
    "solver_supervisor_child_up",
    "1 while the supervised sidecar passes liveness probes",
)
SUPERVISOR_BREAKER_OPEN = SCHEDULER_METRICS.gauge(
    "solver_supervisor_breaker_open",
    "1 while the restart-storm circuit breaker refuses respawns",
)

# -- anti-entropy auditor (scheduler/auditor.py) ----------------------------
# Every drift detection and every repair the StateAuditor performs is
# counted here — the repair ladder (targeted -> cache-rebuild ->
# full-restage) never acts silently (docs/DESIGN.md §14).

AUDIT_SWEEPS = SCHEDULER_METRICS.counter(
    "scheduler_audit_sweeps_total",
    "Anti-entropy sweeps run, by trigger",
    label_names=("kind",),  # periodic | promotion | manual
)
AUDIT_DETECTIONS = SCHEDULER_METRICS.counter(
    "scheduler_audit_detections_total",
    "Drift/invariant detections, by trust boundary and drift kind",
    label_names=("boundary", "kind"),  # cache-bus | accounting | device-parity
)
AUDIT_REPAIRS = SCHEDULER_METRICS.counter(
    "scheduler_audit_repairs_total",
    "Repairs applied, by ladder rung",
    label_names=("action",),  # targeted | cache-rebuild | full-restage
)
AUDIT_SWEEP_DURATION = SCHEDULER_METRICS.histogram(
    "scheduler_audit_sweep_seconds",
    "Wall-clock per anti-entropy sweep",
)
AUDIT_LAST_DRIFT = SCHEDULER_METRICS.gauge(
    "scheduler_audit_last_sweep_drift",
    "Detections in the most recent sweep (0 on a healthy tick)",
)
AUDIT_PROBE_ROWS = SCHEDULER_METRICS.counter(
    "scheduler_audit_probe_rows_total",
    "Staged rows re-lowered and compared by the device-parity probe",
)
AUDIT_UNREPAIRED = SCHEDULER_METRICS.gauge(
    "scheduler_audit_unrepaired",
    "Invariant violations that survived the repair ladder (page on >0)",
)

# -- pipelined tick path (scheduler/pipeline.py) ----------------------------
# The overlapped stage/solve/publish loop's observability: per-stage
# wall-clock histograms (what the pipeline hides vs what stays on the
# round's critical path), round critical-path latency, and the drain /
# deferred-error bookkeeping (docs/DESIGN.md §15).

TICK_STAGE_DURATION = SCHEDULER_METRICS.histogram(
    "scheduler_tick_stage_seconds",
    "Per-stage wall-clock of one scheduling tick",
    label_names=("stage",),  # lower | stage | solve | publish
)
ROUND_CRITICAL_PATH = SCHEDULER_METRICS.histogram(
    "scheduler_round_critical_path_seconds",
    "Host critical path per round: retire-wait + stage + dispatch "
    "(the solve compute and publish ride the pipeline off-path)",
)
PIPELINE_INFLIGHT = SCHEDULER_METRICS.gauge(
    "scheduler_pipeline_inflight",
    "1 while a dispatched tick has not retired (publish pending)",
)
PIPELINE_DRAINS = SCHEDULER_METRICS.counter(
    "scheduler_pipeline_drains_total",
    "Pipeline quiesce events, by reason",
    # run_loop emits auditor-sweep | failover-flip | standby (the
    # deferred-fence surfacing path) | shutdown | once; drain()'s
    # reason is free-form, so benches/tests add their own
    label_names=("reason",),
)
PIPELINE_DEFERRED_ERRORS = SCHEDULER_METRICS.counter(
    "scheduler_pipeline_deferred_errors_total",
    "Publish-side failures surfaced at the next round boundary",
    label_names=("kind",),  # fencing | solver | other
)

# -- scheduling trace fabric (koordinator_tpu/obs/) -------------------------
# Per-pod latency, the span-fed stuck watchdog, and the anomaly flight
# recorder all land beside the round metrics: the operator asking "is
# my scheduler placing pods, and how fast per pod?" reads one scrape
# (docs/DESIGN.md §16).

POD_E2E = SCHEDULER_METRICS.histogram(
    "scheduler_pod_e2e_seconds",
    "Per-pod submit→bind end-to-end latency, by QoS lane "
    "(obs/timeline.py: submit at pending intake, closed when the bind "
    "publishes on the bus)",
    label_names=("lane",),  # system | ls | be
)
STUCK_CYCLES = SCHEDULER_METRICS.counter(
    "scheduler_stuck_cycles_total",
    "Rounds/publishes whose tracer mark stayed open past the watchdog "
    "timeout (scheduler/monitor.py — counted once per stuck mark)",
    label_names=("kind",),  # round | publish
)
FLIGHT_DUMPS = SCHEDULER_METRICS.counter(
    "scheduler_flight_dumps_total",
    "Anomaly flight-recorder dumps written, by trigger",
    # auditor-detection | failover-flip | fencing-abort |
    # pipeline-deferred-error | deadline-exceeded | manual
    label_names=("trigger",),
)

# -- streaming serving mode (scheduler/streaming.py) ------------------------
# The continuous-arrival front end: pods arrive on an open-loop stream
# into QoS-laned intake, rounds fire adaptively (batch-size watermark OR
# oldest-pod deadline), and the headline series is the per-pod
# submit→bind histogram above at a sustained arrival rate
# (docs/DESIGN.md §22).

STREAM_ARRIVALS = SCHEDULER_METRICS.counter(
    "scheduler_streaming_arrivals_total",
    "Pod arrivals admitted into the streaming intake, by QoS lane",
    label_names=("lane",),  # system | ls | be
)
STREAM_SHED = SCHEDULER_METRICS.counter(
    "scheduler_streaming_shed_total",
    "Arrivals refused or evicted by the streaming intake — the "
    "backpressure signal (capacity = intake full; timeline-capacity = "
    "the pod scheduled but its latency sample was refused by the "
    "timeline registry; deadline = expired after max_pod_rounds)",
    label_names=("lane", "reason"),
)
STREAM_TRIGGERS = SCHEDULER_METRICS.counter(
    "scheduler_streaming_round_triggers_total",
    "Adaptively-fired scheduling rounds, by what fired them "
    "(watermark = batch-size; deadline = oldest-pod lane deadline; "
    "idle = the periodic backstop re-solving leftover pending pods)",
    label_names=("reason",),
)
STREAM_QUEUE_DEPTH = SCHEDULER_METRICS.gauge(
    "scheduler_streaming_queue_depth",
    "Arrivals queued in the streaming intake awaiting a round, by lane",
    label_names=("lane",),
)
STREAM_BATCH_PODS = SCHEDULER_METRICS.histogram(
    "scheduler_streaming_round_batch_pods",
    "Arrival-batch size per adaptively-fired round (how well the "
    "trigger amortizes dispatches without stretching the tail)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)

# -- serving SLO controller (koordinator_tpu/control/slo.py) ----------------
# The closed loop over the streaming knobs: every applied knob move is
# counted by its trigger signal, and the per-lane rolling p99 vs the
# declared target is exported as a ratio gauge (<=1 means in-SLO) so a
# dashboard answers "is the serving path converged, and what is the
# controller doing about it" from one scrape (docs/DESIGN.md §25).

SLO_DECISIONS = SCHEDULER_METRICS.counter(
    "scheduler_slo_decisions_total",
    "Knob adjustments the serving SLO controller applied, by knob and "
    "trigger signal (one knob per reconcile, cooldown-gated — a high "
    "rate here means the declared SLO fights the offered load)",
    label_names=("knob", "signal"),
    # knob: watermark | deadline | capacity
    # signal: p99-over | p99-under | shed-capacity | padding-waste
)
SLO_LANE_P99_RATIO = SCHEDULER_METRICS.gauge(
    "scheduler_slo_lane_p99_ratio",
    "Rolling-window submit→bind p99 over the declared per-lane target "
    "(<= 1.0 means the lane meets its SLO; only exported for lanes "
    "with a declared target and enough window samples)",
    label_names=("lane",),  # system | ls | be
)

# -- HBM working-set manager (state/workingset.py) --------------------------
# The device-memory budget over staged tenant worlds (docs/DESIGN.md
# §26): a fixed byte line, per-rung residency counts, and typed counters
# for every demotion, restage, and allocation failure — so memory
# pressure reads as a measured degradation curve on a dashboard, never
# as an unexplained crash or latency cliff. Own registry for the same
# reason as the device observatory below: the ledger lives in whichever
# long-lived process stages worlds — the in-process scheduler AND the
# multi-tenant solver sidecar — so both muxes merge it.

WORKINGSET_METRICS = Registry("hbm-workingset")
HBM_BUDGET_BYTES = WORKINGSET_METRICS.gauge(
    "scheduler_hbm_budget_bytes",
    "Configured HBM budget for staged tenant worlds (0 = unlimited; "
    "the working-set manager demotes victims instead of staging past "
    "this line)",
)
HBM_USED_BYTES = WORKINGSET_METRICS.gauge(
    "scheduler_hbm_used_bytes",
    "Metadata-summed bytes of device-resident staged worlds currently "
    "charged against the HBM budget",
)
TENANT_RESIDENCY = WORKINGSET_METRICS.gauge(
    "scheduler_tenant_residency",
    "Registered staged worlds per residency rung of the eviction "
    "ladder (device-resident, host-pinned, cold)",
    label_names=("rung",),  # device | host | cold
)
WORKINGSET_DEMOTIONS = WORKINGSET_METRICS.counter(
    "scheduler_workingset_demotions_total",
    "Residency demotions (one rung each) applied by the working-set "
    "manager, by cause: headroom for a new/regrown world (admission), "
    "over the budget line after a touch or squeeze (budget), or the "
    "allocation-failure retry ladder (alloc-failure)",
    label_names=("reason",),  # admission | budget | alloc-failure
)
WORKINGSET_RESTAGES = WORKINGSET_METRICS.counter(
    "scheduler_workingset_restages_total",
    "Demoted worlds re-staged onto the device on their next solve, by "
    "the rung they came back from (host = re-upload of the kept host "
    "arrays; cold = full re-lower from typed truth)",
    label_names=("reason",),  # host | cold
)
WORKINGSET_ALLOC_FAILURES = WORKINGSET_METRICS.counter(
    "scheduler_workingset_alloc_failures_total",
    "Device allocation failures (real RESOURCE_EXHAUSTED or injected) "
    "caught at the staging boundary, by which boundary raised: a full "
    "world staging (stage) or a delta row scatter (scatter); each is "
    "followed by demotion + bounded retry, never an unhandled crash",
    label_names=("reason",),  # stage | scatter
)

# -- device-cost observatory (koordinator_tpu/obs/device.py) ----------------
# The device-side twin of the trace fabric: compile telemetry, padding
# waste, and live-buffer accounting. These live in their OWN registry
# because BOTH long-lived processes compile — the scheduler's debug mux
# and the solver sidecar's --debug-port each merge this registry into
# their /metrics (utils/debug_http via MergedGatherer), so whichever
# process an operator scrapes answers "did we just recompile / how much
# HBM is staged state holding" (docs/DESIGN.md §17).

DEVICE_METRICS = Registry("device-observatory")
DEVICE_COMPILES = DEVICE_METRICS.counter(
    "solver_device_compile_total",
    "XLA compilations observed at instrumented jit callsites, by "
    "function — the quantitative, always-on form of graftcheck's "
    "boolean zero-recompile guard (a warmed steady-state tick adds 0)",
    label_names=("fn",),
)
DEVICE_COMPILE_SECONDS = DEVICE_METRICS.histogram(
    "solver_device_compile_seconds",
    "Wall-clock of the signature-miss call that triggered each "
    "observed compilation (trace + lower + XLA compile + dispatch)",
    label_names=("fn",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)
DEVICE_XLA_COMPILES = DEVICE_METRICS.counter(
    "solver_device_xla_compiles_total",
    "ALL backend compilations in this process (jax.monitoring events; "
    "includes helper programs and on-demand analysis lowerings the "
    "per-fn counter does not attribute)",
)
DEVICE_XLA_COMPILE_SECONDS = DEVICE_METRICS.histogram(
    "solver_device_xla_compile_seconds",
    "Backend compile wall-clock per XLA compilation (jax.monitoring)",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)
DEVICE_PADDING_WASTE = DEVICE_METRICS.gauge(
    "solver_device_padding_waste_ratio",
    "1 - real rows / padded rows per shape-bucketed buffer (pod_batch, "
    "resv_table, dirty_rows, coalesced_pods) — the device time burned "
    "on bucket padding, updated at stage time",
    label_names=("buffer",),
)
DEVICE_LIVE_BUFFERS = DEVICE_METRICS.gauge(
    "solver_device_live_buffers",
    "Live jax arrays in this process (jax.live_arrays(), sampled on "
    "status/debug reads — never on the solve path)",
)
DEVICE_LIVE_BYTES = DEVICE_METRICS.gauge(
    "solver_device_live_bytes",
    "Total bytes of live jax arrays (metadata sum; no device sync)",
)
DEVICE_PROFILE_WINDOWS = DEVICE_METRICS.counter(
    "solver_device_profile_windows_total",
    "On-demand jax profiler windows, by outcome",
    label_names=("result",),  # written | error | rate-limited | refused
)

# -- AOT warm pool (service/warmpool.py, docs/DESIGN.md §21) ----------------
# The restart/promotion/failover warm path's health. These live in the
# DEVICE registry because BOTH long-lived processes restore from the
# pool — the scheduler (leader promotion, the failover twin) and the
# solver sidecar (supervisor respawns) — and each already merges this
# registry into its /metrics.

WARM_POOL_HITS = DEVICE_METRICS.counter(
    "scheduler_warm_pool_hits_total",
    "Executable-store loads that served a deserialized AOT program "
    "(a recovery path that skipped trace + compile)",
)
WARM_POOL_MISSES = DEVICE_METRICS.counter(
    "scheduler_warm_pool_misses_total",
    "Clean executable-store misses (no entry for the key) that fell "
    "back to cold compile",
)
WARM_POOL_REJECTS = DEVICE_METRICS.counter(
    "scheduler_warm_pool_rejects_total",
    "Executable-store entries REFUSED by the rejection ladder, by "
    "typed reason — every reject degrades that shape to a loud cold "
    "compile, never a crash and never a stale-executable solve",
    # truncated | corrupt | fingerprint | oversized | stale-host |
    # version-skew
    label_names=("reason",),
)
WARM_RESTORE_SECONDS = DEVICE_METRICS.histogram(
    "scheduler_warm_restore_seconds",
    "Wall-clock per warm-pool restore pass (boot, leader promotion, "
    "failover prewarm): manifest read + executable deserialization",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0),
)
WARM_POOL_QUARANTINED = DEVICE_METRICS.counter(
    "scheduler_warm_pool_quarantined_total",
    "Store entries (or manifests) moved aside after a typed load "
    "failure — never retried in a loop, never a crash",
)

# -- koordlet (pkg/koordlet/metrics: internal + external sets) --------------

KOORDLET_INTERNAL_METRICS = Registry("koordlet-internal")
CGROUP_WRITES = KOORDLET_INTERNAL_METRICS.counter(
    "koordlet_resource_executor_writes_total",
    "Cgroup writes issued by the resource executor",
    label_names=("resource",),
)
COLLECT_DURATION = KOORDLET_INTERNAL_METRICS.histogram(
    "koordlet_collect_duration_seconds",
    "Metrics-advisor collection pass latency",
    label_names=("collector",),
)
PREDICT_DURATION = KOORDLET_INTERNAL_METRICS.histogram(
    "koordlet_predict_duration_seconds",
    "Peak predictor update latency",
)

KOORDLET_EXTERNAL_METRICS = Registry("koordlet-external")
BE_SUPPRESS_CPU_CORES = KOORDLET_EXTERNAL_METRICS.gauge(
    "koordlet_be_suppress_cpu_cores",
    "Current BE CPU suppress target in cores",
)
POD_EVICTIONS = KOORDLET_EXTERNAL_METRICS.counter(
    "koordlet_pod_evictions_total",
    "Pods evicted by QoS strategies",
    label_names=("reason",),
)
NODE_RESOURCE_ALLOCATABLE = KOORDLET_EXTERNAL_METRICS.gauge(
    "koordlet_node_resource_allocatable",
    "Reported node allocatable per resource",
    label_names=("resource",),
)
CONTAINER_CPI_METRIC = KOORDLET_EXTERNAL_METRICS.gauge(
    "koordlet_container_cpi",
    "Latest cycles-per-instruction per container",
    label_names=("pod", "container"),
)

# -- koord-solver sidecar (service/admission.py gate) -----------------------

SOLVER_METRICS = Registry("koord-solver")
# The wait/shed/depth series carry a ``tenant`` label (DESIGN §20):
# the multi-tenant pool's whole point is K front-ends sharing one
# sidecar, so "which tenant is overloaded / starving / flooding" must
# be answerable from /metrics alone. Single-tenant deployments see one
# constant label value ("default").
SOLVER_ADMISSION_WAIT = SOLVER_METRICS.histogram(
    "solver_admission_wait_seconds",
    "Queue wait from enqueue to dispatch, per QoS lane and tenant",
    label_names=("lane", "tenant"),
)
SOLVER_SOLVE_DURATION = SOLVER_METRICS.histogram(
    "solver_batch_solve_seconds",
    "Device solve wall-clock per dispatched admission batch",
)
SOLVER_ADMISSION_SHED = SOLVER_METRICS.counter(
    "solver_admission_shed_total",
    "Requests shed by the admission gate",
    # overloaded | deadline | shutdown
    label_names=("lane", "reason", "tenant"),
)
SOLVER_QUEUE_DEPTH = SOLVER_METRICS.gauge(
    "solver_admission_queue_depth",
    "Currently queued requests per QoS lane and tenant",
    label_names=("lane", "tenant"),
)
SOLVER_ADMISSION_REQUESTS = SOLVER_METRICS.counter(
    "solver_admission_requests_total",
    "Requests dispatched to the device, by batch mode",
    label_names=("mode",),  # coalesced | lanes | solo
)
SOLVER_ADMISSION_BATCHES = SOLVER_METRICS.counter(
    "solver_admission_batches_total",
    "Device dispatches (coalesce ratio = requests_total / this)",
)

# -- koord-descheduler (pkg/descheduler/metrics) ----------------------------

DESCHEDULER_METRICS = Registry("koord-descheduler")
PODS_EVICTED = DESCHEDULER_METRICS.counter(
    "descheduler_pods_evicted_total",
    "Pods evicted/migrated by descheduling",
    label_names=("strategy", "node"),
)
DESCHEDULE_LOOP_DURATION = DESCHEDULER_METRICS.histogram(
    "descheduler_loop_duration_seconds",
    "One descheduling cycle's latency",
)
MIGRATION_JOBS = DESCHEDULER_METRICS.counter(
    "descheduler_migration_jobs_total",
    "PodMigrationJobs by phase transition",
    label_names=("phase",),
)
