"""Prometheus-style metrics: registries per component + merged gather.

Reference: pkg/scheduler/metrics/, pkg/koordlet/metrics/ (internal +
external registries merged by pkg/util/metrics/merged_gather.go),
pkg/descheduler/metrics/.
"""

from koordinator_tpu.metrics.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MergedGatherer,
    Registry,
)
from koordinator_tpu.metrics.components import (  # noqa: F401
    DESCHEDULER_METRICS,
    KOORDLET_EXTERNAL_METRICS,
    KOORDLET_INTERNAL_METRICS,
    SCHEDULER_METRICS,
)
