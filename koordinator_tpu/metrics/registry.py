"""Minimal prometheus-compatible metric primitives.

Counter / Gauge / Histogram with label sets, a Registry that gathers them
into the text exposition format, and a MergedGatherer combining several
registries (reference: component-base prometheus wrappers +
pkg/util/metrics/merged_gather.go).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

LabelValues = Tuple[str, ...]


def _label_str(names: Sequence[str], values: LabelValues) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{v}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = "",
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help_text = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Optional[Mapping[str, str]]) -> LabelValues:
        labels = labels or {}
        extra = set(labels) - set(self.label_names)
        missing = set(self.label_names) - set(labels)
        if extra or missing:
            raise ValueError(
                f"{self.name}: labels mismatch (extra={sorted(extra)}, "
                f"missing={sorted(missing)})"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def samples(self) -> Iterable[Tuple[str, str, float]]:
        """(name, label string, value) triples."""
        raise NotImplementedError

    def expose(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help_text}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for name, label_str, value in self.samples():
            v = int(value) if float(value).is_integer() else value
            lines.append(f"{name}{label_str} {v}")
        return lines


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text="", label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def inc(self, labels: Optional[Mapping[str, str]] = None,
            amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labels: Optional[Mapping[str, str]] = None) -> float:
        return self._values.get(self._key(labels), 0.0)

    def samples(self):
        for key, value in sorted(self._values.items()):
            yield self.name, _label_str(self.label_names, key), value


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text="", label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: Dict[LabelValues, float] = {}

    def set(self, value: float,
            labels: Optional[Mapping[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def add(self, amount: float,
            labels: Optional[Mapping[str, str]] = None) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labels: Optional[Mapping[str, str]] = None) -> float:
        return self._values.get(self._key(labels), 0.0)

    def samples(self):
        for key, value in sorted(self._values.items()):
            yield self.name, _label_str(self.label_names, key), value


#: default duration buckets (prometheus DefBuckets)
DEF_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text="", label_names=(),
                 buckets: Sequence[float] = DEF_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}

    def observe(self, value: float,
                labels: Optional[Mapping[str, str]] = None) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, labels: Optional[Mapping[str, str]] = None) -> int:
        return self._totals.get(self._key(labels), 0)

    def sum(self, labels: Optional[Mapping[str, str]] = None) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def samples(self):
        for key in sorted(self._totals):
            cumulative = 0
            for bound, count in zip(self.buckets, self._counts[key]):
                cumulative += count
                labels = _label_str(
                    self.label_names + ("le",), key + (str(bound),)
                )
                yield f"{self.name}_bucket", labels, cumulative
            inf_labels = _label_str(self.label_names + ("le",), key + ("+Inf",))
            yield f"{self.name}_bucket", inf_labels, self._totals[key]
            base = _label_str(self.label_names, key)
            yield f"{self.name}_sum", base, self._sums[key]
            yield f"{self.name}_count", base, self._totals[key]


class Registry:
    """A named collection of metrics (prometheus.Registry)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self.register(Counter(name, help_text, label_names))

    def gauge(self, name, help_text="", label_names=()) -> Gauge:
        return self.register(Gauge(name, help_text, label_names))

    def histogram(self, name, help_text="", label_names=(),
                  buckets=DEF_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, label_names, buckets))

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def gather(self) -> str:
        """Text exposition of every registered metric."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].expose())
        return "\n".join(lines) + ("\n" if lines else "")


class MergedGatherer:
    """Gathers several registries as one endpoint (merged_gather.go —
    koordlet serves internal + external sets together)."""

    def __init__(self, registries: Sequence[Registry]):
        self.registries = list(registries)

    def gather(self) -> str:
        return "".join(r.gather() for r in self.registries)
