"""koordinator-tpu: a TPU-native QoS-based co-location scheduling framework.

A from-scratch rebuild of the capabilities of Koordinator (a Kubernetes
QoS co-location scheduling system, reference at /root/reference) designed
TPU-first: cluster state (node allocatable/usage, pod requests, QoS /
priority / quota / gang masks) lives as device-resident dense arrays, and
the scheduler's Filter/Score/bin-pack inner loop, the elastic-quota
water-filling, gang admission, and the descheduler's rebalance loop run as
batched, sharded JAX/XLA computations over a `jax.sharding.Mesh`.

Package layout (mirrors the reference's component inventory, SURVEY.md §2):

- ``apis``        — the protocol: QoS classes, priority bands, resource
                    names/units, CRD-equivalent typed objects.
- ``state``       — the array substrate: cluster snapshots as dense arrays.
- ``ops``         — pure jit-safe math: filter masks, scoring, bin-packing,
                    quota water-filling, gang feasibility.
- ``parallel``    — mesh/sharding: the solver sharded over device meshes.
- ``models``      — end-to-end solver pipelines: batched placement with
                    the fine-grained propose/validate/refine loop.
- ``scheduler``   — scheduling framework (plugin extension points), the
                    seven reference plugins, preemption, reservation
                    lifecycle, cache/monitor.
- ``descheduler`` — load-aware rebalancing + migration controller.
- ``manager``     — central controllers: node resource overcommit
                    calculator, NodeSLO renderer, collect policy.
- ``webhook``     — admission: ClusterColocationProfile mutation, pod
                    validation, quota topology guard.
- ``quota``       — hierarchical quota core, multi-tree registry, profile
                    controller.
- ``numa``/``device``/``gang`` — fine-grained allocators + gang states.
- ``koordlet``    — node agent: metric cache, collectors (incl. native
                    CPI), QoS strategies, cgroup executor, runtimehooks,
                    prediction, pleg, audit.
- ``native``      — C++ perf-group reader bound via ctypes.
- ``features``    — the three feature-gate registries.
- ``cmd``         — component entry points (config objects + CLIs).
- ``oracle``      — host-side reference-semantics oracles for testing.
"""

__version__ = "0.1.0"
