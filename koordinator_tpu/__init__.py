"""koordinator-tpu: a TPU-native QoS-based co-location scheduling framework.

A from-scratch rebuild of the capabilities of Koordinator (a Kubernetes
QoS co-location scheduling system, reference at /root/reference) designed
TPU-first: cluster state (node allocatable/usage, pod requests, QoS /
priority / quota / gang masks) lives as device-resident dense arrays, and
the scheduler's Filter/Score/bin-pack inner loop, the elastic-quota
water-filling, gang admission, and the descheduler's rebalance loop run as
batched, sharded JAX/XLA computations over a `jax.sharding.Mesh`.

Package layout (mirrors the reference's component inventory, SURVEY.md §2):

- ``apis``        — the protocol: QoS classes, priority bands, resource
                    names/units, CRD-equivalent typed objects.
- ``state``       — the array substrate: cluster snapshots as dense arrays.
- ``ops``         — pure jit-safe math: filter masks, scoring, bin-packing,
                    quota water-filling, gang feasibility.
- ``parallel``    — mesh/sharding: pjit/shard_map solver over device meshes.
- ``models``      — end-to-end solver pipelines ("flagship models"):
                    placement, rebalance.
- ``scheduler``   — scheduling framework (plugin extension points) + the
                    seven reference plugins rebuilt on the array substrate.
- ``descheduler`` — load-aware rebalancing + migration controller.
- ``manager``     — central controllers: node resource overcommit
                    calculator, NodeSLO renderer, mutating webhooks.
- ``koordlet``    — node agent: metric cache, collectors, QoS strategies,
                    cgroup executor, prediction.
- ``runtimeproxy``— CRI interposition skeleton.
- ``utils``       — cpuset, sloconfig defaults, parallel helpers.
- ``native``      — C++ perf/cgroup helpers loaded via ctypes (optional).
"""

__version__ = "0.1.0"
