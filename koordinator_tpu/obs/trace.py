"""SpanTracer: the low-overhead span layer under the trace fabric.

Design constraints (docs/DESIGN.md §16):

- **Hot-path cost is one append.** Emitting a span is a lock acquire +
  a tuple append into a bounded ``deque`` — no dict churn, no string
  formatting, no I/O. Timestamps come from one monotonic clock
  (``time.perf_counter``), the SAME base the solve path already uses
  for its timing dicts, so retroactive spans (lower/stage, the device
  solve) can be emitted from measurements the hot path took anyway.
- **Tracing never changes scheduling.** Spans record wall time only;
  there is no device read-back, no blocking, and a disabled tracer's
  ``emit`` returns after one attribute read — ticks are bit-identical
  with tracing on or off (bench leg 13 proves it every run).
- **Bounded by construction.** The ring drops the oldest span at
  capacity; a tracer can run for weeks without growing.

Two extra facilities ride the same lock:

- **Open marks** (``mark_open``/``mark_closed``): coarse round/publish
  lifetime markers the :class:`~koordinator_tpu.scheduler.monitor.
  SchedulerMonitor` watchdog reads — a mark that stays open past the
  timeout is a stuck round/publish. Marks are tracked even when span
  recording is disabled, so the watchdog never goes blind.
- **Round/span ids**: ``begin_round`` numbers scheduling rounds; every
  span carries the current round id so cross-thread (and, via the
  codec's ``trace`` group, cross-process) spans join one trace.

Export is Chrome trace event format (``chrome_trace()``): load the
JSON at https://ui.perfetto.dev and each thread (scheduler coordinator,
tick publisher, admission gate, sidecar handler) renders as its own
track — the pipelined overlap of stage(N+1) against solve(N) is
directly visible as overlapping slices.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple


class SpanTracer:
    """Thread-safe bounded span ring + open-mark registry.

    Every mutable attribute below is mapped to ``_lock`` in
    graftcheck's lock-discipline registry; ``enabled`` is a plain flag
    read without the lock (a torn read costs at most one span).
    """

    def __init__(self, capacity: int = 16384,
                 clock=time.perf_counter, enabled: bool = True):
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        #: span tuples (name, cat, t0, dur, track, round_id, args);
        #: dur < 0 marks an instant event
        self._events: deque = deque(maxlen=capacity)
        #: open coarse marks: key -> (t0, track, round_id)
        self._open: Dict[str, Tuple[float, str, int]] = {}
        #: open marks already counted stuck (scheduler/monitor.py) —
        #: lives WITH the mark so N watchdogs over one tracer
        #: (leader + standby in one process) count a stuck mark once
        self._stuck: set = set()
        self._round = 0
        self._next_span = 0
        self._emitted = 0

    # -- clock / ids ---------------------------------------------------------

    def now(self) -> float:
        """The tracer clock (monotonic; same base as perf_counter
        timings taken by the solve path, so retro spans line up)."""
        return self._clock()

    def begin_round(self) -> int:
        """Number a new scheduling round; spans emitted until the next
        call carry this id."""
        with self._lock:
            self._round += 1
            return self._round

    @property
    def round_id(self) -> int:
        with self._lock:
            return self._round

    def next_span_id(self) -> int:
        """A process-unique span id (wire trace context: the sidecar
        tags its spans with the scheduler's (round, span) pair)."""
        with self._lock:
            self._next_span += 1
            return self._next_span

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)

    # -- recording -----------------------------------------------------------

    def emit(self, name: str, cat: str = "", t0: float = 0.0,
             t1: Optional[float] = None, track: Optional[str] = None,
             round_id: Optional[int] = None, args=None) -> None:
        """Record one complete span [t0, t1] (tracer-clock seconds).
        Retro-friendly: the hot path measures with perf_counter anyway,
        so spans are emitted AFTER the fact from those timestamps."""
        if not self.enabled:
            return
        if track is None:
            track = threading.current_thread().name
        if t1 is None:
            t1 = self._clock()
        with self._lock:
            if round_id is None:
                round_id = self._round
            self._emitted += 1
            self._events.append(
                (name, cat, t0, t1 - t0, track, round_id, args)
            )

    def instant(self, name: str, cat: str = "",
                track: Optional[str] = None,
                round_id: Optional[int] = None, args=None) -> None:
        """Record a point event (state transitions: failover flips,
        breaker trips, supervisor restarts, fencing aborts)."""
        if not self.enabled:
            return
        if track is None:
            track = threading.current_thread().name
        t = self._clock()
        with self._lock:
            if round_id is None:
                round_id = self._round
            self._emitted += 1
            self._events.append((name, cat, t, -1.0, track, round_id, args))

    @contextmanager
    def span(self, name: str, cat: str = "", args=None):
        """Convenience context manager for non-hot callers (cmd-level
        wiring, tests). Hot code uses explicit emit() with timestamps
        it already measured."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.emit(name, cat, t0, self._clock(), args=args)

    # -- open marks (the watchdog's food) ------------------------------------

    def mark_open(self, key: str, round_id: Optional[int] = None) -> None:
        """Open a coarse lifetime mark (``round:<id>``/``publish:<id>``).
        Tracked even when span recording is disabled — the stuck-cycle
        watchdog must work with tracing off."""
        track = threading.current_thread().name
        t = self._clock()
        with self._lock:
            if round_id is None:
                round_id = self._round
            self._open[key] = (t, track, round_id)
            self._stuck.discard(key)

    def mark_closed(self, key: str, name: Optional[str] = None,
                    cat: str = "", args=None) -> Optional[float]:
        """Close a mark; with ``name`` set, also emit the covered span.
        Returns the mark's duration (None for an unknown key)."""
        t1 = self._clock()
        with self._lock:
            entry = self._open.pop(key, None)
            self._stuck.discard(key)
            if entry is None:
                return None
            t0, track, round_id = entry
            if name is not None and self.enabled:
                self._emitted += 1
                self._events.append(
                    (name, cat, t0, t1 - t0, track, round_id, args)
                )
        return t1 - t0

    def open_marks(self) -> Dict[str, Tuple[float, str, int]]:
        with self._lock:
            return dict(self._open)

    def flag_stuck(self, key: str) -> bool:
        """Atomically flag an open mark as counted-stuck. True only for
        the FIRST flagging of a still-open mark — the flag lives with
        the mark so N watchdogs over one tracer (leader + standby in
        one process, plus debug-mux status() readers) count a stuck
        mark once, and a mark that closed between the caller's snapshot
        and this call is never flagged (close drops the flag)."""
        with self._lock:
            if key not in self._open or key in self._stuck:
                return False
            self._stuck.add(key)
            return True

    # -- read side -----------------------------------------------------------

    @property
    def span_count(self) -> int:
        """Total spans emitted over the tracer's lifetime (the ring may
        hold fewer) — bench.py derives trace_overhead_ratio from it."""
        with self._lock:
            return self._emitted

    def events(self, tail: Optional[int] = None) -> List[dict]:
        """Structured snapshot of the ring (tests, debug payloads).
        ``tail`` bounds the snapshot to the newest N spans — the flight
        recorder's dumps slice under the lock instead of materializing
        a 16k-span ring to keep 200 entries."""
        with self._lock:
            if tail is not None and len(self._events) > tail:
                from itertools import islice

                snap = list(islice(
                    self._events, len(self._events) - tail, None
                ))
            else:
                snap = list(self._events)
        return [
            {
                "name": name, "cat": cat, "t0": t0,
                "dur": (None if dur < 0 else dur), "track": track,
                "round": rid, "args": args,
            }
            for name, cat, t0, dur, track, rid, args in snap
        ]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._open.clear()
            self._stuck.clear()

    def chrome_trace(self) -> dict:
        """The ring as a Chrome trace event object (Perfetto-loadable).

        Complete spans become ``ph: "X"`` duration events, instants
        become ``ph: "i"``; each distinct track gets a stable tid plus
        a ``thread_name`` metadata record so Perfetto labels the
        coordinator / publisher / gate / sidecar tracks."""
        with self._lock:
            snap = list(self._events)
        tids: Dict[str, int] = {}
        trace_events: List[dict] = []
        for name, cat, t0, dur, track, rid, args in snap:
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
            ev_args = {"round": rid}
            if args:
                ev_args.update(args)
            ev = {
                "name": name, "cat": cat or "span", "pid": 1, "tid": tid,
                "ts": int(t0 * 1e6), "args": ev_args,
            }
            if dur < 0:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = max(int(dur * 1e6), 1)
            trace_events.append(ev)
        for track, tid in tids.items():
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": track},
            })
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def status(self) -> dict:
        """Debug-mux summary (the full export lives at /debug/trace)."""
        with self._lock:
            buffered = len(self._events)
            emitted = self._emitted
            opens = {
                k: {"age_s": self._clock() - t0, "track": track,
                    "round": rid}
                for k, (t0, track, rid) in self._open.items()
            }
            rnd = self._round
        return {
            "enabled": self.enabled,
            "rounds": rnd,
            "spans_emitted": emitted,
            "spans_buffered": buffered,
            "open_marks": opens,
        }


#: the process tracer every component records into (one trace per
#: process, like the metric registries)
TRACER = SpanTracer()
