"""Anomaly flight recorder: recent rounds, dumped on trigger.

When the auditor detects drift, the failover flips, a fenced publish
aborts, a pipelined publish defers an error, or a solve blows its
deadline, the question is always "what were the last N rounds doing?"
— and by the time a human is looking, the answer is gone. This module
keeps a bounded ring of per-round records (stage durations, staged
epoch, solver mode, breaker/failover state, placement counts) that the
tick paths append to every round, and dumps the whole ring — plus the
trace ring's tail — to a JSON file the moment a trigger fires:

- ``auditor-detection``       (scheduler/auditor.py: a sweep found drift)
- ``failover-flip``           (service/failover.py: either direction)
- ``fencing-abort``           (cmd/scheduler.py run_loop: FencingError)
- ``pipeline-deferred-error`` (scheduler/pipeline.py: a publish-side
  failure was deferred to the next round boundary)
- ``deadline-exceeded``       (service/client.py: a solve's latency
  budget ran out)

Dumps are rate-limited per trigger (a flapping failover must not write
a dump storm), counted in ``scheduler_flight_dumps_total{trigger}``,
and indexed in memory for the debug mux. Recording costs one lock +
ring append per round; a dump does file I/O but only ever fires on an
anomaly — never on the healthy path.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from koordinator_tpu.obs.trace import TRACER

#: trace-ring tail included in every dump (enough to see the anomalous
#: round's span structure without shipping the whole ring)
_TRACE_TAIL = 200


def _default_dump_dir() -> str:
    return os.environ.get(
        "KTPU_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "koord-flight"),
    )


class FlightRecorder:
    """Bounded round-record ring + triggered JSON dumps.

    Every mutable attribute below is mapped to ``_lock`` in
    graftcheck's lock-discipline registry."""

    TRIGGERS = (
        "auditor-detection", "failover-flip", "fencing-abort",
        "pipeline-deferred-error", "deadline-exceeded", "manual",
    )

    def __init__(self, capacity: int = 64,
                 dump_dir: Optional[str] = None,
                 min_interval_s: float = 1.0,
                 max_files: int = 64,
                 clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        #: {path, trigger, at, detail} per dump, newest last
        self._dumps: deque = deque(maxlen=32)
        self._last_dump: Dict[str, float] = {}
        self._dump_dir = dump_dir
        self._min_interval_s = min_interval_s
        #: dump files THIS recorder wrote, oldest first; beyond
        #: max_files the oldest is unlinked (disk-bounded by
        #: construction, like every ring in the fabric)
        self._files: List[str] = []
        self._max_files = max_files
        self._seq = 0
        #: name -> zero-arg payload fn stamped into every dump as a
        #: top-level section (the SLO controller registers its
        #: decision-ring tail here; hooks must be cheap and cached-only
        #: — a dump never compiles). A raising hook degrades to a typed
        #: error section, never a lost dump.
        self._payload_hooks: Dict[str, object] = {}

    def configure(self, dump_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  min_interval_s: Optional[float] = None) -> None:
        """Runtime configuration (cmd flags / tests)."""
        with self._lock:
            if dump_dir is not None:
                self._dump_dir = dump_dir
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=capacity)
            if min_interval_s is not None:
                self._min_interval_s = min_interval_s

    def register_payload(self, name: str, fn) -> None:
        """Stamp ``fn()``'s dict into every future dump under ``name``
        (reserved section names are refused loudly — a hook must not
        shadow the core dump sections)."""
        if name in ("trigger", "at", "detail", "extra", "rounds",
                    "device", "warm", "open_spans", "trace_tail"):
            raise ValueError(f"flight payload name {name!r} is reserved")
        with self._lock:
            self._payload_hooks[name] = fn

    def unregister_payload(self, name: str) -> None:
        with self._lock:
            self._payload_hooks.pop(name, None)

    # -- the per-round feed --------------------------------------------------

    def record_round(self, record: dict) -> None:
        """Append one round record (the tick paths call this every
        round — keep records small and host-only)."""
        with self._lock:
            self._ring.append(record)

    # -- triggers ------------------------------------------------------------

    def trigger(self, reason: str, detail: Optional[str] = None,
                extra: Optional[dict] = None) -> Optional[str]:
        """An anomaly fired: dump the ring (+ trace tail) to JSON.
        Returns the dump path, or None when rate-limited or the write
        failed (a failed dump is recorded in memory — observability
        must never crash the scheduler)."""
        from koordinator_tpu.metrics.components import FLIGHT_DUMPS

        at = self._clock()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and at - last < self._min_interval_s:
                return None
            self._last_dump[reason] = at
            self._seq += 1
            seq = self._seq
            rounds = list(self._ring)
            dump_dir = self._dump_dir or _default_dump_dir()
            hooks = dict(self._payload_hooks)
        TRACER.instant("flight-dump", cat="flight",
                       args={"trigger": reason})
        # the device-cost observatory's cached snapshot: "did we just
        # recompile / run out of headroom" answered from the dump alone
        # (cached analyses only — a dump never compiles; imported here
        # rather than at module top to keep obs.device free to import
        # the flight recorder in the future without a cycle)
        from koordinator_tpu.obs.device import DEVICE_OBS

        try:
            device = DEVICE_OBS.flight_payload()
        except Exception as e:  # a dump must land even if jax is upset
            device = {"error": f"{type(e).__name__}: {e}"}
        # the warm pool's cached counters (DESIGN §21): was the round
        # that anomalied served warm or cold, and is the store healthy
        # — counters only, a dump never compiles or touches the store
        try:
            from koordinator_tpu.service.warmpool import WARM_POOL

            warm = WARM_POOL.flight_payload()
        except Exception as e:
            warm = {"error": f"{type(e).__name__}: {e}"}
        payload = {
            "trigger": reason,
            "at": at,
            "detail": detail,
            "extra": extra,
            "rounds": rounds,
            "device": device,
            "warm": warm,
            "open_spans": TRACER.status()["open_marks"],
            "trace_tail": TRACER.events(tail=_TRACE_TAIL),
        }
        for name in sorted(hooks):
            try:
                payload[name] = hooks[name]()
            except Exception as e:  # a broken hook never loses a dump
                payload[name] = {"error": f"{type(e).__name__}: {e}"}
        path = os.path.join(dump_dir, f"flight-{reason}-{seq:04d}.json")
        error = None
        pruned = None
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, default=str)
        except OSError as e:
            error, path = f"{type(e).__name__}: {e}", None
        if error is None:
            # counted AFTER the write lands: the metric says "dumps
            # written", and the runbook sends operators from a nonzero
            # count to the dump directory — a failed write must not
            # point them at a file that does not exist (it is still
            # recorded, with its error, in the in-memory dump log)
            FLIGHT_DUMPS.inc({"trigger": reason})
        with self._lock:
            self._dumps.append({
                "path": path, "trigger": reason, "at": at,
                "detail": detail, "error": error,
            })
            if path is not None:
                # disk retention: the rate limit bounds the RATE, this
                # bounds the TOTAL — a trigger flapping for a week must
                # not fill the disk with dump files
                self._files.append(path)
                if len(self._files) > self._max_files:
                    pruned = self._files.pop(0)
        if pruned is not None:
            try:
                os.unlink(pruned)
            except OSError:
                pass
        return path

    # -- read side -----------------------------------------------------------

    def dumps(self) -> List[dict]:
        with self._lock:
            return list(self._dumps)

    def recent_rounds(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def status(self) -> dict:
        with self._lock:
            return {
                "rounds_buffered": len(self._ring),
                "dump_dir": self._dump_dir or _default_dump_dir(),
                "min_interval_s": self._min_interval_s,
                "dumps": list(self._dumps),
            }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dumps.clear()
            self._last_dump.clear()
            self._files.clear()


#: the process flight recorder (one per process, like the tracer)
FLIGHT = FlightRecorder()
