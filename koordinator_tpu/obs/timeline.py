"""Per-pod timelines: submit → staged → solved → published.

The round-level histograms (PR 11's per-stage tick breakdown) say how
fast ROUNDS are; a latency-SLO serving mode (ROADMAP item 2) needs to
know how fast PODS are — the wall time from a pod entering the pending
queue to its bind publishing on the bus, per QoS lane. This module
keeps a bounded registry of in-flight pod timelines, stamped at the
four scheduler-side lifecycle points:

- **submit**    — the pod entered the pending queue (``Scheduler.
  add_pod``; the in-process bus has no separate intake hop, so submit
  and enqueue collapse to one stamp here).
- **staged**    — a round's snapshot picked the pod up
  (``begin_tick``).
- **solved**    — the device solve placed it (``commit_tick`` — the
  epilogue's assume).
- **published** — the bind landed on the bus (the wiring's
  ``publish_result``). This closes the timeline: the e2e wall is
  observed into ``scheduler_pod_e2e_seconds{lane}`` and the record
  moves to a bounded completed ring the bench legs read p50/p99 from.

A pod deleted or evicted while pending is ``forget``-ten without
observing — an abandoned submit is not a latency sample.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass

#: lane names, mirroring service/admission.LANE_NAMES (not imported:
#: the admission module pulls in jax; the timeline layer stays stdlib)
LANES = ("system", "ls", "be")


def lane_of(pod) -> str:
    """QoS lane label for a pod (system > latency-sensitive > BE) —
    the same mapping as service/admission.lane_for_qos."""
    qos = getattr(pod, "qos", None)
    if qos == QoSClass.SYSTEM:
        return "system"
    if qos == QoSClass.BE:
        return "be"
    return "ls"


class PodTimelines:
    """Bounded per-pod stage-stamp registry + completed-latency ring.

    ``histogram`` defaults to the global ``scheduler_pod_e2e_seconds``;
    tests inject their own (and a fake ``clock``) to check the observed
    buckets exactly. Every mutable attribute below is mapped to
    ``_lock`` in graftcheck's lock-discipline registry."""

    STAGES = ("submit", "staged", "solved", "published")

    def __init__(self, capacity: int = 8192,
                 completed_capacity: int = 4096,
                 clock=time.perf_counter, histogram=None):
        if histogram is None:
            from koordinator_tpu.metrics.components import POD_E2E

            histogram = POD_E2E
        self._histogram = histogram
        self._clock = clock
        self._capacity = capacity
        self._lock = threading.Lock()
        #: uid -> (lane, {stage: t}) — at capacity new submits are
        #: refused (counted in ``_dropped``), the waiting tail is kept
        self._active: Dict[str, tuple] = {}
        #: (lane, e2e_s, {stage: t}) for published pods
        self._completed: deque = deque(maxlen=completed_capacity)
        #: submits refused at capacity (the backlog cost samples)
        self._dropped = 0
        #: optional backpressure hook, called (outside the lock) with
        #: the refused uid whenever a submit is dropped at capacity —
        #: the streaming intake wires its shed accounting here so a
        #: refused sample is visible as backpressure, not silence
        self._on_drop = None
        #: (lane, reason, t) for pods the intake RESOLVED as failures
        #: (shed at capacity / expired past their lane deadline): the
        #: failure tail folded into the same rolling surface the
        #: survivor percentiles come from — a dashboard (or the SLO
        #: controller) reading stats(window_s=) must see a lane that
        #: sheds half its arrivals, not just the p99 of the half that
        #: made it through
        self._failures: deque = deque(maxlen=completed_capacity)

    # -- stamps --------------------------------------------------------------

    def set_drop_hook(self, hook) -> None:
        """Wire (or clear, with None) the capacity-refusal hook: called
        with the refused uid, outside the lock, once per drop."""
        with self._lock:
            self._on_drop = hook

    def submit(self, uid: str, lane: str = "ls") -> None:
        """Open a timeline (idempotent: informer refreshes of a pending
        pod must not reset its submit stamp)."""
        t = self._clock()
        with self._lock:
            if uid in self._active:
                return
            if len(self._active) >= self._capacity:
                # refuse the NEW timeline, never evict the oldest: the
                # longest-waiting pods are exactly the p99 tail the
                # histogram exists to observe, so a backlog past
                # capacity must cost the newest samples, not the tail
                # (and never memory) — counted so the gap is visible
                self._dropped += 1
                hook = self._on_drop
            else:
                self._active[uid] = (lane, {"submit": t})
                return
        if hook is not None:
            hook(uid)

    def mark(self, uid: str, stage: str) -> None:
        t = self._clock()
        with self._lock:
            entry = self._active.get(uid)
            if entry is not None:
                entry[1].setdefault(stage, t)

    def mark_many(self, uids, stage: str) -> None:
        t = self._clock()
        with self._lock:
            for uid in uids:
                entry = self._active.get(uid)
                if entry is not None:
                    entry[1].setdefault(stage, t)

    def published(self, uid: str) -> Optional[float]:
        """Close a timeline: observe submit→published into the lane
        histogram, move the record to the completed ring. Returns the
        e2e seconds (None for an unknown uid)."""
        t = self._clock()
        with self._lock:
            entry = self._active.pop(uid, None)
            if entry is None:
                return None
            lane, stamps = entry
            stamps["published"] = t
            e2e = t - stamps["submit"]
            self._completed.append((lane, e2e, stamps))
        self._histogram.observe(e2e, {"lane": lane})
        return e2e

    def forget(self, uid: str) -> None:
        """Drop a timeline without observing (pod deleted/evicted while
        pending — not a latency sample)."""
        with self._lock:
            self._active.pop(uid, None)

    def note_shed(self, lane: str, reason: str, uid: Optional[str] = None) -> None:
        """Record an intake failure resolution (``capacity`` /
        ``deadline-exceeded``) into the rolling failure ring, and close
        the pod's active timeline without observing — a shed pod is a
        FAILURE sample for the window counters, never a latency one."""
        t = self._clock()
        with self._lock:
            self._failures.append((lane, reason, t))
            if uid is not None:
                self._active.pop(uid, None)

    @contextmanager
    def preserved(self, uid: str):
        """Carry a timeline across a forget/submit round-trip. The
        scheduler's accounted-field refresh of a PENDING pod re-runs
        remove_pod + add_pod for the quota/gang side effects, but the
        pod never left the queue — its original stamps (the submit
        above all) must survive, or the e2e histogram reports only the
        post-refresh tail of the wait. The refreshed pod's lane wins
        (a QoS change relabels the sample); original stamps win over
        the round-trip's fresh ones."""
        with self._lock:
            entry = self._active.get(uid)
            kept = (entry[0], dict(entry[1])) if entry is not None else None
        try:
            yield
        finally:
            if kept is not None:
                with self._lock:
                    cur = self._active.get(uid)
                    if cur is not None:
                        stamps = dict(cur[1])
                        stamps.update(kept[1])
                        self._active[uid] = (cur[0], stamps)
                    else:
                        # the re-add was refused at capacity (or never
                        # happened): the pre-existing sample keeps its
                        # slot rather than being silently dropped
                        self._active[uid] = kept

    # -- read side -----------------------------------------------------------

    def stats(self, window_s: Optional[float] = None) -> dict:
        """p50/p99 submit→published over the completed ring, overall
        and per lane — what bench legs 10/13/18 record. With
        ``window_s``, only samples PUBLISHED within the trailing
        window count: the rolling view a serving dashboard needs (the
        all-time ring mixes a cold start's tail into steady state)."""
        cutoff = None if window_s is None else self._clock() - window_s
        with self._lock:
            samples = [
                (lane, e2e) for lane, e2e, stamps in self._completed
                if cutoff is None or stamps.get("published", 0) >= cutoff
            ]
            failures = [
                (lane, reason) for lane, reason, t in self._failures
                if cutoff is None or t >= cutoff
            ]

        def pct(xs: List[float]) -> dict:
            if not xs:
                return {"count": 0, "p50_s": None, "p99_s": None}
            xs = sorted(xs)
            hi = min(len(xs) - 1, -(-99 * (len(xs) - 1) // 100))
            return {
                "count": len(xs),
                "p50_s": xs[len(xs) // 2],
                "p99_s": xs[hi],
            }

        def shed_counts(fs) -> dict:
            counts: dict = {}
            for _, reason in fs:
                counts[reason] = counts.get(reason, 0) + 1
            return counts

        out = {"all": pct([e for _, e in samples])}
        out["all"]["shed"] = shed_counts(failures)
        for lane in LANES:
            lane_samples = [e for l, e in samples if l == lane]
            lane_failures = [(l, r) for l, r in failures if l == lane]
            if lane_samples or lane_failures:
                out[lane] = pct(lane_samples)
                out[lane]["shed"] = shed_counts(lane_failures)
        return out

    #: rolling-window width served by status() (seconds of the
    #: timeline clock — the trailing view beside the all-time ring)
    ROLLING_WINDOW_S = 30.0

    def status(self) -> dict:
        """Debug-mux payload: in-flight depth, the dropped-sample
        backpressure counter, all-time AND rolling-window latency
        percentiles."""
        with self._lock:
            inflight = len(self._active)
            completed = len(self._completed)
            dropped = self._dropped
        return {
            "inflight": inflight,
            "completed": completed,
            "dropped": dropped,
            "latency": self.stats(),
            "rolling": {
                "window_s": self.ROLLING_WINDOW_S,
                **self.stats(window_s=self.ROLLING_WINDOW_S),
            },
        }

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._completed.clear()
            self._failures.clear()
            self._dropped = 0
            self._on_drop = None
