"""DeviceObservatory: the device-side twin of the span tracer.

The trace fabric (docs/DESIGN.md §16) made the *host* side of every
round observable; the device solve stayed a black box — we knew
``solve_s``, not why. This module closes that gap with four surfaces,
all capability-gated and all off the solve's critical path:

- **Compile telemetry.** The hot jit callsites (models/placement.py,
  ops/binpack.py, service/server.py, service/admission.py,
  service/failover.py, parallel/mesh.py) wrap their callables in
  :meth:`DeviceObservatory.jit`: a signature-miss call is timed and
  recorded — count, wall, and the triggering shape signature — into
  ``solver_device_compile_total{fn}`` / ``solver_device_compile_seconds``
  and a bounded ring served at ``/debug/device``. A process-wide
  ``jax.monitoring`` listener additionally counts EVERY backend
  compilation (``solver_device_xla_compiles_total``), attributed or
  not. Together they turn graftcheck's boolean zero-recompile guard
  into a quantitative, always-on counter.
- **Cost & memory analysis.** Each observed compile registers its
  abstract signature (``jax.ShapeDtypeStruct`` pytree, statics by
  value). :meth:`analyze` later re-lowers FROM THOSE AVALS —
  ``fn.lower(*avals).compile().cost_analysis()`` / ``memory_analysis()``
  — so FLOPs, bytes accessed, and argument/output/temp/peak bytes per
  jitted solve variant come without ever touching live (possibly
  donated) buffers. Analysis is lazy and memoized: it runs on debug
  reads, bench fingerprints, and flight dumps — never per tick — and
  each analysis costs one extra backend compile, counted like any
  other.
- **Padding waste + live buffers.** The pow2/bucket shape paddings
  (pod batches, reservation tables, dirty-row scatters, admission
  coalescing) report real vs padded rows at stage time into
  ``solver_device_padding_waste_ratio{buffer}`` — the number that says
  when bucketing is burning device time. ``jax.live_arrays()``
  count/bytes (plus registered per-owner accounting, e.g. the staged
  state cache) are sampled on status/debug reads only.
- **On-demand profiler windows.** :meth:`request_profile` arms a
  window; the next K scheduling rounds (``on_round`` is called by
  ``Scheduler.begin_tick`` and the sidecar's ``solve_from_request``)
  run under ``jax.profiler.start_trace``/``stop_trace`` with
  :meth:`annotate` scopes matching the span tracer's stage names, so
  the Perfetto host trace and the device profile line up. Windows are
  rate-limited and disk-capped like the flight recorder.

The tick contract mirrors the tracer's: the observatory enabled vs
disabled is observation only — same placements, bit for bit (bench leg
13 proves it every run, paired, with the measured overhead <= 0.02).
Old-jax boxes degrade to loud skips through
:func:`device_observatory_supported`, the same shape as
``parallel.mesh.distributed_kernel_supported``.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Dict, List, Optional, Tuple

import jax

from koordinator_tpu.metrics.components import (
    DEVICE_COMPILES,
    DEVICE_COMPILE_SECONDS,
    DEVICE_LIVE_BUFFERS,
    DEVICE_LIVE_BYTES,
    DEVICE_PADDING_WASTE,
    DEVICE_PROFILE_WINDOWS,
    DEVICE_XLA_COMPILES,
    DEVICE_XLA_COMPILE_SECONDS,
)
from koordinator_tpu.obs.trace import TRACER

#: compile records kept for /debug/device and flight dumps
_RING_CAPACITY = 256
#: analyses memoized per (fn, signature); oldest evicted beyond this
_MAX_ANALYSES = 64
#: un-analyzed signatures queued for the next analyze() pass
_MAX_PENDING = 64
#: (fn, signature) aval pairs retained for the warm-pool manifest
#: (docs/DESIGN.md §21) — the "active shape-bucket set": which jit
#: signatures are hot, with enough aval metadata to AOT-recompile them
#: in a fresh process. Unlike _pending these are NOT consumed by
#: analyze(); beyond the cap new variants are counted but not retained
_MAX_WARM = 64

#: sentinel the warm pool's serve() returns on a miss (any real solve
#: result — including None-free pytrees — must be distinguishable)
WARM_MISS = object()

_NULL_CTX = nullcontext()

#: process-wide guard: the jax.monitoring listener is registered at
#: most once (jax exposes no public unregister)
_MONITOR_INSTALLED = [False]


# -- capability gates --------------------------------------------------------

def _analysis_supported() -> bool:
    """Whether this jax build exposes AOT cost/memory analysis
    (``jax.stages.Compiled.cost_analysis``/``memory_analysis``) and
    aval lowering — jax 0.4.3x does; older builds degrade loudly."""
    compiled = getattr(getattr(jax, "stages", None), "Compiled", None)
    return (
        compiled is not None
        and hasattr(compiled, "cost_analysis")
        and hasattr(compiled, "memory_analysis")
        and hasattr(jax, "ShapeDtypeStruct")
    )


def _monitoring_supported() -> bool:
    return hasattr(
        getattr(jax, "monitoring", None),
        "register_event_duration_secs_listener",
    )


def _profiler_supported() -> bool:
    prof = getattr(jax, "profiler", None)
    return (
        prof is not None
        and hasattr(prof, "start_trace")
        and hasattr(prof, "stop_trace")
    )


def device_observatory_supported() -> bool:
    """Whether the analysis half of the observatory can run on this jax
    build. Compile COUNTING and padding gauges are pure python and work
    everywhere; cost/memory analysis needs the AOT stages API. Callers
    (and tests) treat False as a loud skip, exactly like
    ``distributed_kernel_supported()``."""
    return _analysis_supported()


def _default_profile_dir() -> str:
    return os.environ.get(
        "KTPU_PROFILE_DIR",
        os.path.join(tempfile.gettempdir(), "koord-profile"),
    )


# -- signatures --------------------------------------------------------------

def _leaf_aval(x):
    """An array leaf becomes its abstract signature; static scalars and
    None pass through by value (they ARE part of the program identity
    for static args). The aval branch matters for donated arguments:
    a donated buffer is deleted by the time the post-call recording
    runs, but its aval metadata survives."""
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        return jax.ShapeDtypeStruct(tuple(aval.shape), aval.dtype)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return x


def _leaf_sig(x):
    # the aval fast path matters: str(dtype) on a jax Array costs ~3µs
    # a leaf and this runs per instrumented call — dtype OBJECTS are
    # hashable and compare equal across numpy/jax, so keep them raw
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (aval.shape, aval.dtype)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), dtype)
    return x


def _signature(args, kwargs) -> Tuple:
    """Hashable shape signature of one call: pytree structure + per-leaf
    (shape, dtype), statics by value. One tree_flatten (~µs at solve
    arity) — the only per-call cost of compile telemetry."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


def _sig_str(sig) -> str:
    """Compact human form of a signature for the debug ring."""
    parts = []
    for leaf in sig[1]:
        if isinstance(leaf, tuple) and len(leaf) == 2 and isinstance(
            leaf[0], tuple
        ):
            shape, dtype = leaf
            parts.append("x".join(map(str, shape)) + ":" + str(dtype))
    return ",".join(parts[:12]) + ("..." if len(parts) > 12 else "")


def _cost_dict(ca) -> Dict[str, float]:
    """Normalize ``cost_analysis()`` across jax versions (list-of-dict
    in 0.4.x, dict later) to the two headline numbers."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
    }


def _memory_dict(ma) -> Dict[str, object]:
    """Normalize ``memory_analysis()`` (CompiledMemoryStats; None on
    backends that don't report). ``peak_bytes`` uses the backend's
    peak-buffer stat when present, else the argument+output+temp+alias
    footprint — the staged-residency proxy the bench gate regresses."""
    if ma is None:
        return {"available": False, "argument_bytes": 0, "output_bytes": 0,
                "temp_bytes": 0, "peak_bytes": 0}
    arg = int(getattr(ma, "argument_size_in_bytes", 0))
    out = int(getattr(ma, "output_size_in_bytes", 0))
    temp = int(getattr(ma, "temp_size_in_bytes", 0))
    alias = int(getattr(ma, "alias_size_in_bytes", 0))
    peak = getattr(ma, "peak_buffer_size_in_bytes", None)
    return {
        "available": True,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "peak_bytes": int(peak) if peak else arg + out + temp + alias,
    }


class ObservedJit:
    """A jit-compiled callable with compile telemetry.

    The steady-state cost is two reads of the jit's own C++ cache size
    (~0.1 µs each) and two clock reads: a call that did not grow the
    cache touched nothing else. When the cache DID grow, the call is
    recorded as a compile — count, wall (trace + lower + XLA compile +
    dispatch; no blocking read-back is added to measure it), and the
    triggering shape signature, computed AFTER the fact from the
    arguments' avals (aval metadata survives donation, so donated
    buffers are safe to sign post-call). Callables without a cache-size
    API fall back to a per-call signature probe. The wrapper holds no
    mutable state of its own — everything lives in the observatory
    under its lock."""

    __slots__ = ("fn_name", "_fn", "_obs", "_size_fn", "_warm")

    def __init__(self, fn_name: str, fn, obs: "DeviceObservatory"):
        self.fn_name = fn_name
        self._fn = fn
        self._obs = obs
        self._size_fn = getattr(fn, "_cache_size", None)
        #: warm pool this binding is adopted into (service/warmpool.
        #: WarmPool.adopt) — set-once wiring at construction time, read
        #: per call without a lock like ``enabled``. None = not warm.
        self._warm = None

    def __call__(self, *args, **kwargs):
        warm = self._warm
        if warm is not None and warm.serving:
            # warm-pool fast path: a restored AOT executable answers
            # the call with zero tracing and zero compilation — the
            # restart/promotion/degraded-flip paths' whole point. A
            # miss (unknown signature, poisoned entry) falls through
            # to the ordinary jit below. A warm-served call records no
            # compile telemetry BY DESIGN — there was no compile —
            # exactly like a warmed jit-cache hit; the pool's own
            # hit/served counters are the warm path's observability.
            out = warm.serve(self.fn_name, args, kwargs)
            if out is not WARM_MISS:
                return out
        obs = self._obs
        if not obs.enabled:
            return self._fn(*args, **kwargs)
        size_fn = self._size_fn
        if size_fn is None:
            # fallback probe: dedup by signature alone (no cross-check
            # available — a warm program re-probed counts once)
            sig = _signature(args, kwargs)
            if obs._seen_signature(self.fn_name, sig):
                return self._fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            obs._record_compile(self.fn_name, self._fn, args, kwargs,
                                time.perf_counter() - t0, sig=sig)
            return out
        before = size_fn()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        after = size_fn()
        if after != before:
            obs._record_compile(self.fn_name, self._fn, args, kwargs,
                                wall, cache_size=after,
                                cache_size_before=before)
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


class DeviceObservatory:
    """Process-global device telemetry (one per process, like the span
    tracer and the metric registries).

    ``enabled`` is a plain flag read without the lock (a torn read
    costs at most one unrecorded compile), and ``_profile_hot`` is the
    matching fast-path flag for :meth:`on_round`; every other mutable
    attribute below is mapped to ``_lock`` in graftcheck's
    lock-discipline registry. Slow work — XLA compiles for analysis,
    profiler start/stop I/O — always runs OUTSIDE the lock."""

    def __init__(self, clock=time.monotonic,
                 install_monitoring: bool = False):
        self.enabled = True
        #: fast-path gate for on_round(): True only while a profile
        #: window is armed or active (plain flag, same contract as
        #: ``enabled``)
        self._profile_hot = False
        self._clock = clock
        self._lock = threading.Lock()
        #: serializes profiler window transitions END TO END (decision
        #: + jax.profiler I/O): round boundaries land concurrently on
        #: sidecar handler threads, and without this a preempted
        #: starter could run start_trace AFTER another thread already
        #: took (and failed) the matching stop — an open trace no
        #: on_round would ever close. Lock order: _profile_io_lock
        #: OUTER, _lock inner; never the reverse.
        self._profile_io_lock = threading.Lock()
        #: (fn_name, signature) pairs already probed
        self._seen: set = set()
        #: id(jit fn) -> high-water cache size at the last recorded
        #: compile — dedups the concurrent-cold-call race (two threads
        #: both see the one shared compile grow the cache; only one
        #: records). A pre-call size BELOW the mark means the cache was
        #: cleared since (jax.clear_caches), which resets the mark so
        #: the real recompile still counts.
        self._fn_cache_sizes: Dict[int, int] = {}
        #: newest-last compile records {seq, fn, at, compile_s, shape}
        self._ring: deque = deque(maxlen=_RING_CAPACITY)
        #: (fn_name, sig) -> (fn, aval_args, aval_kwargs) awaiting
        #: analysis; bounded — beyond _MAX_PENDING new variants are
        #: counted but not queued
        self._pending: Dict = {}
        #: (fn_name, sig) -> (aval_args, aval_kwargs): the warm-pool
        #: manifest source (NOT consumed by analyze(); bounded by
        #: _MAX_WARM) — a snapshot of which signatures are hot, with
        #: the avals a fresh process needs to AOT-restore them
        self._warm_avals: Dict = {}
        #: (fn_name, sig) -> {"cost": ..., "memory": ...} | {"error": ...}
        self._analyses: Dict = {}
        self._analysis_order: deque = deque()
        #: buffer -> {"real", "padded", "waste"} (stage-time updates)
        self._padding: Dict[str, Dict] = {}
        #: owner name -> callable() -> bytes (live-buffer attribution)
        self._owners: Dict[str, object] = {}
        #: the HBM working-set manager's pressure view (budget line,
        #: charged bytes, per-rung census) — stamped into live_snapshot
        #: so status/debug/flight device payloads answer "how close to
        #: the line are we" beside the live-buffer attribution
        self._pressure_source: Optional[object] = None
        self._seq = 0
        self._compiles_total = 0
        self._xla_compiles = 0
        self._xla_compile_s = 0.0
        #: profiler window state machine
        self._profile_dir: Optional[str] = None
        self._profile_min_interval_s = 30.0
        self._profile_max_windows = 8
        self._profile_armed = 0       # rounds requested, not yet started
        self._profile_remaining = 0   # rounds left in the active window
        self._profile_path: Optional[str] = None
        self._profile_last_at: Optional[float] = None
        self._profile_windows: List[str] = []
        self._profile_error: Optional[str] = None
        if install_monitoring and _monitoring_supported() \
                and not _MONITOR_INSTALLED[0]:
            # every backend compilation in the process, attributed or
            # not — the listener is a counter bump. Installed ONCE per
            # process, for the DEVICE_OBS singleton only: jax offers no
            # public unregister, so a listener pins its observatory for
            # the process lifetime and a second one would double-count
            # the shared DEVICE_XLA_* metrics (ad-hoc instances in
            # tests keep wrapper-based counting, not the listener)
            _MONITOR_INSTALLED[0] = True
            jax.monitoring.register_event_duration_secs_listener(
                self._on_monitoring_event
            )

    # -- configuration -------------------------------------------------------

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)

    def configure(self, profile_dir: Optional[str] = None,
                  profile_min_interval_s: Optional[float] = None,
                  profile_max_windows: Optional[int] = None) -> None:
        """Runtime configuration (cmd flags / tests)."""
        with self._lock:
            if profile_dir is not None:
                self._profile_dir = profile_dir
            if profile_min_interval_s is not None:
                self._profile_min_interval_s = profile_min_interval_s
            if profile_max_windows is not None:
                self._profile_max_windows = profile_max_windows

    def jit(self, fn_name: str, fn) -> ObservedJit:
        """Wrap a jit-compiled callable with compile telemetry. The
        binding idiom is ``X = DEVICE_OBS.jit("name", jax.jit(f, ...))``
        — graftcheck recognizes an instrumentation wrapper over a jit
        factory as a jit factory, so ``X`` stays a device-value
        producer for the host-sync taint analysis."""
        return ObservedJit(fn_name, fn, self)

    def register_owner(self, name: str, nbytes_fn) -> None:
        """Attribute live-buffer bytes to a named owner (e.g. the
        staged state cache registers a callable summing its device
        arrays' nbytes — metadata only, no sync). Last registration
        per name wins."""
        with self._lock:
            self._owners[name] = nbytes_fn

    def set_pressure_source(self, fn) -> None:
        """Register the working-set manager's pressure view (a cheap
        zero-arg callable returning budget/used/residency) — carried in
        :meth:`live_snapshot` so every device payload shows memory
        pressure next to what is live. Last registration wins."""
        with self._lock:
            self._pressure_source = fn

    # -- compile telemetry ---------------------------------------------------

    def _on_monitoring_event(self, name: str, dur: float, **kw) -> None:
        if not name.endswith("backend_compile_duration") and \
                not name.endswith("backend_compile_time_sec"):
            return
        with self._lock:
            self._xla_compiles += 1
            self._xla_compile_s += dur
        DEVICE_XLA_COMPILES.inc()
        DEVICE_XLA_COMPILE_SECONDS.observe(dur)

    def _seen_signature(self, fn_name: str, sig) -> bool:
        with self._lock:
            return (fn_name, sig) in self._seen

    def _record_compile(self, fn_name: str, fn, args, kwargs,
                        wall: float, sig=None,
                        cache_size: Optional[int] = None,
                        cache_size_before: Optional[int] = None) -> None:
        """A call grew its jit cache (or missed the fallback probe):
        record the compile. The signature is computed HERE, off the
        steady-state path, from aval metadata (safe after donation).
        Every cache-growth event counts — a post-``jax.clear_caches``
        recompile of a known shape is a real compile (the pre-call size
        dropping below the high-water mark resets the mark) — but
        analysis is registered once per distinct signature. The
        high-water dedup handles concurrent cold callers: two threads
        racing ONE shared compile both observe the same post-call
        size, and only the first records (the loser's wall was lock
        wait, not compile time). Two DISTINCT signatures compiling
        truly simultaneously may dedup to one per-fn record — a
        documented undercount; the process-wide monitoring counter
        stays exact."""
        if sig is None:
            sig = _signature(args, kwargs)
        avals = None
        if _analysis_supported():
            try:
                avals = jax.tree_util.tree_map(_leaf_aval, (args, kwargs))
            except Exception:
                avals = None
        with self._lock:
            if cache_size is not None:
                mark = self._fn_cache_sizes.get(id(fn))
                if mark is not None and cache_size_before is not None \
                        and cache_size_before < mark:
                    mark = cache_size_before  # cache cleared since
                if mark is not None and cache_size <= mark:
                    return  # the racing winner already recorded this
                self._fn_cache_sizes[id(fn)] = cache_size
            unseen = (fn_name, sig) not in self._seen
            self._seen.add((fn_name, sig))
            if unseen and avals is not None \
                    and len(self._pending) < _MAX_PENDING:
                self._pending[(fn_name, sig)] = (fn, avals[0], avals[1])
            if unseen and avals is not None \
                    and len(self._warm_avals) < _MAX_WARM:
                self._warm_avals[(fn_name, sig)] = (avals[0], avals[1])
            self._seq += 1
            self._compiles_total += 1
            self._ring.append({
                "seq": self._seq,
                "fn": fn_name,
                "at": time.time(),
                "compile_s": wall,
                "shape": _sig_str(sig),
                "key": (fn_name, sig),
            })
        DEVICE_COMPILES.inc({"fn": fn_name})
        DEVICE_COMPILE_SECONDS.observe(wall, {"fn": fn_name})
        TRACER.instant("device-compile", cat="device",
                       args={"fn": fn_name, "compile_s": round(wall, 4)})

    def warm_manifest(self) -> List[Tuple[str, tuple, dict]]:
        """The active shape-bucket set for the warm pool (docs/DESIGN.md
        §21): every observed (fn × aval-signature) pair as ``(fn_name,
        aval_args, aval_kwargs)`` — exactly what a fresh process needs
        to ``lower(*avals).compile()`` the hot programs before traffic
        arrives. Statics (the solver config) ride in the aval tree by
        value, arrays as ShapeDtypeStructs; nothing references live
        buffers, so snapshotting is safe at any time."""
        with self._lock:
            return [
                (fn_name, avals[0], avals[1])
                for (fn_name, _sig), avals in self._warm_avals.items()
            ]

    # -- cost & memory analysis ----------------------------------------------

    def analyze(self, max_variants: Optional[int] = None) -> List[dict]:
        """Run the pending cost/memory analyses (lazy, memoized): each
        un-analyzed compile signature is re-lowered from its recorded
        avals and AOT-compiled once — one extra backend compile per
        variant, on demand (debug reads, bench fingerprints), never on
        the tick path. Returns the analyses produced by THIS call;
        loud no-op (``[]``) on jax builds without the AOT stages API."""
        if not _analysis_supported():
            return []
        with self._lock:
            items = list(self._pending.items())
            if max_variants is not None:
                items = items[:max_variants]
            for key, _ in items:
                self._pending.pop(key, None)
        produced = []
        for (fn_name, sig), (fn, aval_args, aval_kwargs) in items:
            try:
                compiled = fn.lower(*aval_args, **aval_kwargs).compile()
                entry = {
                    "fn": fn_name,
                    "shape": _sig_str(sig),
                    "cost": _cost_dict(compiled.cost_analysis()),
                    "memory": _memory_dict(compiled.memory_analysis()),
                }
            except Exception as e:
                entry = {
                    "fn": fn_name,
                    "shape": _sig_str(sig),
                    "error": f"{type(e).__name__}: {e}",
                }
            produced.append(entry)
            with self._lock:
                self._analyses[(fn_name, sig)] = entry
                self._analysis_order.append((fn_name, sig))
                while len(self._analysis_order) > _MAX_ANALYSES:
                    self._analyses.pop(self._analysis_order.popleft(),
                                       None)
        return produced

    # -- padding waste -------------------------------------------------------

    def note_padding(self, buffer: str, real: int, padded: int) -> None:
        """A shape-bucketed staging just padded ``real`` rows up to
        ``padded`` — update the per-buffer waste gauge (called at stage
        time by _pad_pods/_pad_resv/bucket_row_update/solve_coalesced;
        cost is one lock + one gauge set)."""
        if not self.enabled:
            return
        real = int(real)
        padded = max(int(padded), 1)
        with self._lock:
            prev = self._padding.get(buffer)
            if prev is not None and prev["real"] == real \
                    and prev["padded"] == padded:
                return  # steady state: same bucket fill, nothing to move
            waste = 1.0 - min(real, padded) / padded
            self._padding[buffer] = {
                "real": real, "padded": padded, "waste": waste,
            }
        DEVICE_PADDING_WASTE.set(waste, {"buffer": buffer})

    # -- live buffers --------------------------------------------------------

    def live_snapshot(self) -> dict:
        """Live jax arrays right now: count and metadata-summed bytes,
        plus registered per-owner attribution. Sampled on status/debug
        reads only — iterating the live set is O(arrays) and has no
        business on the tick path."""
        try:
            arrays = jax.live_arrays()
            count = len(arrays)
            total = int(sum(getattr(a, "nbytes", 0) for a in arrays))
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        with self._lock:
            owners = dict(self._owners)
            pressure = self._pressure_source
        by_owner = {}
        for name, fn in owners.items():
            try:
                by_owner[name] = int(fn())
            except Exception as e:
                by_owner[name] = f"{type(e).__name__}: {e}"
        DEVICE_LIVE_BUFFERS.set(count)
        DEVICE_LIVE_BYTES.set(total)
        out = {"count": count, "bytes": total, "owners": by_owner}
        if pressure is not None:
            try:
                out["workingset"] = pressure()
            except Exception as e:
                out["workingset"] = {
                    "error": f"{type(e).__name__}: {e}"
                }
        return out

    # -- profiler windows ----------------------------------------------------

    def request_profile(self, rounds: int = 8) -> dict:
        """Arm a profiler window over the next ``rounds`` scheduling
        rounds (the debug-mux ``/debug/profile?rounds=K`` handler).
        Refused while a window is armed/active; rate-limited between
        windows; window directories are disk-capped (oldest pruned)
        like the flight recorder's dumps."""
        if not _profiler_supported():
            DEVICE_PROFILE_WINDOWS.inc({"result": "refused"})
            # ``unsupported`` distinguishes a permanent refusal from
            # rate-limiting: the mux answers 501, not a retryable 429
            return {"error": "jax.profiler unavailable on this build",
                    "unsupported": True}
        rounds = max(1, int(rounds))
        now = self._clock()
        with self._lock:
            if self._profile_armed or self._profile_remaining:
                DEVICE_PROFILE_WINDOWS.inc({"result": "refused"})
                return {"error": "profile window already armed/active"}
            last = self._profile_last_at
            if last is not None and \
                    now - last < self._profile_min_interval_s:
                DEVICE_PROFILE_WINDOWS.inc({"result": "rate-limited"})
                return {
                    "error": "rate-limited",
                    "retry_in_s": self._profile_min_interval_s
                    - (now - last),
                }
            self._profile_last_at = now
            self._profile_armed = rounds
            self._profile_error = None
            target = self._profile_dir or _default_profile_dir()
        self._profile_hot = True
        return {"armed": True, "rounds": rounds, "dir": target}

    def on_round(self) -> None:
        """Round boundary hook (Scheduler.begin_tick; the sidecar calls
        it per solve): drives the armed→active→closed profile window.
        One plain-flag read when no window is in play."""
        if not self._profile_hot:
            return
        # window transitions are serialized end to end (decision + the
        # profiler I/O) so concurrent round boundaries (sidecar handler
        # threads) can never run a stop before its matching start lands
        with self._profile_io_lock:
            self._window_transition()

    def _window_transition(self) -> None:
        action = None
        with self._lock:
            if self._profile_armed:
                self._seq += 1
                path = os.path.join(
                    self._profile_dir or _default_profile_dir(),
                    f"window-{self._seq:04d}",
                )
                self._profile_remaining = self._profile_armed
                self._profile_armed = 0
                self._profile_path = path
                action = ("start", path)
            elif self._profile_remaining > 1:
                self._profile_remaining -= 1
            elif self._profile_remaining == 1:
                self._profile_remaining = 0
                path = self._profile_path
                self._profile_path = None
                self._profile_hot = False
                action = ("stop", path)
        if action is None:
            return
        kind, arg = action
        try:
            if kind == "start":
                os.makedirs(arg, exist_ok=True)
                jax.profiler.start_trace(arg)
                TRACER.instant("profile-window-open", cat="device",
                               args={"dir": arg})
            else:
                jax.profiler.stop_trace()
                TRACER.instant("profile-window-closed", cat="device")
                DEVICE_PROFILE_WINDOWS.inc({"result": "written"})
                # track + disk-cap ONLY after a successful stop: a
                # failed stop must neither list a broken window as
                # written nor pop an old path it never got to prune
                pruned = None
                with self._lock:
                    self._profile_windows.append(arg)
                    if len(self._profile_windows) > \
                            self._profile_max_windows:
                        pruned = self._profile_windows.pop(0)
                if pruned is not None:
                    import shutil

                    shutil.rmtree(pruned, ignore_errors=True)
        except Exception as e:  # observability must never crash a round
            DEVICE_PROFILE_WINDOWS.inc({"result": "error"})
            with self._lock:
                self._profile_error = f"{type(e).__name__}: {e}"
                self._profile_armed = 0
                self._profile_remaining = 0
                self._profile_path = None
            self._profile_hot = False

    def annotate(self, name: str):
        """A ``jax.profiler.TraceAnnotation`` scope while a profile
        window is active (so device events line up with the span
        tracer's stage names in Perfetto) — a shared null context
        otherwise: one flag read on the hot path."""
        if self._profile_hot:
            ann = getattr(jax.profiler, "TraceAnnotation", None) \
                if _profiler_supported() else None
            if ann is not None:
                return ann(f"ktpu:{name}")
        return _NULL_CTX

    # -- read side -----------------------------------------------------------

    def status(self) -> dict:
        """Cheap snapshot for plugin/status surfaces: counters, recent
        compiles, padding, CACHED analyses only — no compiles, no
        live-array walk beyond one pass."""
        with self._lock:
            ring = [
                {k: v for k, v in r.items() if k != "key"}
                for r in self._ring
            ]
            analyses = [
                dict(self._analyses[k]) for k in self._analysis_order
                if k in self._analyses
            ]
            payload = {
                "enabled": self.enabled,
                "supported": device_observatory_supported(),
                "compiles_total": self._compiles_total,
                "xla_compiles_total": self._xla_compiles,
                "xla_compile_seconds_total": self._xla_compile_s,
                "pending_analyses": len(self._pending),
                "recent_compiles": ring,
                "analyses": analyses,
                "padding": {k: dict(v) for k, v in self._padding.items()},
                "profile": {
                    "dir": self._profile_dir or _default_profile_dir(),
                    "armed_rounds": self._profile_armed,
                    "active_rounds_left": self._profile_remaining,
                    "windows": list(self._profile_windows),
                    "min_interval_s": self._profile_min_interval_s,
                    "last_error": self._profile_error,
                },
            }
        payload["live"] = self.live_snapshot()
        return payload

    def debug_payload(self) -> dict:
        """The ``/debug/device`` body: :meth:`status` with pending
        analyses materialized first (a debug GET may pay the on-demand
        analysis compiles; the tick path never does)."""
        self.analyze()
        return self.status()

    def flight_payload(self) -> dict:
        """The flight recorder's ``device`` section: cached-only (a
        dump must not compile anything) — did we just recompile, what
        did the last variants cost, how much is live."""
        with self._lock:
            ring = [
                {k: v for k, v in r.items() if k != "key"}
                for r in list(self._ring)[-16:]
            ]
            analyses = [
                dict(self._analyses[k])
                for k in list(self._analysis_order)[-8:]
                if k in self._analyses
            ]
            payload = {
                "compiles_total": self._compiles_total,
                "xla_compiles_total": self._xla_compiles,
                "recent_compiles": ring,
                "analyses": analyses,
                "padding": {k: dict(v) for k, v in self._padding.items()},
            }
        payload["live"] = self.live_snapshot()
        return payload

    def padding_waste(self) -> float:
        """Worst current padding-waste ratio across the staged buffers
        — the SLO controller's batch-amortization signal
        (koordinator_tpu/control/slo.py). One lock hold, no device
        work, 0.0 before anything staged."""
        with self._lock:
            return max(
                (v["waste"] for v in self._padding.values()), default=0.0
            )

    def compile_ring(self, since_seq: int = 0) -> Tuple[List[dict], int]:
        """Ring entries newer than ``since_seq`` WITH their raw
        ``(fn_name, signature)`` keys, plus the current sequence — the
        shape-flow sentinel's read surface (testing/shapeflow.py):
        per-window marks isolate one test's compiles, and the keys
        carry the per-leaf (shape, dtype) tuples the sentinel checks
        against the static enumeration. Bounded by the ring capacity
        like every other reader; one lock hold, no device work."""
        with self._lock:
            return (
                [dict(r) for r in self._ring if r["seq"] > since_seq],
                self._seq,
            )

    # -- bench fingerprinting ------------------------------------------------

    def mark(self) -> dict:
        """A point-in-time marker for :meth:`fingerprint` deltas."""
        with self._lock:
            return {
                "seq": self._seq,
                "compiles": self._compiles_total,
                "xla_compiles": self._xla_compiles,
                "xla_compile_s": self._xla_compile_s,
            }

    def fingerprint(self, mark: Optional[dict] = None) -> dict:
        """The device fingerprint a bench leg records next to its
        timings: compile counts/wall since ``mark``, the summed
        FLOPs/bytes and max peak bytes of the variants compiled in that
        window, the worst current padding-waste ratio, and a live-buffer
        sample. Compile deltas are snapshotted BEFORE the on-demand
        analysis pass so the analysis's own compiles never pollute the
        leg they describe."""
        mark = mark or {"seq": 0, "compiles": 0, "xla_compiles": 0,
                        "xla_compile_s": 0.0}
        with self._lock:
            compiles = self._compiles_total - mark["compiles"]
            xla = self._xla_compiles - mark["xla_compiles"]
            xla_s = self._xla_compile_s - mark["xla_compile_s"]
            keys = [
                r["key"] for r in self._ring if r["seq"] > mark["seq"]
            ]
        self.analyze()
        flops = 0.0
        bytes_accessed = 0.0
        peak = 0
        with self._lock:
            for key in keys:
                entry = self._analyses.get(key)
                if entry is None or "cost" not in entry:
                    continue
                flops += entry["cost"]["flops"]
                bytes_accessed += entry["cost"]["bytes_accessed"]
                peak = max(peak, entry["memory"]["peak_bytes"])
            waste = max(
                (v["waste"] for v in self._padding.values()), default=0.0
            )
        live = self.live_snapshot()
        return {
            "supported": device_observatory_supported(),
            "compiles": compiles,
            "xla_compiles": xla,
            "xla_compile_s": xla_s,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "peak_bytes": peak,
            "padding_waste_ratio": waste,
            "live_buffers": live.get("count", 0),
            "live_bytes": live.get("bytes", 0),
        }

    def reset(self) -> None:
        """Forget telemetry (tests). Counters restart; an ACTIVE
        profiler window is stopped here — its state is being erased,
        so the on_round stop path could never close it, and a trace
        left open would make every later start_trace fail for the
        process lifetime."""
        with self._lock:
            active = self._profile_path is not None
            self._seen.clear()
            self._fn_cache_sizes.clear()
            self._ring.clear()
            self._pending.clear()
            self._warm_avals.clear()
            self._analyses.clear()
            self._analysis_order.clear()
            self._padding.clear()
            self._owners.clear()
            self._seq = 0
            self._compiles_total = 0
            self._xla_compiles = 0
            self._xla_compile_s = 0.0
            self._profile_armed = 0
            self._profile_remaining = 0
            self._profile_path = None
            self._profile_last_at = None
            self._profile_windows.clear()
            self._profile_error = None
        self._profile_hot = False
        if active and _profiler_supported():
            # _lock released above: the io lock is only ever taken
            # without _lock held (on_round nests them the other way)
            with self._profile_io_lock:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass


#: the process observatory every component records into (one per
#: process, like the tracer and the flight recorder); only the
#: singleton installs the process-wide compile listener
DEVICE_OBS = DeviceObservatory(install_monitoring=True)
