"""Observability: the scheduling trace fabric (docs/DESIGN.md §16).

Four surfaces, all off the solve's device path:

- ``obs.trace``    — thread-safe span tracer (bounded ring, monotonic
  clocks, Chrome-trace-event export: load the JSON in Perfetto and the
  pipelined stage(N+1)/solve(N) overlap is visible as overlapping
  tracks).
- ``obs.timeline`` — per-pod submit→staged→solved→published timelines
  feeding the ``scheduler_pod_e2e_seconds`` histograms by QoS lane.
- ``obs.flight``   — anomaly flight recorder: a bounded ring of recent
  round records dumped to JSON when an anomaly trigger fires (auditor
  detection, failover flip, fencing abort, deferred pipeline error,
  deadline-exceeded).
- ``obs.explain``  — placement explainability: an off-hot-path jitted
  score breakdown (per-node, per-feature-column scores + filter
  verdicts, oracle-parity-checked) answering "why did pod X land on
  node Y / why is it unschedulable" from the debug mux.
- ``obs.device``   — the device-cost observatory (docs/DESIGN.md §17):
  compile telemetry at the hot jit callsites, lazy XLA cost/memory
  analysis per solve variant, padding-waste and live-buffer gauges,
  and on-demand ``jax.profiler`` windows served from the debug mux.
"""

from koordinator_tpu.obs.device import (
    DEVICE_OBS,
    DeviceObservatory,
    device_observatory_supported,
)
from koordinator_tpu.obs.flight import FLIGHT, FlightRecorder
from koordinator_tpu.obs.timeline import PodTimelines, lane_of
from koordinator_tpu.obs.trace import TRACER, SpanTracer

__all__ = [
    "DEVICE_OBS",
    "DeviceObservatory",
    "FLIGHT",
    "FlightRecorder",
    "PodTimelines",
    "SpanTracer",
    "TRACER",
    "device_observatory_supported",
    "lane_of",
]
