"""Placement explainability: per-node, per-feature-column score
breakdowns for one pod.

The batched solver answers "where does the whole queue go" in one
program; when an operator asks "why did pod X land on node Y" or "why
is pod X unschedulable", the fused scan's argmax is opaque. This
module runs an OFF-hot-path breakdown solve: the same filter/score
primitives the scan composes (ops/fit.py, ops/loadaware.py — the
device twins of the oracle's per-node decision functions), jitted once
and evaluated for a single pod against the full node set, returning
every column separately:

- filter verdicts: ``schedulable``, ``fit_feasible``,
  ``loadaware_feasible`` (+ the host-side ``selector`` row)
- score columns: ``fit_score`` (NodeResourcesFit/LeastAllocated),
  ``loadaware_score`` (LoadAwareScheduling), each UNWEIGHTED — exactly
  what the incremental plugin chain's per-plugin ``score`` returns —
  plus the ``weighted_total`` the argmax ranks by.

Parity contract (docs/DESIGN.md §16, tested in tests/test_obs.py):
each column is bit-identical to the oracle's scalar transliteration
(``least_allocated_score_node`` / ``loadaware_score_node`` /
``fit_filter_node`` / ``loadaware_filter_node``) on the same lowered
arrays — explain never computes scores "its own way", so a breakdown
that disagrees with a placement is a bug, not a rounding story.

This is the ONE new intentional read-back of the observability layer:
``explain_scores`` materializes the breakdown columns to host
(allowlisted in graftcheck.toml). It runs on debug-mux demand, never
inside the solve loop.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.common import reciprocal_for
from koordinator_tpu.ops.fit import fit_filter, least_allocated_score
from koordinator_tpu.ops.loadaware import loadaware_filter, loadaware_score


def _breakdown(state, req, est, is_prod, is_ds, params, config):
    """Per-column (never argmax-fused) single-pod scoring — the same
    primitives score_one_pod composes, returned unreduced."""
    recip = reciprocal_for(state.alloc)
    fit_ok = fit_filter(req, state.alloc, state.used_req)
    load_ok = loadaware_filter(
        state.alloc, state.usage, state.prod_usage, state.metric_fresh,
        params.thresholds, params.prod_thresholds, is_ds, is_prod,
    )
    fit_sc = least_allocated_score(
        req, state.alloc, state.used_req, params.weights, recip
    )
    load_sc = loadaware_score(
        est, state.alloc, state.usage, state.est_extra, state.prod_base,
        state.metric_fresh, params.weights, is_prod,
        config.score_according_prod, recip,
    )
    total = config.fit_weight * fit_sc + config.loadaware_weight * load_sc
    return {
        "schedulable": state.schedulable,
        "fit_feasible": fit_ok,
        "loadaware_feasible": load_ok,
        "fit_score": fit_sc,
        "loadaware_score": load_sc,
        "weighted_total": total,
    }


#: one compiled breakdown per (N, config) — explain is on-demand, so
#: the compile amortizes across debug queries against a stable cluster
_jit_breakdown = jax.jit(
    _breakdown, static_argnames=("config",), donate_argnums=()
)


def explain_scores(model, snapshot, pod) -> Tuple[object, Dict[str, np.ndarray]]:
    """(lowered NodeArrays, {column: host array}) for one pod against
    the snapshot's full node set, lowered and scored exactly as a solve
    would (same lowering kwargs, same params/config)."""
    from koordinator_tpu.state.cluster import (
        lower_nodes,
        lower_pending_pods,
    )

    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    pod_arrays = lower_pending_pods(
        [pod],
        scaling_factors=model.scaling_factors,
        resource_weights=model.resource_weights,
    )
    state = model.stage_nodes(arrays)
    out = _jit_breakdown(
        state,
        jnp.asarray(pod_arrays.req[0]),
        jnp.asarray(pod_arrays.est[0]),
        jnp.asarray(bool(pod_arrays.is_prod[0])),
        jnp.asarray(bool(pod_arrays.is_daemonset[0])),
        model.params,
        config=model.config,
    )
    cols: Dict[str, np.ndarray] = {}
    for name, col in out.items():
        # the observability layer's one designated read-back: breakdown
        # columns land on host for the debug payload / parity check.
        # Trimmed to the REAL node count: a node-sharded model stages a
        # bucket-padded world (DESIGN.md §19), and untrimmed columns
        # would count padding rows as "unschedulable" rejections — and
        # let a padding index reach names[i] in the top-K detail
        cols[name] = np.asarray(col)[: arrays.n]
    return arrays, cols


class PlacementExplainer:
    """Debug-mux front end over :func:`explain_scores` for a wired
    Scheduler: device columns plus the host-side verdicts the batched
    epilogue enforces (node selector, quota admission, gang blocking,
    reservation matches), recorded into the seed ``DebugRecorder``."""

    #: nodes listed in full detail per payload (the rest summarized)
    TOP_K = 10

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def explain(self, pod_uid: str, node: Optional[str] = None,
                now: Optional[float] = None) -> dict:
        sched = self.scheduler
        pod = sched.cache.pending.get(pod_uid) or sched.cache.pods.get(pod_uid)
        if pod is None:
            return {"error": f"unknown pod {pod_uid!r}"}
        snapshot = sched.cache.snapshot(now=now)
        arrays, cols = explain_scores(sched.model, snapshot, pod)
        n = arrays.n
        names = list(arrays.names)
        mask = (
            cols["schedulable"]
            & cols["fit_feasible"]
            & cols["loadaware_feasible"]
        )
        selector_row = None
        if pod.node_selector:
            from koordinator_tpu.apis.types import selector_matches

            selector_row = np.fromiter(
                (
                    selector_matches(pod.node_selector, nd.labels)
                    for nd in snapshot.nodes
                ),
                dtype=bool, count=n,
            )
            mask = mask & selector_row

        verdicts: Dict[str, object] = {}
        if pod.gang:
            verdicts["gang_known"] = pod.gang in snapshot.gangs
        if pod.quota:
            from koordinator_tpu.scheduler.framework import CycleState

            status = sched._quota_plugin.pre_filter(
                CycleState(sched.framework.cycle_seed), snapshot, pod
            )
            verdicts["quota_admitted"] = status.ok
            if not status.ok:
                verdicts["quota_reason"] = status.reason
        if snapshot.reservations:
            from koordinator_tpu.scheduler.plugins.reservation import (
                reservation_matches_pod,
            )

            verdicts["reservation_matches"] = [
                r.name for r in snapshot.reservations
                if reservation_matches_pod(r, pod)
            ]

        total = cols["weighted_total"]
        ranked = np.where(mask, total, -1)
        best = int(np.argmax(ranked)) if n else -1
        winner = names[best] if n and ranked[best] >= 0 else None

        def node_detail(i: int) -> dict:
            d = {
                "node": names[i],
                "feasible": bool(mask[i]),
                "filters": {
                    "schedulable": bool(cols["schedulable"][i]),
                    "fit": bool(cols["fit_feasible"][i]),
                    "loadaware": bool(cols["loadaware_feasible"][i]),
                },
                "scores": {
                    "NodeResourcesFit": int(cols["fit_score"][i]),
                    "LoadAwareScheduling": int(cols["loadaware_score"][i]),
                    "weighted_total": int(total[i]),
                },
            }
            if selector_row is not None:
                d["filters"]["selector"] = bool(selector_row[i])
            return d

        order = np.argsort(-ranked, kind="stable")[: self.TOP_K]
        payload = {
            "pod": pod_uid,
            "assigned": pod.node_name,
            "winner": winner,
            "node_count": n,
            "feasible_count": int(mask.sum()),
            "filter_rejections": {
                "unschedulable": int((~cols["schedulable"]).sum()),
                "fit": int((~cols["fit_feasible"]).sum()),
                "loadaware": int((~cols["loadaware_feasible"]).sum()),
                **(
                    {"selector": int((~selector_row).sum())}
                    if selector_row is not None else {}
                ),
            },
            "verdicts": verdicts,
            "top_nodes": [node_detail(int(i)) for i in order],
        }
        if node is not None:
            if node in names:
                payload["queried_node"] = node_detail(names.index(node))
            else:
                payload["queried_node"] = {"error": f"unknown node {node!r}"}
        sched.debug.record_explain(payload)
        return payload
