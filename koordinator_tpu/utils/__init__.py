"""Shared utilities (reference: pkg/util — the slices every component
imports; here only what the typed design still needs)."""
