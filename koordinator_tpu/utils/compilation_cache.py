"""Persistent XLA compilation cache (cold-start blackout mitigation).

VERDICT r4 weak #5: every solver start paid a ~7.6 s compile warmup,
so a control-plane restart (leader failover + sidecar respawn) meant
~8 s of solver blackout. With the persistent cache enabled, a fresh
process deserializes the compiled executable from disk instead of
recompiling: warm-start warmup drops under a second (measured by
``bench.py``'s warm-probe and ``tests/test_compilation_cache.py``).

The cache keys include the program, compile options, and accelerator
identity, so a shared directory is safe across processes and restarts
(writes are atomic renames). Reference counterpart: the Go scheduler has
no compilation step — this is the TPU-native cost the sidecar/cache
design pays once per (program, chip) instead of once per process.

Operational note: a cache entry corrupted by an abnormal process death
(observed once after a machine-wide OOM) can crash JAX's zstd cache
READER, which our code cannot catch — the recovery is deleting the
cache directory (or KTPU_COMPILATION_CACHE_DIR="" to disable). The test
suite therefore isolates itself from the user-global directory
(tests/conftest.py); production restarts share it on purpose.
"""

from __future__ import annotations

import os

#: default on-disk location; override with KTPU_COMPILATION_CACHE_DIR,
#: disable with KTPU_COMPILATION_CACHE_DIR=""
_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "koordinator_tpu", "xla-cache"
)


def host_fingerprint() -> str:
    """A short identity for THIS host's CPU: machine architecture + a
    hash of the CPU feature flags.

    The persistent/AOT caches replay compiled code, and XLA:CPU
    executables are compiled FOR the build host's CPU features — a
    cache directory shared across heterogeneous machines (network home
    dirs, container images with baked caches) replays AOT results
    compiled for a different feature set: SIGILL at best, multi-minute
    stalls at worst (the MULTICHIP_r05 rc=124 dryrun hang). Scoping the
    cache by this fingerprint makes cross-machine reuse structurally
    impossible while same-machine restarts still warm-start."""
    import hashlib
    import platform

    ident = platform.machine() or "unknown"
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 exposes "flags", arm64 "Features"
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    digest = hashlib.sha256(f"{ident}|{flags}".encode()).hexdigest()[:12]
    return f"{ident}-{digest}"


def _host_scoped(cache_dir: str) -> str:
    """``cache_dir`` scoped to this host's CPU identity (see
    :func:`host_fingerprint`). KTPU_CACHE_HOST_SCOPE=0 restores the
    shared layout for fleets known to be homogeneous."""
    if os.environ.get("KTPU_CACHE_HOST_SCOPE", "1") == "0":
        return cache_dir
    return os.path.join(cache_dir, f"host-{host_fingerprint()}")


# -- typed store errors (docs/DESIGN.md §21) ---------------------------------
# The warm pool (service/warmpool.py) restores serialized executables
# on the scheduler's RECOVERY paths — leader promotion, sidecar
# respawn, degraded-mode flips — exactly when a crash may have left the
# store torn. Every way an entry can be bad is therefore a TYPED error
# the caller can count and quarantine; a raw pickle/zstd traceback out
# of this module would turn a disk problem into a scheduler crash.

class WarmEntryError(Exception):
    """Base of every typed executable-store load failure. ``reason``
    is the metric label (``scheduler_warm_pool_rejects_total``)."""

    reason = "corrupt"


class WarmEntryTruncated(WarmEntryError):
    """The entry file ends before its declared payload does (torn
    write / torn copy / disk-full)."""

    reason = "truncated"


class WarmEntryCorrupt(WarmEntryError):
    """The entry is structurally unreadable: bad magic (foreign or
    pre-framing file) or a payload that fails to unpickle/deserialize."""

    reason = "corrupt"


class WarmEntryFingerprintMismatch(WarmEntryError):
    """The payload does not hash to the fingerprint in the header —
    bit rot or a torn overwrite. (An INTEGRITY check, not a security
    boundary: the keyless digest lives beside the payload it hashes,
    and the body feeds pickle — the store directory must be
    trusted-local-disk, same trust level as the code itself.)"""

    reason = "fingerprint"


class WarmEntryOversized(WarmEntryError):
    """The entry (or its declared payload) exceeds the load cap — a
    corrupt length prefix (or a foreign file) must not make a restart
    path buffer gigabytes."""

    reason = "oversized"


class WarmEntryHostMismatch(WarmEntryError):
    """The entry embeds a DIFFERENT host fingerprint than this
    machine's. The store directory is already host-scoped
    (:func:`_host_scoped`), but a copied/renamed store — a container
    image with a baked cache, a fleet rollout that pre-seeded the
    wrong host dir — would bypass the path scoping; the embedded
    fingerprint catches it at load time (XLA:CPU executables replay
    foreign CPU features as SIGILL/stalls, the MULTICHIP_r05 class)."""

    reason = "stale-host"


class WarmEntryVersionSkew(WarmEntryError):
    """The entry embeds a different jax version than this process
    runs. The store key already scopes by jax version, so skew means
    a renamed/copied entry — refuse it typed rather than feeding a
    foreign serialization format to the deserializer."""

    reason = "version-skew"


#: framed-entry magic (version-bearing: bump on format change — old
#: entries then read as WarmEntryCorrupt and fall back to cold compile).
#: v2 embeds provenance (host fingerprint + jax version) in the body.
_ENTRY_MAGIC = b"KTPUEXE2"
#: blake2b digest bytes stored in the header
_DIGEST_SIZE = 16
#: hard cap on entry payloads; override with KTPU_WARM_MAX_ENTRY_BYTES
_MAX_ENTRY_BYTES = 512 << 20


def max_entry_bytes() -> int:
    try:
        return int(os.environ.get("KTPU_WARM_MAX_ENTRY_BYTES",
                                  _MAX_ENTRY_BYTES))
    except ValueError:
        return _MAX_ENTRY_BYTES


def frame_payload(body: bytes) -> bytes:
    """Frame ``body`` for the executable store: magic + 8-byte length +
    blake2b fingerprint + body. The fingerprint makes a flipped bit a
    typed :class:`WarmEntryFingerprintMismatch` instead of a crash
    inside JAX's deserializer (which this code cannot catch). It is an
    integrity check against accidental corruption, NOT authentication
    — the store is trusted local disk (see the mismatch class)."""
    import hashlib
    import struct

    digest = hashlib.blake2b(body, digest_size=_DIGEST_SIZE).digest()
    return _ENTRY_MAGIC + struct.pack(">Q", len(body)) + digest + body


def unframe_payload(raw: bytes, what: str = "entry") -> bytes:
    """Verify and strip the :func:`frame_payload` header, raising the
    typed :class:`WarmEntryError` family on every defect."""
    import hashlib
    import struct

    header = len(_ENTRY_MAGIC) + 8 + _DIGEST_SIZE
    if len(raw) < header:
        raise WarmEntryTruncated(f"{what}: {len(raw)}B is shorter than "
                                 f"the {header}B header")
    if raw[: len(_ENTRY_MAGIC)] != _ENTRY_MAGIC:
        raise WarmEntryCorrupt(f"{what}: bad magic")
    (length,) = struct.unpack(
        ">Q", raw[len(_ENTRY_MAGIC): len(_ENTRY_MAGIC) + 8]
    )
    if length > max_entry_bytes():
        raise WarmEntryOversized(
            f"{what}: declared {length}B > cap {max_entry_bytes()}B"
        )
    digest = raw[len(_ENTRY_MAGIC) + 8: header]
    body = raw[header:]
    if len(body) < length:
        raise WarmEntryTruncated(
            f"{what}: payload {len(body)}B < declared {length}B"
        )
    body = body[:length]
    if hashlib.blake2b(body, digest_size=_DIGEST_SIZE).digest() != digest:
        raise WarmEntryFingerprintMismatch(f"{what}: payload fingerprint "
                                           f"does not match header")
    return body


class ExecutableCache:
    """AOT warm-start cache: serialized COMPILED executables on disk.

    The persistent XLA cache above removes recompilation but every
    process still re-traces the program (a 32-unrolled scan traces a
    large jaxpr — seconds of pure Python). Serializing the compiled
    executable (jax.experimental.serialize_executable) skips tracing,
    lowering AND compilation on restart: measured warm start ~0.7 s vs
    ~15 s cold for the flagship program. Entries are keyed by a caller
    key + backend identity; loads fall back to plain compilation on any
    mismatch (a moved cache directory is never fatal).
    """

    def __init__(self, cache_dir: str | None = None):
        if cache_dir is None:
            cache_dir = os.environ.get(
                "KTPU_COMPILATION_CACHE_DIR", _DEFAULT_DIR
            )
        self.dir = (
            os.path.join(_host_scoped(cache_dir), "executables")
            if cache_dir else None
        )

    def _path(self, key: str) -> str | None:
        if not self.dir:
            return None
        import hashlib

        import jax

        backend = jax.devices()[0]
        ident = f"{key}|{backend.platform}|{backend.device_kind}|{jax.__version__}"
        digest = hashlib.sha256(ident.encode()).hexdigest()[:24]
        return os.path.join(self.dir, f"{digest}.exec")

    def load_checked(self, key: str):
        """The cached compiled callable for ``key``; None when no entry
        exists. Every OTHER failure mode is a typed
        :class:`WarmEntryError` — truncated, corrupt, oversized,
        fingerprint-mismatched, stale-host, version-skewed — so a
        warm-pool caller can count the reject and quarantine the file
        instead of crashing (or silently retrying a poisoned entry
        forever)."""
        path = self._path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        if size > max_entry_bytes() + 64:
            # refuse BEFORE reading: a corrupt length prefix inside a
            # giant file must not be discovered by buffering it
            raise WarmEntryOversized(
                f"{key}: file {size}B > cap {max_entry_bytes()}B"
            )
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise WarmEntryTruncated(f"{key}: unreadable: {e}") from e
        body = unframe_payload(raw, what=key)
        import pickle

        import jax

        try:
            record = pickle.loads(body)
        except Exception as e:
            raise WarmEntryCorrupt(
                f"{key}: body unpickle failed: {type(e).__name__}: {e}"
            ) from e
        if not isinstance(record, tuple) or len(record) != 4:
            raise WarmEntryCorrupt(f"{key}: stale entry record shape")
        host, version, payload, trees = record
        # provenance checks BEFORE the deserializer sees any bytes: the
        # path scoping (host dir, jax-version key) can be bypassed by a
        # copied/renamed store, and a foreign executable replayed on
        # the wrong CPU is SIGILL/stall territory (DESIGN §21)
        if host != host_fingerprint():
            raise WarmEntryHostMismatch(
                f"{key}: entry built on host {host!r}, this is "
                f"{host_fingerprint()!r}"
            )
        if version != jax.__version__:
            raise WarmEntryVersionSkew(
                f"{key}: entry built under jax {version!r}, this "
                f"process runs {jax.__version__!r}"
            )
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            return deserialize_and_load(payload, *pickle.loads(trees))
        except Exception as e:
            # the fingerprint matched, so the BYTES are what store()
            # wrote — a deserializer rejection means a stale format /
            # wrong backend build, still a typed, quarantinable outcome
            raise WarmEntryCorrupt(
                f"{key}: deserialize failed: {type(e).__name__}: {e}"
            ) from e

    def load(self, key: str):
        """The cached compiled callable for ``key``, or None (silent
        form of :meth:`load_checked` — legacy callers that treat any
        bad entry as a plain miss)."""
        try:
            return self.load_checked(key)
        except WarmEntryError:
            return None

    def quarantine(self, key: str):
        """Move ``key``'s entry aside (``<entry>.quarantined``) so a
        poisoned file is never retried in a loop: the next load is a
        clean miss, the next store publishes a fresh entry, and the
        bad bytes stay on disk for forensics. Returns the quarantine
        path, or None when there was nothing to move."""
        path = self._path(key)
        if path is None or not os.path.exists(path):
            return None
        target = f"{path}.quarantined"
        try:
            os.replace(path, target)
        except OSError:
            return None
        return target

    def store(self, key: str, compiled) -> bool:
        path = self._path(key)
        if path is None:
            return False
        try:
            import pickle

            from jax.experimental.serialize_executable import serialize

            import jax

            payload, in_tree, out_tree = serialize(compiled)
            os.makedirs(self.dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            # v2 record: provenance (host fingerprint + jax version)
            # rides INSIDE the fingerprinted body, so a copied store
            # that dodges the path scoping still loads as a typed
            # stale-host / version-skew reject, never a foreign replay
            body = pickle.dumps((
                host_fingerprint(), jax.__version__,
                payload, pickle.dumps((in_tree, out_tree)),
            ))
            with open(tmp, "wb") as f:
                f.write(frame_payload(body))
            os.replace(tmp, path)  # atomic publish
            return True
        except Exception:
            return False

    def get_or_compile(self, key: str, jit_fn, *args):
        """Cached executable for ``key``, else ``jit_fn.lower(*args)
        .compile()`` persisted for the next restart. The returned
        callable takes the same arguments as ``jit_fn``."""
        compiled = self.load(key)
        if compiled is not None:
            return compiled
        compiled = jit_fn.lower(*args).compile()
        self.store(key, compiled)
        return compiled


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    lower the persistence thresholds so the solver programs qualify.
    Returns the directory in effect, or None when disabled. Safe to
    call more than once; must run before the first jit compilation to
    cover it."""
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("KTPU_COMPILATION_CACHE_DIR", _DEFAULT_DIR)
    if not cache_dir:
        return None
    # host-CPU-scoped subdirectory: AOT results never replay across
    # machines with different CPU feature sets (SIGILL / stall risk —
    # the MULTICHIP_r05 dryrun timeout)
    cache_dir = _host_scoped(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip small/fast programs; the matrix-config
        # solves compile in 0.2-2 s each and all of them matter for the
        # restart path
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        # cache is an optimization: never fail startup over it
        return None
    return cache_dir
