"""Persistent XLA compilation cache (cold-start blackout mitigation).

VERDICT r4 weak #5: every solver start paid a ~7.6 s compile warmup,
so a control-plane restart (leader failover + sidecar respawn) meant
~8 s of solver blackout. With the persistent cache enabled, a fresh
process deserializes the compiled executable from disk instead of
recompiling: warm-start warmup drops under a second (measured by
``bench.py``'s warm-probe and ``tests/test_compilation_cache.py``).

The cache keys include the program, compile options, and accelerator
identity, so a shared directory is safe across processes and restarts
(writes are atomic renames). Reference counterpart: the Go scheduler has
no compilation step — this is the TPU-native cost the sidecar/cache
design pays once per (program, chip) instead of once per process.

Operational note: a cache entry corrupted by an abnormal process death
(observed once after a machine-wide OOM) can crash JAX's zstd cache
READER, which our code cannot catch — the recovery is deleting the
cache directory (or KTPU_COMPILATION_CACHE_DIR="" to disable). The test
suite therefore isolates itself from the user-global directory
(tests/conftest.py); production restarts share it on purpose.
"""

from __future__ import annotations

import os

#: default on-disk location; override with KTPU_COMPILATION_CACHE_DIR,
#: disable with KTPU_COMPILATION_CACHE_DIR=""
_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "koordinator_tpu", "xla-cache"
)


def host_fingerprint() -> str:
    """A short identity for THIS host's CPU: machine architecture + a
    hash of the CPU feature flags.

    The persistent/AOT caches replay compiled code, and XLA:CPU
    executables are compiled FOR the build host's CPU features — a
    cache directory shared across heterogeneous machines (network home
    dirs, container images with baked caches) replays AOT results
    compiled for a different feature set: SIGILL at best, multi-minute
    stalls at worst (the MULTICHIP_r05 rc=124 dryrun hang). Scoping the
    cache by this fingerprint makes cross-machine reuse structurally
    impossible while same-machine restarts still warm-start."""
    import hashlib
    import platform

    ident = platform.machine() or "unknown"
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 exposes "flags", arm64 "Features"
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    digest = hashlib.sha256(f"{ident}|{flags}".encode()).hexdigest()[:12]
    return f"{ident}-{digest}"


def _host_scoped(cache_dir: str) -> str:
    """``cache_dir`` scoped to this host's CPU identity (see
    :func:`host_fingerprint`). KTPU_CACHE_HOST_SCOPE=0 restores the
    shared layout for fleets known to be homogeneous."""
    if os.environ.get("KTPU_CACHE_HOST_SCOPE", "1") == "0":
        return cache_dir
    return os.path.join(cache_dir, f"host-{host_fingerprint()}")


class ExecutableCache:
    """AOT warm-start cache: serialized COMPILED executables on disk.

    The persistent XLA cache above removes recompilation but every
    process still re-traces the program (a 32-unrolled scan traces a
    large jaxpr — seconds of pure Python). Serializing the compiled
    executable (jax.experimental.serialize_executable) skips tracing,
    lowering AND compilation on restart: measured warm start ~0.7 s vs
    ~15 s cold for the flagship program. Entries are keyed by a caller
    key + backend identity; loads fall back to plain compilation on any
    mismatch (a moved cache directory is never fatal).
    """

    def __init__(self, cache_dir: str | None = None):
        if cache_dir is None:
            cache_dir = os.environ.get(
                "KTPU_COMPILATION_CACHE_DIR", _DEFAULT_DIR
            )
        self.dir = (
            os.path.join(_host_scoped(cache_dir), "executables")
            if cache_dir else None
        )

    def _path(self, key: str) -> str | None:
        if not self.dir:
            return None
        import hashlib

        import jax

        backend = jax.devices()[0]
        ident = f"{key}|{backend.platform}|{backend.device_kind}|{jax.__version__}"
        digest = hashlib.sha256(ident.encode()).hexdigest()[:24]
        return os.path.join(self.dir, f"{digest}.exec")

    def load(self, key: str):
        """The cached compiled callable for ``key``, or None."""
        path = self._path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            import pickle

            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            with open(path, "rb") as f:
                payload, trees = pickle.load(f)
            return deserialize_and_load(payload, *pickle.loads(trees))
        except Exception:
            return None

    def store(self, key: str, compiled) -> bool:
        path = self._path(key)
        if path is None:
            return False
        try:
            import pickle

            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            os.makedirs(self.dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(
                    (payload, pickle.dumps((in_tree, out_tree))), f
                )
            os.replace(tmp, path)  # atomic publish
            return True
        except Exception:
            return False

    def get_or_compile(self, key: str, jit_fn, *args):
        """Cached executable for ``key``, else ``jit_fn.lower(*args)
        .compile()`` persisted for the next restart. The returned
        callable takes the same arguments as ``jit_fn``."""
        compiled = self.load(key)
        if compiled is not None:
            return compiled
        compiled = jit_fn.lower(*args).compile()
        self.store(key, compiled)
        return compiled


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    lower the persistence thresholds so the solver programs qualify.
    Returns the directory in effect, or None when disabled. Safe to
    call more than once; must run before the first jit compilation to
    cover it."""
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("KTPU_COMPILATION_CACHE_DIR", _DEFAULT_DIR)
    if not cache_dir:
        return None
    # host-CPU-scoped subdirectory: AOT results never replay across
    # machines with different CPU feature sets (SIGILL / stall risk —
    # the MULTICHIP_r05 dryrun timeout)
    cache_dir = _host_scoped(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip small/fast programs; the matrix-config
        # solves compile in 0.2-2 s each and all of them matter for the
        # restart path
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        # cache is an optimization: never fail startup over it
        return None
    return cache_dir
