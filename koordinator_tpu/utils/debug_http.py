"""Debug/observability HTTP server: the mux every reference binary runs.

Reference: cmd/koord-scheduler/app/server.go:293-303 installs pprof, the
runtime-settable score/filter debug toggles (PUT /debug/flags/s and /f,
pkg/scheduler/frameworkext/debug.go), the per-plugin REST services
(pkg/scheduler/frameworkext/services/services.go:44-104 — GET
/apis/v1/plugins/<name>), plus /metrics and /healthz on every binary.

One stdlib ThreadingHTTPServer serves the same surface over the typed
registries this framework already keeps:

- ``GET /healthz``                  -> 200 "ok"
- ``GET /metrics``                  -> prometheus text exposition
- ``GET /apis/v1/plugins``          -> registered debug service names
- ``GET /apis/v1/plugins/<name>``   -> that service's JSON payload
- ``PUT /debug/flags/s|f?value=1``  -> toggle score/filter dumps
- ``GET /debug/dumps``              -> collected score/filter/explain dumps
- ``GET /debug/trace``              -> Chrome-trace-event JSON of the span
                                       tracer's ring (load in Perfetto:
                                       the pipelined stage/solve overlap
                                       renders as crossing tracks)
- ``GET /debug/device``             -> the device-cost observatory
                                       (obs/device.py): compile ring,
                                       per-variant cost/memory analyses
                                       (materialized on this read),
                                       padding-waste and live-buffer
                                       accounting
- ``GET /debug/profile?rounds=K``   -> arm a jax profiler window over
                                       the next K scheduling rounds
                                       (429 when rate-limited or a
                                       window is already in play; 501
                                       when this jax build has no
                                       profiler)
- ``GET /explain?pod=<uid>[&node=<name>]``
                                    -> placement explanation for one pod
                                       (obs/explain.py: per-node filter
                                       verdicts + per-plugin score columns)
- ``GET /audit?group=&subject=&operation=&since=&limit=``
                                    -> koordlet audit query
                                       (pkg/koordlet/audit HTTP endpoint)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class DebugHTTPServer:
    """Serves a DebugServices registry, a DebugRecorder, and a metrics
    gatherer (anything with ``gather() -> str``) on one port."""

    def __init__(self, services=None, debug=None, metrics=None,
                 auditor=None, tracer=None, explain=None,
                 device=None, profile=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.services = services
        self.debug = debug
        self.metrics = metrics
        self.auditor = auditor
        #: a SpanTracer (obs/trace.py) served at /debug/trace
        self.tracer = tracer
        #: ``explain(pod_uid, node=None) -> dict`` served at /explain
        self.explain = explain
        #: ``device() -> dict`` served at /debug/device (obs/device.py
        #: DEVICE_OBS.debug_payload)
        self.device = device
        #: ``profile(rounds) -> dict`` served at /debug/profile
        #: (DEVICE_OBS.request_profile)
        self.profile = profile
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet by default
                pass

            def _send(self, code: int, body: str,
                      content_type: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                # service callables read live scheduler state from
                # handler threads: any race/iteration error must come
                # back as a 500, not an aborted connection
                try:
                    self._get()
                except Exception as e:
                    try:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}))
                    except Exception:
                        pass

            def _get(self):
                path = urlparse(self.path).path.rstrip("/")
                if path == "/healthz":
                    return self._send(200, "ok", "text/plain")
                if path == "/metrics":
                    if outer.metrics is None:
                        return self._send(404, "no metrics registry",
                                          "text/plain")
                    return self._send(200, outer.metrics.gather(),
                                      "text/plain; version=0.0.4")
                if path == "/apis/v1/plugins":
                    names = outer.services.names() if outer.services else []
                    return self._send(200, json.dumps(names))
                if path.startswith("/apis/v1/plugins/"):
                    name = path[len("/apis/v1/plugins/"):]
                    payload = (outer.services.query(name)
                               if outer.services else None)
                    if payload is None:
                        return self._send(404, json.dumps(
                            {"error": f"unknown plugin {name!r}"}))
                    return self._send(200, json.dumps(payload, default=str))
                if path == "/audit":
                    if outer.auditor is None:
                        return self._send(404, "no auditor", "text/plain")
                    q = parse_qs(urlparse(self.path).query)

                    def one(key):
                        return q.get(key, [None])[0]

                    events = outer.auditor.query(
                        group=one("group"), subject=one("subject"),
                        operation=one("operation"),
                        since=float(one("since")) if one("since") else None,
                        limit=int(one("limit")) if one("limit") else None,
                    )
                    import dataclasses as _dc

                    return self._send(200, json.dumps(
                        [_dc.asdict(e) for e in events]))
                if path == "/debug/dumps":
                    if outer.debug is None:
                        return self._send(404, "no debug recorder",
                                          "text/plain")
                    return self._send(200, json.dumps({
                        "scores": outer.debug.scores,
                        "filters": outer.debug.filters,
                        "explains": list(
                            getattr(outer.debug, "explains", ())
                        ),
                    }, default=str))
                if path == "/debug/trace":
                    if outer.tracer is None:
                        return self._send(404, "no tracer", "text/plain")
                    return self._send(
                        200, json.dumps(outer.tracer.chrome_trace(),
                                        default=str)
                    )
                if path == "/debug/device":
                    if outer.device is None:
                        return self._send(404, "no device observatory",
                                          "text/plain")
                    return self._send(
                        200, json.dumps(outer.device(), default=str)
                    )
                if path == "/debug/profile":
                    if outer.profile is None:
                        return self._send(404, "no device observatory",
                                          "text/plain")
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        rounds = int(q.get("rounds", ["8"])[0])
                    except ValueError:
                        return self._send(400, json.dumps(
                            {"error": "rounds must be an integer"}))
                    payload = outer.profile(rounds)
                    # a permanent incapacity (old jax) is 501 — a
                    # retry loop honoring 429 must not spin on it
                    if payload.get("unsupported"):
                        code = 501
                    elif "error" in payload:
                        code = 429
                    else:
                        code = 200
                    return self._send(code, json.dumps(payload,
                                                       default=str))
                if path == "/explain":
                    if outer.explain is None:
                        return self._send(404, "no explainer",
                                          "text/plain")
                    q = parse_qs(urlparse(self.path).query)
                    uid = q.get("pod", [None])[0]
                    if uid is None:
                        return self._send(400, json.dumps(
                            {"error": "missing ?pod=<uid>"}))
                    payload = outer.explain(
                        uid, node=q.get("node", [None])[0]
                    )
                    return self._send(200, json.dumps(payload, default=str))
                return self._send(404, "not found", "text/plain")

            def do_PUT(self):
                try:
                    self._put()
                except Exception as e:
                    try:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}))
                    except Exception:
                        pass

            def _put(self):
                # the reference's runtime toggles: PUT /debug/flags/s, /f
                # with value=1|0 (server.go:300-303 DebugScoresSetter)
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/")
                if outer.debug is not None and path in (
                    "/debug/flags/s", "/debug/flags/f"
                ):
                    raw = parse_qs(parsed.query).get("value", ["1"])[0]
                    on = raw.lower() not in ("0", "false", "off")
                    if path.endswith("/s"):
                        outer.debug.dump_scores = on
                    else:
                        outer.debug.dump_filters = on
                    return self._send(200, json.dumps({"enabled": on}))
                return self._send(404, "not found", "text/plain")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "DebugHTTPServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
