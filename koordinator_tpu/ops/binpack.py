"""Batched pod placement: the scheduler's hot loop as one XLA computation.

The reference schedules pods one at a time: per pod it runs Filter over all
nodes, Score over the feasible ones, picks the best, and *assumes* the pod
into the in-memory cache so the next pod sees it (SURVEY.md §3.1). Here the
entire pending queue is placed in a single ``lax.scan`` over pods (schedule
order), where each step is fully vectorized over the node axis:

    mask  = fit_filter & loadaware_filter & schedulable        # [N]
    score = Σ_plugin weight · plugin_score                     # [N]
    node  = argmax(score masked)                                # []
    state += pod (requests into used_req, estimate into est_extra)

This preserves the reference's observable semantics (same pod order, same
per-pod view of prior placements) while compiling to one TPU program — no
host round-trips per pod. Tie-breaking is deterministic lowest-index
(the reference picks uniformly among max-score nodes; any member of that
set is a legal outcome, we fix the first).

Fine-grained plugins integrate three ways (reference parity map):

- **Reservation matched credit** (transformer.go restoreMatchedReservation):
  carried ``resv.free [V,R]`` remainders are credited back per scan step to
  pods matching each reservation, and consumed (best-free-first) when a
  matching pod places on the reservation's node.
- **NUMA score + aggregate consumption** (nodenumaresource/scoring.go): the
  per-node least/most-allocated score over aggregated NUMA resources is
  computed in-scan from ``NodeState.numa_cap/numa_free``; pods subject to a
  NUMA topology policy subtract their request on placement.
- **Host-computed extras** (``Extras.mask/score [P,N]``): per-pod×node
  feasibility and score injections for the inherently sequential greedy
  sub-algorithms (cpuset take, device joint-allocate, hint merge) computed
  by the host against manager state, validated post-solve and re-solved on
  conflict (models/placement.py).

Reference: pkg/scheduler/frameworkext/framework_extender.go:167-262
(RunPreFilter/Filter/Score) and the plugin semantics in ops/fit.py,
ops/loadaware.py.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.common import reciprocal_for
from koordinator_tpu.ops.fit import fit_filter, least_allocated_score
from koordinator_tpu.ops.loadaware import loadaware_filter, loadaware_score


class SolverConfig(NamedTuple):
    """Static (trace-time) solver configuration."""

    fit_weight: int = 1          # NodeResourcesFit LeastAllocated plugin weight
    loadaware_weight: int = 1    # LoadAwareScheduling plugin weight
    score_according_prod: bool = False
    numa_most_allocated: bool = False  # NUMA scorer: MostAllocated vs Least
    #: scan unroll factor: amortizes per-step loop overhead; results are
    #: identical at any value. Measured r4 on one v5e chip at 10k x 5k:
    #: 4 -> 51.6k, 8 -> 53.4k, 16 -> 59.5k, 32 -> 63.4k, 64 -> 61.0k
    #: pods/s. The default stays 8 because unroll 32 triples XLA compile
    #: time (2.2s -> 7.3s CPU), which dominates tests and cold starts;
    #: production (cmd/scheduler) and the bench scan legs set 32.
    unroll: int = 8
    #: pallas kernel inner-loop unroll (per-pod fori_loop). Mosaic only
    #: lowers unroll=1 or full (=128); measured r5 on one v5e at
    #: 10k x 5k: full unroll is NO faster (88.9 ms vs 85.0 ms) and
    #: costs 55 s compile — the kernel is not loop-overhead-bound.
    #: Kept as a knob for future shapes; leave at 1.
    kernel_unroll: int = 1


class NodeState(NamedTuple):
    """Device-resident node-side solver state (the scan carry).

    All arrays int32 canonical units; bool masks. ``numa_cap``/``numa_free``
    are the aggregated per-node NUMA inventories ([N,R], None when no node
    reports topology) feeding the in-scan NUMA score.
    """

    alloc: jnp.ndarray         # [N,R]
    used_req: jnp.ndarray      # [N,R] assigned pod requests (mutated by solve)
    usage: jnp.ndarray         # [N,R] reported usage (static within a solve)
    prod_usage: jnp.ndarray    # [N,R] prod Filter base (Σ prod reported usage)
    est_extra: jnp.ndarray     # [N,R] assigned-pod estimation correction
    prod_base: jnp.ndarray     # [N,R] prod-mode score base
    metric_fresh: jnp.ndarray  # [N]
    schedulable: jnp.ndarray   # [N]
    numa_cap: Optional[jnp.ndarray] = None   # [N,R] Σ NUMA-node allocatable
    numa_free: Optional[jnp.ndarray] = None  # [N,R] Σ NUMA-node free


class PodBatch(NamedTuple):
    """Pending pods in schedule order (the scan xs)."""

    req: jnp.ndarray           # [P,R]
    est: jnp.ndarray           # [P,R]
    is_prod: jnp.ndarray       # [P]
    is_daemonset: jnp.ndarray  # [P]
    quota_id: jnp.ndarray      # [P] int32, -1 = not quota-managed
    non_preemptible: jnp.ndarray  # [P] bool
    gang_id: jnp.ndarray       # [P] int32, -1 = not gang-managed
    blocked: jnp.ndarray       # [P] bool — host-side hard reject (e.g. a
    #                            gang pod whose GangSpec is not yet known)
    # [P] bool — pod declares its own NUMA topology policy (annotation
    # override); with NumaAux it marks the pod as consuming numa_free
    has_numa_policy: Optional[jnp.ndarray] = None

    @classmethod
    def build(
        cls,
        req,
        est,
        is_prod,
        is_daemonset,
        quota_id=None,
        non_preemptible=None,
        gang_id=None,
        blocked=None,
        has_numa_policy=None,
    ):
        p = req.shape[0]
        return cls(
            req=req,
            est=est,
            is_prod=is_prod,
            is_daemonset=is_daemonset,
            quota_id=(
                quota_id if quota_id is not None else jnp.full(p, -1, jnp.int32)
            ),
            non_preemptible=(
                non_preemptible
                if non_preemptible is not None
                else jnp.zeros(p, bool)
            ),
            gang_id=(
                gang_id if gang_id is not None else jnp.full(p, -1, jnp.int32)
            ),
            blocked=(blocked if blocked is not None else jnp.zeros(p, bool)),
            has_numa_policy=has_numa_policy,
        )


class ScoreParams(NamedTuple):
    """Per-solve scoring parameters (device arrays)."""

    weights: jnp.ndarray          # [R] resource weights
    thresholds: jnp.ndarray       # [R] loadaware usage thresholds (%)
    prod_thresholds: jnp.ndarray  # [R] loadaware prod-usage thresholds (%)


class Extras(NamedTuple):
    """Host-injected per-pod×node feasibility and score (fine-grained
    plugins: NUMA hint-merge/cpuset feasibility, DeviceShare)."""

    mask: jnp.ndarray   # [P,N] bool
    score: jnp.ndarray  # [P,N] int32 added to feasible nodes' scores


class ResvArrays(NamedTuple):
    """Reservation matched-credit arrays (reference: reservation
    transformer.go restore + plugin Reserve allocation)."""

    node: jnp.ndarray           # [V] int32 node index of each reservation
    free: jnp.ndarray           # [V,R] int32 initial free remainder
    allocate_once: jnp.ndarray  # [V] bool
    match: jnp.ndarray          # [P,V] bool pod↔reservation owner match


class NumaAux(NamedTuple):
    """Enables in-scan NUMA scoring/consumption (requires
    ``NodeState.numa_cap/numa_free`` and ``PodBatch.has_numa_policy``)."""

    node_policy: jnp.ndarray  # [N] bool — node declares a topology policy


#: the NodeState columns a staged-state delta update rewrites (the
#: numa inventories ride the fine-grained path, which always restages)
STAGED_NODE_FIELDS = (
    "alloc", "used_req", "usage", "prod_usage", "est_extra", "prod_base",
    "metric_fresh", "schedulable",
)


def scatter_node_rows(state: NodeState, idx, rows) -> NodeState:
    """Write the re-lowered rows of the dirty nodes into a staged
    ``NodeState`` at ``idx`` — the device half of incremental staging
    (state/cluster.lower_nodes_delta is the host half). ``rows`` maps
    each :data:`STAGED_NODE_FIELDS` name to its ``[D, ...]`` update.

    Callers jit this with ``donate_argnums=(0,)`` (see
    :data:`scatter_node_rows_donated`) so XLA double-buffers: the old
    staged arrays are donated to the scatter and steady-state ticks
    never re-upload the ``[N, R]`` world."""
    updates = {
        f: getattr(state, f).at[idx].set(rows[f])
        for f in STAGED_NODE_FIELDS
    }
    return state._replace(**updates)


#: the jitted, input-donating form every staging cache shares (one
#: compiled program per (N, D) shape pair); the DEVICE_OBS wrapper adds
#: compile telemetry (docs/DESIGN.md §17) and is call-transparent
scatter_node_rows_donated = DEVICE_OBS.jit("scatter_node_rows_donated", jax.jit(
    scatter_node_rows, donate_argnums=(0,), static_argnums=()
))

#: the non-donating twin: used by the staging cache while a dispatched
#: solve still holds the current staged generation (the pipelined tick
#: path's double buffer, docs/DESIGN.md §15) — donating a buffer a
#: live computation reads would hand XLA a license to clobber it, so
#: the scatter writes a fresh generation instead and the pinned one
#: stays immutable until the solve retires
scatter_node_rows_copied = DEVICE_OBS.jit("scatter_node_rows_copied", jax.jit(
    scatter_node_rows, donate_argnums=(), static_argnums=()
))


def dirty_row_bucket(d: int) -> int:
    """The dirty-row scatter's shape bucket (next power of two, floor
    8) — a named member of the repo bucket family so graftcheck's
    shape-flow passes can enumerate its finite image and sanction the
    flows through it (docs/DESIGN.md §23)."""
    return max(8, 1 << (d - 1).bit_length())


def bucket_row_update(idx, rows):
    """Pad a dirty-row update to a power-of-two bucket by repeating the
    last row — identical writes land on the same index, so the scatter
    result is unchanged while drifting dirty counts reuse one compiled
    scatter per bucket instead of retracing per count."""
    import numpy as np

    d = int(idx.shape[0])
    target = dirty_row_bucket(d)
    DEVICE_OBS.note_padding("dirty_rows", d, target)
    if target == d:
        return idx, rows
    pad = target - d
    idx = np.concatenate([idx, np.repeat(idx[-1:], pad)])
    rows = {
        f: np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
        for f, a in rows.items()
    }
    return idx, rows


class SolveResult(NamedTuple):
    """Everything one batched solve produces.

    ``assign`` is the post-gang committed/waiting node per pod (-1 else);
    ``raw_assign`` is the scan's placement before gang resolution (what the
    host validation loop replays). Reservation consumption comes back as
    per-pod ``resv_vstar``/``resv_delta`` so the host can mutate the
    matching ReservationSpec exactly as the incremental Reserve does.
    """

    node_state: NodeState
    quota_state: Optional[object]        # QuotaState when quotas present
    resv_free: Optional[jnp.ndarray]     # [V,R] final free remainders
    assign: jnp.ndarray                  # [P] int32
    commit: jnp.ndarray                  # [P] bool
    waiting: jnp.ndarray                 # [P] bool
    rejected: jnp.ndarray                # [P] bool
    raw_assign: jnp.ndarray              # [P] int32
    resv_vstar: Optional[jnp.ndarray]    # [P] int32 consumed reservation, -1
    resv_delta: Optional[jnp.ndarray]    # [P,R] consumed amount
    numa_consumed: Optional[jnp.ndarray]  # [P] bool


def score_one_pod(
    state: NodeState,
    req: jnp.ndarray,
    est: jnp.ndarray,
    is_prod: jnp.ndarray,
    is_daemonset: jnp.ndarray,
    params: ScoreParams,
    config: SolverConfig,
    alloc_recip: Optional[jnp.ndarray] = None,
) -> tuple:
    """(mask[N], score[N]) for one pod against the full node set.

    ``alloc_recip`` (``reciprocal_for(state.alloc)``, computed once per
    solve) replaces the two per-step int32 divisions with the exact
    reciprocal-multiply path — identical results, ~4x the throughput on
    TPU (int32 division lowers to a long scalar expansion).
    """
    mask = (
        state.schedulable
        & fit_filter(req, state.alloc, state.used_req)
        & loadaware_filter(
            state.alloc,
            state.usage,
            state.prod_usage,
            state.metric_fresh,
            params.thresholds,
            params.prod_thresholds,
            is_daemonset,
            is_prod,
        )
    )
    score = config.fit_weight * least_allocated_score(
        req, state.alloc, state.used_req, params.weights, alloc_recip
    ) + config.loadaware_weight * loadaware_score(
        est,
        state.alloc,
        state.usage,
        state.est_extra,
        state.prod_base,
        state.metric_fresh,
        params.weights,
        is_prod,
        config.score_according_prod,
        alloc_recip,
    )
    return mask, score


def numa_node_score(
    cap: jnp.ndarray,   # [N,R]
    free: jnp.ndarray,  # [N,R]
    req: jnp.ndarray,   # [R]
    config: SolverConfig,
) -> jnp.ndarray:
    """[N] NUMA least/most-allocated score, the in-scan counterpart of
    scheduler/plugins/nodenumaresource.py ``score`` (reference:
    nodenumaresource/scoring.go): per requested resource,
    ``requested = cap - free + req``; least = ``(cap-requested)*100//cap``,
    0 when cap==0 or requested>cap; mean over requested resources."""
    member = req > 0                      # [R]
    requested = cap - free + req          # [N,R]
    capq = jnp.maximum(cap, 1)
    least = ((cap - requested) * 100) // capq
    most = (requested * 100) // capq
    per = jnp.where(
        member & (cap > 0) & (requested <= cap),
        most if config.numa_most_allocated else least,
        0,
    )
    w = member.sum()
    return jnp.where(w > 0, per.sum(axis=-1) // jnp.maximum(w, 1), 0)


def place_one_pod(
    state: NodeState,
    req: jnp.ndarray,
    est: jnp.ndarray,
    is_prod: jnp.ndarray,
    is_daemonset: jnp.ndarray,
    params: ScoreParams,
    config: SolverConfig,
    extra_mask: Optional[jnp.ndarray] = None,
    admit: Optional[jnp.ndarray] = None,
) -> tuple:
    """Place a single pod; returns (new_state, chosen_node or -1).

    ``extra_mask`` lets upper layers inject per-node feasibility;
    ``admit`` gates the whole pod (quota / gang admission) without
    disturbing scan shape. (Thin single-pod wrapper kept for tests and
    the incremental path's cross-checks.)
    """
    mask, score = score_one_pod(state, req, est, is_prod, is_daemonset, params, config)
    if extra_mask is not None:
        mask = mask & extra_mask
    if admit is not None:
        mask = mask & admit
    masked_score = jnp.where(mask, score, -1)
    best = jnp.argmax(masked_score)          # first max index == deterministic tie-break
    ok = masked_score[best] >= 0
    node = jnp.where(ok, best, -1).astype(jnp.int32)
    add_req = jnp.where(ok, req, 0)
    add_est = jnp.where(ok, est, 0)
    new_state = state._replace(
        used_req=state.used_req.at[best].add(add_req),
        est_extra=state.est_extra.at[best].add(add_est),
        prod_base=state.prod_base.at[best].add(jnp.where(is_prod, add_est, 0)),
    )
    return new_state, node


def solve_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config: SolverConfig = SolverConfig(),
    quota_state=None,
    gang_state=None,
    extras: Optional[Extras] = None,
    resv: Optional[ResvArrays] = None,
    numa: Optional[NumaAux] = None,
) -> SolveResult:
    """Schedule a whole pending queue with every enabled subsystem fused
    into one scan. Optional features add structure only when present, so
    the plain fast path compiles to the same program as before.

    Semantics match scheduling the pods one-by-one through the reference's
    Filter→Score→Reserve cycle: quota admission gates each pod
    (plugin.go:210-255), reservation credit/consumption follows the
    restore/Reserve chain, NUMA scoring/consumption follows scoring.go,
    and gang-group all-or-nothing admission resolves at batch end with
    rejected Strict gangs' resources (including reservation consumption
    and NUMA holds) released.

    Every step is integer arithmetic end to end (scores included), so
    ``jax.vmap`` over a leading request axis is bit-identical to
    running each lane alone — the admission gate's coalescing
    (service/admission.py) leans on exactly this property.
    """
    n_pods = pods.req.shape[0]
    use_q = quota_state is not None
    use_x = extras is not None
    use_r = resv is not None
    use_n = numa is not None

    if state.alloc.shape[0] == 0:  # static shape: no nodes, nothing placeable
        empty = jnp.full(n_pods, -1, dtype=jnp.int32)
        falses = jnp.zeros(n_pods, bool)
        return SolveResult(
            node_state=state,
            quota_state=quota_state,
            resv_free=resv.free if use_r else None,
            assign=empty,
            commit=falses,
            waiting=falses,
            rejected=falses,
            raw_assign=empty,
            resv_vstar=jnp.full(n_pods, -1, jnp.int32) if use_r else None,
            resv_delta=jnp.zeros_like(pods.req) if use_r else None,
            numa_consumed=falses if use_n else None,
        )

    if use_q:
        from koordinator_tpu.ops.quota import (
            quota_admit,
            quota_assume,
            quota_runtime,
        )

        # Requests are static within a solve (registered at pod creation),
        # so the water-filled runtime is computed once for the whole batch.
        runtime = quota_runtime(quota_state)

    # allocatable is static within a solve: precompute the reciprocal once
    # so every scan step scores without int32 division
    alloc_recip = reciprocal_for(state.alloc)

    xs = [pods.req, pods.est, pods.is_prod, pods.is_daemonset, pods.blocked]
    if use_q:
        xs += [pods.quota_id, pods.non_preemptible]
    if use_x:
        xs += [extras.mask, extras.score]
    if use_r:
        xs += [resv.match]
    if use_n:
        assert pods.has_numa_policy is not None
        assert state.numa_cap is not None and state.numa_free is not None
        xs += [pods.has_numa_policy]

    init = [state]
    if use_q:
        init.append(quota_state)
    if use_r:
        init.append(resv.free)

    def step(carry, x):
        ci = iter(carry)
        ns = next(ci)
        qs = next(ci) if use_q else None
        rfree = next(ci) if use_r else None
        xi = iter(x)
        req = next(xi)
        est = next(xi)
        is_prod = next(xi)
        is_ds = next(xi)
        blocked = next(xi)
        if use_q:
            quota_id = next(xi)
            non_pre = next(xi)
        if use_x:
            emask = next(xi)
            escore = next(xi)
        if use_r:
            match = next(xi)
        if use_n:
            pod_numa = next(xi)

        eff = ns
        if use_r:
            # matched reservations' free remainder credited back on their
            # nodes for this pod's Filter/Score (fit path only — the
            # incremental restore adjusts requested, not usage)
            credit = jnp.zeros_like(ns.used_req).at[resv.node].add(
                jnp.where(match[:, None], rfree, 0)
            )
            eff = ns._replace(used_req=ns.used_req - credit)
        mask, score = score_one_pod(
            eff, req, est, is_prod, is_ds, params, config, alloc_recip
        )
        if use_n:
            score = score + numa_node_score(ns.numa_cap, ns.numa_free, req, config)
        if use_x:
            mask = mask & emask
            score = score + escore
        admit = ~blocked
        if use_q:
            admit = admit & quota_admit(qs, runtime, quota_id, req, non_pre)
        mask = mask & admit

        masked = jnp.where(mask, score, -1)
        best = jnp.argmax(masked)   # first max index == deterministic tie-break
        ok = masked[best] >= 0
        node = jnp.where(ok, best, -1).astype(jnp.int32)
        add_req = jnp.where(ok, req, 0)
        add_est = jnp.where(ok, est, 0)
        net_req = add_req
        outs = [node]

        if use_r:
            # consume the matched reservation with the most free capacity
            # on the chosen node (reservation.py Reserve); allocate_once
            # reservations become SUCCEEDED: remaining hold released, no
            # further matches (zero free ⇒ zero credit/consumption).
            on_node = match & (resv.node == best) & ok
            fsum = jnp.where(on_node, rfree.sum(axis=-1), -1)
            v_raw = jnp.argmax(fsum)
            has = fsum[v_raw] > 0
            delta = jnp.where(has, jnp.minimum(rfree[v_raw], req), 0)
            once = has & resv.allocate_once[v_raw]
            rem = jnp.where(once, rfree[v_raw] - delta, 0)
            rfree = rfree.at[v_raw].set(
                jnp.where(has, jnp.where(once, 0, rfree[v_raw] - delta), rfree[v_raw])
            )
            vstar = jnp.where(has, v_raw, -1).astype(jnp.int32)
            # the pod's request lands on the node minus what the
            # reservation hold already accounted (delta) and minus the
            # released remainder of an allocate_once reservation (rem)
            net_req = net_req - delta - rem
            outs += [vstar, delta, rem]

        new_ns = ns._replace(
            used_req=ns.used_req.at[best].add(net_req),
            est_extra=ns.est_extra.at[best].add(add_est),
            prod_base=ns.prod_base.at[best].add(jnp.where(is_prod, add_est, 0)),
        )
        if use_n:
            consume = ok & (pod_numa | numa.node_policy[best])
            new_ns = new_ns._replace(
                numa_free=new_ns.numa_free.at[best].add(
                    -jnp.where(consume, req, 0)
                )
            )
            outs.append(consume)
        if use_q:
            qs = quota_assume(qs, quota_id, req, non_pre, node >= 0)

        out_carry = [new_ns]
        if use_q:
            out_carry.append(qs)
        if use_r:
            out_carry.append(rfree)
        return tuple(out_carry), tuple(outs)

    final_carry, ys = jax.lax.scan(
        step, tuple(init), tuple(xs), unroll=config.unroll
    )
    fi = iter(final_carry)
    final_state = next(fi)
    final_qstate = next(fi) if use_q else None
    final_rfree = next(fi) if use_r else None
    yi = iter(ys)
    assignments = next(yi)
    if use_r:
        resv_vstar = next(yi)
        resv_delta = next(yi)
        resv_rem = next(yi)
    else:
        resv_vstar = resv_delta = resv_rem = None
    numa_consumed = next(yi) if use_n else None

    if gang_state is None:
        placed = assignments >= 0
        return SolveResult(
            node_state=final_state,
            quota_state=final_qstate,
            resv_free=final_rfree,
            assign=assignments,
            commit=placed,
            waiting=jnp.zeros(n_pods, bool),
            rejected=jnp.zeros(n_pods, bool),
            raw_assign=assignments,
            resv_vstar=resv_vstar,
            resv_delta=resv_delta,
            numa_consumed=numa_consumed,
        )

    from koordinator_tpu.ops.gang import gang_outcomes, release_rejected

    commit, waiting, rejected = gang_outcomes(assignments, pods.gang_id, gang_state)
    # a rejected pod held only its net request (reservation delta+rem were
    # absorbed by the hold shrink) — release exactly that
    rel_req = pods.req
    if use_r:
        rel_req = pods.req - resv_delta - resv_rem
    used_req, est_extra, prod_base = release_rejected(
        final_state.used_req,
        final_state.est_extra,
        final_state.prod_base,
        assignments,
        rejected,
        rel_req,
        pods.est,
        pods.is_prod,
    )
    final_state = final_state._replace(
        used_req=used_req, est_extra=est_extra, prod_base=prod_base
    )
    if use_r:
        # restore rejected pods' reservation consumption (+ the released
        # allocate_once remainder): the incremental Unreserve equivalent
        v = resv.free.shape[0]
        take = rejected & (resv_vstar >= 0)
        vidx = jnp.where(take, resv_vstar, v)
        back = jnp.where(take[:, None], resv_delta + resv_rem, 0)
        final_rfree = final_rfree + jax.ops.segment_sum(
            back, vidx, num_segments=v + 1
        )[:v]
    if use_n:
        n = final_state.used_req.shape[0]
        take = rejected & numa_consumed
        nidx = jnp.where(take, assignments, n)
        back = jnp.where(take[:, None], pods.req, 0)
        final_state = final_state._replace(
            numa_free=final_state.numa_free
            + jax.ops.segment_sum(back, nidx, num_segments=n + 1)[:n]
        )
    out_assign = jnp.where(commit | waiting, assignments, -1).astype(jnp.int32)

    if final_qstate is not None:
        # release rejected pods' quota accounting too
        q = final_qstate.used.shape[0]
        qidx = jnp.where(rejected & (pods.quota_id >= 0), pods.quota_id, q)
        rel = jnp.where((rejected & (pods.quota_id >= 0))[:, None], pods.req, 0)
        sub = jax.ops.segment_sum(rel, qidx, num_segments=q + 1)[:q]
        np_rel = jnp.where(pods.non_preemptible[:, None], rel, 0)
        np_sub = jax.ops.segment_sum(np_rel, qidx, num_segments=q + 1)[:q]
        final_qstate = final_qstate._replace(
            used=final_qstate.used - sub, np_used=final_qstate.np_used - np_sub
        )

    return SolveResult(
        node_state=final_state,
        quota_state=final_qstate,
        resv_free=final_rfree,
        assign=out_assign,
        commit=commit,
        waiting=waiting,
        rejected=rejected,
        raw_assign=assignments,
        resv_vstar=resv_vstar,
        resv_delta=resv_delta,
        numa_consumed=numa_consumed,
    )


def schedule_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config: SolverConfig = SolverConfig(),
    quota_state=None,
    gang_state=None,
) -> tuple:
    """Legacy-shaped wrapper over :func:`solve_batch`.

    Returns ``(final_state, assignments[P])``; with ``quota_state``,
    ``final_state`` is ``(node_state, quota_state)``; with ``gang_state``,
    assignments is replaced by ``(assignments, commit[P], waiting[P])``
    after the gang-group feasibility pass.
    """
    r = solve_batch(state, pods, params, config, quota_state, gang_state)
    out_state = r.node_state if quota_state is None else (r.node_state, r.quota_state)
    if gang_state is None:
        return out_state, r.assign
    return out_state, (r.assign, r.commit, r.waiting)
