"""Batched pod placement: the scheduler's hot loop as one XLA computation.

The reference schedules pods one at a time: per pod it runs Filter over all
nodes, Score over the feasible ones, picks the best, and *assumes* the pod
into the in-memory cache so the next pod sees it (SURVEY.md §3.1). Here the
entire pending queue is placed in a single ``lax.scan`` over pods (schedule
order), where each step is fully vectorized over the node axis:

    mask  = fit_filter & loadaware_filter & schedulable        # [N]
    score = Σ_plugin weight · plugin_score                     # [N]
    node  = argmax(score masked)                                # []
    state += pod (requests into used_req, estimate into est_extra)

This preserves the reference's observable semantics (same pod order, same
per-pod view of prior placements) while compiling to one TPU program — no
host round-trips per pod. Tie-breaking is deterministic lowest-index
(the reference picks uniformly among max-score nodes; any member of that
set is a legal outcome, we fix the first).

Reference: pkg/scheduler/frameworkext/framework_extender.go:167-262
(RunPreFilter/Filter/Score) and the plugin semantics in ops/fit.py,
ops/loadaware.py.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from koordinator_tpu.ops.fit import fit_filter, least_allocated_score
from koordinator_tpu.ops.loadaware import loadaware_filter, loadaware_score


class SolverConfig(NamedTuple):
    """Static (trace-time) solver configuration."""

    fit_weight: int = 1          # NodeResourcesFit LeastAllocated plugin weight
    loadaware_weight: int = 1    # LoadAwareScheduling plugin weight
    score_according_prod: bool = False


class NodeState(NamedTuple):
    """Device-resident node-side solver state (the scan carry).

    All arrays int32 canonical units; bool masks.
    """

    alloc: jnp.ndarray         # [N,R]
    used_req: jnp.ndarray      # [N,R] assigned pod requests (mutated by solve)
    usage: jnp.ndarray         # [N,R] reported usage (static within a solve)
    prod_usage: jnp.ndarray    # [N,R] prod Filter base (Σ prod reported usage)
    est_extra: jnp.ndarray     # [N,R] assigned-pod estimation correction
    prod_base: jnp.ndarray     # [N,R] prod-mode score base
    metric_fresh: jnp.ndarray  # [N]
    schedulable: jnp.ndarray   # [N]


class PodBatch(NamedTuple):
    """Pending pods in schedule order (the scan xs)."""

    req: jnp.ndarray           # [P,R]
    est: jnp.ndarray           # [P,R]
    is_prod: jnp.ndarray       # [P]
    is_daemonset: jnp.ndarray  # [P]
    quota_id: jnp.ndarray      # [P] int32, -1 = not quota-managed
    non_preemptible: jnp.ndarray  # [P] bool
    gang_id: jnp.ndarray       # [P] int32, -1 = not gang-managed
    blocked: jnp.ndarray       # [P] bool — host-side hard reject (e.g. a
    #                            gang pod whose GangSpec is not yet known)

    @classmethod
    def build(
        cls,
        req,
        est,
        is_prod,
        is_daemonset,
        quota_id=None,
        non_preemptible=None,
        gang_id=None,
        blocked=None,
    ):
        p = req.shape[0]
        return cls(
            req=req,
            est=est,
            is_prod=is_prod,
            is_daemonset=is_daemonset,
            quota_id=(
                quota_id if quota_id is not None else jnp.full(p, -1, jnp.int32)
            ),
            non_preemptible=(
                non_preemptible
                if non_preemptible is not None
                else jnp.zeros(p, bool)
            ),
            gang_id=(
                gang_id if gang_id is not None else jnp.full(p, -1, jnp.int32)
            ),
            blocked=(blocked if blocked is not None else jnp.zeros(p, bool)),
        )


class ScoreParams(NamedTuple):
    """Per-solve scoring parameters (device arrays)."""

    weights: jnp.ndarray          # [R] resource weights
    thresholds: jnp.ndarray       # [R] loadaware usage thresholds (%)
    prod_thresholds: jnp.ndarray  # [R] loadaware prod-usage thresholds (%)


def score_one_pod(
    state: NodeState,
    req: jnp.ndarray,
    est: jnp.ndarray,
    is_prod: jnp.ndarray,
    is_daemonset: jnp.ndarray,
    params: ScoreParams,
    config: SolverConfig,
) -> tuple:
    """(mask[N], score[N]) for one pod against the full node set."""
    mask = (
        state.schedulable
        & fit_filter(req, state.alloc, state.used_req)
        & loadaware_filter(
            state.alloc,
            state.usage,
            state.prod_usage,
            state.metric_fresh,
            params.thresholds,
            params.prod_thresholds,
            is_daemonset,
            is_prod,
        )
    )
    score = config.fit_weight * least_allocated_score(
        req, state.alloc, state.used_req, params.weights
    ) + config.loadaware_weight * loadaware_score(
        est,
        state.alloc,
        state.usage,
        state.est_extra,
        state.prod_base,
        state.metric_fresh,
        params.weights,
        is_prod,
        config.score_according_prod,
    )
    return mask, score


def place_one_pod(
    state: NodeState,
    req: jnp.ndarray,
    est: jnp.ndarray,
    is_prod: jnp.ndarray,
    is_daemonset: jnp.ndarray,
    params: ScoreParams,
    config: SolverConfig,
    extra_mask: Optional[jnp.ndarray] = None,
    admit: Optional[jnp.ndarray] = None,
) -> tuple:
    """Place a single pod; returns (new_state, chosen_node or -1).

    ``extra_mask`` lets upper layers (reservation matching, node affinity,
    NUMA admit) inject per-node feasibility; ``admit`` gates the whole pod
    (quota / gang admission) without disturbing scan shape.
    """
    mask, score = score_one_pod(state, req, est, is_prod, is_daemonset, params, config)
    if extra_mask is not None:
        mask = mask & extra_mask
    if admit is not None:
        mask = mask & admit
    masked_score = jnp.where(mask, score, -1)
    best = jnp.argmax(masked_score)          # first max index == deterministic tie-break
    ok = masked_score[best] >= 0
    node = jnp.where(ok, best, -1).astype(jnp.int32)
    add_req = jnp.where(ok, req, 0)
    add_est = jnp.where(ok, est, 0)
    # An assumed pod has no reported usage yet, so it is "estimated" for
    # subsequent pods in this solve: non-prod correction always grows by
    # its estimate; the prod score base grows only for prod pods.
    new_state = state._replace(
        used_req=state.used_req.at[best].add(add_req),
        est_extra=state.est_extra.at[best].add(add_est),
        prod_base=state.prod_base.at[best].add(jnp.where(is_prod, add_est, 0)),
    )
    return new_state, node


def schedule_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config: SolverConfig = SolverConfig(),
    quota_state=None,
    gang_state=None,
) -> tuple:
    """Schedule a whole pending queue.

    Returns ``(final_state, assignments[P])``; with ``quota_state``,
    ``final_state`` is ``(node_state, quota_state)``; with ``gang_state``,
    assignments is replaced by ``(assignments, commit[P], waiting[P])``
    after the gang-group feasibility pass.

    ``assignments[i]`` is the node index for pod i (in the given order) or
    -1 if unschedulable at its turn. Semantics match scheduling the pods
    one-by-one through the reference's Filter→Score→Reserve cycle; with
    ``quota_state``, each pod additionally passes the ElasticQuota
    PreFilter gate (plugin.go:210-255; ops/quota.py); with ``gang_state``,
    gang-group all-or-nothing admission resolves at batch end with
    rejected Strict gangs' resources released (ops/gang.py).
    """
    n_pods = pods.req.shape[0]
    if state.alloc.shape[0] == 0:  # static shape: no nodes, nothing placeable
        empty = jnp.full(n_pods, -1, dtype=jnp.int32)
        out_state = state if quota_state is None else (state, quota_state)
        if gang_state is not None:
            falses = jnp.zeros(n_pods, bool)
            return out_state, (empty, falses, falses)
        return out_state, empty

    if quota_state is None:

        def step(carry: NodeState, xs):
            req, est, is_prod, is_ds, blocked = xs
            new_state, node = place_one_pod(
                carry, req, est, is_prod, is_ds, params, config, admit=~blocked
            )
            return new_state, node

        final_state, assignments = jax.lax.scan(
            step,
            state,
            (pods.req, pods.est, pods.is_prod, pods.is_daemonset, pods.blocked),
        )
        final_qstate = None
    else:
        from koordinator_tpu.ops.quota import (
            quota_admit,
            quota_assume,
            quota_runtime,
        )

        # Requests are static within a solve (registered at pod creation),
        # so the water-filled runtime is computed once for the whole batch.
        runtime = quota_runtime(quota_state)

        def step_q(carry, xs):
            node_state, qstate = carry
            req, est, is_prod, is_ds, quota_id, non_preempt, blocked = xs
            admit = ~blocked & quota_admit(qstate, runtime, quota_id, req, non_preempt)
            new_state, node = place_one_pod(
                node_state, req, est, is_prod, is_ds, params, config, admit=admit
            )
            new_qstate = quota_assume(qstate, quota_id, req, non_preempt, node >= 0)
            return (new_state, new_qstate), node

        (final_state, final_qstate), assignments = jax.lax.scan(
            step_q,
            (state, quota_state),
            (
                pods.req,
                pods.est,
                pods.is_prod,
                pods.is_daemonset,
                pods.quota_id,
                pods.non_preemptible,
                pods.blocked,
            ),
        )

    if gang_state is None:
        if final_qstate is None:
            return final_state, assignments
        return (final_state, final_qstate), assignments

    from koordinator_tpu.ops.gang import gang_outcomes, release_rejected

    commit, waiting, rejected = gang_outcomes(assignments, pods.gang_id, gang_state)
    used_req, est_extra, prod_base = release_rejected(
        final_state.used_req,
        final_state.est_extra,
        final_state.prod_base,
        assignments,
        rejected,
        pods.req,
        pods.est,
        pods.is_prod,
    )
    final_state = final_state._replace(
        used_req=used_req, est_extra=est_extra, prod_base=prod_base
    )
    out_assign = jnp.where(commit | waiting, assignments, -1).astype(jnp.int32)

    if final_qstate is not None:
        # release rejected pods' quota accounting too
        q = final_qstate.used.shape[0]
        qidx = jnp.where(rejected & (pods.quota_id >= 0), pods.quota_id, q)
        rel = jnp.where((rejected & (pods.quota_id >= 0))[:, None], pods.req, 0)
        sub = jax.ops.segment_sum(rel, qidx, num_segments=q + 1)[:q]
        np_rel = jnp.where(pods.non_preemptible[:, None], rel, 0)
        np_sub = jax.ops.segment_sum(np_rel, qidx, num_segments=q + 1)[:q]
        final_qstate = final_qstate._replace(
            used=final_qstate.used - sub, np_used=final_qstate.np_used - np_sub
        )
        return (final_state, final_qstate), (out_assign, commit, waiting)
    return final_state, (out_assign, commit, waiting)
