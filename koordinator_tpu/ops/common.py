"""Shared integer math helpers used across scheduling ops.

All score math is exact int32 arithmetic. The reference computes in Go
int64 (occasionally via float64 with half-away-from-zero rounding); the
identities below reproduce those results exactly for the canonical-unit
value ranges (documented in apis/extension.py): percent math requires
values ≤ ~10.7M canonical units (10k cores / 10 TiB per node).
"""

from __future__ import annotations

import jax.numpy as jnp

#: framework.MaxNodeScore in the k8s scheduler framework.
MAX_NODE_SCORE = 100


def percent_rounded(used: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """``round(used / total * 100)`` with half-away-from-zero rounding in
    exact integer arithmetic: ``floor((200*used + total) / (2*total))``.
    ``total == 0`` yields 0.

    The reference (load_aware.go:215) computes this through float64, which
    can round an exact .5 boundary down (23/40 → 57 instead of 58); this
    framework defines the exact rational result as the semantics (see
    oracle/scheduler.py percent_rounded for the full note).
    """
    total_safe = jnp.maximum(total, 1)
    pct = (200 * used + total_safe) // (2 * total_safe)
    return jnp.where(total > 0, pct, 0)


def mul_percent_floor(x: jnp.ndarray, pct: jnp.ndarray) -> jnp.ndarray:
    """``floor(x * pct / 100)`` without the ``x * pct`` intermediate, via
    the exact identity ``(x//100)*pct + ((x%100)*pct)//100`` — safe in
    int32 for any non-negative x and pct <= ~100 (a plain ``x * pct``
    wraps for memory columns above ~21.4M MiB)."""
    return (x // 100) * pct + ((x % 100) * pct) // 100


def percent_exceeds(diff: jnp.ndarray, base: jnp.ndarray,
                    pct: jnp.ndarray) -> jnp.ndarray:
    """Exact ``100*diff > base*pct`` for non-negative int32 operands
    without overflowing either product: with integer diff,
    ``diff > floor(base*pct/100)`` is equivalent (a strict integer bound
    clears any fractional remainder)."""
    return diff > mul_percent_floor(base, pct)


def least_requested_score(requested: jnp.ndarray, capacity: jnp.ndarray) -> jnp.ndarray:
    """``(capacity - requested) * 100 / capacity``; 0 when capacity is 0 or
    requested exceeds capacity (reference: load_aware.go:388-397).
    Integer (truncating) division — operands are non-negative so Go's
    truncation equals floor division.
    """
    cap_safe = jnp.maximum(capacity, 1)
    score = ((capacity - requested) * MAX_NODE_SCORE) // cap_safe
    return jnp.where((capacity == 0) | (requested > capacity), 0, score)


def weighted_mean_scores(scores: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """``Σ_r score_r * w_r // Σ_r w_r`` along the last axis (the single
    final integer division matches loadAwareSchedulingScorer,
    load_aware.go:378-386)."""
    weight_sum = jnp.maximum(jnp.sum(weights), 1)
    return jnp.sum(scores * weights, axis=-1) // weight_sum
