"""Shared integer math helpers used across scheduling ops.

All score math is exact int32 arithmetic. The reference computes in Go
int64 (occasionally via float64 with half-away-from-zero rounding); the
identities below reproduce those results exactly for the canonical-unit
value ranges (documented in apis/extension.py): percent math requires
values ≤ ~10.7M canonical units (10k cores / 10 TiB per node).
"""

from __future__ import annotations

import jax.numpy as jnp

#: framework.MaxNodeScore in the k8s scheduler framework.
MAX_NODE_SCORE = 100


def percent_rounded(used: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """``round(used / total * 100)`` with half-away-from-zero rounding in
    exact integer arithmetic: ``floor((200*used + total) / (2*total))``.
    ``total == 0`` yields 0.

    The reference (load_aware.go:215) computes this through float64, which
    can round an exact .5 boundary down (23/40 → 57 instead of 58); this
    framework defines the exact rational result as the semantics (see
    oracle/scheduler.py percent_rounded for the full note).
    """
    total_safe = jnp.maximum(total, 1)
    pct = (200 * used + total_safe) // (2 * total_safe)
    return jnp.where(total > 0, pct, 0)


def mul_percent_floor(x: jnp.ndarray, pct: jnp.ndarray) -> jnp.ndarray:
    """``floor(x * pct / 100)`` without the ``x * pct`` intermediate, via
    the exact identity ``(x//100)*pct + ((x%100)*pct)//100`` — safe in
    int32 for any non-negative x and pct <= ~100 (a plain ``x * pct``
    wraps for memory columns above ~21.4M MiB)."""
    return (x // 100) * pct + ((x % 100) * pct) // 100


def percent_exceeds(diff: jnp.ndarray, base: jnp.ndarray,
                    pct: jnp.ndarray) -> jnp.ndarray:
    """Exact ``100*diff > base*pct`` for non-negative int32 operands
    without overflowing either product: with integer diff,
    ``diff > floor(base*pct/100)`` is equivalent (a strict integer bound
    clears any fractional remainder)."""
    return diff > mul_percent_floor(base, pct)


def reciprocal_for(divisor: jnp.ndarray) -> jnp.ndarray:
    """f32 ``1/max(divisor,1)`` — precompute ONCE for a static divisor and
    feed :func:`floor_div_exact`. TPU int32 division lowers to a long
    scalar expansion (~10x the cost of the whole score body); a float
    reciprocal multiply plus a one-step integer correction computes the
    same exact floor quotient."""
    return 1.0 / jnp.maximum(divisor, 1).astype(jnp.float32)


def floor_div_exact(y: jnp.ndarray, divisor: jnp.ndarray,
                    recip: jnp.ndarray) -> jnp.ndarray:
    """Exact ``floor(y / max(divisor,1))`` for non-negative int32 ``y``.

    ``q0 = floor(f32(y) * recip)`` carries relative error < 3·2⁻²⁴, so its
    absolute error is < 1 whenever the true quotient is < ~2²². The two
    one-step corrections then pin the exact floor.

    Domain (int32 correction products must not wrap): quotient < 2²² AND
    ``y + divisor < 2³¹``. Score math satisfies both with wide headroom:
    quotients are ≤ 100 and ``y ≤ 100·capacity`` with capacity bounded at
    ~10.7M canonical units (apis/extension.py), so ``y + divisor ≤
    101·10.7M ≈ 2³⁰``.
    """
    y = jnp.maximum(y, 0)
    div_safe = jnp.maximum(divisor, 1)
    q0 = jnp.floor(y.astype(jnp.float32) * recip).astype(jnp.int32)
    return q0 - (q0 * div_safe > y) + ((q0 + 1) * div_safe <= y)


def least_requested_score(
    requested: jnp.ndarray,
    capacity: jnp.ndarray,
    recip: jnp.ndarray = None,
) -> jnp.ndarray:
    """``(capacity - requested) * 100 / capacity``; 0 when capacity is 0 or
    requested exceeds capacity (reference: load_aware.go:388-397).
    Integer (truncating) division — operands are non-negative so Go's
    truncation equals floor division. Pass ``recip``
    (:func:`reciprocal_for` of the static capacity) on hot paths: the
    result is identical, computed without the slow int32 divide.
    """
    if recip is not None:
        score = floor_div_exact(
            (capacity - requested) * MAX_NODE_SCORE, capacity, recip
        )
    else:
        cap_safe = jnp.maximum(capacity, 1)
        score = ((capacity - requested) * MAX_NODE_SCORE) // cap_safe
    return jnp.where((capacity == 0) | (requested > capacity), 0, score)


def weighted_mean_scores(scores: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """``Σ_r score_r * w_r // Σ_r w_r`` along the last axis (the single
    final integer division matches loadAwareSchedulingScorer,
    load_aware.go:378-386)."""
    weight_sum = jnp.maximum(jnp.sum(weights), 1)
    return jnp.sum(scores * weights, axis=-1) // weight_sum
