"""Joint place+evict: vectorized victim selection over the resident world.

Device twin of the host preemption oracle (scheduler/preemption.py —
``SelectVictimsOnNode``/``find_preemption``, transliterated from
preempt.go:103-294). The host path walks every node in Python, sorting
and reprieving per node at ~10 sweeps/s on a 5k-node world; here the
same decision is three vectorized passes over a dense ``[N, P]``
resident-pod world plus one ``lax.scan`` over the (bucketed) resident
axis, so a whole-cluster victim selection is one XLA dispatch.

Semantics reproduced bit-exactly (property-tested against the oracle in
tests/test_quota_preemption.py):

- **candidacy** (canPreempt, preempt.go:276-294): a resident is a
  candidate iff it is preemptible, has STRICTLY lower priority than the
  preemptor, and belongs to the same quota group;
- **remove-all gate**: evict every candidate; if the preemptor still
  fails fit (or the node fails the loadaware filter — usage does not
  change on eviction, so eviction cannot help) the node is out;
- **reprieve in importance order** (util.MoreImportantPod: priority
  desc, then earlier assignment): candidates are re-added
  most-important-first unless the preemptor would stop fitting. The
  ``[N, P]`` world arrives PRE-SORTED per node in importance order
  (state/cluster.lower_resident_pods), so the reprieve loop is a
  ``lax.scan`` over the P axis, vectorized over all nodes at once, and
  the surviving victim mask read in column order IS the oracle's
  victim order;
- **constant quota gate** (preempt.go:176-201): ``used + podReq >
  usedLimit`` is checked against the PostFilter-snapshot used — an
  over-runtime quota reprieves NOTHING;
- **ranking** (pickOneNodeForPreemption spirit): fewest victims, then
  lowest top victim priority, then the host's node iteration order
  (shipped as ``node_rank``).

The scan variant (:func:`preempt_scan`) runs the whole preemptor batch
in one program with eviction deltas applied in-carry; the defrag
variant (:func:`headroom_repack`) drains least-important-first to
restore a gang-sized hole. All integer arithmetic is int32 end-to-end,
matching the solver's bit-identity contract (ops/binpack.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
from jax import lax

from koordinator_tpu.ops.binpack import SolverConfig
from koordinator_tpu.ops.fit import fit_filter
from koordinator_tpu.ops.loadaware import loadaware_filter

I32_MAX = jnp.int32(2**31 - 1)
I32_MIN = jnp.int32(-(2**31))


class ResidentWorld(NamedTuple):
    """Dense per-node resident-pod state, pre-sorted per node in
    importance order (priority desc, then earlier assignment — the
    oracle's ``_more_important`` key). Padding columns are
    ``valid=False`` and inert everywhere."""

    req: jnp.ndarray          # [N,P,R] int32 victim requests
    priority: jnp.ndarray     # [N,P] int32
    quota_id: jnp.ndarray     # [N,P] int32, -1 = no quota group
    preemptible: jnp.ndarray  # [N,P] bool
    valid: jnp.ndarray        # [N,P] bool (False = padding or evicted)


class PreemptorBatch(NamedTuple):
    """Preemptor pods for the scanned joint solve (xs over K)."""

    req: jnp.ndarray          # [K,R] int32
    priority: jnp.ndarray     # [K] int32
    quota_id: jnp.ndarray     # [K] int32, -1 = no quota group
    is_daemonset: jnp.ndarray  # [K] bool
    is_prod: jnp.ndarray      # [K] bool
    quota_used: jnp.ndarray   # [K,R] int32 PostFilter-snapshot used
    used_limit: jnp.ndarray   # [K,R] int32 runtime (usedLimit)
    quota_enabled: jnp.ndarray  # [K] bool — quota gate armed for this pod
    active: jnp.ndarray       # [K] bool — False = padding row, a no-op step


def victim_candidacy(
    world: ResidentWorld,
    pod_priority: jnp.ndarray,   # [] int32
    pod_quota: jnp.ndarray,      # [] int32
) -> jnp.ndarray:
    """canPreempt as a ``[N,P]`` mask (preempt.go:276-294)."""
    return (
        world.valid
        & world.preemptible
        & (world.priority < pod_priority)
        & (world.quota_id == pod_quota)
    )


def _reprieve_scan(
    pod_req, node_alloc, kept0, cand, res_req, quota_blocks, unroll
):
    """The reprieve loop over the importance-ordered P axis, vectorized
    over nodes: carry is the per-node kept allocation; a candidate is
    reprieved when the preemptor still fits with it re-added and the
    quota gate does not block. Returns ``(kept [N,R],
    reprieved [N,P])``."""

    def step(kept, xs):
        req_p, cand_p = xs                       # [N,R], [N]
        trial = kept + req_p
        ok = cand_p & fit_filter(pod_req, node_alloc, trial) & ~quota_blocks
        kept = jnp.where(ok[:, None], trial, kept)
        return kept, ok

    xs = (jnp.swapaxes(res_req, 0, 1), jnp.swapaxes(cand, 0, 1))
    kept, reprieved = lax.scan(step, kept0, xs, unroll=unroll)
    return kept, jnp.swapaxes(reprieved, 0, 1)


def _select_core(
    config: SolverConfig,
    pod_req, pod_priority, pod_quota, pod_is_ds, pod_is_prod,
    quota_used, used_limit, quota_enabled,
    alloc, used_req, usage, prod_usage, metric_fresh, schedulable,
    node_rank, thresholds, prod_thresholds,
    world: ResidentWorld,
):
    """One preemptor against the whole world. Shared verbatim by the
    per-pod entry and the scanned joint solve so the two can never
    disagree on a step's outcome."""
    cand = victim_candidacy(world, pod_priority, pod_quota)
    has_cand = jnp.any(cand, axis=1)                       # [N]
    removed = jnp.sum(
        jnp.where(cand[..., None], world.req, 0), axis=1
    )                                                      # [N,R]
    la_ok = loadaware_filter(
        alloc, usage, prod_usage, metric_fresh,
        thresholds, prod_thresholds, pod_is_ds, pod_is_prod,
    )
    kept0 = used_req - removed
    fit_all = fit_filter(pod_req, alloc, kept0)
    # quota gate: CONSTANT across the reprieve loop (preempt.go:191-199)
    quota_blocks = quota_enabled & jnp.any(
        (pod_req > 0) & (quota_used + pod_req > used_limit)
    )
    node_ok = schedulable & has_cand & la_ok & fit_all
    _, reprieved = _reprieve_scan(
        pod_req, alloc, kept0, cand, world.req, quota_blocks,
        config.unroll,
    )
    victims = cand & ~reprieved
    n_victims = jnp.sum(victims, axis=1).astype(jnp.int32)
    feasible = node_ok & (n_victims > 0)
    top_prio = jnp.max(
        jnp.where(victims, world.priority, I32_MIN), axis=1
    )
    # rank lexicographically — fewest victims, lowest top priority,
    # host iteration order — via staged int32 argmin (no int64: the
    # solver substrate is x32)
    nv_key = jnp.where(feasible, n_victims, I32_MAX)
    best_nv = jnp.min(nv_key)
    tie1 = feasible & (n_victims == best_nv)
    tp_key = jnp.where(tie1, top_prio, I32_MAX)
    best_tp = jnp.min(tp_key)
    tie2 = tie1 & (top_prio == best_tp)
    rank_key = jnp.where(tie2, node_rank, I32_MAX)
    best = jnp.where(
        jnp.any(feasible),
        jnp.argmin(rank_key).astype(jnp.int32),
        jnp.int32(-1),
    )
    return best, victims, cand, n_victims


def select_victims(
    config: SolverConfig,
    pod_req: jnp.ndarray,        # [R] int32
    pod_priority: jnp.ndarray,   # [] int32
    pod_quota: jnp.ndarray,      # [] int32, -1 = none
    pod_is_ds: jnp.ndarray,      # [] bool
    pod_is_prod: jnp.ndarray,    # [] bool
    quota_used: jnp.ndarray,     # [R] int32
    used_limit: jnp.ndarray,     # [R] int32
    quota_enabled: jnp.ndarray,  # [] bool
    alloc: jnp.ndarray,          # [N,R] int32
    used_req: jnp.ndarray,       # [N,R] int32
    usage: jnp.ndarray,          # [N,R] int32
    prod_usage: jnp.ndarray,     # [N,R] int32
    metric_fresh: jnp.ndarray,   # [N] bool
    schedulable: jnp.ndarray,    # [N] bool
    node_rank: jnp.ndarray,      # [N] int32 host iteration order
    thresholds: jnp.ndarray,     # [R] int32
    prod_thresholds: jnp.ndarray,  # [R] int32
    world: ResidentWorld,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Whole-cluster victim selection for ONE preemptor.

    Returns ``(best_node [], victims [N,P], candidates [N,P],
    n_victims [N])`` — ``best_node`` is -1 when no node is viable;
    ``victims`` read along the (importance-sorted) P axis of the best
    row is the oracle's ordered victim list."""
    return _select_core(
        config, pod_req, pod_priority, pod_quota, pod_is_ds, pod_is_prod,
        quota_used, used_limit, quota_enabled,
        alloc, used_req, usage, prod_usage, metric_fresh, schedulable,
        node_rank, thresholds, prod_thresholds, world,
    )


def preempt_scan(
    config: SolverConfig,
    pods: PreemptorBatch,
    alloc: jnp.ndarray,          # [N,R] int32
    used_req0: jnp.ndarray,      # [N,R] int32
    usage: jnp.ndarray,          # [N,R]
    prod_usage: jnp.ndarray,     # [N,R]
    metric_fresh: jnp.ndarray,   # [N]
    schedulable: jnp.ndarray,    # [N]
    node_rank: jnp.ndarray,      # [N] int32
    thresholds: jnp.ndarray,     # [R]
    prod_thresholds: jnp.ndarray,  # [R]
    world: ResidentWorld,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The joint place+evict solve: every preemptor in ONE program.

    A scan over the (bucketed) preemptor axis whose carry is the
    eviction-adjusted world — each step runs :func:`_select_core` and,
    on a hit, scatters the victims OUT of the carry (``used_req`` row
    decremented, resident columns invalidated) exactly the way placed
    rows scatter in on the solve path. Per-pod quota rows are the
    PostFilter-snapshot values held constant for the round — identical
    to the host loop whenever the preemptors' quota groups don't
    overlap within a round (the per-pod dispatch path handles the
    general case; docs/DESIGN.md §24).

    Returns ``(best_node [K] int32 (-1 = none), victims [K,P] bool)``
    where ``victims[k]`` is the chosen node's victim-column mask for
    preemptor ``k``."""

    def step(carry, xs):
        used_req, valid = carry
        (req, prio, quota, is_ds, is_prod,
         q_used, q_limit, q_en, active) = xs
        w = world._replace(valid=valid)
        best, victims, _cand, _nv = _select_core(
            config, req, prio, quota, is_ds, is_prod,
            q_used, q_limit, q_en,
            alloc, used_req, usage, prod_usage, metric_fresh,
            schedulable, node_rank, thresholds, prod_thresholds, w,
        )
        hit = active & (best >= 0)
        b = jnp.maximum(best, 0)
        row_victims = victims[b] & hit                     # [P]
        freed = jnp.sum(
            jnp.where(row_victims[:, None], world.req[b], 0), axis=0
        )                                                  # [R]
        used_req = used_req.at[b].add(-freed)
        valid = valid.at[b].set(valid[b] & ~row_victims)
        return (used_req, valid), (jnp.where(hit, best, -1), row_victims)

    xs = (
        pods.req, pods.priority, pods.quota_id, pods.is_daemonset,
        pods.is_prod, pods.quota_used, pods.used_limit,
        pods.quota_enabled, pods.active,
    )
    (_, _), (best_nodes, victim_cols) = lax.scan(
        step, (used_req0, world.valid), xs, unroll=1
    )
    return best_nodes, victim_cols


def headroom_repack(
    config: SolverConfig,
    target_req: jnp.ndarray,       # [R] int32 the gang-sized hole to restore
    max_victim_priority: jnp.ndarray,  # [] int32 drain only below this
    alloc: jnp.ndarray,            # [N,R] int32
    used_req: jnp.ndarray,         # [N,R] int32
    schedulable: jnp.ndarray,      # [N] bool
    node_rank: jnp.ndarray,        # [N] int32
    world: ResidentWorld,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Defrag planner: cheapest node to DRAIN until ``target_req`` fits.

    Drain candidacy is preemptible residents strictly below
    ``max_victim_priority``; draining goes least-important-first (the
    reverse of the importance-sorted P axis), so the plan evicts the
    cheapest tail of each fragmented node. No scan — the cumulative
    freed prefix is one ``cumsum`` and the minimal drain count per node
    one masked ``min``.

    Returns ``(best_node [] int32 (-1 = none), drain_mask [N,P],
    n_drain [N] int32 (I32_MAX = cannot restore the hole),
    fits_now [N] bool)``. Nodes where the hole already fits are not
    drain targets (``fits_now`` reports them)."""
    cand = (
        world.valid & world.preemptible
        & (world.priority < max_victim_priority)
    )                                                      # [N,P]
    fits_now = fit_filter(target_req, alloc, used_req)     # [N]
    # reverse the importance axis: position j drains the j+1
    # least-important slots (non-candidates contribute nothing)
    cand_rev = cand[:, ::-1]
    req_rev = jnp.where(cand_rev[..., None], world.req[:, ::-1, :], 0)
    freed = jnp.cumsum(req_rev, axis=1)                    # [N,P,R]
    ncand = jnp.cumsum(cand_rev.astype(jnp.int32), axis=1)  # [N,P]
    remain = used_req[:, None, :] - freed                  # [N,P,R]
    fits_j = jnp.all(
        (target_req == 0)
        | (remain + target_req <= alloc[:, None, :]),
        axis=-1,
    )                                                      # [N,P]
    # only positions that actually drained a candidate count as plans
    # (a non-candidate slot repeats the previous prefix)
    plan = fits_j & cand_rev
    n_drain = jnp.min(jnp.where(plan, ncand, I32_MAX), axis=1)
    n_drain = jnp.where(fits_now, jnp.int32(0), n_drain)
    feasible = schedulable & ~fits_now & (n_drain < I32_MAX)
    nd_key = jnp.where(feasible, n_drain, I32_MAX)
    best_nd = jnp.min(nd_key)
    tie = feasible & (n_drain == best_nd)
    rank_key = jnp.where(tie, node_rank, I32_MAX)
    best = jnp.where(
        jnp.any(feasible),
        jnp.argmin(rank_key).astype(jnp.int32),
        jnp.int32(-1),
    )
    drain_rev = cand_rev & (ncand <= n_drain[:, None])
    drain_mask = drain_rev[:, ::-1]
    return best, drain_mask, n_drain, fits_now
