"""Vectorized colocation-overcommit calculator (batch/mid resources).

TPU-native rebuild of koord-manager's noderesource batch calculator
(reference: pkg/slo-controller/noderesource/plugins/batchresource/plugin.go:171
Calculate, :226 calculateOnNode; policy math in util.go:38-91
calculateBatchResourceByPolicy; mid resource in
plugins/midresource/plugin.go:128).

The reference reconciles one node at a time in Go. Here the whole cluster
is computed in ONE fused XLA program: pod-level contributions are reduced
onto their nodes with ``segment_sum`` (an MXU-friendly scatter-add over a
[P, R] matrix), then the per-node policy arithmetic runs elementwise over
the [N, R] capacity matrix. A 5k-node / 50k-pod cluster is a single device
dispatch instead of 5k reconcile invocations.

Formulas (reference util.go:40-53):

  by_usage   = max(cap - margin - max(sys, reserved) - hp_used, 0)
  by_request = max(cap - margin - reserved            - hp_req, 0)
  by_max     = max(cap - margin - max(sys, reserved) - hp_max_used_req, 0)

with ``margin = cap * (100 - reclaim_percent) / 100`` (util.go:205-213)
and the per-pod High-Priority (non batch/free) contributions
(plugin.go:226-283):

  no metric reported  -> used += req,            max_used_req += req
  QoS LSE             -> used += (req.cpu, use.mem), max_used_req += max(req, use)
  otherwise           -> used += use,            max_used_req += max(req, use)

Dangling pods (reported in NodeMetric but absent from the pod list,
plugin.go:295-303) are modeled as pods with ``req = 0, has_metric=True``:
the "otherwise" row then adds exactly their usage to both sums.

Stale NodeMetric degrades the node's batch resources to zero
(plugin.go:480-499 isDegradeNeeded/degradeCalculate) — here a mask.

All arithmetic is exact int32 in canonical units (mCPU / MiB).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.ops.common import mul_percent_floor, percent_exceeds


class CalculatePolicy:
    """Batch-resource calculate policies (reference:
    apis/configuration/slo_controller_config.go CalculatePolicy)."""

    USAGE = 0
    REQUEST = 1
    MAX_USAGE_REQUEST = 2


class OvercommitParams(NamedTuple):
    """Strategy knobs (reference: ColocationStrategy defaults,
    pkg/util/sloconfig/colocation_config.go:54-70). Each field is either
    cluster-wide ([R] / scalar) or per-node ([N, R] / [N]) — per-node
    strategies (node-selector overrides) stay one fused dispatch."""

    #: [R] or [N, R] reclaim-threshold percent per resource column; the
    #: safety margin is cap*(100-p)/100. Defaults: CPU 60, memory 65.
    reclaim_percent: jnp.ndarray
    #: [R] or [N, R] mid-resource threshold percent of node allocatable
    #: (cap on prod-reclaimable). Default 100 (midresource/plugin.go:137).
    mid_threshold_percent: jnp.ndarray
    #: scalar or [N] int32 CalculatePolicy for batch CPU
    #: (usage|maxUsageRequest).
    cpu_policy: jnp.ndarray
    #: scalar or [N] int32 CalculatePolicy for batch memory.
    memory_policy: jnp.ndarray


class NodeOvercommitInputs(NamedTuple):
    """Per-node inputs, [N, R] unless noted."""

    capacity: jnp.ndarray       # node allocatable (native columns)
    system_used: jnp.ndarray    # NodeMetric system usage + prod host apps
    reserved: jnp.ndarray       # max(kubelet reserved, annotation reserved)
    prod_reclaimable: jnp.ndarray  # predictor output (mid resource input)
    metric_fresh: jnp.ndarray   # [N] bool; False -> degrade to zero


class PodOvercommitInputs(NamedTuple):
    """Per-pod inputs, [P, ...]; inactive rows are masked out."""

    node_idx: jnp.ndarray    # [P] int32 owning node, -1 for unbound
    req: jnp.ndarray         # [P, R] requests
    usage: jnp.ndarray       # [P, R] reported usage (0 if no metric)
    has_metric: jnp.ndarray  # [P] bool
    is_hp: jnp.ndarray       # [P] bool: priority class not batch/free
    is_lse: jnp.ndarray      # [P] bool: QoS == LSE
    active: jnp.ndarray      # [P] bool: phase Running/Pending


def hp_pod_contributions(
    pods: PodOvercommitInputs, num_nodes: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Segment-sum the HP pod rows onto nodes.

    Returns ``(hp_req, hp_used, hp_max_used_req)`` each [N, R]
    (reference: plugin.go:226-283 loop body, :295-303 dangling).
    """
    counted = pods.active & pods.is_hp & (pods.node_idx >= 0)
    cm = counted[:, None]

    req = jnp.where(cm, pods.req, 0)
    usage = jnp.where(cm, pods.usage, 0)
    max_used_req = jnp.maximum(req, usage)

    # used contribution by metric/QoS row (see module docstring)
    lse_mix = usage.at[:, ResourceName.CPU].set(req[:, ResourceName.CPU])
    used = jnp.where(
        ~pods.has_metric[:, None],
        req,
        jnp.where(pods.is_lse[:, None], lse_mix, usage),
    )
    used = jnp.where(cm, used, 0)
    # without a metric, max(req, usage) must be req, not max(req, stale 0s)
    max_used_req = jnp.where(~pods.has_metric[:, None], req, max_used_req)
    max_used_req = jnp.where(cm, max_used_req, 0)

    seg = jnp.where(counted, pods.node_idx, num_nodes)  # park masked rows
    sum_req = jax.ops.segment_sum(req, seg, num_segments=num_nodes + 1)[:-1]
    sum_used = jax.ops.segment_sum(used, seg, num_segments=num_nodes + 1)[:-1]
    sum_max = jax.ops.segment_sum(
        max_used_req, seg, num_segments=num_nodes + 1
    )[:-1]
    return sum_req, sum_used, sum_max


def _select_policy(
    policy: jnp.ndarray,
    by_usage: jnp.ndarray,
    by_request: jnp.ndarray,
    by_max: jnp.ndarray,
) -> jnp.ndarray:
    return jnp.where(
        policy == CalculatePolicy.MAX_USAGE_REQUEST,
        by_max,
        jnp.where(policy == CalculatePolicy.REQUEST, by_request, by_usage),
    )


def batch_allocatable(
    nodes: NodeOvercommitInputs,
    pods: PodOvercommitInputs,
    params: OvercommitParams,
) -> jnp.ndarray:
    """Batch-reclaimable allocatable per node, [N, R] with only the
    BATCH_CPU / BATCH_MEMORY columns populated."""
    num_nodes = nodes.capacity.shape[0]
    hp_req, hp_used, hp_max = hp_pod_contributions(pods, num_nodes)

    cap = nodes.capacity
    margin = mul_percent_floor(cap, 100 - params.reclaim_percent)
    sys_or_reserved = jnp.maximum(nodes.system_used, nodes.reserved)

    base = cap - margin
    by_usage = jnp.maximum(base - sys_or_reserved - hp_used, 0)
    by_request = jnp.maximum(base - nodes.reserved - hp_req, 0)
    by_max = jnp.maximum(base - sys_or_reserved - hp_max, 0)

    batch_cpu = _select_policy(
        params.cpu_policy,
        by_usage[:, ResourceName.CPU],
        by_request[:, ResourceName.CPU],
        by_max[:, ResourceName.CPU],
    )
    batch_mem = _select_policy(
        params.memory_policy,
        by_usage[:, ResourceName.MEMORY],
        by_request[:, ResourceName.MEMORY],
        by_max[:, ResourceName.MEMORY],
    )

    fresh = nodes.metric_fresh
    out = jnp.zeros((num_nodes, NUM_RESOURCES), dtype=cap.dtype)
    out = out.at[:, ResourceName.BATCH_CPU].set(jnp.where(fresh, batch_cpu, 0))
    out = out.at[:, ResourceName.BATCH_MEMORY].set(
        jnp.where(fresh, batch_mem, 0)
    )
    return out


def mid_allocatable(
    nodes: NodeOvercommitInputs, params: OvercommitParams
) -> jnp.ndarray:
    """Mid-tier allocatable per node:
    ``min(allocatable * threshold%, prod_reclaimable)`` clamped at zero
    (reference: midresource/plugin.go:128-162), degraded with the metric
    mask like batch. [N, R] with MID_CPU / MID_MEMORY populated."""
    num_nodes = nodes.capacity.shape[0]
    ceiling = mul_percent_floor(nodes.capacity, params.mid_threshold_percent)
    mid = jnp.clip(jnp.minimum(nodes.prod_reclaimable, ceiling), 0)

    out = jnp.zeros((num_nodes, NUM_RESOURCES), dtype=nodes.capacity.dtype)
    for col, native in (
        (ResourceName.MID_CPU, ResourceName.CPU),
        (ResourceName.MID_MEMORY, ResourceName.MEMORY),
    ):
        out = out.at[:, col].set(
            jnp.where(nodes.metric_fresh, mid[:, native], 0)
        )
    return out


def overcommit_allocatable(
    nodes: NodeOvercommitInputs,
    pods: PodOvercommitInputs,
    params: OvercommitParams,
) -> jnp.ndarray:
    """Full overcommit pass: batch + mid columns in one [N, R] array."""
    return batch_allocatable(nodes, pods, params) + mid_allocatable(
        nodes, params
    )


def needs_sync(
    old_alloc: jnp.ndarray,
    new_alloc: jnp.ndarray,
    diff_threshold_percent: jnp.ndarray,
) -> jnp.ndarray:
    """Which nodes changed enough to write back: [N] bool.

    Reference: util.IsResourceDiff (pkg/util/resource.go:106-126):
    ``|new - old| > old * threshold`` per resource (zero old -> any nonzero
    new is a diff). Threshold given in percent to stay integer-exact
    (default 0.1 -> 10); scalar or per-node [N].
    """
    thr = jnp.asarray(diff_threshold_percent)
    if thr.ndim == old_alloc.ndim - 1:
        thr = thr[..., None]
    diff = jnp.abs(new_alloc - old_alloc)
    per_res = percent_exceeds(diff, old_alloc, thr)
    return jnp.any(per_res, axis=-1)
