"""Pallas TPU kernel for the placement scan's hot paths.

The jit `lax.scan` solver (ops/binpack.py) streams the [N,R] node state
through HBM every step; this kernel keeps the whole carry in VMEM across
all P sequential placements — one `pallas_call`, zero HBM round trips in
the loop — for ~2x the scan's throughput (~114k pods/s vs ~56k at
10k x 5k on one v5e chip; the baseline target is 10k/s).

Bit-identical to ``solve_batch`` on the covered paths (differentially
tested in interpret mode and on hardware):

- node arrays are laid out ``[R, N]`` (lanes = nodes) so the VPU runs
  full-width; pods stream through SMEM in 128-pod grid chunks (the TPU
  grid is sequential, VMEM scratch persists across chunks);
- Mosaic forbids dynamic lane indexing, so the per-pod column read is 8
  SMEM scalar reads folded into an ``[R,1]`` vector via sublane-iota
  selects, and the scatter at the chosen node is an iota-masked add;
- Mosaic's argmax does not guarantee first-occurrence tie-breaks, so the
  winner is ``min(lane where score == max)``;
- integer division uses the same exact reciprocal-multiply identity as
  the scan path (ops/common.floor_div_exact).

**Quota admission runs inside the kernel** (BASELINE config #3): the
per-group ``used``/``np_used`` arrays live in VMEM scratch beside the
node carry, laid out ``[R, Qp]`` — groups on lanes, resources on
sublanes, the same orientation as the node arrays — so each pod's gate
is a single-tile lane-masked ``used + req <= runtime`` check (runtime is
water-filled ONCE per solve outside the kernel — requests are static
within a solve, ops/quota.py). **Gang resolution**
(config #4) needs no kernel support at all: the scan places gang members
individually and resolves all-or-nothing at batch end, so the same
``gang_outcomes``/``release_rejected`` XLA ops run on the kernel's
outputs — identical by construction.

**NUMA scoring/consumption runs inside the kernel** too: ``numa_free``
is one more ``[R, N]`` VMEM carry beside ``used``; the per-pod
least/most-allocated score divides by the requested-resource count with
the same two-step floor correction, and the winner's consumption
(pod-policy OR node-policy gated) subtracts in place — reference
semantics nodenumaresource/scoring.go via ops/binpack.numa_node_score.

**Reservations run inside the kernel** (r5): the ``[R,Vp]`` free-
remainder table (reservations on lanes) is one more VMEM carry. The
per-pod matched credit — the transformer.go restore that discounts a
node's used by its matched reservations' free — is an MXU matmul:
``credit[R,N] = masked_rfree[R,Vp] @ onehot[Vp,N]`` with the static
reservation→node one-hot, split hi/lo 16 bits so every f32 partial is
an exact integer (Vp <= 256 keeps lo-sums < 2^24; the int32
recombination wraps exactly like the scan's ``at[].add``). The winner's
consumption picks the most-free matched reservation on the chosen node
(first-max tie-break) with lane-masked column updates, and emits
per-pod vstar/delta/rem for the host's incremental Reserve mutation.

Supported configuration (checked by :func:`pallas_supported`):
``score_according_prod=False``, unit plugin weights, zero prod
thresholds; quota, gang, NUMA, and reservation states are covered
(reservations additionally gated by :func:`pallas_resv_supported`),
extras still ride the scan. Reference semantics: elasticquota
plugin.go:210-255 (admission), coscheduling core/core.go:358-385
(batch-end gang gate), reservation transformer.go:241-266 (restore) +
plugin Reserve (consumption).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolveResult,
)
from koordinator_tpu.ops.common import floor_div_exact, percent_rounded

CHUNK = 128

# Explored-and-rejected (r5, one v5e, 10k x 5k): (a) full inner-loop
# unroll — Mosaic lowers only unroll 1 or 128; 128 is no faster
# (88.9 ms vs 85.0 ms) and costs 55 s compile; (b) loop-carried VALUES
# for the [R,N] carries instead of VMEM-ref RMW — 117 ms vs 85 ms
# (Mosaic spills the carries with worse scheduling than the explicit
# refs). The VMEM-ref RMW form below is the measured optimum.


def _make_kernel(R: int, wsum: int, use_quota: bool, use_numa: bool,
                 most_allocated: bool = False, n_shards: int = 1,
                 axis_name: Optional[str] = None, kernel_unroll: int = 1,
                 use_resv: bool = False):
    """``n_shards > 1`` builds the DISTRIBUTED kernel (VERDICT r4 #3):
    each device keeps its node shard's carry in VMEM and, per pod,
    all-to-all exchanges its packed local best (score<<16 | lane
    complement, lane GLOBAL) over remote DMAs, takes the max, and
    mutates its carry only when the winning node is local. Quota arrays
    are replicated and every shard replays identical quota mutations,
    so the gate stays bit-exact without extra traffic. The packed max
    ordering is unchanged, so tie-breaks (smallest global node index)
    are bit-identical to the single-device kernel and the scan."""
    MOST_ALLOCATED = most_allocated
    dist = n_shards > 1
    def kernel(*refs):
        it = iter(refs)
        req_ref, est_ref, flags_ref = next(it), next(it), next(it)  # SMEM
        alloc_ref, recip_ref, usage_ref, weight_ref = (
            next(it), next(it), next(it), next(it))
        la_ok_ref, sched_ref, fresh_ref = next(it), next(it), next(it)
        used0_ref, est0_ref, prod0_ref = next(it), next(it), next(it)
        if use_quota:
            qmin_ref, qrt_ref, qused0_ref, qnp0_ref = (
                next(it), next(it), next(it), next(it))
        if use_numa:
            ncap_ref, nrecip_ref, npol_ref, nfree0_ref = (
                next(it), next(it), next(it), next(it))
        if use_resv:
            rnode_ref, aonce_ref, bhot_ref, rfree0_ref, match_ref = (
                next(it), next(it), next(it), next(it), next(it))
        assign_ref, used_out_ref, est_out_ref, prod_out_ref = (
            next(it), next(it), next(it), next(it))
        if use_quota:
            qused_out_ref, qnp_out_ref = next(it), next(it)
        if use_numa:
            consumed_ref, nfree_out_ref = next(it), next(it)
        if use_resv:
            vstar_ref, delta_ref, rem_ref, rfree_out_ref = (
                next(it), next(it), next(it), next(it))
        used_ref, estx_ref, prod_ref = next(it), next(it), next(it)
        if use_quota:
            qused_ref, qnp_ref = next(it), next(it)
        if use_numa:
            nfree_ref = next(it)
        if use_resv:
            rfree_ref = next(it)
        if dist:
            inbox_ref, outbox_ref, send_sem, recv_sem, ack_sem = (
                next(it), next(it), next(it), next(it), next(it))
            me = jax.lax.axis_index(axis_name)
            shard_lane = jax.lax.broadcasted_iota(
                jnp.int32, (1, n_shards), 1
            )
        c = pl.program_id(0)

        @pl.when(c == 0)
        def _init():
            used_ref[...] = used0_ref[...]
            estx_ref[...] = est0_ref[...]
            prod_ref[...] = prod0_ref[...]
            if use_quota:
                qused_ref[...] = qused0_ref[...]
                qnp_ref[...] = qnp0_ref[...]
            if use_numa:
                nfree_ref[...] = nfree0_ref[...]
            if use_resv:
                rfree_ref[...] = rfree0_ref[...]

        alloc = alloc_ref[...]
        recip = recip_ref[...]
        usage = usage_ref[...]
        weight = weight_ref[...]                  # [R,1] int32
        la_ok = la_ok_ref[...].astype(jnp.bool_)
        sched = sched_ref[...].astype(jnp.bool_)
        fresh = fresh_ref[...].astype(jnp.bool_)
        N = alloc.shape[1]
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        chunk_lane = jax.lax.broadcasted_iota(jnp.int32, (1, CHUNK), 1)
        sub = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
        if use_quota:
            qmin = qmin_ref[...]
            qrt = qrt_ref[...]
            # groups on LANES, resources on sublanes ([R, Qp]) — the
            # same layout as the node arrays, so the whole gate works a
            # single (8, 128k) tile instead of a row-padded [Q, 128]
            Qp = qmin.shape[1]
            qlane = jax.lax.broadcasted_iota(jnp.int32, (1, Qp), 1)
        if use_numa:
            ncap = ncap_ref[...]
            nrecip = nrecip_ref[...]
            npol = npol_ref[...].astype(jnp.bool_)   # [1,N]
        if use_resv:
            rnode = rnode_ref[...]                   # [1,Vp] global node ids
            aonce = aonce_ref[...]                   # [1,Vp] allocate_once
            Vp = rnode.shape[1]
            vlane = jax.lax.broadcasted_iota(jnp.int32, (1, Vp), 1)
            msub = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, Vp), 0)

        def exact_div(y):
            # the shared exact reciprocal-multiply floor division — plain
            # jnp ops, so it lowers inside the kernel unchanged
            return floor_div_exact(y, alloc, recip)

        def body(j, _):
            used = used_ref[...]
            estx = estx_ref[...]
            req_v = jnp.zeros((R, 1), jnp.int32)
            est_v = jnp.zeros((R, 1), jnp.int32)
            for r in range(R):
                req_v = jnp.where(sub == r, req_ref[j, r], req_v)
                est_v = jnp.where(sub == r, est_ref[j, r], est_v)
            if use_resv:
                # matched reservations' free remainder credited back on
                # their nodes for this pod's fit path (transformer.go
                # restore): credit[R,N] = masked_rfree[R,Vp] @ onehot[Vp,N]
                # on the MXU, hi/lo 16-bit split so every f32 partial is
                # an exact integer (Vp <= 256 bounds the lo sums < 2^24;
                # the int32 recombination wraps exactly like the scan's
                # at[].add)
                mrow = jnp.sum(
                    jnp.where(msub == j, match_ref[...], 0),
                    axis=0, keepdims=True,
                )                                         # [1,Vp]
                rfree = rfree_ref[...]                    # [R,Vp]
                mfree = jnp.where(mrow > 0, rfree, 0)
                bhot = bhot_ref[...]                      # [Vp,N] f32 0/1
                # precision pinned HIGHEST (ADVICE r5 high): the MXU's
                # default f32 dot rounds operands toward bfloat16 (8-bit
                # mantissa), which would corrupt the exact hi/lo integer
                # partials on hardware — interpret-mode CI is exact f32
                # and cannot catch it
                hi_s = jnp.dot(
                    (mfree >> 16).astype(jnp.float32), bhot,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                ).astype(jnp.int32)
                lo_s = jnp.dot(
                    (mfree & 0xFFFF).astype(jnp.float32), bhot,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                ).astype(jnp.int32)
                used_fit = used - ((hi_s << 16) + lo_s)
            else:
                used_fit = used
            requested = used_fit + req_v
            fit = sched & jnp.all(
                (req_v == 0) | (requested <= alloc), axis=0, keepdims=True
            )
            q1 = exact_div((alloc - requested) * 100) * weight
            s1 = jnp.sum(
                jnp.where((alloc == 0) | (requested > alloc), 0, q1),
                axis=0, keepdims=True,
            ) // wsum
            eu = usage + estx + est_v
            q2 = exact_div((alloc - eu) * 100) * weight
            s2 = jnp.sum(
                jnp.where((alloc == 0) | (eu > alloc), 0, q2),
                axis=0, keepdims=True,
            ) // wsum
            s2 = jnp.where(fresh, s2, 0)
            is_ds = flags_ref[j, 0] > 0
            is_prod = flags_ref[j, 1] > 0
            mask = fit & (is_ds | ~fresh | la_ok)
            score = s1 + s2

            if use_numa:
                # in-scan NUMA least/most-allocated score
                # (ops/binpack.numa_node_score) over the VMEM-resident
                # free carry; the divisor is the requested-resource
                # count w <= R, pinned exact by the same two-step
                # floor correction as floor_div_exact
                nfree = nfree_ref[...]
                member = req_v > 0                   # [R,1]
                nreq = ncap - nfree + req_v          # [R,N]
                numer = (
                    nreq if MOST_ALLOCATED else (ncap - nreq)
                ) * 100
                per = floor_div_exact(numer, ncap, nrecip)
                per = jnp.where(
                    member & (ncap > 0) & (nreq <= ncap), per, 0
                )
                psum = jnp.sum(per, axis=0, keepdims=True)  # [1,N]
                w = jnp.sum(member.astype(jnp.int32))
                nscore = floor_div_exact(
                    psum, w, 1.0 / jnp.maximum(w, 1).astype(jnp.float32)
                )
                score = score + jnp.where(w > 0, nscore, 0)

            if use_quota:
                # masked admission (ops/quota.quota_admit): on the pod's
                # requested dims, used+req <= runtime, and for
                # non-preemptible pods np_used+req <= min. sel picks the
                # pod's group column x its requested resource rows; the
                # per-pod req_v column vector broadcasts across lanes.
                qid = flags_ref[j, 2]
                non_pre = flags_ref[j, 3] > 0
                sel = (qlane == qid) & (req_v > 0)         # [R,Qp]
                qused = qused_ref[...]
                qnp = qnp_ref[...]
                # no bool-select here: Mosaic rejects select_n on i1
                # vectors (i8->i1 trunci); violations compose from
                # comparisons and ANDs like the plain kernel's masks
                viol_rt = sel & (qused + req_v > qrt)
                viol_np = sel & non_pre & (qnp + req_v > qmin)
                admit = (qid < 0) | ~(jnp.any(viol_rt) | jnp.any(viol_np))
                mask = mask & admit

            # single-reduction argmax: pack (score, first-occurrence
            # tie-break) into one int32 — score <= 300 (three
            # 100-capped weighted means: fit, loadaware, numa), lane <
            # 2^16, so score<<16 | (65535-lane) <= 300*65536+65535 <
            # 2^31 with room; max of the pack IS the max score at its
            # smallest lane. Halves the [1,N]-to-scalar reductions vs
            # max-then-min-where. 16 lane bits lift the node cap to
            # 65536 (VMEM becomes the binding constraint first).
            # Distributed mode packs the GLOBAL lane (shard offset +
            # local lane) so the cross-shard max IS the global argmax
            # with the same smallest-node-index tie-break.
            glane = lane + (me * N if dist else 0)
            packed = jnp.where(
                mask, (score << 16) | (65535 - glane), -1
            )
            m = jnp.max(packed)
            if dist:
                # per-pod cross-shard winner merge. My packed best goes
                # to peers from a separate one-slot outbox; the inbox is
                # written ONLY by peer RDMAs — never read-modify-written
                # locally — so an in-flight peer delivery can't be lost
                # to a stale full-row store. Protocol per pod: stage
                # outbox, send to all peers, wait K-1 deliveries, read
                # the inbox (my own column masked with my local best),
                # THEN ack — a peer may only overwrite my inbox for the
                # next pod after my ack, so the read is race-free.
                outbox_ref[0, 0] = m
                for d in range(n_shards):
                    @pl.when(d != me)
                    def _send():
                        rdma = pltpu.make_async_remote_copy(
                            src_ref=outbox_ref.at[0, 0],
                            dst_ref=inbox_ref.at[0, me],
                            send_sem=send_sem,
                            recv_sem=recv_sem,
                            device_id=d,
                            device_id_type=pltpu.DeviceIdType.LOGICAL,
                        )
                        rdma.start()
                        rdma.wait_send()
                for _i in range(n_shards - 1):
                    pltpu.make_async_remote_copy(
                        src_ref=outbox_ref.at[0, 0],
                        dst_ref=inbox_ref.at[0, me],
                        send_sem=send_sem,
                        recv_sem=recv_sem,
                        device_id=me,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).wait_recv()
                # my own inbox column was never written: mask it with
                # the local best
                m = jnp.max(jnp.where(shard_lane == me, m, inbox_ref[...]))
                for d in range(n_shards):
                    @pl.when(d != me)
                    def _ack():
                        pltpu.semaphore_signal(
                            ack_sem, inc=1, device_id=d,
                            device_id_type=pltpu.DeviceIdType.LOGICAL,
                        )
                pltpu.semaphore_wait(ack_sem, n_shards - 1)
            ok = m >= 0
            best = (65535 - (m & 65535)).astype(jnp.int32)
            node = jnp.where(ok, best, -1).astype(jnp.int32)
            assign_ref[...] = jnp.where(chunk_lane == j, node, assign_ref[...])
            hit = (glane == best) & ok
            net_req = req_v
            if use_resv:
                # consume the matched reservation with the most free
                # capacity on the chosen node (reservation.py Reserve;
                # first-max tie-break = smallest reservation index);
                # allocate_once releases the remainder with the hold
                on_node = (mrow > 0) & (rnode == best) & ok   # [1,Vp]
                fsum = jnp.sum(rfree, axis=0, keepdims=True)  # int32 wrap
                fm = jnp.max(jnp.where(on_node, fsum, -1))
                has = fm > 0
                vsel = on_node & (fsum == fm)
                v_star = jnp.min(jnp.where(vsel, vlane, Vp))
                col = vlane == v_star                         # [1,Vp]
                rfree_col = jnp.sum(
                    jnp.where(col, rfree, 0), axis=1, keepdims=True
                )                                             # [R,1]
                delta = jnp.where(has, jnp.minimum(rfree_col, req_v), 0)
                once = has & (jnp.max(jnp.where(col, aonce, 0)) > 0)
                rem = jnp.where(once, rfree_col - delta, 0)
                new_col = jnp.where(once, 0, rfree_col - delta)
                rfree_ref[...] = jnp.where(col & has, new_col, rfree)
                vstar_v = jnp.where(has, v_star, -1).astype(jnp.int32)
                vstar_ref[...] = jnp.where(
                    chunk_lane == j, vstar_v, vstar_ref[...]
                )
                delta_ref[...] = jnp.where(
                    chunk_lane == j, delta, delta_ref[...]
                )
                rem_ref[...] = jnp.where(chunk_lane == j, rem, rem_ref[...])
                net_req = req_v - delta - rem
            used_ref[...] = used + jnp.where(hit, net_req, 0)
            estx_ref[...] = estx + jnp.where(hit, est_v, 0)
            prod_ref[...] = prod_ref[...] + jnp.where(
                hit & is_prod, est_v, 0
            )
            if use_quota:
                addq = jnp.where(sel & ok & (qid >= 0), req_v, 0)
                qused_ref[...] = qused + addq
                qnp_ref[...] = qnp + jnp.where(non_pre, addq, 0)
            if use_numa:
                # consume numa_free iff the pod OR the winning node
                # declares a topology policy (solve_batch's consume)
                pod_numa = flags_ref[j, 4] > 0
                consume_lane = hit & (pod_numa | npol)    # [1,N]
                nfree_ref[...] = nfree - jnp.where(consume_lane, req_v, 0)
                did = (jnp.max(jnp.where(consume_lane, 1, 0)) > 0)
                consumed_ref[...] = jnp.where(
                    chunk_lane == j, did.astype(jnp.int32),
                    consumed_ref[...],
                )
            return 0

        jax.lax.fori_loop(0, CHUNK, body, 0, unroll=kernel_unroll)
        used_out_ref[...] = used_ref[...]
        est_out_ref[...] = estx_ref[...]
        prod_out_ref[...] = prod_ref[...]
        if use_quota:
            qused_out_ref[...] = qused_ref[...]
            qnp_out_ref[...] = qnp_ref[...]
        if use_numa:
            nfree_out_ref[...] = nfree_ref[...]
        if use_resv:
            rfree_out_ref[...] = rfree_ref[...]

    return kernel


def pallas_supported(params: ScoreParams, config) -> bool:
    """Whether this configuration maps onto the kernel (quota and gang
    states are additionally supported as solve arguments)."""
    return (
        not config.score_according_prod
        and config.fit_weight == 1
        and config.loadaware_weight == 1
        and not bool(np.asarray(params.prod_thresholds).any())
    )


@functools.partial(
    jax.jit,
    static_argnames=("wsum", "interpret", "most_allocated", "n_shards",
                     "axis_name", "kernel_unroll"),
    donate_argnums=(),
)
def _pallas_solve(state: NodeState, pods: PodBatch, params: ScoreParams,
                  wsum: int, interpret: bool, quota=None, numa=None,
                  most_allocated: bool = False, n_shards: int = 1,
                  axis_name: Optional[str] = None, kernel_unroll: int = 1,
                  resv=None, resv_onehot=None):
    """quota = None | (min[Q,R], runtime[Q,R], used[Q,R], np_used[Q,R]);
    numa = None | (cap[N,R], free[N,R], node_policy[N]);
    resv = None | (node[V], free[V,R], allocate_once[V], match[P,V]) —
    node indices are GLOBAL under sharding, free/match replicated.
    Returns (new_state, assign[P], qused[Q,R]|None, qnp[Q,R]|None,
    consumed[P]|None, resv_out) where resv_out is None or
    (vstar[P], delta[P,R], rem[P,R], rfree[V,R]) — the updated
    numa_free rides new_state.

    With ``n_shards > 1`` this runs INSIDE ``jax.shard_map`` on the
    node-shard local arrays: assign carries GLOBAL packed lane ids
    (shard * padded_local_width + local lane — the caller remaps),
    consumed is the LOCAL consumption bit (caller ORs across shards),
    and quota outputs are replicated (every shard replays the same
    global quota trajectory)."""
    n, r = state.alloc.shape
    p = pods.req.shape[0]
    N = ((n + 127) // 128) * 128
    P = ((p + CHUNK - 1) // CHUNK) * CHUNK
    use_quota = quota is not None
    use_numa = numa is not None
    use_resv = resv is not None

    def padn(a2):
        return jnp.zeros((r, N), jnp.int32).at[:, :n].set(
            a2.astype(jnp.int32).T
        )

    def padmask(m):
        return jnp.zeros((1, N), jnp.int32).at[0, :n].set(m.astype(jnp.int32))

    alloc = padn(state.alloc)
    recip = 1.0 / jnp.maximum(alloc, 1).astype(jnp.float32)
    usage = padn(state.usage)
    used0 = padn(state.used_req)
    est0 = padn(state.est_extra)
    prod0 = padn(state.prod_base)
    weight = jnp.asarray(params.weights, jnp.int32).reshape(r, 1)
    upct = percent_rounded(state.usage, state.alloc)
    over = (
        (state.alloc > 0)
        & (params.thresholds > 0)
        & (upct >= params.thresholds)
    )
    la_ok = padmask(~jnp.any(over, axis=-1))
    sched = padmask(state.schedulable)
    fresh = padmask(state.metric_fresh)
    reqs = jnp.zeros((P, r), jnp.int32).at[:p].set(pods.req)
    ests = jnp.zeros((P, r), jnp.int32).at[:p].set(pods.est)
    flags = jnp.zeros((P, 5), jnp.int32)
    flags = flags.at[:p, 0].set(
        (pods.is_daemonset & ~pods.blocked).astype(jnp.int32)
    )
    flags = flags.at[:p, 1].set(pods.is_prod.astype(jnp.int32))
    flags = flags.at[:, 2].set(-1)
    flags = flags.at[:p, 2].set(pods.quota_id.astype(jnp.int32))
    flags = flags.at[:p, 3].set(pods.non_preemptible.astype(jnp.int32))
    if use_numa and pods.has_numa_policy is not None:
        flags = flags.at[:p, 4].set(pods.has_numa_policy.astype(jnp.int32))
    # padding pods (and host-blocked pods) can never fit
    blocked_req = jnp.int32(2**30)
    reqs = reqs.at[:p, 0].set(
        jnp.where(pods.blocked, blocked_req, reqs[:p, 0])
    )
    if P > p:
        reqs = reqs.at[p:, 0].set(blocked_req)

    full = lambda shape: pl.BlockSpec(shape, lambda c: (0, 0))
    in_specs = [
        pl.BlockSpec((CHUNK, r), lambda c: (c, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((CHUNK, r), lambda c: (c, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((CHUNK, 5), lambda c: (c, 0), memory_space=pltpu.SMEM),
        full((r, N)), full((r, N)), full((r, N)),
        pl.BlockSpec((r, 1), lambda c: (0, 0)),
        full((1, N)), full((1, N)), full((1, N)),
        full((r, N)), full((r, N)), full((r, N)),
    ]
    out_specs = [
        pl.BlockSpec((1, CHUNK), lambda c: (0, c)),
        full((r, N)), full((r, N)), full((r, N)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((1, P), jnp.int32),
        jax.ShapeDtypeStruct((r, N), jnp.int32),
        jax.ShapeDtypeStruct((r, N), jnp.int32),
        jax.ShapeDtypeStruct((r, N), jnp.int32),
    ]
    scratch = [
        pltpu.VMEM((r, N), jnp.int32),
        pltpu.VMEM((r, N), jnp.int32),
        pltpu.VMEM((r, N), jnp.int32),
    ]
    args = [reqs, ests, flags, alloc, recip, usage, weight, la_ok, sched,
            fresh, used0, est0, prod0]
    if use_quota:
        qmin, qrt, qused0, qnp0 = quota
        q = qmin.shape[0]
        Qp = ((q + 127) // 128) * 128  # groups on lanes, tile-aligned

        def padq(a2):
            # [Q, R] -> [R, Qp]: group lanes, resource sublanes (the
            # node-array layout, so the admission gate is one tile)
            return jnp.zeros((r, Qp), jnp.int32).at[:, :q].set(
                a2.astype(jnp.int32).T
            )

        args += [padq(qmin), padq(qrt), padq(qused0), padq(qnp0)]
        in_specs += [full((r, Qp))] * 4
        out_specs += [full((r, Qp))] * 2
        out_shape += [jax.ShapeDtypeStruct((r, Qp), jnp.int32)] * 2
        scratch += [pltpu.VMEM((r, Qp), jnp.int32)] * 2
    if use_numa:
        ncap_in, nfree_in, npol_in = numa
        ncap = padn(ncap_in)
        nrecip = 1.0 / jnp.maximum(ncap, 1).astype(jnp.float32)
        npol = padmask(npol_in)
        nfree0 = padn(nfree_in)
        args += [ncap, nrecip, npol, nfree0]
        in_specs += [full((r, N)), full((r, N)), full((1, N)),
                     full((r, N))]
        out_specs += [pl.BlockSpec((1, CHUNK), lambda c: (0, c)),
                      full((r, N))]
        out_shape += [jax.ShapeDtypeStruct((1, P), jnp.int32),
                      jax.ShapeDtypeStruct((r, N), jnp.int32)]
        scratch += [pltpu.VMEM((r, N), jnp.int32)]
    if use_resv:
        rnode_in, rfree_in, aonce_in, match_in = resv
        v = rnode_in.shape[0]
        Vp = ((v + 127) // 128) * 128
        rn = jnp.full((Vp,), -1, jnp.int32).at[:v].set(
            rnode_in.astype(jnp.int32)
        )
        aonce = jnp.zeros((1, Vp), jnp.int32).at[0, :v].set(
            aonce_in.astype(jnp.int32)
        )
        rfree0 = jnp.zeros((r, Vp), jnp.int32).at[:, :v].set(
            rfree_in.astype(jnp.int32).T
        )
        # zero blocked pods' match rows so their credit stays 0 and the
        # blocked_req fit trick keeps them unplaceable exactly
        match_pad = jnp.zeros((P, Vp), jnp.int32).at[:p, :v].set(
            (match_in & ~pods.blocked[:, None]).astype(jnp.int32)
        )
        # static reservation -> node-lane one-hot for the credit matmul;
        # lanes are GLOBAL node ids (shard offset under shard_map). A
        # caller-cached one-hot (resv_node_onehot — ADVICE r5 low #3:
        # it depends only on the static reservation table, so repeated
        # solves must not rebuild the up-to-8MB [Vp,N] operand) is used
        # verbatim; the sharded path always derives it locally because
        # its lanes carry the per-shard offset.
        if resv_onehot is not None and n_shards == 1:
            if resv_onehot.shape != (Vp, N):
                raise ValueError(
                    f"resv_onehot shape {resv_onehot.shape} != {(Vp, N)}"
                )
            bhot = resv_onehot
        else:
            lane_ids = jax.lax.broadcasted_iota(jnp.int32, (Vp, N), 1)
            if n_shards > 1:
                lane_ids = lane_ids + jax.lax.axis_index(axis_name) * N
            bhot = (rn[:, None] == lane_ids).astype(jnp.float32)
        args += [rn[None, :], aonce, bhot, rfree0, match_pad]
        in_specs += [full((1, Vp)), full((1, Vp)), full((Vp, N)),
                     full((r, Vp)),
                     pl.BlockSpec((CHUNK, Vp), lambda c: (c, 0))]
        out_specs += [pl.BlockSpec((1, CHUNK), lambda c: (0, c)),
                      pl.BlockSpec((r, CHUNK), lambda c: (0, c)),
                      pl.BlockSpec((r, CHUNK), lambda c: (0, c)),
                      full((r, Vp))]
        out_shape += [jax.ShapeDtypeStruct((1, P), jnp.int32),
                      jax.ShapeDtypeStruct((r, P), jnp.int32),
                      jax.ShapeDtypeStruct((r, P), jnp.int32),
                      jax.ShapeDtypeStruct((r, Vp), jnp.int32)]
        scratch += [pltpu.VMEM((r, Vp), jnp.int32)]

    dist = n_shards > 1
    compiler_params = None
    if dist:
        scratch += [
            pltpu.VMEM((1, n_shards), jnp.int32),  # peer-written inbox
            pltpu.VMEM((1, 1), jnp.int32),         # my staged outbox
            pltpu.SemaphoreType.DMA,               # send
            pltpu.SemaphoreType.DMA,               # recv
            pltpu.SemaphoreType.REGULAR,           # ack barrier
        ]
        compiler_params = pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        )
        if interpret:
            # the distributed interpreter (remote DMAs + semaphores
            # under shard_map) needs InterpretParams, not legacy True
            interpret = pltpu.InterpretParams()
    out = pl.pallas_call(
        _make_kernel(r, wsum, use_quota, use_numa, most_allocated,
                     n_shards, axis_name, kernel_unroll, use_resv),
        grid=(P // CHUNK,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=compiler_params,
    )(*args)
    out = list(out)
    assign, used, est, prod = out[:4]
    rest = out[4:]
    qused = qnp = nfree = consumed = resv_out = None
    if use_quota:
        qused, qnp = rest[0][:, :q].T, rest[1][:, :q].T
        rest = rest[2:]
    if use_numa:
        consumed = rest[0][0, :p] > 0
        nfree = rest[1][:, :n].T
        rest = rest[2:]
    if use_resv:
        resv_out = (rest[0][0, :p], rest[1][:, :p].T, rest[2][:, :p].T,
                    rest[3][:, :v].T)
    new_state = state._replace(
        used_req=used[:, :n].T,
        est_extra=est[:, :n].T,
        prod_base=prod[:, :n].T,
    )
    if use_numa:
        new_state = new_state._replace(numa_free=nfree)
    return new_state, assign[0, :p], qused, qnp, consumed, resv_out


@functools.partial(
    jax.jit,
    static_argnames=("wsum", "interpret", "has_gang", "most_allocated",
                     "kernel_unroll"),
    donate_argnums=(),
)
def _solve_full(state, pods, params, quota_state, gang_state, numa_aux,
                wsum: int, interpret: bool, has_gang: bool,
                most_allocated: bool, kernel_unroll: int = 1, resv=None,
                resv_onehot=None):
    """Kernel scan + the scan solver's exact post-batch epilogue (gang
    resolution, rejected releases) — one jitted program."""
    from koordinator_tpu.ops.quota import quota_runtime

    quota_in = None
    if quota_state is not None:
        runtime = quota_runtime(quota_state)
        quota_in = (
            quota_state.min, runtime, quota_state.used, quota_state.np_used
        )
    numa_in = None
    if numa_aux is not None:
        numa_in = (state.numa_cap, state.numa_free, numa_aux.node_policy)
    resv_in = None
    if resv is not None:
        resv_in = (resv.node, resv.free, resv.allocate_once, resv.match)
    new_state, assign, qused, qnp, consumed, resv_out = _pallas_solve(
        state, pods, params, wsum, interpret, quota_in, numa_in,
        most_allocated, kernel_unroll=kernel_unroll, resv=resv_in,
        resv_onehot=resv_onehot,
    )
    final_qstate = (
        None if quota_state is None
        else quota_state._replace(used=qused, np_used=qnp)
    )
    return _kernel_epilogue(
        new_state, assign, consumed, final_qstate, pods, gang_state,
        has_gang, numa_aux is not None, resv_out=resv_out,
    )


def _kernel_epilogue(new_state, assign, consumed, final_qstate, pods,
                     gang_state, has_gang: bool, has_numa: bool,
                     resv_out=None):
    """The scan solver's exact post-batch tail (gang resolution +
    rejected releases) on a kernel's outputs — shared by the
    single-chip and sharded kernel paths. ``resv_out`` is the kernel's
    (vstar[P], delta[P,R], rem[P,R], rfree[V,R]) reservation outputs."""
    from koordinator_tpu.ops.gang import gang_outcomes, release_rejected

    n_pods = pods.req.shape[0]
    falses = jnp.zeros(n_pods, bool)
    has_resv = resv_out is not None
    if has_resv:
        resv_vstar, resv_delta, resv_rem, final_rfree = resv_out
    else:
        resv_vstar = resv_delta = resv_rem = final_rfree = None
    if not has_gang:
        return SolveResult(
            node_state=new_state,
            quota_state=final_qstate,
            resv_free=final_rfree,
            assign=assign,
            commit=assign >= 0,
            waiting=falses,
            rejected=falses,
            raw_assign=assign,
            resv_vstar=resv_vstar,
            resv_delta=resv_delta,
            numa_consumed=consumed,
        )
    commit, waiting, rejected = gang_outcomes(assign, pods.gang_id, gang_state)
    # a rejected pod held only its net request (reservation delta+rem
    # were absorbed by the hold shrink) — release exactly that
    rel_req = pods.req
    if has_resv:
        rel_req = pods.req - resv_delta - resv_rem
    used_req, est_extra, prod_base = release_rejected(
        new_state.used_req,
        new_state.est_extra,
        new_state.prod_base,
        assign,
        rejected,
        rel_req,
        pods.est,
        pods.is_prod,
    )
    new_state = new_state._replace(
        used_req=used_req, est_extra=est_extra, prod_base=prod_base
    )
    if has_numa:
        # restore rejected pods' NUMA consumption (solve_batch's tail)
        n = new_state.used_req.shape[0]
        take = rejected & consumed
        nidx = jnp.where(take, assign, n)
        back = jnp.where(take[:, None], pods.req, 0)
        new_state = new_state._replace(
            numa_free=new_state.numa_free
            + jax.ops.segment_sum(back, nidx, num_segments=n + 1)[:n]
        )
    if has_resv:
        # restore rejected pods' reservation consumption (+ the released
        # allocate_once remainder): the incremental Unreserve equivalent
        v = final_rfree.shape[0]
        take = rejected & (resv_vstar >= 0)
        vidx = jnp.where(take, resv_vstar, v)
        back = jnp.where(take[:, None], resv_delta + resv_rem, 0)
        final_rfree = final_rfree + jax.ops.segment_sum(
            back, vidx, num_segments=v + 1
        )[:v]
    out_assign = jnp.where(commit | waiting, assign, -1).astype(jnp.int32)
    if final_qstate is not None:
        # release rejected pods' quota accounting (solve_batch's tail)
        q = final_qstate.used.shape[0]
        qidx = jnp.where(rejected & (pods.quota_id >= 0), pods.quota_id, q)
        rel = jnp.where((rejected & (pods.quota_id >= 0))[:, None], pods.req, 0)
        sub = jax.ops.segment_sum(rel, qidx, num_segments=q + 1)[:q]
        np_rel = jnp.where(pods.non_preemptible[:, None], rel, 0)
        np_sub = jax.ops.segment_sum(np_rel, qidx, num_segments=q + 1)[:q]
        final_qstate = final_qstate._replace(
            used=final_qstate.used - sub, np_used=final_qstate.np_used - np_sub
        )
    return SolveResult(
        node_state=new_state,
        quota_state=final_qstate,
        resv_free=final_rfree,
        assign=out_assign,
        commit=commit,
        waiting=waiting,
        rejected=rejected,
        raw_assign=assign,
        resv_vstar=resv_vstar,
        resv_delta=resv_delta,
        numa_consumed=consumed,
    )


def pallas_solve_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config,
    quota_state=None,
    gang_state=None,
    numa_aux=None,
    resv=None,
    interpret: Optional[bool] = None,
    resv_score_checked: bool = False,
    resv_onehot=None,
) -> SolveResult:
    """Drop-in for ``solve_batch`` on the kernel paths (plain, quota,
    gang, NUMA, reservation, and their combinations). Raises ValueError
    for unsupported configurations — callers gate on
    :func:`pallas_supported` / :func:`pallas_resv_supported`.
    ``resv_score_checked=True`` skips the per-solve
    :func:`pallas_resv_score_safe` host check for callers that already
    validated the initial table (the verdict cannot change within a
    solve — in-kernel rfree only decreases). ``resv_onehot`` is an
    optional cached :func:`resv_node_onehot` of ``resv.node`` — repeat
    solves against a static reservation table then skip rebuilding the
    [Vp,N] credit-matmul operand per solve."""
    if not pallas_supported(params, config):
        raise ValueError("configuration not supported by the pallas kernel")
    if state.alloc.shape[0] == 0 or pods.req.shape[0] == 0:
        raise ValueError("empty solve: use solve_batch's shape early-out")
    if state.alloc.shape[0] > 65536:
        # the packed single-reduction argmax carries the lane in 16 bits
        raise ValueError("more than 65536 nodes: use the scan solver")
    if numa_aux is not None and (
        state.numa_cap is None or state.numa_free is None
    ):
        raise ValueError("numa_aux requires NodeState.numa_cap/numa_free")
    if resv is not None:
        if not pallas_resv_supported(
            resv.node.shape[0], state.alloc.shape[0]
        ):
            raise ValueError(
                "reservation table unsupported by the kernel (empty "
                "table: pass resv=None; the hi/lo f32 credit matmul is "
                "exact for <= 256 reservations and the one-hot must fit "
                "VMEM) — use the scan solver"
            )
        safe = True
        if not resv_score_checked:
            try:
                safe = pallas_resv_score_safe(
                    resv.node, resv.free, state.alloc
                )
            except (jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError) as e:
                # the gate must stay loud: silently skipping it under
                # tracing could return placements that diverge from the
                # scan on an unsafe table
                raise ValueError(
                    "cannot validate the reservation score budget under "
                    "tracing: pre-validate with pallas_resv_score_safe "
                    "and pass resv_score_checked=True"
                ) from e
        if not safe:
            raise ValueError(
                "reservation credit could overflow the packed argmax's "
                "15-bit score budget — use the scan solver"
            )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    wsum = int(np.asarray(params.weights).sum()) or 1
    return _solve_full(
        state, pods, params, quota_state, gang_state, numa_aux, wsum,
        interpret, gang_state is not None, bool(config.numa_most_allocated),
        kernel_unroll=int(getattr(config, "kernel_unroll", 1)), resv=resv,
        resv_onehot=resv_onehot,
    )


@functools.partial(jax.jit, static_argnames=("n_nodes",), donate_argnums=())
def resv_node_onehot(node, n_nodes: int):
    """The [Vp, Np] reservation→node-lane one-hot the in-kernel credit
    matmul contracts against — exactly the padding math `_pallas_solve`
    applies (tile-aligned axes, -1 rows beyond the real table so padding
    matches no lane). Depends only on the static reservation node table,
    so callers cache it across solves (models/placement.py) instead of
    rebuilding up to 8 MB per solve (ADVICE r5 low #3)."""
    v = node.shape[0]
    vp = ((v + 127) // 128) * 128
    n_pad = ((n_nodes + 127) // 128) * 128
    rn = jnp.full((vp,), -1, jnp.int32).at[:v].set(node.astype(jnp.int32))
    lane_ids = jax.lax.broadcasted_iota(jnp.int32, (vp, n_pad), 1)
    return (rn[:, None] == lane_ids).astype(jnp.float32)


def pallas_resv_supported(n_resv: int, n_nodes: int) -> bool:
    """Whether a reservation table maps onto the kernel: at least one
    reservation (an empty table must be passed as ``resv=None`` — the
    kernel's lane padding cannot express zero-width tables), <= 256
    (keeps every f32 lo-partial of the credit matmul an exact integer:
    256 * (2^16 - 1) < 2^24), and a one-hot small enough to leave VMEM
    for the [R,N] carries (~8 MB budget)."""
    if n_resv < 1:
        return False
    vp = ((n_resv + 127) // 128) * 128
    np_ = ((n_nodes + 127) // 128) * 128
    return vp <= 256 and vp * np_ * 4 <= 8 * 2**20


def pallas_routing_ok(state, pods, extras, resv, resv_score_safe=True,
                      numa_aux=None) -> bool:
    """Shared kernel-eligibility predicate for the dispatch layers (the
    in-process PlacementModel and the solver sidecar) — shape bounds,
    feature support, and the reservation gates, so the two routers
    cannot drift. Deliberately EXCLUDES ``pallas_supported(params,
    config)``: that check reads the params arrays (a device->host sync
    on the hot path), so callers evaluate it once on host data and
    cache the verdict."""
    n = int(state.alloc.shape[0])
    return (
        extras is None
        # empty solves take the scan's shape early-out; they must not
        # trip a caller's kernel breaker
        and 0 < n <= 65536  # the packed argmax carries the lane in 16 bits
        and pods.req.shape[0] > 0
        # a numa request without node inventories is a per-request input
        # problem (both solvers reject it), not a kernel failure
        and (
            numa_aux is None
            or (state.numa_cap is not None and state.numa_free is not None)
        )
        and (
            resv is None
            or (
                pallas_resv_supported(int(resv.node.shape[0]), n)
                and resv_score_safe
            )
        )
    )


def pallas_resv_score_safe(node, free, alloc) -> bool:
    """The packed single-reduction argmax budgets 15 bits for the score
    (``score << 16`` must stay positive in int32). Without reservations
    every component is <= 100 (fit + loadaware + numa <= 300); the
    matched credit can push the fit term to ~100 * (1 + credit/alloc)
    because ``used - credit`` may go far negative. A table whose
    worst-case per-node credit ratio could overflow the budget must
    ride the scan. In-kernel ``rfree`` only ever decreases from the
    initial table, so the initial per-node column sums bound the credit
    for the whole solve. Host-side (concrete arrays) check."""
    node = np.asarray(node)
    free = np.asarray(free).astype(np.int64)
    alloc = np.asarray(alloc).astype(np.int64)
    credit = np.zeros_like(alloc)
    np.add.at(credit, node, free)
    ratio = -(-credit // np.maximum(alloc, 1))  # ceil; alloc==0 scores 0
    worst = 300 + 100 * int(np.where(alloc > 0, ratio, 0).max(initial=0))
    return worst <= 32767


def pallas_schedule_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config,
    interpret: bool = None,
) -> Tuple[NodeState, jnp.ndarray]:
    """Legacy-shaped plain-path wrapper: ``(new_state, assignments)``."""
    result = pallas_solve_batch(
        state, pods, params, config, interpret=interpret
    )
    return result.node_state, result.assign
