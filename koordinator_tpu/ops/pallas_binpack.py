"""Pallas TPU kernel for the placement scan's plain fast path.

The jit `lax.scan` solver (ops/binpack.py) streams the [N,R] node state
through HBM every step; this kernel keeps the whole carry in VMEM across
all P sequential placements — one `pallas_call`, zero HBM round trips in
the loop — for ~2x the scan's throughput (~114k pods/s vs ~56k at
10k x 5k on one v5e chip; the baseline target is 10k/s).

Bit-identical to ``schedule_batch``'s plain path (differentially tested
in interpret mode and on hardware):

- node arrays are laid out ``[R, N]`` (lanes = nodes) so the VPU runs
  full-width; pods stream through SMEM in 128-pod grid chunks (the TPU
  grid is sequential, VMEM scratch persists across chunks);
- Mosaic forbids dynamic lane indexing, so the per-pod column read is 8
  SMEM scalar reads folded into an ``[R,1]`` vector via sublane-iota
  selects, and the scatter at the chosen node is an iota-masked add;
- Mosaic's argmax does not guarantee first-occurrence tie-breaks, so the
  winner is ``min(lane where score == max)``;
- integer division uses the same exact reciprocal-multiply identity as
  the scan path (ops/common.floor_div_exact).

Supported configuration (checked by :func:`pallas_supported`): no quota/
gang/reservation/extras/NUMA state, ``score_according_prod=False``, and
zero prod thresholds — exactly the flagship churn configuration. Other
configurations use `solve_batch`.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from koordinator_tpu.ops.binpack import NodeState, PodBatch, ScoreParams
from koordinator_tpu.ops.common import floor_div_exact, percent_rounded

CHUNK = 128


def _make_kernel(R: int, wsum: int):
    def kernel(req_ref, est_ref, flags_ref,       # SMEM pod chunks
               alloc_ref, recip_ref, usage_ref, weight_ref,
               la_ok_ref, sched_ref, fresh_ref,
               used0_ref, est0_ref, prod0_ref,    # VMEM node state
               assign_ref, used_out_ref, est_out_ref, prod_out_ref,
               used_ref, estx_ref, prod_ref):     # VMEM scratch carries
        c = pl.program_id(0)

        @pl.when(c == 0)
        def _init():
            used_ref[...] = used0_ref[...]
            estx_ref[...] = est0_ref[...]
            prod_ref[...] = prod0_ref[...]

        alloc = alloc_ref[...]
        recip = recip_ref[...]
        usage = usage_ref[...]
        weight = weight_ref[...]                  # [R,1] int32
        la_ok = la_ok_ref[...].astype(jnp.bool_)
        sched = sched_ref[...].astype(jnp.bool_)
        fresh = fresh_ref[...].astype(jnp.bool_)
        N = alloc.shape[1]
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        chunk_lane = jax.lax.broadcasted_iota(jnp.int32, (1, CHUNK), 1)
        sub = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)

        def exact_div(y):
            # the shared exact reciprocal-multiply floor division — plain
            # jnp ops, so it lowers inside the kernel unchanged
            return floor_div_exact(y, alloc, recip)

        def body(j, _):
            used = used_ref[...]
            estx = estx_ref[...]
            req_v = jnp.zeros((R, 1), jnp.int32)
            est_v = jnp.zeros((R, 1), jnp.int32)
            for r in range(R):
                req_v = jnp.where(sub == r, req_ref[j, r], req_v)
                est_v = jnp.where(sub == r, est_ref[j, r], est_v)
            requested = used + req_v
            fit = sched & jnp.all(
                (req_v == 0) | (requested <= alloc), axis=0, keepdims=True
            )
            q1 = exact_div((alloc - requested) * 100) * weight
            s1 = jnp.sum(
                jnp.where((alloc == 0) | (requested > alloc), 0, q1),
                axis=0, keepdims=True,
            ) // wsum
            eu = usage + estx + est_v
            q2 = exact_div((alloc - eu) * 100) * weight
            s2 = jnp.sum(
                jnp.where((alloc == 0) | (eu > alloc), 0, q2),
                axis=0, keepdims=True,
            ) // wsum
            s2 = jnp.where(fresh, s2, 0)
            is_ds = flags_ref[j, 0] > 0
            is_prod = flags_ref[j, 1] > 0
            mask = fit & (is_ds | ~fresh | la_ok)
            masked = jnp.where(mask, s1 + s2, -1)
            top = jnp.max(masked)
            # first-max tie-break (Mosaic argmax doesn't guarantee it)
            best = jnp.min(
                jnp.where(masked == top, lane, jnp.int32(2**30))
            ).astype(jnp.int32)
            ok = top >= 0
            node = jnp.where(ok, best, -1).astype(jnp.int32)
            assign_ref[...] = jnp.where(chunk_lane == j, node, assign_ref[...])
            hit = (lane == best) & ok
            used_ref[...] = used + jnp.where(hit, req_v, 0)
            estx_ref[...] = estx + jnp.where(hit, est_v, 0)
            prod_ref[...] = prod_ref[...] + jnp.where(
                hit & is_prod, est_v, 0
            )
            return 0

        jax.lax.fori_loop(0, CHUNK, body, 0)
        used_out_ref[...] = used_ref[...]
        est_out_ref[...] = estx_ref[...]
        prod_out_ref[...] = prod_ref[...]

    return kernel


def pallas_supported(params: ScoreParams, config) -> bool:
    """Whether this configuration maps onto the kernel (the flagship
    plain path)."""
    return (
        not config.score_according_prod
        and config.fit_weight == 1
        and config.loadaware_weight == 1
        and not bool(np.asarray(params.prod_thresholds).any())
    )


@functools.partial(jax.jit, static_argnames=("wsum", "interpret"))
def _pallas_solve(state: NodeState, pods: PodBatch, params: ScoreParams,
                  wsum: int, interpret: bool):
    n, r = state.alloc.shape
    p = pods.req.shape[0]
    N = ((n + 127) // 128) * 128
    P = ((p + CHUNK - 1) // CHUNK) * CHUNK

    def padn(a2):
        return jnp.zeros((r, N), jnp.int32).at[:, :n].set(
            a2.astype(jnp.int32).T
        )

    def padmask(m):
        return jnp.zeros((1, N), jnp.int32).at[0, :n].set(m.astype(jnp.int32))

    alloc = padn(state.alloc)
    recip = 1.0 / jnp.maximum(alloc, 1).astype(jnp.float32)
    usage = padn(state.usage)
    used0 = padn(state.used_req)
    est0 = padn(state.est_extra)
    prod0 = padn(state.prod_base)
    weight = jnp.asarray(params.weights, jnp.int32).reshape(r, 1)
    upct = percent_rounded(state.usage, state.alloc)
    over = (
        (state.alloc > 0)
        & (params.thresholds > 0)
        & (upct >= params.thresholds)
    )
    la_ok = padmask(~jnp.any(over, axis=-1))
    sched = padmask(state.schedulable)
    fresh = padmask(state.metric_fresh)
    reqs = jnp.zeros((P, r), jnp.int32).at[:p].set(pods.req)
    ests = jnp.zeros((P, r), jnp.int32).at[:p].set(pods.est)
    flags = jnp.zeros((P, 2), jnp.int32)
    flags = flags.at[:p, 0].set(
        (pods.is_daemonset & ~pods.blocked).astype(jnp.int32)
    )
    flags = flags.at[:p, 1].set(pods.is_prod.astype(jnp.int32))
    # padding pods (and host-blocked pods) can never fit
    blocked_req = jnp.int32(2**30)
    reqs = reqs.at[:p, 0].set(
        jnp.where(pods.blocked, blocked_req, reqs[:p, 0])
    )
    if P > p:
        reqs = reqs.at[p:, 0].set(blocked_req)

    full = lambda shape: pl.BlockSpec(shape, lambda c: (0, 0))
    out = pl.pallas_call(
        _make_kernel(r, wsum),
        grid=(P // CHUNK,),
        in_specs=[
            pl.BlockSpec((CHUNK, r), lambda c: (c, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CHUNK, r), lambda c: (c, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CHUNK, 2), lambda c: (c, 0),
                         memory_space=pltpu.SMEM),
            full((r, N)), full((r, N)), full((r, N)),
            pl.BlockSpec((r, 1), lambda c: (0, 0)),
            full((1, N)), full((1, N)), full((1, N)),
            full((r, N)), full((r, N)), full((r, N)),
        ],
        out_specs=[
            pl.BlockSpec((1, CHUNK), lambda c: (0, c)),
            full((r, N)), full((r, N)), full((r, N)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, P), jnp.int32),
            jax.ShapeDtypeStruct((r, N), jnp.int32),
            jax.ShapeDtypeStruct((r, N), jnp.int32),
            jax.ShapeDtypeStruct((r, N), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((r, N), jnp.int32),
            pltpu.VMEM((r, N), jnp.int32),
            pltpu.VMEM((r, N), jnp.int32),
        ],
        interpret=interpret,
    )
    assign, used, est, prod = out(
        reqs, ests, flags, alloc, recip, usage, weight, la_ok, sched,
        fresh, used0, est0, prod0,
    )
    new_state = state._replace(
        used_req=used[:, :n].T,
        est_extra=est[:, :n].T,
        prod_base=prod[:, :n].T,
    )
    return new_state, assign[0, :p]


def pallas_schedule_batch(
    state: NodeState,
    pods: PodBatch,
    params: ScoreParams,
    config,
    interpret: bool = None,
) -> Tuple[NodeState, jnp.ndarray]:
    """Drop-in for ``schedule_batch``'s plain path on the kernel.

    Raises ValueError for unsupported configurations — callers gate on
    :func:`pallas_supported`.
    """
    if not pallas_supported(params, config):
        raise ValueError("configuration not supported by the pallas kernel")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    wsum = int(np.asarray(params.weights).sum()) or 1
    return _pallas_solve(state, pods, params, wsum, interpret)
