"""NodeResourcesFit: resource-fit filter + LeastAllocated scoring, batched.

The upstream k8s scheduler's NodeResourcesFit plugin (the reference relies
on it for baseline fitting; SURVEY.md A.6) checks, per requested resource,
``request <= allocatable - requested_on_node`` and scores nodes by the
least-allocated formula. Here both are single vectorized expressions over
``[N, R]`` node matrices — the whole cluster is filtered/scored in one shot.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.ops.common import least_requested_score, weighted_mean_scores


def fit_filter(
    pod_req: jnp.ndarray,      # [R] int32
    node_alloc: jnp.ndarray,   # [N,R] int32
    node_used: jnp.ndarray,    # [N,R] int32 (sum of assigned pod requests)
) -> jnp.ndarray:
    """Boolean ``[N]`` mask: node has room for the pod's requests.

    Resources the pod does not request (req==0) impose no constraint,
    matching upstream Fit which iterates only requested resources.
    """
    fits = (pod_req == 0) | (node_used + pod_req <= node_alloc)
    return jnp.all(fits, axis=-1)


def least_allocated_score(
    pod_req: jnp.ndarray,      # [R] int32
    node_alloc: jnp.ndarray,   # [N,R] int32
    node_used: jnp.ndarray,    # [N,R] int32
    weights: jnp.ndarray,      # [R] int32 (0 = resource not scored)
    alloc_recip: jnp.ndarray = None,  # reciprocal_for(node_alloc), hot path
) -> jnp.ndarray:
    """LeastAllocated score ``[N]`` in 0..100:
    ``Σ_r w_r * (alloc - (used+req)) * 100 / alloc  //  Σ_r w_r``
    (SURVEY.md A.6; same form as the reference's leastRequestedScore but
    over requests rather than estimated usage)."""
    requested = node_used + pod_req
    per_resource = least_requested_score(requested, node_alloc, alloc_recip)
    return weighted_mean_scores(per_resource, weights)
